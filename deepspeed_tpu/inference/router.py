"""SLO-aware continuous-batching router over N engine_v2 replicas.

The serving tier's front end (ROADMAP open items 1a/2): one process-level
scheduler dispatching requests over N :class:`InferenceEngineV2` replicas.
The engines' serving loop (``generate``) stays the single-replica path; the
router drives the same primitives directly — ``can_schedule`` admission,
fused ``_put_sample`` prefill, ``decode_chain``/``decode_spec_chain`` — so
every fast-path invariant (one dispatch + one host sync per K tokens,
on-device sampling, prefix-cache reuse, speculative chains) holds per
replica unchanged.

Scheduling model (thread-per-replica, chain-granular):

  - **Dispatch loop**: every replica runs its own round loop on its own
    thread (``dispatch="threads"``, the default for >1 replica): host
    bookkeeping serializes under one router lock, but the DISPATCHES — the
    part that blocks on the device — run concurrently, so a prefill-pool
    replica's long prefill no longer delays a decode-pool replica's chain
    boundaries (ROADMAP #1 "one thread per replica"). ``dispatch="serial"``
    keeps the single-threaded walk (deterministic round ordering for
    debugging). Each replica threads its own committed PRNG key
    (``fold_in(seed, replica)``) — greedy output is unaffected.
  - **Assignment**: an arrived request is bound to the least-loaded replica
    among those that serve prefills. The load signal is the same
    per-replica ``serving/queue_depth`` / ``serving/goodput`` state the
    PR-5 gauges expose.
  - **SLO-aware admission** (``serving_slo`` config block): unchanged from
    PR 12 — projected TTFT judged BEFORE the prefill dispatch;
    ``admission="shed"`` rejects, ``"defer"`` holds/rebinds while any
    prefill-capable replica could still make the budget. Shedding happens
    strictly BEFORE admission: an admitted request is never dropped.
  - **Phase-aware placement** (ISSUE 14): replicas declare a role —
    ``prefill`` | ``decode`` | ``mixed`` (``RaggedInferenceConfig.role``).
    Fresh admissions route to the prefill pool; when a prefill-role
    replica finishes a request's prefill, the router enqueues a KV-block
    **migration**: the source exports the request's (values + scale) pages
    as one contiguous buffer (``engine.export_request`` — quantized bytes
    verbatim, asynchronous dispatch double-buffered against the next
    prefill), and the destination decode replica imports it at its next
    round (``engine.import_request`` — allocate + scatter, block table
    rewritten), re-admits the request, and continues its decode chains.
    TTFT stays pinned to the ORIGINAL arrival (the first token was served
    by the prefill replica); the TPOT chain restarts cleanly on the decode
    replica; ``serving/migration_ms|migrated_blocks|migration_failures``
    stamp the data plane. A migration that cannot import (destination
    capacity, any failure) leaves the request live on its SOURCE replica,
    which degrades to mixed-mode serving for it — and an empty prefill or
    decode pool degrades the whole roster to mixed placement. Admitted
    requests are never dropped, migrated or not.
  - **Replica-affine re-admission**: a preemption at a chain boundary
    re-queues the request pinned to its replica (where its prefix-cache
    blocks live) — under disagg that is the decode replica, which then
    re-prefills locally (mixed-mode for that request).

Observability: per-replica ``LifecycleTracker``s (labels ``{"replica": i}``)
feed the standard ``serving/*`` SLO metrics per replica, ``router/*``
counters/gauges cover the router's own decisions, each replica gets its own
Perfetto track, and every migration emits a ``serve:migrate`` span on the
destination with an in-span flow step bound to the request's fleet
``TraceContext`` — in a multi-process deployment ``tools/trace_merge.py``
joins the prefill-replica arrow onto the decode-replica slice.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import nullcontext
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
from deepspeed_tpu.inference.lifecycle import LifecycleTracker
from deepspeed_tpu.inference.migrate import (
    DEFAULT_MIGRATION_DEPTH,
    MigrationTicket,
)
from deepspeed_tpu.telemetry import get_tracer
from deepspeed_tpu.telemetry import fleet
from deepspeed_tpu.utils.logging import logger

# virtual Perfetto track ids for replica tracks (request tracks live at
# lifecycle.TRACK_BASE = 0x5E51_0000; replicas get their own range)
REPLICA_TRACK_BASE = 0x5E52_0000


class _Replica:
    """Router-side view of one engine replica."""

    def __init__(self, index: int, engine: InferenceEngineV2,
                 role: Optional[str] = None):
        self.index = index
        self.engine = engine
        self.role = role if role is not None else engine.config.validated_role
        self.active: Dict[int, int] = {}  # uid -> rid
        self.order: Dict[int, None] = {}  # admission order (insertion-ordered)
        self.assigned: deque = deque()  # rids bound here, not yet admitted
        self.tracker: Optional[LifecycleTracker] = None
        # migration plumbing (ISSUE 14)
        self.migrate_in: deque = deque()   # inbound MigrationTickets
        self.await_export: deque = deque()  # rids awaiting an export slot
        self.migrating: set = set()        # rids in limbo (skip decode here)
        self.tickets: List[MigrationTicket] = []  # outbound, in flight
        self.rng: Optional[jax.Array] = None  # per-replica committed key
        # host-observed EMAs (seconds): the admission gate's TTFT projection
        self.prefill_ema = 0.0
        self.chain_ema = 0.0
        self.dispatches = 0
        # serving-fabric lifecycle (ISSUE 18): a draining replica takes no
        # new admissions (its in-flight requests hand off to peers); a dead
        # one — heartbeat timeout or a transport error mid-dispatch — has
        # its requests re-admitted on survivors
        self.draining = False
        self.dead = False

    def load(self) -> float:
        """Queue-depth-based load score, goodput-discounted: replicas
        missing their SLO window attract less new load. A remote replica
        adds its own heartbeat-reported load (``remote_load``) — work the
        daemon carries that this router did not dispatch."""
        if self.dead:
            return float("inf")
        depth = len(self.assigned) + len(self.active)
        goodput = 1.0
        if self.tracker is not None and self.tracker._emit:
            g = self.tracker._g_goodput.value
            if g is not None and self.tracker._win_slo:
                goodput = float(g)
        return depth + (1.0 - goodput) + float(
            getattr(self.engine, "remote_load", 0.0))

    def ema(self, attr: str, value: float, alpha: float = 0.3) -> None:
        cur = getattr(self, attr)
        setattr(self, attr, value if cur == 0.0 else (1 - alpha) * cur + alpha * value)

    def has_work(self) -> bool:
        return bool(self.assigned or self.active or self.migrate_in
                    or self.await_export or self.tickets)


class _Serve:
    """Mutable state of one ``serve()`` call, shared across replica threads
    (every mutation happens under the router lock)."""

    def __init__(self, prompts, arr, t_start, max_new_tokens, eos_token_id,
                 sample_kw, spec):
        self.prompts = prompts
        self.arr = arr
        self.t_start = t_start
        self.max_new_tokens = max_new_tokens
        self.eos = eos_token_id
        self.sample_kw = sample_kw
        self.spec = spec
        n = len(prompts)
        self.pending: deque = deque(sorted(range(n), key=lambda i: arr[i]))
        self.gen: Dict[int, List[int]] = {i: [] for i in range(n)}
        self.outputs: Dict[int, Optional[np.ndarray]] = {}
        self.affinity: List[Optional[int]] = [None] * n
        self.admitted_once: set = set()
        self.next_uid = 0
        self.abort: Optional[BaseException] = None

    def context(self, idx: int) -> np.ndarray:
        return np.concatenate(
            [self.prompts[idx], np.asarray(self.gen[idx], np.int32)])


class ServingRouter:
    """Continuous-batching front end over N engine replicas.

    ``engines`` must share model/config semantics AND — when roles are
    specialized — an identical KV-pool layout (block size, storage dtype,
    quantization mode): migration moves pool bytes verbatim. ``slo``
    defaults to the first engine's ``serving_slo`` block; ``clock`` is
    injectable so the admission gate is testable against a fake clock;
    ``roles`` overrides the engines' ``config.role``; ``dispatch`` picks
    the replica-round execution: ``"threads"`` (default for >1 replica)
    runs one loop thread per replica, ``"serial"`` keeps the
    single-threaded walk.
    """

    def __init__(self, engines: Sequence[InferenceEngineV2], slo=None,
                 clock=time.perf_counter, roles: Optional[Sequence[str]] = None,
                 dispatch: str = "auto"):
        if not engines:
            raise ValueError("ServingRouter needs at least one engine replica")
        if roles is not None and len(roles) != len(engines):
            raise ValueError(
                f"{len(roles)} roles for {len(engines)} engines")
        self.replicas = [
            _Replica(i, e, role=None if roles is None else roles[i])
            for i, e in enumerate(engines)]
        for rep in self.replicas:
            if rep.role not in ("prefill", "decode", "mixed"):
                raise ValueError(
                    f"replica {rep.index}: role must be prefill|decode|mixed, "
                    f"got {rep.role!r}")
        if dispatch not in ("auto", "threads", "serial"):
            raise ValueError(
                f"dispatch must be auto|threads|serial, got {dispatch!r}")
        self.dispatch = ("threads" if len(engines) > 1 else "serial") \
            if dispatch == "auto" else dispatch
        # disagg placement is live only when BOTH phases have a home:
        # an empty prefill or decode pool degrades to mixed placement
        specialized = any(r.role != "mixed" for r in self.replicas)
        prefill_ok = any(r.role in ("prefill", "mixed") for r in self.replicas)
        decode_ok = any(r.role in ("decode", "mixed") for r in self.replicas)
        self.disagg = specialized and prefill_ok and decode_ok
        if specialized and not self.disagg:
            logger.warning(
                "ServingRouter: specialized roles "
                f"{[r.role for r in self.replicas]} leave a phase without a "
                "pool — degrading to mixed placement (no migration)")
            for rep in self.replicas:
                rep.role = "mixed"
        if self.disagg:
            # migration moves pool bytes verbatim: layouts must agree
            ref = self.replicas[0].engine
            for rep in self.replicas[1:]:
                e = rep.engine
                if (e.config.kv_block_size != ref.config.kv_block_size
                        or e.pool.quant != ref.pool.quant
                        or e.pool.k.dtype != ref.pool.k.dtype):
                    raise ValueError(
                        "disaggregated replicas must share the KV-pool "
                        f"layout: replica {rep.index} has (bs="
                        f"{e.config.kv_block_size}, quant={e.pool.quant}, "
                        f"dtype={e.pool.k.dtype}) vs replica 0 (bs="
                        f"{ref.config.kv_block_size}, quant={ref.pool.quant}, "
                        f"dtype={ref.pool.k.dtype})")
        self.migration_depth = max(
            int(getattr(engines[0].config, "migration_depth",
                        DEFAULT_MIGRATION_DEPTH)), 1)
        self.slo = slo if slo is not None else engines[0].config.serving_slo
        self._clock = clock
        self._tracer = get_tracer()
        self._lock = threading.Lock()
        # decision accounting (always on — the smoke and tests read these)
        self.shed_count = 0
        self.deferred_count = 0
        self.preemptions = 0
        self.affine_readmits = 0
        self.migrations = 0
        self.migrated_blocks = 0
        self.migration_failures = 0
        # serving-fabric accounting (ISSUE 18)
        self.dead_replicas = 0
        self.drains = 0
        self.readmits_dead = 0
        self._serve_state: Optional[_Serve] = None
        # distributed-trace contexts minted per request (fleet.TraceContext):
        # rid -> ctx; the wire form (`dispatch_context`) is what a real
        # process-boundary replica receives with its dispatch, and the flow
        # id is derived from (run_id, rid) so BOTH processes compute it —
        # the in-process replicas consume it through the lifecycle trackers
        self._trace_ctx: Dict[int, fleet.TraceContext] = {}
        self._request_seq = 0
        # multi-process crash forensics: a replica's flight-recorder dumps
        # must name which replica (and which run) they came from
        ident = fleet.get_identity()
        for rep in self.replicas:
            rec = getattr(rep.engine, "_recorder", None)
            if rec is not None:
                rec.set_context(replica=rep.index, role=rep.role,
                                run_id=ident.run_id,
                                process_index=ident.process_index)

    @classmethod
    def build(cls, model_config, params, engine_config=None, replicas: int = 2,
              roles: Optional[Sequence[str]] = None,
              prefill_share: float = 0.25, **kw) -> "ServingRouter":
        """N replicas from one (config, params) — each gets its own KV pool
        and scheduler state; params are shared (same host arrays).

        ``roles`` specializes the roster (e.g. ``["prefill", "decode"]``).
        With specialized roles AND a ``kv_pool_bytes`` budget in
        ``engine_config``, the budget is read as the TIER total and split
        per role through ``utils/hbm.disagg_pool_bytes`` (prefill pools
        hold KV transiently, so the decode side gets the bulk); a
        mixed roster keeps the budget per replica, unchanged."""
        base = dict(engine_config or {})
        role_list = list(roles) if roles is not None \
            else [base.get("role", "mixed")] * replicas
        if len(role_list) != replicas:
            raise ValueError(f"{len(role_list)} roles for {replicas} replicas")
        total = base.get("kv_pool_bytes")
        if total and any(r != "mixed" for r in role_list):
            from deepspeed_tpu.utils.hbm import disagg_pool_bytes

            budgets = disagg_pool_bytes(total, role_list,
                                        prefill_share=prefill_share)
        else:
            budgets = [total] * replicas
        engines = []
        for i in range(replicas):
            cfg = dict(base, role=role_list[i])
            if budgets[i] is not None:
                cfg["kv_pool_bytes"] = budgets[i]
            engines.append(InferenceEngineV2(model_config, params, cfg))
        return cls(engines, roles=role_list, **kw)

    # ------------------------------------------------------------ placement
    def _live(self) -> List[_Replica]:
        return [r for r in self.replicas if not r.dead]

    def _prefill_candidates(self) -> List[_Replica]:
        """Replicas that take FRESH admissions: the prefill pool under
        disagg, everyone otherwise (mixed replicas serve both phases).
        Dead replicas never qualify; draining ones only as a last resort —
        an already-admitted request re-queued off a dead peer must land
        SOMEWHERE (it is never dropped), even mid-drain."""
        live = self._live()
        if not live:
            raise RuntimeError("no live replicas to admit on")
        accepting = [r for r in live if not r.draining] or live
        if self.disagg:
            pre = [r for r in accepting if r.role == "prefill"]
            if pre:
                return pre
            mixed = [r for r in accepting if r.role == "mixed"]
            if mixed:
                return mixed
        return accepting

    def _migration_target(self, src: _Replica) -> Optional[_Replica]:
        """Least-loaded live, non-draining decode-pool replica (mixed as
        fallback) to receive a request's KV blocks; None = no target,
        serve mixed."""
        live = [r for r in self._live()
                if r is not src and not r.draining]
        cands = [r for r in live if r.role == "decode"]
        if not cands:
            cands = [r for r in live if r.role == "mixed"]
        if not cands:
            return None
        return min(cands, key=lambda r: (r.load(), r.index))

    def _least_loaded(self, candidates: Optional[List[_Replica]] = None
                      ) -> _Replica:
        cands = candidates if candidates is not None else self._live()
        return min(cands, key=lambda r: (r.load(), r.index))

    # ------------------------------------------------------------ admission
    def _projected_ttft_s(self, waited_s: float, rep: _Replica) -> float:
        """Wait so far + the replica's estimated time to first token: one
        prefill dispatch — which the scheduling round runs BEFORE the decode
        chains, so a replica with admission capacity prefills immediately; a
        full replica adds one chain boundary (its earliest slot)."""
        est = rep.prefill_ema
        if len(rep.active) >= rep.engine.config.max_seqs:
            est += rep.chain_ema
        return waited_s + est

    def _admission_decision(self, waited_s: float, rep: _Replica) -> str:
        """'admit' | 'defer' | 'shed' for a request that has waited
        ``waited_s`` and would prefill on ``rep`` next. Pure function of the
        SLO block + replica EMAs — pinned by the fake-clock tests."""
        slo = self.slo
        mode = getattr(slo, "admission", "none") if slo is not None else "none"
        ttft_ms = getattr(slo, "ttft_ms", None) if slo is not None else None
        if mode == "none" or ttft_ms is None:
            return "admit"
        budget_s = ttft_ms * getattr(slo, "admission_ttft_factor", 1.0) / 1e3
        if self._projected_ttft_s(waited_s, rep) <= budget_s:
            return "admit"
        if mode == "defer":
            # hold while ANY prefill-capable replica could still make the
            # budget; shed only when the wait alone has blown it everywhere
            if any(self._projected_ttft_s(waited_s, r) <= budget_s
                   for r in self._prefill_candidates()):
                return "defer"
            return "shed" if waited_s > budget_s else "defer"
        return "shed"

    # ---------------------------------------------------------------- serve
    def serve(
        self,
        prompts: Sequence[np.ndarray],
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_k: int = 0,
        top_p: float = 1.0,
        seed: int = 0,
        arrival_times: Optional[Sequence[float]] = None,
    ) -> List[Optional[np.ndarray]]:
        """Route ``prompts`` across the replicas; returns one output per
        prompt, ``None`` for requests the admission gate shed. The loop is
        the engine's ``generate`` lifted one level: assignment + SLO gate,
        then per replica the migrate/admit/prefill/chain round — each
        replica's device work is still one fused program per phase, and
        with ``dispatch="threads"`` those programs run concurrently across
        replicas."""
        prompts = [np.asarray(p, np.int32) for p in prompts]
        n_req = len(prompts)
        live = self._live()
        if not live:
            raise RuntimeError("ServingRouter.serve: no live replicas")
        spec = live[0].engine.config.spec_decode > 0
        if spec and do_sample:
            raise ValueError(
                "spec_decode is greedy-only (verify-and-accept compares "
                "argmax targets); disable do_sample or set spec_decode=0")
        # the same feasibility guards engine.generate applies — a prompt no
        # replica can ever serve must raise here, not stall the router loop.
        # A prefill-role replica only ever holds a request's PROMPT KV (the
        # decode window lives on its migration destination), so its pool is
        # guarded for the prompt alone; mixed/decode replicas need the full
        # prompt + generation window like a standalone engine.
        for rep in live:
            eng = rep.engine
            pool_tokens = eng.num_kv_blocks * eng.config.kv_block_size
            margin = eng.config.spec_decode
            decode_here = 0 if rep.role == "prefill" else max_new_tokens + margin
            for i, p in enumerate(prompts):
                if len(p) + max_new_tokens + margin > eng.max_seq_len:
                    raise ValueError(
                        f"prompt {i} ({len(p)} tokens) + max_new_tokens="
                        f"{max_new_tokens} (+{margin} speculative slack) "
                        f"exceeds replica {rep.index} max_seq_len={eng.max_seq_len}")
                if len(p) + decode_here > pool_tokens:
                    raise ValueError(
                        f"prompt {i} ({len(p)} tokens) + {decode_here} "
                        f"decode-window tokens cannot ever fit replica "
                        f"{rep.index}'s KV pool ({pool_tokens} slots)")
        sample_kw = (("do_sample", do_sample), ("temperature", temperature),
                     ("top_k", top_k), ("top_p", top_p))
        t_start = self._clock()
        if arrival_times is not None and len(arrival_times) != n_req:
            raise ValueError(
                f"arrival_times has {len(arrival_times)} entries for {n_req} prompts")
        arr = [float(a) for a in arrival_times] if arrival_times is not None \
            else [0.0] * n_req
        S = _Serve(prompts, arr, t_start, max_new_tokens, eos_token_id,
                   sample_kw, spec)
        # one TraceContext per request, fleet-unique request ids (monotonic
        # across serve() calls): the flow id both the admission arrow here
        # and a remote replica's serve:dispatch step derive independently
        seq0 = self._request_seq
        self._request_seq += n_req
        self._trace_ctx = {i: fleet.TraceContext.mint(seq0 + i)
                           for i in range(n_req)}
        # per-replica committed replicated keys, like engine.generate: an
        # uncommitted PRNGKey makes every replica's second admission wave
        # recompile its prefill program mid-burst (jit caches on
        # committed-ness); one key PER replica so concurrent dispatch never
        # races a shared key (greedy output is key-independent)
        from jax.sharding import NamedSharding, PartitionSpec as P

        for rep in live:
            rep.rng = jax.device_put(
                jax.random.fold_in(jax.random.PRNGKey(seed), rep.index),
                NamedSharding(rep.engine.mesh, P()))
        tr = self._tracer
        registry = tr.registry if tr.enabled else None
        handles = None
        if registry is not None:
            handles = {
                "c_requests": registry.counter("router/requests"),
                "c_shed": registry.counter("router/shed_requests"),
                "c_defer": registry.counter("router/deferred"),
                "c_preempt": registry.counter("router/preemptions"),
                "c_affine": registry.counter("router/affine_readmissions"),
                "c_migrations": registry.counter("router/migrations"),
                "g_depth": [registry.gauge("router/replica_queue_depth",
                                           replica=r.index)
                            for r in self.replicas],
                "g_active": [registry.gauge("router/replica_active",
                                            replica=r.index)
                             for r in self.replicas],
                "c_disp": [registry.counter("router/dispatches",
                                            replica=r.index)
                           for r in self.replicas],
            }
            handles["c_requests"].add(float(n_req))
            for r in self.replicas:
                # role-suffixed only under disagg: the plain name is a
                # pinned contract for mixed rosters
                suffix = f" [{r.role}]" if r.role != "mixed" else ""
                tr.name_track(REPLICA_TRACK_BASE + r.index,
                              f"replica {r.index}{suffix}")
        for r in self.replicas:
            if tr.enabled or r.engine._recorder is not None:
                r.tracker = LifecycleTracker(
                    tr, slo=self.slo, clock=self._clock,
                    labels={"k": r.engine.config.decode_chain,
                            "replica": r.index},
                    recorder=r.engine._recorder)
        self._handles = handles

        self._serve_state = S
        try:
            if self.dispatch == "threads" and len(self.replicas) > 1:
                self._serve_threaded(S)
            else:
                self._serve_serial(S)
        finally:
            self._serve_state = None
        if S.abort is not None:
            raise S.abort
        for rep in self.replicas:
            if rep.tracker is not None:
                rep.tracker.sample_gauges()
        if handles is not None:
            for rep in self.replicas:
                handles["g_depth"][rep.index].set(0.0)
                handles["g_active"][rep.index].set(0.0)
        return [S.outputs.get(i) for i in range(n_req)]

    # ------------------------------------------------------------ loop drivers
    def _work_left(self, S: _Serve) -> bool:
        return bool(S.pending) or any(r.has_work() for r in self.replicas)

    def _serve_serial(self, S: _Serve) -> None:
        while True:
            with self._lock:
                if S.abort is not None or not self._work_left(S):
                    return
                self._check_liveness(S)
                self._bind_arrivals(S)
            did_work = False
            for rep in self.replicas:
                if rep.dead:
                    continue
                try:
                    did_work |= self._replica_round(rep, S)
                except BaseException as e:  # noqa: BLE001 — propagate to caller
                    if getattr(e, "replica_gone", False):
                        # the replica's process died under a dispatch: fold
                        # it into the liveness path (its admitted requests
                        # re-queue on survivors), don't abort the serve
                        with self._lock:
                            self._mark_dead(rep, S)
                        did_work = True
                        continue
                    with self._lock:
                        S.abort = e
                    return
            if not did_work:
                self._idle_wait(S)

    def _serve_threaded(self, S: _Serve) -> None:
        """One loop thread per replica: each replica rounds independently,
        so one replica's blocking dispatch never delays another's chain
        boundary. The coordinator thread only binds arrivals (the shared
        clock-driven part) and watches for termination."""

        def run(rep: _Replica) -> None:
            try:
                while True:
                    with self._lock:
                        if S.abort is not None or not self._work_left(S):
                            return
                        if rep.dead:
                            # survivors carry the re-queued work; this
                            # thread only waits for the serve to finish
                            pass
                        # tight-poll only while a sibling might hand work
                        # over any moment; a drained roster waiting out an
                        # open-loop arrival gap (or a deferred request's
                        # admission window) sleeps toward it instead of
                        # burning a core per replica on the shared lock
                        busy = any(r.active or r.migrate_in or r.await_export
                                   or r.tickets for r in self.replicas)
                    if not rep.dead:
                        try:
                            if self._replica_round(rep, S):
                                continue
                        except BaseException as e:  # noqa: BLE001
                            if not getattr(e, "replica_gone", False):
                                raise
                            # process died under a dispatch — mark dead and
                            # keep the serve alive on the survivors
                            with self._lock:
                                self._mark_dead(rep, S)
                            continue
                    if busy:
                        time.sleep(0.0002)
                    else:
                        self._idle_wait(S)
            except BaseException as e:  # noqa: BLE001 — surface on the caller
                with self._lock:
                    if S.abort is None:
                        S.abort = e

        threads = [threading.Thread(target=run, args=(rep,), daemon=True,
                                    name=f"dstpu-replica-{rep.index}")
                   for rep in self.replicas]
        for t in threads:
            t.start()
        try:
            while True:
                with self._lock:
                    if S.abort is not None or not self._work_left(S):
                        break
                    self._check_liveness(S)
                    self._bind_arrivals(S)
                time.sleep(0.0005)
        finally:
            for t in threads:
                t.join()

    def _idle_wait(self, S: _Serve) -> None:
        """Serial-mode idle: advance wall time toward the next arrival or an
        admission-gate decision instead of spinning hot."""
        with self._lock:
            nxt = S.pending[0] if S.pending else None
            active_any = any(r.active for r in self.replicas)
            assigned_any = any(r.assigned for r in self.replicas)
        if nxt is not None:
            wait = S.t_start + S.arr[nxt] - self._clock()
            if wait > 0:  # open-loop: idle until the next arrival
                time.sleep(min(wait, 0.02))
            return
        if assigned_any and not active_any:
            # deferred requests waiting out their admission gate — let wall
            # time advance (they admit or shed as `waited` grows)
            time.sleep(0.001)

    # ------------------------------------------------------- shared bookkeeping
    def _bind_arrivals(self, S: _Serve) -> None:
        """Phase 1 (lock held): bind arrived requests to the least-loaded
        prefill-capable replica (preempted requests keep their affinity —
        their cached prefix blocks live there)."""
        handles = self._handles
        now = self._clock()
        while S.pending and now - S.t_start >= S.arr[S.pending[0]]:
            idx = S.pending.popleft()
            aff = S.affinity[idx]
            if aff is not None and (self.replicas[aff].dead
                                    or self.replicas[aff].draining):
                # the affine replica left the roster: its cached prefix is
                # gone with it — rebind fresh on a survivor
                S.affinity[idx] = aff = None
            if aff is not None:
                rep = self.replicas[aff]
                self.affine_readmits += 1
                if handles is not None:
                    handles["c_affine"].add(1.0)
            else:
                rep = self._least_loaded(self._prefill_candidates())
                S.affinity[idx] = rep.index
            rep.assigned.append(idx)

    # -------------------------------------------------- fabric roster lifecycle
    def _check_liveness(self, S: _Serve) -> None:
        """Lock held. Fold heartbeat-detected deaths (``engine.alive`` is
        False after ``heartbeat_miss_limit`` consecutive missed beats on a
        ``RemoteReplica``) into the roster."""
        for rep in self.replicas:
            if not rep.dead and getattr(rep.engine, "alive", True) is False:
                self._mark_dead(rep, S)

    def _mark_dead(self, rep: _Replica, S: Optional[_Serve]) -> None:
        """Lock held. Remove ``rep`` from the roster and re-queue every
        admitted request it held on the survivors — the PR-14 invariant
        ("an admitted request is never dropped") extended across process
        death. Generated tokens live router-side in ``S.gen``, so a
        survivor re-prefills the full context and the output continues
        exactly where the dead replica stopped."""
        if rep.dead:
            return
        rep.dead = True
        rep.draining = True
        self.dead_replicas += 1
        if self._tracer.enabled:
            self._tracer.registry.counter("router/dead_replicas").add(1.0)
        msg = (f"replica {rep.index} marked dead "
               f"({len(rep.active)} active, {len(rep.assigned)} assigned): "
               "re-admitting its requests on survivors")
        logger.warning(msg)
        from deepspeed_tpu.telemetry.events import emit_event

        emit_event("fabric", "replica_dead", msg, severity="critical",
                   labels={"replica": rep.index, "role": rep.role,
                           "active": len(rep.active)})
        if S is None:
            rep.active.clear()
            rep.order.clear()
            rep.assigned.clear()
            rep.migrating.clear()
            rep.await_export.clear()
            rep.tickets = []
            rep.migrate_in.clear()
            return
        # requests already safely en route to (or landed on) a live peer:
        # the exported bytes live in router memory, so the import path
        # carries them through — no re-prefill, no double-serve
        safe = {t.idx for t in rep.tickets
                if t.status in ("inflight", "done")
                and not self.replicas[t.dst].dead}
        rep.tickets = []
        # bounce inbound tickets: their (live) sources see "failed" and
        # resume mixed or retry toward a live destination
        while rep.migrate_in:
            rep.migrate_in.popleft().status = "failed"
        while rep.assigned:
            idx = rep.assigned.popleft()
            S.affinity[idx] = None
            S.pending.appendleft(idx)
        for uid, idx in list(rep.active.items()):
            if idx in safe or S.outputs.get(idx) is not None:
                continue
            S.affinity[idx] = None
            S.pending.appendleft(idx)
            self.readmits_dead += 1
            if rep.tracker is not None:
                rep.tracker.preempt(idx)
        rep.active.clear()
        rep.order.clear()
        rep.migrating.clear()
        rep.await_export.clear()
        if not self._live():
            S.abort = RuntimeError(
                "all replicas dead: admitted requests cannot complete")

    def request_drain(self, index: int) -> None:
        """Quiesce replica ``index``: no new admissions, and every in-flight
        request hands off to a peer over the ordinary migration-ticket
        plane (wire KV for a remote peer — quantized bytes verbatim, prefix
        cache re-indexed from the imported blocks). Safe to call mid-serve
        from another thread; outside a serve it just marks the roster."""
        rep = self.replicas[index]
        drain_rpc = getattr(rep.engine, "drain", None)
        if callable(drain_rpc):
            drain_rpc()  # the daemon refuses admissions at its own door too
        with self._lock:
            rep.draining = True
            self.drains += 1
            if self._tracer.enabled:
                self._tracer.registry.counter("router/drains").add(1.0)
            S = self._serve_state
            if S is None:
                return
            while rep.assigned:
                idx = rep.assigned.popleft()
                S.affinity[idx] = None
                S.pending.appendleft(idx)
            for uid, idx in rep.active.items():
                if idx not in rep.migrating:
                    rep.migrating.add(idx)
                    rep.await_export.append(idx)

    def join(self, engine: Any, role: Optional[str] = None) -> _Replica:
        """Register a fresh replica (local engine or ``RemoteReplica``) into
        the roster. Join happens at serve() boundaries only: a serve in
        flight holds per-replica threads, metric handles and rng state
        sized to the roster it started with."""
        with self._lock:
            if self._serve_state is not None:
                raise RuntimeError(
                    "join during an in-flight serve() is not supported; "
                    "join between serve() calls")
            rep = _Replica(len(self.replicas), engine, role=role)
            if rep.role not in ("prefill", "decode", "mixed"):
                raise ValueError(
                    f"joining replica: role must be prefill|decode|mixed, "
                    f"got {rep.role!r}")
            if self.disagg:
                ref = self.replicas[0].engine
                e = engine
                if (e.config.kv_block_size != ref.config.kv_block_size
                        or e.pool.quant != ref.pool.quant
                        or e.pool.k.dtype != ref.pool.k.dtype):
                    raise ValueError(
                        "joining replica must share the KV-pool layout: "
                        f"(bs={e.config.kv_block_size}, quant={e.pool.quant}, "
                        f"dtype={e.pool.k.dtype}) vs replica 0 (bs="
                        f"{ref.config.kv_block_size}, quant={ref.pool.quant}, "
                        f"dtype={ref.pool.k.dtype})")
            self.replicas.append(rep)
            return rep

    def _accept(self, rep: _Replica, S: _Serve, u: int, t: int) -> None:
        """Record token t for uid u on rep; retire the row if done. Lock
        held by the caller."""
        idx = rep.active[u]
        S.gen[idx].append(int(t))
        if len(S.gen[idx]) >= S.max_new_tokens or (
                S.eos is not None and int(t) == S.eos):
            S.outputs[idx] = np.asarray(S.gen[idx], np.int32)
            rep.active.pop(u)
            rep.order.pop(u)
            rep.migrating.discard(idx)
            rep.engine.flush(u)
            if rep.tracker is not None:
                rep.tracker.finish(idx)

    def _shed(self, idx: int, rep: Optional[_Replica], S: _Serve) -> None:
        """Lock held by the caller."""
        S.outputs[idx] = None
        self.shed_count += 1
        if self._handles is not None:
            self._handles["c_shed"].add(1.0)
        if rep is not None and rep.tracker is not None:
            # an arrived-but-never-served request still counts against
            # the replica's request totals (goodput's denominator is
            # finished requests only; shed ones are reported separately)
            rep.tracker.arrive(idx, now=S.t_start + S.arr[idx])

    def _replica_span(self, rep: _Replica, name: str, t0: float,
                      t1: float) -> None:
        if self._handles is None:
            return
        tr = self._tracer
        tr.append_events([{
            "kind": "span", "name": name, "cat": "router",
            "ts": t0 - tr.origin(), "dur": max(t1 - t0, 0.0),
            "tid": REPLICA_TRACK_BASE + rep.index,
            "args": {"replica": rep.index}}])

    # ---------------------------------------------------------- replica round
    def _replica_round(self, rep: _Replica, S: _Serve) -> bool:
        """One scheduling round of ONE replica: drain inbound migrations,
        reap outbound tickets, pump exports, admit + prefill, then one
        chained decode. Host bookkeeping under the router lock; every
        device dispatch outside it."""
        did = self._drain_migrations(rep, S)
        did |= self._reap_outbound(rep, S)
        did |= self._pump_exports(rep, S)
        did |= self._prefill_phase(rep, S)
        did |= self._pump_exports(rep, S)
        did |= self._chain_phase(rep, S)
        return did

    # ---------------------------------------------------------- migration plane
    def _pump_exports(self, rep: _Replica, S: _Serve) -> bool:
        """Source side: export awaiting requests up to the double-buffer
        depth. The export dispatch is asynchronous — request N's pages
        stream while this replica assembles request N+1's prefill."""
        did = False
        while True:
            with self._lock:
                inflight = sum(1 for t in rep.tickets
                               if t.status == "inflight")
                if not rep.await_export or inflight >= self.migration_depth:
                    return did
                idx = rep.await_export.popleft()
                uid = next((u for u, i in rep.active.items() if i == idx),
                           None)
                if uid is None:  # finished/retired while awaiting export
                    rep.migrating.discard(idx)
                    continue
                dst = self._migration_target(rep)
                if dst is None:  # decode pool vanished: serve mixed
                    rep.migrating.discard(idx)
                    continue
                tokens = S.context(idx)
            t0 = self._clock()
            if rep.tracker is not None:
                rep.tracker.migrate_start(idx, now=t0)
            export = rep.engine.export_request(uid)
            ticket = MigrationTicket(idx=idx, uid=uid, src=rep.index,
                                     dst=dst.index, export=export,
                                     tokens=tokens, t_start=t0)
            with self._lock:
                rep.tickets.append(ticket)
                dst.migrate_in.append(ticket)
            did = True

    def _drain_migrations(self, rep: _Replica, S: _Serve) -> bool:
        """Destination side: import inbound tickets and re-admit their
        requests — the decode pool's arrival path."""
        did = False
        while True:
            with self._lock:
                if not rep.migrate_in:
                    return did
                ticket = rep.migrate_in.popleft()
                new_uid = S.next_uid
                S.next_uid += 1
            ctx = self._trace_ctx.get(ticket.idx)
            span = fleet.dispatch_span(ctx, name="serve:migrate",
                                       replica=rep.index) \
                if (self._tracer.enabled and ctx is not None) else nullcontext()
            ok = False
            try:
                with span:
                    ok = rep.engine.import_request(new_uid, ticket.export)
            except Exception:  # noqa: BLE001 — failure degrades, never drops
                msg = (f"migration of request {ticket.idx} to replica "
                       f"{rep.index} failed; serving mixed on replica "
                       f"{ticket.src}")
                logger.warning(msg, exc_info=True)
                from deepspeed_tpu.telemetry.events import emit_event

                emit_event("fabric", "migration_failure", msg,
                           severity="warn",
                           labels={"replica": rep.index, "src": ticket.src},
                           request_id=ticket.idx,
                           dedup_key=f"fabric:migration_failure:{rep.index}")
            now = self._clock()
            with self._lock:
                src_rep = self.replicas[ticket.src]
                if ok:
                    ticket.new_uid = new_uid
                    ticket.status = "done"
                    rep.active[new_uid] = ticket.idx
                    rep.order[new_uid] = None
                    S.affinity[ticket.idx] = rep.index
                    self.migrations += 1
                    self.migrated_blocks += ticket.export["n_blocks"]
                    if self._handles is not None:
                        self._handles["c_migrations"].add(1.0)
                    if src_rep.tracker is not None and rep.tracker is not None:
                        src_rep.tracker.transfer(ticket.idx, rep.tracker)
                else:
                    ticket.status = "failed"
                    self.migration_failures += 1
                    if src_rep.dead and S.outputs.get(ticket.idx) is None:
                        # a dead source cannot resume the request mixed —
                        # re-admit it from scratch on a survivor instead
                        # (the never-dropped invariant outranks the lost
                        # prefix reuse)
                        S.affinity[ticket.idx] = None
                        S.pending.appendleft(ticket.idx)
                        self.readmits_dead += 1
            if ok:
                if rep.tracker is not None:
                    rep.tracker.admit(ticket.idx, new_uid, now=now)
                    rep.tracker.migrated(
                        ticket.idx, ticket.export["n_blocks"], now=now)
                if rep.engine.prefix_cache is not None:
                    # the imported blocks carry the SAME quantized bytes —
                    # index them here so later prompts sharing the prefix
                    # hit on the decode replica too (content hashes match
                    # the source's insert-time digests bit-for-bit). Only
                    # tokens whose KV the pool actually HOLDS are indexed:
                    # the context's trailing sampled token has no KV yet
                    # (its write happens when it feeds the next decode
                    # step), and a digest over its still-unwritten slot
                    # would go stale the moment that write lands.
                    seen = ticket.export["seen_tokens"]
                    rep.engine._insert_prefix(new_uid, ticket.tokens[:seen])
            did = True

    def _reap_outbound(self, rep: _Replica, S: _Serve) -> bool:
        """Source side: finalize tickets the destination resolved — release
        the migrated request's blocks on success (its own thread owns the
        allocator), or resume serving it here on failure (mixed fallback)."""
        with self._lock:
            resolved = [t for t in rep.tickets if t.status != "inflight"]
            if not resolved:
                return False
            rep.tickets = [t for t in rep.tickets if t.status == "inflight"]
        for t in resolved:
            if t.status == "done":
                with self._lock:
                    rep.active.pop(t.uid, None)
                    rep.order.pop(t.uid, None)
                    rep.migrating.discard(t.idx)
                rep.engine.flush(t.uid)
            else:
                eng = rep.engine
                pool_tokens = eng.num_kv_blocks * eng.config.kv_block_size
                window = (len(S.prompts[t.idx]) + S.max_new_tokens
                          + eng.config.spec_decode)
                if window > pool_tokens:
                    # failed import on a source whose pool can never host
                    # the request's full decode window (prefill pools are
                    # guarded for the PROMPT alone): mixed fallback here
                    # would wedge the chain phase, so RETRY the ticket —
                    # refused or errored alike — the serve() guard pinned
                    # that the destination fits the window, and its
                    # capacity frees as its chains finish. The exported
                    # buffer is still the live bytes: rows in
                    # ``migrating`` never decode on the source.
                    if rep.tracker is not None:
                        rep.tracker.migrate_retry(t.idx)
                    with self._lock:
                        dst = self.replicas[t.dst]
                        if dst.dead or dst.draining:
                            # the destination left the roster mid-retry:
                            # re-aim the ticket at a live one
                            nd = self._migration_target(rep)
                            if nd is None:
                                # no live destination can host the window;
                                # the request re-admits from scratch on
                                # whatever survives
                                rep.migrating.discard(t.idx)
                                if S.outputs.get(t.idx) is None:
                                    S.affinity[t.idx] = None
                                    S.pending.appendleft(t.idx)
                                continue
                            t.dst = nd.index
                            dst = nd
                        t.status = "inflight"
                        rep.tickets.append(t)
                        dst.migrate_in.append(t)
                    continue
                with self._lock:
                    rep.migrating.discard(t.idx)
                if rep.tracker is not None:
                    rep.tracker.migrate_failed(t.idx)
        return True

    # ------------------------------------------------------------- prefill phase
    def _prefill_phase(self, rep: _Replica, S: _Serve) -> bool:
        eng = rep.engine
        now = self._clock()
        with self._lock:
            adm_uids: List[int] = []
            adm_tokens: List[np.ndarray] = []
            adm_counts: List[int] = []
            adm_full: List[np.ndarray] = []
            decoding = list(rep.active.keys())
            deferred: List[int] = []
            while rep.assigned and len(rep.active) < eng.config.max_seqs:
                idx = rep.assigned[0]
                waited = now - (S.t_start + S.arr[idx])
                # the SLO gate applies to FIRST admissions only: a
                # preempted request was already admitted and holds
                # generated tokens — dropping it now would violate the
                # "an admitted request is never dropped" invariant (it
                # re-admits unconditionally, on its affine replica)
                decision = ("admit" if idx in S.admitted_once
                            else self._admission_decision(waited, rep))
                if decision == "shed":
                    rep.assigned.popleft()
                    self._shed(idx, rep, S)
                    continue
                if decision == "defer":
                    # migrate toward the replica the decision says could
                    # still make the budget — a never-admitted request
                    # has no KV and no cached prefix to lose by rebinding
                    rep.assigned.popleft()
                    best = min(self._prefill_candidates(),
                               key=lambda r: self._projected_ttft_s(waited, r))
                    if best is not rep:
                        S.affinity[idx] = best.index
                        best.assigned.append(idx)
                    else:
                        deferred.append(idx)
                    self.deferred_count += 1
                    if self._handles is not None:
                        self._handles["c_defer"].add(1.0)
                    continue
                cand = S.context(idx)
                suffix = eng.try_admit(S.next_uid, cand, decoding + adm_uids,
                                       [1] * len(decoding) + adm_counts)
                if suffix is None:
                    break
                rep.assigned.popleft()
                S.admitted_once.add(idx)
                adm_uids.append(S.next_uid)
                adm_tokens.append(suffix)
                adm_counts.append(len(suffix))
                adm_full.append(cand)
                if rep.tracker is not None:
                    rep.tracker.arrive(idx, now=S.t_start + S.arr[idx])
                    rep.tracker.admit(idx, S.next_uid)
                    rep.tracker.set_trace_context(idx, self._trace_ctx[idx])
                rep.active[S.next_uid] = idx
                rep.order[S.next_uid] = None
                S.next_uid += 1
            rep.assigned.extend(deferred)
            if adm_uids:
                adm_rids = [rep.active[u] for u in adm_uids]
        if not adm_uids:
            return False
        t0 = self._clock()
        toks, rep.rng = eng._put_sample(
            adm_uids, adm_tokens, rep.rng, S.sample_kw,
            tracker=rep.tracker, rids=adm_rids)
        t1 = self._clock()
        rep.ema("prefill_ema", t1 - t0)
        rep.dispatches += 1
        self._replica_span(rep, "prefill", t0, t1)
        if self._handles is not None:
            self._handles["c_disp"][rep.index].add(1.0)
        if eng.prefix_cache is not None:
            for u, full in zip(adm_uids, adm_full):
                eng._insert_prefix(u, full)
        if rep.tracker is not None:
            rep.tracker.emitted_batch(adm_rids, (1,) * len(adm_rids))
        with self._lock:
            if rep.dead:
                # heartbeat death folded in while this dispatch was in
                # flight: _mark_dead already re-queued these requests on
                # survivors, so the dead replica's tokens are discarded
                return True
            for u, t in zip(adm_uids, toks):
                self._accept(rep, S, u, t)
            # disagg hand-off: a prefill-pool replica's finished prefills
            # queue for migration to the decode pool (unless the request
            # already finished at its first token)
            if self.disagg and rep.role == "prefill":
                for u in adm_uids:
                    if u in rep.active:
                        idx = rep.active[u]
                        rep.migrating.add(idx)
                        rep.await_export.append(idx)
        return True

    # --------------------------------------------------------------- chain phase
    def _chain_phase(self, rep: _Replica, S: _Serve) -> bool:
        eng = rep.engine
        with self._lock:
            # rows in migration limbo decode on their DESTINATION once the
            # import commits — never here (their exported pages must stay
            # the bytes the destination receives)
            uids = [u for u in rep.active
                    if rep.active[u] not in rep.migrating]
            if not uids:
                return False
            budgets = [S.max_new_tokens - len(S.gen[rep.active[u]])
                       for u in uids]
            k = eng.config.decode_chain
            while True:
                while k > 1 and not eng._can_schedule_evicting(
                        uids, eng.chain_window(budgets, k)):
                    k -= 1
                if eng._can_schedule_evicting(uids, eng.chain_window(budgets, k)):
                    break
                # preempt the youngest non-migrating row; it re-queues
                # pinned to THIS replica so its cached prefix blocks stay
                # useful
                uid_set = set(uids)
                victim = next((u for u in reversed(rep.order)
                               if u in uid_set), None)
                if victim is None:
                    raise RuntimeError(
                        f"replica {rep.index}: KV pool cannot host its "
                        "non-migrating rows and none are preemptible")
                del rep.order[victim]
                i = uids.index(victim)
                uids.pop(i)
                budgets.pop(i)
                idx = rep.active.pop(victim)
                eng.flush(victim)
                S.pending.appendleft(idx)
                self.preemptions += 1
                if rep.tracker is not None:
                    rep.tracker.preempt(idx)
                if self._handles is not None:
                    self._handles["c_preempt"].add(1.0)
                if not uids:
                    if rep.migrating or rep.tickets:
                        # transient pressure: in-limbo rows hold their
                        # blocks only until their exports land; the row
                        # preempted above re-queued and re-admits when
                        # the limbo drains — skip this chain round
                        return False
                    raise RuntimeError(
                        f"replica {rep.index}: KV pool too small for a "
                        f"single sequence ({eng.num_kv_blocks} blocks)")
                k = eng.config.decode_chain
            last = [S.gen[rep.active[u]][-1] for u in uids]
            chain_rids = [rep.active[u] for u in uids]
            histories = [S.context(rep.active[u]) for u in uids] \
                if S.spec else None
        t0 = self._clock()
        if S.spec:
            out, emitted, rep.rng = eng.decode_spec_chain(
                uids, last, budgets, k, rep.rng, histories,
                eos_id=S.eos, tracker=rep.tracker, rids=chain_rids)
        else:
            out, emitted, rep.rng = eng.decode_chain(
                uids, last, budgets, k, rep.rng, eos_id=S.eos,
                sample_kw=S.sample_kw, tracker=rep.tracker, rids=chain_rids)
        t1 = self._clock()
        rep.ema("chain_ema", t1 - t0)
        rep.dispatches += 1
        self._replica_span(rep, "chain", t0, t1)
        eng.tokens_decoded += int(emitted.sum())
        if rep.tracker is not None:
            rep.tracker.emitted_batch(chain_rids, emitted, now=t1)
            rep.tracker.sample_gauges(now=t1)
        if self._handles is not None:
            self._handles["c_disp"][rep.index].add(1.0)
            self._handles["g_depth"][rep.index].set(float(len(rep.assigned)))
            self._handles["g_active"][rep.index].set(float(len(rep.active)))
        with self._lock:
            for i, u in enumerate(uids):
                for t in out[i, : emitted[i]]:
                    if u in rep.active:
                        self._accept(rep, S, u, t)
        return True

    def dispatch_context(self, idx: int) -> Optional[Dict[str, Any]]:
        """Wire-form trace context for request ``idx`` of the current/most
        recent ``serve()`` — what a REAL process-boundary replica receives
        alongside its dispatch payload. The receiver rebuilds it with
        ``fleet.TraceContext.from_wire`` and wraps its work in
        ``fleet.dispatch_span(ctx)`` (name ``serve:dispatch`` for a decode
        hand-off's chain, ``serve:migrate`` for the KV import), which emits
        the span + in-span flow step that binds into this router's
        admission arrow once ``tools/trace_merge.py`` joins the streams."""
        ctx = self._trace_ctx.get(idx)
        return ctx.to_wire() if ctx is not None else None

    def reset_estimates(self) -> None:
        """Zero the per-replica latency EMAs. Call after a warmup pass: the
        first dispatch of each program carries its XLA compile time, and an
        EMA seeded with compile latency makes the admission gate project
        every cold request over budget (it would shed the whole burst)."""
        for rep in self.replicas:
            rep.prefill_ema = 0.0
            rep.chain_ema = 0.0

    # ------------------------------------------------------------- reporting
    def goodput(self) -> Tuple[int, int]:
        """(slo_met, slo_missed) summed over the replica trackers."""
        met = missed = 0
        for rep in self.replicas:
            t = rep.tracker
            if t is None or not t._emit:
                continue
            met += int(t._c_slo_met.value)
            missed += int(t._c_slo_missed.value)
        return met, missed

    def stats(self) -> Dict[str, Any]:
        return {
            "replicas": len(self.replicas),
            "roles": [r.role for r in self.replicas],
            "dispatch": self.dispatch,
            "shed": self.shed_count,
            "deferred": self.deferred_count,
            "preemptions": self.preemptions,
            "affine_readmissions": self.affine_readmits,
            "migrations": self.migrations,
            "migrated_blocks": self.migrated_blocks,
            "migration_failures": self.migration_failures,
            "dispatches": [r.dispatches for r in self.replicas],
            "dead": [r.index for r in self.replicas if r.dead],
            "draining": [r.index for r in self.replicas if r.draining],
            "dead_replicas": self.dead_replicas,
            "drains": self.drains,
            "readmits_dead": self.readmits_dead,
        }

    def reset_stats(self) -> None:
        """Zero the router-lifetime decision counters ``stats()`` reports —
        benches call this after warmup so the reported shed/migration
        counts cover only the measured window."""
        self.shed_count = self.deferred_count = 0
        self.preemptions = self.affine_readmits = 0
        self.migrations = self.migrated_blocks = self.migration_failures = 0
