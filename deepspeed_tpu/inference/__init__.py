"""deepspeed_tpu.inference: generation engines.

v1 (``engine.py``): TP-sharded whole-batch generation with a dense KV cache —
the analog of reference ``InferenceEngine`` (inference/engine.py:40).
v2 (``engine_v2.py``): continuous batching over a paged KV cache — the analog
of FastGen ``InferenceEngineV2`` (inference/v2/engine_v2.py:30).
"""

from deepspeed_tpu.inference.config import InferenceConfig
from deepspeed_tpu.inference.engine import InferenceEngine, init_inference
from deepspeed_tpu.inference.engine_v2 import (
    InferenceEngineV2,
    RaggedInferenceConfig,
    build_hf_engine,
)
from deepspeed_tpu.inference.migrate import MigrationTicket, remote_copy_pages
from deepspeed_tpu.inference.model import KVCache, decode_step, init_cache, prefill
from deepspeed_tpu.inference.paged import (
    MigrationBuffer,
    export_pool_blocks,
    import_pool_blocks,
)
from deepspeed_tpu.inference.ragged import BlockedAllocator, PrefixCache, StateManager
from deepspeed_tpu.inference.router import ServingRouter
from deepspeed_tpu.inference.sampling import greedy_tokens, sample_logits
