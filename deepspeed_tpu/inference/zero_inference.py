"""ZeRO-Inference NVMe weight streaming.

Reference analog: ZeRO-Inference's stage-3 + AIO path
(``deepspeed/inference/config.py`` ZeRO config for inference,
``runtime/swap_tensor/partitioned_param_swapper.py:37``
``AsyncPartitionedParameterSwapper`` — serve models LARGER THAN HOST RAM by
keeping weights on NVMe and streaming each layer in ahead of use).

TPU design: the stacked per-layer parameter tree is sliced into L per-layer
pytrees written to disk through the native AIO pool; at most ``num_buffers``
layers are resident at once. The forward becomes a Python loop over layers
calling ONE jitted block function (every layer has identical shapes, so the
whole model costs a single compile), and layer l+1's AIO reads are issued
before layer l's compute is dispatched — JAX's async dispatch returns
immediately, so disk reads overlap device compute (the reference's
double-buffered prefetch, without streams). Composes with WOQ: quantized
leaves are what's written to disk, so int4/fp8 cuts disk traffic 4x — the
reference's headline ZeRO-Inference + quant combo.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.model import (
    KVCache,
    _block_step,
    _logits,
    decode_inputs,
    init_cache,
    prefill_inputs,
)
from deepspeed_tpu.models.transformer import TransformerConfig
from deepspeed_tpu.runtime.swap_tensor import AsyncTensorSwapper
from deepspeed_tpu.utils.logging import log_dist


class NVMeStreamedParams:
    """Layer weights on NVMe; at most ``num_buffers`` layers in RAM at once.

    ``params`` must be the stacked-layers tree (``scan_layers=True`` layout:
    every leaf under ``params['layers']`` has leading dim L). Non-layer
    params (embeddings, final norm, lm head) stay resident on device — they
    are consumed by gather/the logits matmul every step and are small
    relative to the layer stack.
    """

    def __init__(self, params: Any, folder: str, num_buffers: int = 2,
                 num_threads: int = 4, quant_fmt: Optional[str] = None,
                 quant_min_size: int = 1 << 16):
        if "layers" not in params:
            raise ValueError("NVMe streaming requires stacked layer params "
                             "('layers'; scan_layers=True checkpoint layout)")
        layers = params["layers"]
        self.num_layers = jax.tree_util.tree_leaves(layers)[0].shape[0]
        self.resident = {k: v for k, v in params.items() if k != "layers"}
        self.num_buffers = max(2, num_buffers)
        self.swapper = AsyncTensorSwapper(folder, num_threads=num_threads)

        # WOQ composes here, PER LAYER SLICE: quantizing the stacked tree
        # would interleave quantization blocks across layers and break
        # slicing (scale shapes lose the L dim). One jitted quantizer serves
        # all layers (identical shapes — jit caches per structure).
        quant = None
        if quant_fmt:
            from deepspeed_tpu.inference.woq import quantize_params

            quant = jax.jit(
                lambda p: quantize_params(p, quant_fmt, min_size=quant_min_size))
            self.resident = quant(self.resident)

        bytes_disk = 0
        self._like = None  # first layer's device tree: sharding template for swap-in
        for layer_idx in range(self.num_layers):
            sl = jax.tree_util.tree_map(lambda x, i=layer_idx: x[i], layers)
            if quant is not None:
                sl = quant(sl)
            if self._like is None:
                # re-pinning template so streamed layers come back with the
                # placements place_parameters established (tp sharding!)
                self._like = sl
            bytes_disk += sum(leaf.size * leaf.dtype.itemsize
                              for leaf in jax.tree_util.tree_leaves(sl))
            self.swapper.swap_out(f"layer_{layer_idx}", sl)
        for layer_idx in range(self.num_layers):
            self.swapper.wait(f"layer_{layer_idx}")
        self._inflight: Dict[int, Any] = {}  # layer idx -> swap_in token
        self._ready: Dict[int, Any] = {}  # layer idx -> device tree (LRU)
        log_dist(
            f"ZeRO-Inference NVMe: {self.num_layers} layers "
            f"({bytes_disk / 1e6:.0f} MB{' ' + quant_fmt if quant_fmt else ''}) "
            f"on disk at {folder}; <= {self.num_buffers} layers resident",
            ranks=[0])

    # ---------------------------------------------------------------- fetch
    def prefetch(self, layer_idx: int) -> None:
        layer_idx %= self.num_layers
        if layer_idx in self._inflight or layer_idx in self._ready:
            return
        self._inflight[layer_idx] = self.swapper.swap_in_begin(f"layer_{layer_idx}")

    def layer(self, layer_idx: int) -> Any:
        """Device tree for one layer (blocking if its reads are in flight)."""
        if layer_idx not in self._ready:
            if layer_idx not in self._inflight:
                self.prefetch(layer_idx)
            token = self._inflight.pop(layer_idx)
            self._ready[layer_idx] = self.swapper.swap_in_end(token, like=self._like)
        tree = self._ready.pop(layer_idx)
        self._ready[layer_idx] = tree  # refresh LRU position
        while len(self._ready) > self.num_buffers:
            self._ready.pop(next(iter(self._ready)))
        return tree

    def close(self) -> None:
        # drain in-flight preads FIRST: the AIO threads write into the numpy
        # buffers held by the tokens, which must stay alive until then
        for token in self._inflight.values():
            self.swapper.swap_in_end(token, device_put=False)
        self._inflight.clear()
        self._ready.clear()
        self.swapper.close()

    def __del__(self):  # best-effort; explicit close() preferred
        try:
            self.close()
        except Exception:
            pass


class StreamedForward:
    """Layer-looped prefill/decode over NVMe-streamed params.

    The per-layer block function is jitted ONCE (identical shapes across
    layers); the L-iteration Python loop issues layer l+1's disk reads, then
    dispatches layer l — async dispatch makes the read and the compute
    overlap. The KV cache stays the stacked ``[L, ...]`` layout of
    ``inference/model.py`` so downstream code (sampling, TTFT accounting)
    is unchanged.
    """

    def __init__(self, streamed: NVMeStreamedParams, cfg: TransformerConfig,
                 compute_dtype):
        self.p = streamed

        @jax.jit
        def block(lp, x, ck, cv, kv_mask, positions, write_start):
            lp = _dequant_tree(lp, compute_dtype)
            return _block_step(lp, cfg, x, ck, cv, kv_mask, positions, write_start)

        @jax.jit
        def head(resident, x, lengths):
            logits = _logits(resident, cfg, x)
            last = jnp.take_along_axis(logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
            return last

        @jax.jit
        def head_decode(resident, x):  # x: [B, 1, E] — one new token per row
            return _logits(resident, cfg, x)[:, 0]

        # pre-layer input computation is SHARED with inference/model.py
        # (prefill_inputs/decode_inputs) — one definition, no parity drift
        self._embed_prefill = jax.jit(
            lambda resident, ids, m: prefill_inputs(resident, cfg, ids, m))
        self._decode_inputs = jax.jit(
            lambda resident, cache, tokens: decode_inputs(resident, cfg, cache, tokens))
        self._block = block
        self._head = head
        self._head_decode = head_decode
        self._samplers: Dict[tuple, Any] = {}  # sample_cfg -> jitted sampler

    # ------------------------------------------------------------- forward
    def _run_layers(self, x, cache: KVCache, positions, write_start, kv_mask):
        ks, vs = [], []
        self.p.prefetch(0)
        for layer_idx in range(self.p.num_layers):
            if layer_idx + 1 < self.p.num_layers:
                self.p.prefetch(layer_idx + 1)
            lp = self.p.layer(layer_idx)
            x, ck, cv = self._block(lp, x, cache.k[layer_idx], cache.v[layer_idx],
                                    kv_mask, positions, write_start)
            ks.append(ck)
            vs.append(cv)
        return x, cache._replace(k=jnp.stack(ks), v=jnp.stack(vs))

    def prefill(self, cache: KVCache, input_ids, prompt_mask):
        B, S = input_ids.shape
        x, positions, lengths = self._embed_prefill(
            self.p.resident, input_ids, prompt_mask)
        kv_mask = jnp.zeros((B, cache.max_len), jnp.bool_).at[:, :S].set(prompt_mask)
        write_start = jnp.zeros((B,), jnp.int32)
        x, cache = self._run_layers(x, cache, positions, write_start, kv_mask)
        cache = cache._replace(kv_mask=kv_mask, lengths=lengths)
        return self._head(self.p.resident, x, lengths), cache

    def decode_step(self, cache: KVCache, tokens):
        x, positions, kv_mask = self._decode_inputs(self.p.resident, cache, tokens)
        x, cache = self._run_layers(x, cache, positions, cache.lengths, kv_mask)
        cache = cache._replace(kv_mask=kv_mask, lengths=cache.lengths + 1)
        return self._head_decode(self.p.resident, x), cache

    def sampler(self, sample_cfg: dict):
        """Jitted sampler cached per sample config (mirrors the resident
        engine's _generate_cache — no retrace per generate() call)."""
        key = tuple(sorted(sample_cfg.items()))
        if key not in self._samplers:
            from deepspeed_tpu.inference.sampling import sample_logits

            self._samplers[key] = jax.jit(
                functools.partial(sample_logits, **sample_cfg))
        return self._samplers[key]


def _dequant_tree(tree: Any, dtype) -> Any:
    """Dense view of a (possibly WOQ-wrapped) layer tree for the block fn."""
    from deepspeed_tpu.inference.woq import WOQTensor

    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if isinstance(x, WOQTensor) else x,
        tree, is_leaf=lambda x: isinstance(x, WOQTensor))


def streamed_generate(
    fwd: StreamedForward,
    cfg: TransformerConfig,
    kv_dtype,
    input_ids,
    prompt_mask,
    max_new_tokens: int,
    sample_cfg: dict,
    eos_id: Optional[int],
    pad_id: int,
    rng,
) -> np.ndarray:
    """Python-loop generate for the NVMe mode (the decode loop cannot be one
    lax.scan when each layer's weights arrive via host AIO reads)."""
    B, S_pad = input_ids.shape
    cache = init_cache(cfg, B, S_pad + max_new_tokens, kv_dtype)
    logits, cache = fwd.prefill(cache, jnp.asarray(input_ids), jnp.asarray(prompt_mask))
    rngs = jax.random.split(rng, max_new_tokens)
    sample = fwd.sampler(sample_cfg)
    tok = sample(logits, rngs[0])
    done = tok == eos_id if eos_id is not None else jnp.zeros((B,), jnp.bool_)
    toks = [tok]
    for step in range(1, max_new_tokens):
        logits, cache = fwd.decode_step(cache, toks[-1])
        nxt = sample(logits, rngs[step])
        if eos_id is not None:
            nxt = jnp.where(done, pad_id, nxt)
            done = done | (nxt == eos_id)
        toks.append(nxt)
    return np.stack([np.asarray(t) for t in toks], axis=1)  # [B, new_tokens]
