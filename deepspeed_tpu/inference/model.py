"""Functional KV-cache decoding for the CausalLM family.

TPU-native analog of the reference's inference model implementations
(``deepspeed/ops/transformer/inference/ds_attention.py``,
``model_implementations/transformers/ds_transformer.py``): instead of swapping
nn.Modules for fused-kernel modules, we provide *functional twins* of the
training model that thread an explicit KV cache through the layer stack, so
prefill and decode compile to single XLA programs over the same parameter
pytree the training engine produced (no weight transpose/fusion step needed).

Layout decisions (TPU-first):
  - cache K/V are ``[L, B, maxS, kvH, hd]`` — stacked over layers so the layer
    loop is one ``lax.scan`` (same stacked-params layout as ``nn.scan`` in
    ``models/transformer.py``), heads shardable over ``tp``, batch over ``dp``
  - per-row sequence lengths (ragged prompts via right-padding + masks), so a
    batch of uneven prompts is one compiled program
  - attention over the cache is einsum + masking (flash-decode Pallas kernel
    plugs in via the ops registry for long contexts, v2 paged path)
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.transformer import (
    TransformerConfig,
    _apply_norm,
    _embed_tokens,
    act_fn,
)


class KVCache(NamedTuple):
    """Decoder state for one batch of sequences.

    k/v: ``[L, B, maxS, kvH, hd]`` in ``cache_dtype``; ``kv_mask``: ``[B, maxS]``
    marks valid (non-pad) cache slots; ``lengths``: ``[B]`` tokens written per
    row (== next write position).
    """

    k: jax.Array
    v: jax.Array
    kv_mask: jax.Array
    lengths: jax.Array

    @property
    def max_len(self) -> int:
        return self.k.shape[2]


def init_cache(
    cfg: TransformerConfig,
    batch_size: int,
    max_len: int,
    dtype: Any = jnp.bfloat16,
) -> KVCache:
    """Allocate an empty cache (reference ``InferenceContext`` workspace,
    ``csrc/transformer/inference/includes/inference_context.h`` — here it is
    just a pytree of preallocated arrays XLA can donate/alias)."""
    hd = cfg.dims_per_head
    shape = (cfg.num_layers, batch_size, max_len, cfg.kv_heads, hd)
    return KVCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        kv_mask=jnp.zeros((batch_size, max_len), jnp.bool_),
        lengths=jnp.zeros((batch_size,), jnp.int32),
    )


# ------------------------------------------------------------------ layers
def _qkv(lp, cfg: TransformerConfig, x):
    """Project hidden states to q/k/v using the training params.

    Matches ``nn.DenseGeneral`` in ``models/transformer.py:142-147``:
    kernel shapes wq [E,H,hd], wk/wv [E,kvH,hd]; bias present iff layernorm
    family (GPT-2 style).
    """
    q = jnp.einsum("bse,ehd->bshd", x, lp["wq"]["kernel"].astype(cfg.dtype))
    k = jnp.einsum("bse,ehd->bshd", x, lp["wk"]["kernel"].astype(cfg.dtype))
    v = jnp.einsum("bse,ehd->bshd", x, lp["wv"]["kernel"].astype(cfg.dtype))
    if "bias" in lp["wq"]:
        q = q + lp["wq"]["bias"].astype(cfg.dtype)
        k = k + lp["wk"]["bias"].astype(cfg.dtype)
        v = v + lp["wv"]["bias"].astype(cfg.dtype)
    return q, k, v


def _attn_out(lp, cfg: TransformerConfig, ctx):
    out = jnp.einsum("bshd,hde->bse", ctx, lp["wo"]["kernel"].astype(cfg.dtype))
    if "bias" in lp["wo"]:
        out = out + lp["wo"]["bias"].astype(cfg.dtype)
    return out


def _mlp(lp, cfg: TransformerConfig, x):
    def dense(p, y):
        o = y @ p["kernel"].astype(cfg.dtype)
        if "bias" in p:
            o = o + p["bias"].astype(cfg.dtype)
        return o

    if cfg.activation == "silu_glu":
        h = jax.nn.silu(dense(lp["w_gate"], x)) * dense(lp["w_up"], x)
    else:
        h = act_fn(cfg.activation)(dense(lp["w_up"], x))
    return dense(lp["w_down"], h)


def _moe(lp, cfg: TransformerConfig, x):
    """MoE FFN at inference: exact top-k routing with no capacity drops.

    Two dispatch regimes, chosen by the (static) token count:

    - decode (few tokens): compute every expert and combine with the gate
      weights — one einsum over the stacked expert params (reference
      ``moe/sharded_moe.py`` combine). At T ~ batch size, gathering by
      expert costs more than the E/top_k extra FLOPs it saves.
    - prefill (T >= 2E tokens): RAGGED dispatch (round 5; reference FastGen's
      ``inference/v2/kernels/ragged_ops`` moe_gather/moe_scatter +
      ``cutlass_ops`` grouped GEMM) — sort the (token, expert) pairs by
      expert and run grouped matmuls via ``lax.ragged_dot``, so prompt FFN
      FLOPs scale with top_k, not E (8x2 Mixtral-style: 4x fewer).
    """
    B, S, M = x.shape
    tokens = x.reshape(B * S, M)
    T, E, k = tokens.shape[0], cfg.num_experts, cfg.moe_top_k
    logits = tokens.astype(jnp.float32) @ lp["gate"]["wg"]["kernel"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)  # [T, E]
    top_p, top_i = jax.lax.top_k(probs, k)
    top_p = top_p / jnp.maximum(top_p.sum(-1, keepdims=True), 1e-9)

    ep = lp["experts"]
    if _moe_ep_size() > 1:
        # expert-parallel serving (ISSUE 15): the ep-sharded experts are
        # reached through the explicit collective dispatch — the SAME
        # facade all_to_all the training path rides, so quantized token
        # routing, hop spans and observatory signatures apply to serving
        # MoE traffic too. Falls back to the replicated paths below (GSPMD
        # reshards the ep-sharded kernels) only on non-divisible shapes.
        out = _moe_ep_collective(cfg, ep, tokens, top_p, top_i)
        if out is not None:
            return out.reshape(B, S, M)
    if T >= 2 * E:
        return _moe_ragged(cfg, ep, tokens, top_p, top_i).reshape(B, S, M)

    gate = jnp.zeros_like(probs).at[jnp.arange(T)[:, None], top_i].set(top_p)
    h1 = jnp.einsum("tm,emh->teh", tokens, ep["w_up"].astype(cfg.dtype))
    if cfg.activation == "silu_glu":
        h1 = jax.nn.silu(jnp.einsum("tm,emh->teh", tokens, ep["w_gate"].astype(cfg.dtype))) * h1
    else:
        h1 = act_fn(cfg.activation)(h1)
    out_e = jnp.einsum("teh,ehm->tem", h1, ep["w_down"].astype(cfg.dtype))
    out = jnp.einsum("te,tem->tm", gate.astype(cfg.dtype), out_e)
    return out.reshape(B, S, M)


# The no-drop collective dispatch materializes [T*k, E, T*k] routing
# one-hots (capacity = T*k for exactness) — quadratic in the token count.
# Fine at decode/short-prefill shapes; a long prefill would OOM on the
# one-hots alone, so beyond this bound the ep>1 engine falls back to the
# replicated ragged/dense paths (GSPMD reshards the ep-sharded kernels —
# same math, no collective wire).
_MOE_EP_COLLECTIVE_MAX_TOKENS = 1024


def _moe_ep_size() -> int:
    """Expert-parallel width of the active mesh (1 = no ep sharding)."""
    from deepspeed_tpu.topology.mesh import get_mesh, has_mesh

    if not has_mesh():
        return 1
    return int(get_mesh().shape.get("ep", 1))


def _moe_ep_collective(cfg: TransformerConfig, ep, tokens, top_p, top_i):
    """Expert-parallel inference dispatch through the facade all-to-all.

    Builds NO-DROP dispatch/combine one-hots (capacity = T*k: every
    (token, expert) pair owns a globally unique slot, so routing is exact —
    token-identity with the ep=1 paths is a sum reordering, never a drop)
    and runs the training layer's :func:`collective_moe_apply`: one
    shard_map region, the [E, C, M] reshard as ONE facade ``all_to_all``
    over ep each way, the expert FFN on the LOCAL ep shard. Returns None
    when the (mesh, shape) cannot be served (caller falls back to the
    replicated compute with GSPMD resharding)."""
    from deepspeed_tpu.parallel.moe import _token_axes, collective_moe_apply
    from deepspeed_tpu.topology.mesh import get_mesh
    from deepspeed_tpu.utils.logging import logger

    mesh = get_mesh()
    T, M = tokens.shape
    E, k = cfg.num_experts, cfg.moe_top_k
    shards = 1
    for a in _token_axes(mesh):
        shards *= mesh.shape[a]
    if E % mesh.shape["ep"] or T % shards:
        # trace-time, so this fires once per compiled program shape — the
        # operator's signal that wire codec / hop spans will NOT engage
        logger.warning(
            f"moe ep dispatch: shape unservable ({T} tokens vs {shards} "
            f"token shards, E={E} vs ep={mesh.shape['ep']}); falling back "
            "to replicated compute (GSPMD reshards the ep-sharded kernels)")
        return None
    if T > _MOE_EP_COLLECTIVE_MAX_TOKENS:
        logger.warning(
            f"moe ep dispatch: {T} tokens exceeds the "
            f"{_MOE_EP_COLLECTIVE_MAX_TOKENS}-token collective bound "
            "(no-drop one-hots are quadratic); falling back to replicated "
            "compute for this program")
        return None
    C = T * k  # the no-drop static bound: capacity can never overflow
    flat_e = top_i.reshape(-1)  # [T*k] token-major expert choices
    onehot = (flat_e[:, None] == jnp.arange(E)[None, :]).astype(jnp.int32)
    pos = jnp.cumsum(onehot, axis=0) - onehot  # slot within expert, global
    pos_in_e = (pos * onehot).sum(-1)  # [T*k]
    slot = (pos_in_e[:, None] == jnp.arange(C)[None, :])  # [T*k, C] one-hot
    pair = onehot.astype(bool)[:, :, None] & slot[:, None, :]  # [T*k, E, C]
    dispatch = pair.reshape(T, k, E, C).sum(1).astype(cfg.dtype)
    combine = (pair.reshape(T, k, E, C)
               * top_p.reshape(T, k, 1, 1)).sum(1).astype(cfg.dtype)
    w_gate = (ep["w_gate"].astype(cfg.dtype)
              if cfg.activation == "silu_glu" else None)
    kernels = (w_gate, ep["w_up"].astype(cfg.dtype),
               ep["w_down"].astype(cfg.dtype))
    return collective_moe_apply(
        tokens, combine, dispatch, kernels, activation=cfg.activation,
        dtype=cfg.dtype, algorithm=cfg.moe_dispatch_algorithm,
        codec=cfg.moe_wire_codec)


def _gmm_padded(lhs, rhs, group_sizes, interpret: bool = False):
    """megablox ``gmm`` with the row count padded to the m-tile: gmm requires
    ``m % tm == 0``, so pad lhs with zero rows credited to the LAST group
    (zero rows produce zero outputs, sliced off after)."""
    from jax.experimental.pallas.ops.tpu.megablox import gmm

    m, K = lhs.shape
    tm = min(128, -(-m // 8) * 8)  # sublane-aligned tile, capped at 128
    m_p = -(-m // tm) * tm
    if m_p != m:
        lhs = jnp.pad(lhs, ((0, m_p - m), (0, 0)))
        group_sizes = group_sizes.at[-1].add(m_p - m)
    out = gmm(lhs, rhs, group_sizes.astype(jnp.int32),
              preferred_element_type=lhs.dtype,
              tiling=(tm, min(128, K), min(128, rhs.shape[-1])),
              interpret=interpret)
    return out[:m]


def _grouped_matmul(lhs, rhs, group_sizes):
    """``lhs[rows of group g] @ rhs[g]`` for expert-contiguous rows.

    TPU (dims permitting): the megablox Pallas grouped-matmul kernel
    (tile-skips at group boundaries — the reference's ``cutlass_ops`` grouped
    GEMM analog). Elsewhere: ``lax.ragged_dot`` (XLA-CPU lowers it densely
    over groups; correct, and only the fallback)."""
    K, N = lhs.shape[1], rhs.shape[-1]
    if jax.default_backend() == "tpu" and K % 128 == 0 and N % 128 == 0:
        return _gmm_padded(lhs, rhs, group_sizes)
    return jax.lax.ragged_dot(lhs, rhs, group_sizes)


def _moe_ragged(cfg: TransformerConfig, ep, tokens, top_p, top_i):
    """Grouped-GEMM expert dispatch: [T*k] (token, expert) pairs sorted by
    expert, expert-contiguous matmuls via :func:`_grouped_matmul`, weighted
    scatter-add combine. Exact same math as the dense-combine path (sum
    reordering only)."""
    T, M = tokens.shape
    E, k = cfg.num_experts, cfg.moe_top_k
    e_flat = top_i.reshape(-1)                       # [T*k]
    order = jnp.argsort(e_flat, stable=True)
    tok_idx = (jnp.arange(T * k) // k)[order]        # source token per pair
    gates = top_p.reshape(-1)[order].astype(cfg.dtype)
    group_sizes = jnp.bincount(e_flat, length=E)

    xg = tokens[tok_idx]                             # [T*k, M] gather
    up = _grouped_matmul(xg, ep["w_up"].astype(cfg.dtype), group_sizes)
    if cfg.activation == "silu_glu":
        h = jax.nn.silu(_grouped_matmul(
            xg, ep["w_gate"].astype(cfg.dtype), group_sizes)) * up
    else:
        h = act_fn(cfg.activation)(up)
    out_g = _grouped_matmul(h, ep["w_down"].astype(cfg.dtype), group_sizes)
    out = jnp.zeros((T, M), out_g.dtype)
    return out.at[tok_idx].add(out_g * gates[:, None])


def _cached_attention(q, ck, cv, kv_mask, q_positions, alibi=None):
    """GQA attention of new queries against the full cache.

    q: [B,S,H,hd]; ck/cv: [B,maxS,kvH,hd]; kv_mask: [B,maxS] valid slots;
    q_positions: [B,S] global position of each query. Causality: query at
    position p sees cache slot t iff slot_pos(t) <= p; because slots are
    written in position order, slot index == position, so the mask is
    ``t <= q_positions`` ∧ kv_mask. ``alibi``: per-head slopes [H]; slot
    index == position, so the bias is slopes * t (HF bloom convention —
    softmax cancels the per-row offset vs slopes*(t-p)).
    """
    B, S, H, hd = q.shape
    kvH = ck.shape[2]
    G = H // kvH
    qg = q.reshape(B, S, kvH, G, hd)
    scores = jnp.einsum("bskgd,btkd->bkgst", qg, ck).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(hd))
    t_idx = jnp.arange(ck.shape[1])
    if alibi is not None:
        scores = scores + (alibi.reshape(kvH, G)[None, :, :, None, None]
                           * t_idx.astype(jnp.float32)[None, None, None, None, :])
    ok = (t_idx[None, None, :] <= q_positions[:, :, None]) & kv_mask[:, None, :]
    scores = jnp.where(ok[:, None, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(cv.dtype)
    ctx = jnp.einsum("bkgst,btkd->bskgd", probs, cv)
    return ctx.reshape(B, S, H, hd)


def _block_step(lp, cfg: TransformerConfig, x, ck, cv, kv_mask, positions, write_start):
    """One decoder block over S new tokens with cache read/write.

    Returns (x_out, new_k_slab, new_v_slab) where the slabs are the K/V of the
    new tokens (caller merges into the cache — keeps this fn scan-friendly).
    """
    h = _apply_norm(lp["attn_norm"], cfg, x)
    q, k, v = _qkv(lp["attn"], cfg, h)
    alibi = None
    if cfg.position == "rope":
        from deepspeed_tpu.models.transformer import apply_qk_rope

        q, k = apply_qk_rope(cfg, q, k, positions)
    elif cfg.position == "alibi":
        from deepspeed_tpu.models.transformer import alibi_slopes

        alibi = alibi_slopes(cfg.num_heads)

    # merge new K/V into cache at per-row write offsets
    ck = _write_cache(ck, k.astype(ck.dtype), write_start)
    cv = _write_cache(cv, v.astype(cv.dtype), write_start)
    ctx = _cached_attention(q, ck, cv, kv_mask, positions, alibi=alibi)
    attn_out = _attn_out(lp["attn"], cfg, ctx)

    if cfg.parallel_block:
        # falcon-style: attn and FFN both read the shared input norm `h`;
        # gpt-neox-style (parallel_mlp_norm): FFN reads its own norm of x
        if cfg.parallel_mlp_norm:
            h = _apply_norm(lp["mlp_norm"], cfg, x)
        ffn = _moe(lp["moe"], cfg, h) if cfg.num_experts > 0 else _mlp(lp["mlp"], cfg, h)
        return x + attn_out + ffn, ck, cv
    x = x + attn_out
    h = _apply_norm(lp["mlp_norm"], cfg, x)
    if cfg.num_experts > 0:
        x = x + _moe(lp["moe"], cfg, h)
    else:
        x = x + _mlp(lp["mlp"], cfg, h)
    return x, ck, cv


def _write_cache(cache: jax.Array, new: jax.Array, start: jax.Array) -> jax.Array:
    """Write ``new`` [B,S,kvH,hd] into ``cache`` [B,maxS,kvH,hd] at per-row
    offsets ``start`` [B] (vmapped dynamic_update_slice — one fused scatter)."""

    def row(c, n, s):
        return jax.lax.dynamic_update_slice(c, n, (s, 0, 0))

    return jax.vmap(row)(cache, new, start)


def _layer_stack(params, cfg, x, cache: KVCache, positions, write_start, kv_mask):
    """Run all layers via lax.scan over stacked layer params + cache slabs."""
    if "layers" not in params:
        raise ValueError("inference requires scan_layers=True stacked params ('layers')")

    def body(carry, xs):
        x = carry
        lp, ck, cv = xs
        x, ck, cv = _block_step(lp, cfg, x, ck, cv, kv_mask, positions, write_start)
        return x, (ck, cv)

    x, (k_new, v_new) = jax.lax.scan(body, x, (params["layers"], cache.k, cache.v))
    return x, cache._replace(k=k_new, v=v_new)


def _logits(params, cfg: TransformerConfig, x):
    x = _apply_norm(params["final_norm"], cfg, x)
    if cfg.tie_embeddings:
        return x @ params["embed"]["embedding"].T.astype(cfg.dtype)
    logits = x @ params["lm_head"]["kernel"].astype(cfg.dtype)
    if "bias" in params["lm_head"]:
        logits = logits + params["lm_head"]["bias"].astype(cfg.dtype)
    return logits


# ------------------------------------------------------------------ api
def prefill_inputs(params, cfg: TransformerConfig, input_ids, prompt_mask):
    """Shared pre-layer computation of the prefill path: embeddings, per-row
    positions, and lengths (used by both the scan forward below and the
    NVMe layer-streamed forward — one definition, no drift)."""
    prompt_mask = prompt_mask.astype(jnp.bool_)
    lengths = prompt_mask.sum(axis=1).astype(jnp.int32)
    positions = jnp.where(prompt_mask, jnp.cumsum(prompt_mask, axis=1) - 1, 0).astype(jnp.int32)
    x = _embed_tokens(params, cfg, input_ids)
    return x, positions, lengths


def decode_inputs(params, cfg: TransformerConfig, cache: KVCache, tokens):
    """Shared pre-layer computation of the decode path: next-token embedding
    (in cfg.dtype), positions, and the kv_mask with the new slot marked."""
    positions = cache.lengths[:, None]  # [B,1]
    x = jnp.take(params["embed"]["embedding"], tokens[:, None], axis=0).astype(cfg.dtype)
    if cfg.embed_norm:
        x = _apply_norm(params["embed_norm"], cfg, x)
    if cfg.position == "learned":
        x = x + jnp.take(params["pos_embed"], positions, axis=0).astype(cfg.dtype)
    kv_mask = jax.vmap(lambda m, i: m.at[i].set(True))(cache.kv_mask, cache.lengths)
    return x, positions, kv_mask


def prefill(
    params,
    cfg: TransformerConfig,
    cache: KVCache,
    input_ids: jax.Array,
    prompt_mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, KVCache]:
    """Process right-padded prompts; returns (last-token logits [B,V], cache).

    Reference analog: the first forward of ``InferenceEngine`` /
    ``DeepSpeedTransformerInference`` that fills the KV workspace.
    """
    B, S = input_ids.shape
    if prompt_mask is None:
        prompt_mask = jnp.ones((B, S), jnp.bool_)
    prompt_mask = prompt_mask.astype(jnp.bool_)
    x, positions, lengths = prefill_inputs(params, cfg, input_ids, prompt_mask)
    kv_mask = jnp.zeros((B, cache.max_len), jnp.bool_).at[:, :S].set(prompt_mask)
    write_start = jnp.zeros((B,), jnp.int32)
    x, cache = _layer_stack(params, cfg, x, cache, positions, write_start, kv_mask)
    cache = cache._replace(kv_mask=kv_mask, lengths=lengths)

    logits = _logits(params, cfg, x)  # [B, S, V]
    last = jnp.take_along_axis(logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
    return last, cache


def decode_step(
    params, cfg: TransformerConfig, cache: KVCache, tokens: jax.Array
) -> Tuple[jax.Array, KVCache]:
    """One token per row: tokens [B] -> (logits [B,V], cache).

    The generated token's position is ``cache.lengths`` (per row).
    """
    x, positions, kv_mask = decode_inputs(params, cfg, cache, tokens)
    x, cache = _layer_stack(params, cfg, x, cache, positions, cache.lengths, kv_mask)
    cache = cache._replace(kv_mask=kv_mask, lengths=cache.lengths + 1)
    return _logits(params, cfg, x)[:, 0], cache
