"""Paged KV cache + ragged forward step (device side).

TPU-native analog of the reference FastGen kernel suite
(``inference/v2/kernels/ragged_ops/``: ``blocked_flash`` paged attention,
``linear_blocked_kv_rotary`` fused KV-insert+RoPE): the KV pool is a flat
``[L, NB*bs + 1, kvH, hd]`` array (last slot = trash for pad-row writes), a
sequence's cache is addressed through its block table, and one jitted step
processes a mixed prefill/decode ragged batch:

  - KV insert = one scatter per layer (``.at[idx].set``) at
    ``block_table[pos // bs] * bs + pos % bs`` — the fused-KV-copy+RoPE kernel
  - paged attention = gather the row's pages to ``[P*bs, kvH, hd]`` then
    masked GQA attention (slot index within the gathered view == global
    position, so causality is ``slot <= q_pos``). A Pallas flash-decode kernel
    that skips the materialized gather is the registered fast path upgrade.

Static shapes everywhere: (rows, chunk, pages) are bucketed by the host layer
(``ragged.py``), so XLA compiles a handful of step programs.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.model import _apply_norm, _attn_out, _logits, _mlp, _moe, _qkv
from deepspeed_tpu.inference.sampling import greedy_tokens, sample_logits
from deepspeed_tpu.models.transformer import TransformerConfig


class PagedKVPool(NamedTuple):
    """k/v: ``[L, NB*bs + 1, kvH, hd]`` flat slot-major pool; the final slot is
    the trash slot (reference: FastGen preallocates the KV arena up front from
    a memory budget, ``DSStateManager`` + ``KVCacheConfig``). ``block_size``
    is carried by the engine, not here — this NamedTuple is a jit pytree and
    must hold only arrays.

    Quantized storage (``kv_quant='int8'|'fp8'``): k/v hold int8/e4m3 values
    and ``k_scale``/``v_scale`` carry one fp32 scale per (layer, slot, kv-head)
    — the quantization block is the ``hd`` head vector, so a token's KV write
    is one ``ops.quant`` block-math call and dequant needs only the slot's own
    scale (fused into the paged-attention block loads). ``None`` scales mean a
    full-precision pool (the pre-quantization layout, unchanged)."""

    k: jax.Array
    v: jax.Array
    k_scale: Optional[jax.Array] = None  # [L, S_flat, kvH, 1] fp32, or None
    v_scale: Optional[jax.Array] = None

    @property
    def num_slots(self) -> int:  # excludes trash
        return self.k.shape[1] - 1

    @property
    def quant(self) -> Optional[str]:
        """Storage quantization mode, derived from the value dtype (trace-time
        static): None | 'int8' | 'fp8'."""
        if self.k_scale is None:
            return None
        return "fp8" if self.k.dtype == jnp.float8_e4m3fn else "int8"


_KV_QUANT_DTYPES = {"int8": jnp.int8, "fp8": jnp.float8_e4m3fn}


def init_pool(
    cfg: TransformerConfig, num_blocks: int, block_size: int, dtype: Any = jnp.bfloat16,
    kv_quant: Optional[str] = None,
) -> PagedKVPool:
    shape = (cfg.num_layers, num_blocks * block_size + 1, cfg.kv_heads, cfg.dims_per_head)
    if kv_quant is None:
        return PagedKVPool(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))
    if kv_quant not in _KV_QUANT_DTYPES:
        raise ValueError(f"kv_quant must be None|'int8'|'fp8', got {kv_quant!r}")
    qdt = _KV_QUANT_DTYPES[kv_quant]
    sshape = shape[:3] + (1,)
    return PagedKVPool(k=jnp.zeros(shape, qdt), v=jnp.zeros(shape, qdt),
                       k_scale=jnp.zeros(sshape, jnp.float32),
                       v_scale=jnp.zeros(sshape, jnp.float32))


def _kv_block_quant(x: jax.Array, quant: str):
    """``[T, kvH, hd] float -> (values [T, kvH, hd], scales [T, kvH, 1])``
    through THE shared block math (``ops.quant``): one symmetric absmax block
    per (token, head) ``hd`` vector, so pool scatters stay one-scatter-per-
    array and dequant is a per-slot multiply."""
    from deepspeed_tpu.ops.quant import fp8_block_math, int8_block_math

    T, kvH, hd = x.shape
    x2 = x.astype(jnp.float32).reshape(T * kvH, hd)
    q, s = int8_block_math(x2) if quant == "int8" else fp8_block_math(x2)
    return q.reshape(T, kvH, hd), s.reshape(T, kvH, 1)


def _slot_ids(block_tables: jax.Array, positions: jax.Array, valid: jax.Array,
              block_size: int, trash: int) -> jax.Array:
    """Flat pool slot for each (row, token): bt[pos//bs]*bs + pos%bs, or trash."""
    page = jnp.take_along_axis(block_tables, positions // block_size, axis=1)
    slot = page * block_size + positions % block_size
    return jnp.where(valid, slot, trash)


from deepspeed_tpu.ops.registry import dispatch, register


@register("paged_attention", "xla")
def _xla_paged_attention(q, pool_k_l, pool_v_l, block_tables, q_positions, block_size,
                         new_lens=None, alibi_slopes=None, k_scale=None, v_scale=None):
    """Masked GQA attention of new queries against paged caches (dense-gather
    fallback; the Pallas flash-decode kernel in
    ``ops/pallas/paged_attention.py`` wins dispatch on TPU).

    q: [N, C, H, hd]; pool_{k,v}_l: [S_flat, kvH, hd] (one layer's pool);
    block_tables: [N, P]; q_positions: [N, C]. Returns [N, C, H, hd].

    ``k_scale``/``v_scale`` ([S_flat, kvH, 1] fp32) mark a quantized pool:
    dequantization happens on the GATHERED blocks ([N, P*bs, ...], bounded by
    the batch's block tables) — the full-precision [S_flat, kvH, hd] pool is
    never materialized.
    """
    N, C, H, hd = q.shape
    P = block_tables.shape[1]
    slot = block_tables[:, :, None] * block_size + jnp.arange(block_size)[None, None, :]
    slot = slot.reshape(N, P * block_size)  # global position j -> pool slot
    ck = pool_k_l[slot]  # [N, P*bs, kvH, hd]
    cv = pool_v_l[slot]
    if k_scale is not None:
        ck = (ck.astype(jnp.float32) * k_scale[slot]).astype(q.dtype)
        cv = (cv.astype(jnp.float32) * v_scale[slot]).astype(q.dtype)
    kvH = ck.shape[2]
    G = H // kvH
    qg = q.reshape(N, C, kvH, G, hd)
    scores = jnp.einsum("nckgd,ntkd->nkgct", qg, ck).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(hd))
    t_idx = jnp.arange(P * block_size)
    if alibi_slopes is not None:
        # slot index within the gathered view == global position, so the
        # bloom convention slopes * key-position applies directly
        scores = scores + (alibi_slopes.reshape(kvH, G)[None, :, :, None, None]
                           * t_idx.astype(jnp.float32)[None, None, None, None, :])
    ok = t_idx[None, None, :] <= q_positions[:, :, None]  # causal over positions
    scores = jnp.where(ok[:, None, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(cv.dtype)
    ctx = jnp.einsum("nkgct,ntkd->nckgd", probs, cv)
    return ctx.reshape(N, C, H, hd)


def paged_attention(q, pool_k_l, pool_v_l, block_tables, q_positions, block_size,
                    new_lens=None, impl: str = "auto", alibi_slopes=None,
                    k_scale=None, v_scale=None):
    import deepspeed_tpu.ops.pallas.paged_attention  # noqa: F401  (registers the kernel)

    # alibi is fused in BOTH implementations (the Pallas flash-decode kernel
    # adds slope * key-position on its existing position iota), so dispatch
    # is uniform — bloom keeps the fast decode path. Likewise quantized-pool
    # dequant: the kernel fuses it into its VMEM block loads, the XLA
    # fallback applies it to the gathered blocks.
    return dispatch("paged_attention", impl)(
        q, pool_k_l, pool_v_l, block_tables, q_positions, block_size,
        new_lens=new_lens, alibi_slopes=alibi_slopes,
        k_scale=k_scale, v_scale=v_scale,
    )


def _forward_hidden(
    params,
    cfg: TransformerConfig,
    pool: PagedKVPool,
    tokens: jax.Array,  # [N, C] int32
    positions: jax.Array,  # [N, C] int32
    new_lens: jax.Array,  # [N] int32
    block_tables: jax.Array,  # [N, P] int32
    block_size: int,
    all_positions: bool = False,
) -> Tuple[jax.Array, PagedKVPool]:
    """One mixed prefill/decode layer-stack pass -> (last-token hidden [N, E],
    pool). Shared by the single-step ``ragged_forward`` and the K-step
    ``ragged_decode_chain`` — one definition of the serving transformer math.

    ``all_positions=True`` returns the full ``[N, C, E]`` hidden states
    instead of the last-token selection — the speculative verify step needs
    a logit at EVERY draft position to accept/reject in one pass.
    """
    N, C = tokens.shape
    bs = block_size
    trash = pool.k.shape[1] - 1
    valid = jnp.arange(C)[None, :] < new_lens[:, None]  # [N, C]
    slot = _slot_ids(block_tables, positions, valid, bs, trash)  # [N, C]
    flat_slot = slot.reshape(-1)

    x = jnp.take(params["embed"]["embedding"], tokens, axis=0).astype(cfg.dtype)
    if cfg.embed_norm:
        x = _apply_norm(params["embed_norm"], cfg, x)
    if cfg.position == "learned":
        x = x + jnp.take(params["pos_embed"], positions, axis=0).astype(cfg.dtype)
    alibi = None
    if cfg.position == "alibi":
        from deepspeed_tpu.models.transformer import alibi_slopes

        alibi = alibi_slopes(cfg.num_heads)

    if "layers" not in params:
        raise ValueError("ragged inference requires scan_layers=True stacked params")

    quant = pool.quant  # static at trace time (value dtype + scale presence)

    def body(x, xs):
        lp, pk, pv, psk, psv = xs
        h = _apply_norm(lp["attn_norm"], cfg, x)
        q, k, v = _qkv(lp["attn"], cfg, h)
        if cfg.position == "rope":
            from deepspeed_tpu.models.transformer import apply_qk_rope

            q, k = apply_qk_rope(cfg, q, k, positions)
        kvH, hd = k.shape[-2], k.shape[-1]
        if quant is not None:
            # quantized KV write: the same one-scatter-per-array shape, plus
            # one scale scatter per array (pad rows route to the trash slot
            # for values AND scales alike)
            kq, ks = _kv_block_quant(k.reshape(-1, kvH, hd), quant)
            vq, vs = _kv_block_quant(v.reshape(-1, kvH, hd), quant)
            pk = pk.at[flat_slot].set(kq.astype(pk.dtype), mode="drop")
            pv = pv.at[flat_slot].set(vq.astype(pv.dtype), mode="drop")
            psk = psk.at[flat_slot].set(ks, mode="drop")
            psv = psv.at[flat_slot].set(vs, mode="drop")
        else:
            pk = pk.at[flat_slot].set(k.astype(pk.dtype).reshape(-1, kvH, hd), mode="drop")
            pv = pv.at[flat_slot].set(v.astype(pv.dtype).reshape(-1, kvH, hd), mode="drop")
        ctx = paged_attention(q, pk, pv, block_tables, positions, bs,
                              new_lens=new_lens, alibi_slopes=alibi,
                              k_scale=psk, v_scale=psv)
        attn_out = _attn_out(lp["attn"], cfg, ctx)
        if cfg.parallel_block:
            # falcon/phi-style: attn and FFN read the shared input norm;
            # gpt-neox-style (parallel_mlp_norm): FFN reads its own ln2(x)
            ffn_in = _apply_norm(lp["mlp_norm"], cfg, x) if cfg.parallel_mlp_norm else h
            ffn = _moe(lp["moe"], cfg, ffn_in) if cfg.num_experts > 0 else _mlp(lp["mlp"], cfg, ffn_in)
            return x + attn_out + ffn, (pk, pv, psk, psv)
        x = x + attn_out
        h = _apply_norm(lp["mlp_norm"], cfg, x)
        if cfg.num_experts > 0:
            x = x + _moe(lp["moe"], cfg, h)
        else:
            x = x + _mlp(lp["mlp"], cfg, h)
        return x, (pk, pv, psk, psv)

    x, (k_new, v_new, ks_new, vs_new) = jax.lax.scan(
        body, x, (params["layers"], pool.k, pool.v, pool.k_scale, pool.v_scale))
    pool = pool._replace(k=k_new, v=v_new, k_scale=ks_new, v_scale=vs_new)

    if all_positions:
        return x, pool  # [N, C, E]
    last = jnp.take_along_axis(
        x, jnp.maximum(new_lens - 1, 0)[:, None, None], axis=1
    )[:, 0]  # [N, E]
    return last, pool


def ragged_forward(
    params,
    cfg: TransformerConfig,
    pool: PagedKVPool,
    tokens: jax.Array,  # [N, C] int32
    positions: jax.Array,  # [N, C] int32
    new_lens: jax.Array,  # [N] int32
    block_tables: jax.Array,  # [N, P] int32
    block_size: int,
) -> Tuple[jax.Array, PagedKVPool]:
    """One mixed prefill/decode step -> (last-token logits [N, V], pool).

    Reference analog: the whole FastGen model forward over a
    ``RaggedBatchWrapper`` (``inference/v2/engine_v2.py:107`` → model
    implementations → ragged kernels), as one XLA program. The final norm +
    LM head run on the [N, E] last-token hiddens only (norm is positionwise,
    so selecting first is the same math at 1/C the head cost).
    """
    last, pool = _forward_hidden(
        params, cfg, pool, tokens, positions, new_lens, block_tables, block_size)
    return _logits(params, cfg, last), pool


def ragged_decode_chain(
    params,
    cfg: TransformerConfig,
    pool: PagedKVPool,
    tokens: jax.Array,  # [N] int32 — last sampled token per row (next input)
    start_pos: jax.Array,  # [N] int32 — global position of that input token
    block_tables: jax.Array,  # [N, P] int32, pre-extended for the K-token window
    block_size: int,
    active: jax.Array,  # [N] bool — live rows (pad rows False)
    budgets: jax.Array,  # [N] int32 — max tokens this chain may emit per row
    rng: jax.Array,  # PRNG key, threaded through the scan and returned
    k_steps: int,
    eos_id: Optional[int] = None,
    *,
    do_sample: bool = False,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, PagedKVPool]:
    """K decode iterations + on-device sampling as ONE compiled program.

    The serving fast path: the host dispatches once and fetches once per K
    decoded tokens instead of shipping [N, vocab] logits to the host for
    every token (each dispatched program carries ~6-7 ms fixed relay overhead
    on this platform — see PERF.md "secondary platform facts"). A
    ``lax.scan`` runs the single-token forward, samples the next token with
    the threaded PRNG key, writes the input token's KV through the
    pre-extended block table, and masks finished rows in-scan: a row goes
    inactive when it samples ``eos_id`` or exhausts its ``budgets`` entry,
    after which its KV writes route to the trash slot and its emitted slots
    are -1.

    Returns ``(out_tokens [N, K], emitted [N], active [N], rng, pool)`` where
    ``out_tokens[i, :emitted[i]]`` are valid and ``emitted[i]`` is also the
    number of KV slots row i consumed (== seen_tokens advance).

    Observability contract: the chain boundary is the host's ONLY visibility
    quantum — the K in-scan tokens carry no host timestamps by design, so
    per-token latency (TPOT) is derived as (boundary delta) / ``emitted``
    by the request lifecycle layer (``inference/lifecycle.py``). Anything
    that needs per-token host stamps would reintroduce the per-token sync
    this program exists to eliminate.
    """

    def step(carry, _):
        pool, tok, pos, live, emitted, key = carry
        new_lens = live.astype(jnp.int32)
        last, pool = _forward_hidden(
            params, cfg, pool, tok[:, None], pos[:, None], new_lens,
            block_tables, block_size)
        logits = _logits(params, cfg, last)
        key, sub = jax.random.split(key)
        nxt = sample_logits(logits, sub, do_sample=do_sample,
                            temperature=temperature, top_k=top_k, top_p=top_p)
        emitted = emitted + new_lens
        out = jnp.where(live, nxt, -1)
        still = live & (emitted < budgets)
        if eos_id is not None:
            still = still & (nxt != eos_id)
        return (pool, jnp.where(live, nxt, tok), pos + new_lens, still,
                emitted, key), out

    carry0 = (pool, tokens, start_pos, active,
              jnp.zeros_like(start_pos), rng)
    (pool, _, _, active, emitted, rng), outs = jax.lax.scan(
        step, carry0, None, length=k_steps)
    return outs.T, emitted, active, rng, pool


class MigrationBuffer(NamedTuple):
    """Contiguous, block-table-ordered page buffer for KV-block migration
    (ISSUE 14): one request's pool pages — values AND scale pages, the PR-10
    layout travelling as a unit — gathered in block-table order so the
    destination can scatter them into an arbitrarily fragmented allocation
    with the block table rewritten. The bytes are the pool's bytes verbatim
    (int8/fp8 values stay int8/fp8, fp32 scales stay fp32): migration never
    re-quantizes, so the blake2b content identity of every block survives
    and prefix-cache entries stay valid at the destination."""

    k: jax.Array  # [L, pages*bs, kvH, hd], pool value dtype
    v: jax.Array
    k_scale: Optional[jax.Array] = None  # [L, pages*bs, kvH, 1] fp32
    v_scale: Optional[jax.Array] = None


def export_pool_blocks(pool: PagedKVPool, blocks: jax.Array,
                       block_size: int) -> MigrationBuffer:
    """Gather ``blocks`` (block ids, block-table order, [B] int32 traced) out
    of the pool into one contiguous :class:`MigrationBuffer`. A pure gather —
    the quantized bytes move verbatim; block ids ride as traced values so ONE
    compiled program serves every migration of the same page bucket. Pad
    entries (callers bucket B) may repeat any valid block; the host slices
    the valid prefix by ``n_blocks``."""
    slots = (blocks[:, None] * block_size
             + jnp.arange(block_size)[None, :]).reshape(-1)

    def g(a):
        return None if a is None else a[:, slots]

    return MigrationBuffer(k=g(pool.k), v=g(pool.v),
                           k_scale=g(pool.k_scale), v_scale=g(pool.v_scale))


def import_pool_blocks(pool: PagedKVPool, buf: MigrationBuffer,
                       blocks: jax.Array, n_valid: jax.Array,
                       block_size: int) -> PagedKVPool:
    """Scatter a :class:`MigrationBuffer` into ``blocks`` of the destination
    pool — the block-table rewrite made physical. ``blocks`` is the
    DESTINATION allocation (any fragmentation; ids need not be contiguous or
    ordered), ``n_valid`` masks the bucket's pad entries (their writes index
    out of bounds and drop). Dtypes must match the destination pool exactly:
    the scatter is verbatim bytes, never a convert — the caller validates
    layout compatibility so quantized pages are never re-quantized."""
    B = blocks.shape[0]
    slots = blocks[:, None] * block_size + jnp.arange(block_size)[None, :]
    valid = jnp.arange(B)[:, None] < n_valid
    oob = pool.k.shape[1]  # one past the trash slot: dropped by the scatter
    slots = jnp.where(valid, slots, oob).reshape(-1)

    def s(dst, src):
        if dst is None:
            return None
        return dst.at[:, slots].set(src, mode="drop")

    return PagedKVPool(k=s(pool.k, buf.k), v=s(pool.v, buf.v),
                       k_scale=s(pool.k_scale, buf.k_scale),
                       v_scale=s(pool.v_scale, buf.v_scale))


def copy_pool_blocks(pool: PagedKVPool, src: jax.Array, dst: jax.Array,
                     block_size: int) -> PagedKVPool:
    """Copy one block's slots (values + scale pages together — the PR-10
    layout travels as a unit) from block ``src`` to block ``dst`` across
    every layer. The prefix cache's copy-on-write: a shared block diverging
    mid-block is cloned into a private block before the divergent token's
    KV write. ``src``/``dst`` are traced scalars, so ONE jitted program
    serves every COW event."""

    def cp(arr):
        if arr is None:
            return None
        sl = jax.lax.dynamic_slice_in_dim(arr, src * block_size, block_size, axis=1)
        return jax.lax.dynamic_update_slice_in_dim(arr, sl, dst * block_size, axis=1)

    return PagedKVPool(k=cp(pool.k), v=cp(pool.v),
                       k_scale=cp(pool.k_scale), v_scale=cp(pool.v_scale))


def _ngram_propose(hist: jax.Array, hist_len: jax.Array, n_spec: int,
                   ngram: int) -> jax.Array:
    """Prompt-lookup draft proposal, fully on device: for each row find the
    LAST previous occurrence of the trailing ``ngram`` tokens in the row's
    history and propose the ``n_spec`` tokens that followed it. Rows with no
    match (or matches running off the valid history) fall back to repeating
    the current token — verification rejects bad drafts, so the fallback
    only costs acceptance, never correctness.

    hist: [N, H] token history (entries >= hist_len are ignored);
    hist_len: [N] tokens valid per row (the current input token is
    ``hist[hist_len - 1]``). Returns drafts [N, n_spec] int32.
    """
    N, H = hist.shape
    pat_idx = jnp.maximum(hist_len[:, None] - ngram + jnp.arange(ngram)[None, :], 0)
    pat = jnp.take_along_axis(hist, pat_idx, axis=1)  # [N, ngram]
    histp = jnp.pad(hist, ((0, 0), (0, ngram + n_spec)), constant_values=-1)
    ok = jnp.ones((N, H), bool)
    for i in range(ngram):
        ok = ok & (histp[:, i: i + H] == pat[:, i: i + 1])
    # window must be a PREVIOUS occurrence fully inside valid history
    ok = ok & (jnp.arange(H)[None, :] < (hist_len - ngram)[:, None])
    any_m = ok.any(axis=1)
    t_star = jnp.where(any_m, H - 1 - jnp.argmax(ok[:, ::-1], axis=1), 0)
    didx = t_star[:, None] + ngram + jnp.arange(n_spec)[None, :]
    drafts = jnp.take_along_axis(histp, didx, axis=1)
    cur = jnp.take_along_axis(hist, jnp.maximum(hist_len - 1, 0)[:, None], axis=1)
    # a draft slot is valid only INSIDE the row's history: positions in
    # [hist_len, H) are buffer zeros (not the -1 pad), which would otherwise
    # propose token id 0 on matches ending near the tail — exactly where a
    # repetitive text's proposer should shine
    valid = (didx < hist_len[:, None]) & (drafts >= 0)
    return jnp.where(any_m[:, None] & valid, drafts, cur).astype(jnp.int32)


def ragged_spec_decode_chain(
    params,
    cfg: TransformerConfig,
    pool: PagedKVPool,
    tokens: jax.Array,  # [N] int32 — last sampled token per row (next input)
    start_pos: jax.Array,  # [N] int32 — global position of that input token
    block_tables: jax.Array,  # [N, P], pre-extended for window + n_spec slack
    block_size: int,
    active: jax.Array,  # [N] bool
    budgets: jax.Array,  # [N] int32 — max tokens this chain may emit per row
    rng: jax.Array,
    k_steps: int,  # outer verify iterations (model forwards) per dispatch
    eos_id: Optional[int],
    history: jax.Array,  # [N, H] int32 — context incl. the input token
    hist_len: jax.Array,  # [N] int32 — valid history length per row
    *,
    n_spec: int,
    ngram: int = 2,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, PagedKVPool]:
    """Speculative K-step decode chain: greedy verify-and-accept over n-gram
    drafts, still ONE dispatch + ONE host sync per chain.

    Each of the ``k_steps`` scan iterations forwards ``1 + n_spec`` tokens
    (the current input plus proposed drafts) through the SAME ragged layer
    stack as the plain chain, takes greedy targets at every position, and
    accepts the longest draft prefix that matches — emitting between 1 and
    ``1 + n_spec`` tokens per model forward. Rejected-draft KV writes are
    position-addressed, so the next iteration's writes simply overwrite
    them; accepted-draft KV is already correct (the verify forward IS the
    target forward at those positions). Greedy only: acceptance compares
    against argmax targets, which keeps spec output token-identical to the
    plain chain by construction.

    Transient KV writes run ``n_spec`` positions past the last emitted
    token, so the caller pre-extends block tables for ``window + n_spec``
    tokens (see ``InferenceEngineV2.decode_spec_chain``).

    Returns ``(out_tokens [N, k_steps*(1+n_spec)] compacted, emitted [N],
    active [N], steps [N], rng, pool)`` — ``out_tokens[i, :emitted[i]]``
    valid, ``steps[i]`` = model forwards row i was live for (the
    accepted-tokens/forward telemetry denominator).
    """
    m = 1 + n_spec
    N = tokens.shape[0]
    idx = jnp.arange(m)[None, :]

    def step(carry, _):
        pool, tok, pos, live, emitted, hist, hlen, steps, key = carry
        drafts = _ngram_propose(hist, hlen, n_spec, ngram)  # [N, n_spec]
        inputs = jnp.concatenate([tok[:, None], drafts], axis=1)  # [N, m]
        positions = pos[:, None] + jnp.arange(m)[None, :]
        new_lens = jnp.where(live, m, 0)
        hs, pool = _forward_hidden(params, cfg, pool, inputs, positions,
                                   new_lens, block_tables, block_size,
                                   all_positions=True)
        logits = _logits(params, cfg, hs)  # [N, m, V]
        g = greedy_tokens(logits)  # [N, m] greedy targets
        # draft j accepted iff it matches the target at its previous
        # position AND every earlier draft was accepted (cumulative)
        match = (inputs[:, 1:] == g[:, :-1]).astype(jnp.int32)  # [N, n_spec]
        n_acc = jnp.cumprod(match, axis=1).sum(axis=1)
        e = jnp.minimum(n_acc + 1, budgets - emitted)
        has_eos = jnp.zeros((N,), bool)
        if eos_id is not None:
            is_eos = (g == eos_id) & (idx < e[:, None])
            has_eos = is_eos.any(axis=1)
            e = jnp.where(has_eos, jnp.argmax(is_eos, axis=1) + 1, e)
        e = jnp.where(live, e, 0)
        out = jnp.where((idx < e[:, None]) & live[:, None], g, -1)
        nxt = jnp.take_along_axis(g, jnp.maximum(e - 1, 0)[:, None], axis=1)[:, 0]
        # append the emitted tokens to the on-device history (the proposer's
        # source); masked slots scatter out of bounds and drop
        hidx = jnp.where(idx < e[:, None], hlen[:, None] + idx, hist.shape[1])
        hist = hist.at[jnp.arange(N)[:, None], hidx].set(g, mode="drop")
        emitted = emitted + e
        still = live & (emitted < budgets) & ~has_eos
        steps = steps + live.astype(jnp.int32)
        return (pool, jnp.where(live, nxt, tok), pos + e, still, emitted,
                hist, hlen + e, steps, key), out

    zeros = jnp.zeros_like(start_pos)
    carry0 = (pool, tokens, start_pos, active, zeros, history, hist_len,
              zeros, rng)
    (pool, _, _, active, emitted, _, _, steps, rng), outs = jax.lax.scan(
        step, carry0, None, length=k_steps)
    # compact: each iteration's emitted prefix packs to the row's front, so
    # the host contract stays out[i, :emitted[i]] exactly like the plain chain
    o = outs.transpose(1, 0, 2).reshape(N, k_steps * m)
    valid = o >= 0
    tgt = jnp.where(valid, jnp.cumsum(valid, axis=1) - 1, k_steps * m)
    compact = jnp.full((N, k_steps * m), -1, jnp.int32)
    compact = compact.at[jnp.arange(N)[:, None], tgt].set(o, mode="drop")
    return compact, emitted, active, steps, rng, pool
