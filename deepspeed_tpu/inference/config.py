"""Inference config (reference ``deepspeed/inference/config.py`` —
``DeepSpeedInferenceConfig`` pydantic model, tp via ``DeepSpeedTPConfig``)."""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from pydantic import Field

from deepspeed_tpu.config.config_utils import DeepSpeedConfigModel

_DTYPES = {
    "bf16": jnp.bfloat16,
    "bfloat16": jnp.bfloat16,
    "fp16": jnp.float16,
    "float16": jnp.float16,
    "half": jnp.float16,
    "fp32": jnp.float32,
    "float32": jnp.float32,
    "int8": jnp.int8,
}


class TPConfig(DeepSpeedConfigModel):
    """Tensor-parallel sizing (reference ``DeepSpeedTPConfig``)."""

    enabled: bool = True
    tp_size: int = 1


class QuantConfig(DeepSpeedConfigModel):
    """Weight-only quantization (reference ``QuantizationConfig`` int4/int8 +
    ``ops/fp_quantizer`` fp8; implementation ``inference/woq.py``)."""

    enabled: bool = False
    bits: int = 8
    group_size: int = 128
    qtype: str = "int"  # 'int' (int8/int4 by bits) | 'fp' (fp8)
    min_leaf_size: int = 1 << 16  # kernels smaller than this stay dense
    # Per-tensor-class selection (woq.TENSOR_CLASSES): which weight families
    # quantize — 'attn' (wq/wk/wv/wo), 'mlp' (w_up/w_gate/w_down), 'experts',
    # 'lm_head'. None = every eligible kernel (the legacy behavior).
    tensor_classes: Optional[list] = None


class ZeroInferenceConfig(DeepSpeedConfigModel):
    """ZeRO-Inference: weights live in host memory and stream through the
    forward (reference stage-3-for-inference + AIO, blogs/deepspeed-gds)."""

    enabled: bool = False
    offload: str = "cpu"  # 'cpu' (pinned host memory) | 'nvme' (AIO-streamed layers)
    min_leaf_size: int = 1 << 16  # leaves smaller than this stay on device (cpu mode)
    nvme_path: Optional[str] = None  # required for offload='nvme'
    num_buffers: int = 2  # layers resident at once in nvme mode (double buffer)


class ServingSLOConfig(DeepSpeedConfigModel):
    """``serving_slo`` block — the targets that turn per-request latency
    records into **goodput** (fraction of finished requests meeting SLO,
    the number a capacity plan is written against).

    A finished request meets its SLO when TTFT (arrival -> first token) is
    within ``ttft_ms`` AND its mean per-output-token latency is within
    ``tpot_ms``; a ``None`` target is not enforced. ``window_s`` bounds the
    rolling windows behind the ``serving/goodput``, ``serving/tokens_per_s``
    and ``serving/preemption_rate`` gauges (see ``inference/lifecycle.py``).

    Admission control (serving router, ISSUE 12): ``admission`` turns the
    TTFT target into a gate applied BEFORE dispatching a prefill — a request
    whose projected TTFT (wait so far + the replica's estimated time to
    first token) already exceeds ``ttft_ms * admission_ttft_factor`` is
    **shed** (rejected immediately, so it stops consuming queue capacity
    that on-budget requests could use) or **deferred** (left queued for a
    replica that can still make the budget; it sheds only when every replica
    is over). ``"none"`` admits everything — the engine-only behavior.
    """

    ttft_ms: Optional[float] = None  # time-to-first-token target
    tpot_ms: Optional[float] = None  # mean time-per-output-token target
    window_s: float = 30.0  # rolling window for goodput/rate gauges
    admission: str = "none"  # none | shed | defer (router-level gate)
    admission_ttft_factor: float = 1.0  # shed when projected TTFT > target*factor


class InferenceConfig(DeepSpeedConfigModel):
    """Reference ``DeepSpeedInferenceConfig`` (inference/config.py:77)."""

    dtype: str = "bf16"
    tensor_parallel: TPConfig = Field(default_factory=TPConfig)
    quant: QuantConfig = Field(default_factory=QuantConfig)
    zero_inference: ZeroInferenceConfig = Field(default_factory=ZeroInferenceConfig)
    max_out_tokens: int = 1024  # hard cap on generate(max_new_tokens=...)
    min_out_tokens: int = 1  # reserved (reference scheduler admission knob)
    max_batch_size: Optional[int] = None  # hard cap on generate batch size
    replace_with_kernel_inject: bool = True  # accepted for parity; Pallas ops
    # are selected via the ops registry rather than module swapping
    seq_bucket: int = 64  # pad prompt lengths up to a multiple (compile reuse)
    kv_cache_dtype: Optional[str] = None  # default: same as dtype
    # Recompile detection (diagnostics/recompile.py) on the engine's jitted
    # programs: the seq_bucket claim above ("recompiles are rare") is checked,
    # not hoped — a recompile of an already-compiled program warns with the
    # offending argument shape diff, and runaway bucket-cache growth warns
    # too. Host-side, one cache-size check per call; disable to shave that.
    recompile_warnings: bool = True
    # distinct compiled generate programs before the cache-growth warning
    max_generate_buckets: int = 16
    # Pre-flight HBM-fit check (utils/hbm.py) before param placement:
    # "warn" | "refuse" | "off". An over-budget materialization on this
    # platform wedges the device without raising (PERF.md round 5), so the
    # bench extras run "refuse". With WOQ enabled the estimate uses the
    # quantized byte formula (woq.quantized_bytes_estimate — values + scales
    # through the same eligibility predicate the real pass applies), so a
    # model that only fits quantized is admitted; zero_inference keeps the
    # big weights off-device and skips the check entirely.
    hbm_check: str = "warn"

    @property
    def jax_dtype(self) -> Any:
        return _DTYPES[self.dtype.lower()]

    @property
    def kv_dtype(self) -> Any:
        return _DTYPES[(self.kv_cache_dtype or self.dtype).lower()]
