"""Per-request lifecycle tracking for the v2 serving engine.

The request-level half of serving observability (the span tracer sees the
*engine's* phases; this sees each *request's*). Every request carries one
lightweight ``RequestRecord`` stamped at: arrival -> admission (queue wait)
-> prefill dispatch -> first token (TTFT) -> each decode-chain boundary
(TPOT) -> finish / preempt / re-admit.

Hot-path discipline (the reason this can ride the PR-4 fast path):

  - **O(1) per chain boundary.** A chain boundary costs one
    ``perf_counter()`` plus a float append and a histogram observe per live
    row. There are NO per-token host timestamps — the K tokens inside a
    chained program are invisible to the host by design, so TPOT derives
    from consecutive boundary stamps divided by the tokens the chain
    emitted.
  - **Deferred trace emission.** Per-request Perfetto output (one virtual
    track per request: queue/prefill/decode slices, plus flow arrows linking
    its admission to the prefill and every chain dispatch span on the engine
    thread) is materialized ONCE at request finish from the stamps — the
    steady-state loop never appends trace events per row.
  - **Nothing allocated when disabled.** The engine constructs a tracker
    only when the tracer is enabled (or a serving flight recorder is
    configured); otherwise the serving path is byte-identical to PR 4.

Metrics (shared ``MetricsRegistry``; all labelled with the engine's chain
length ``k`` so multi-config processes stay separable):

  histograms  serving/ttft_ms, serving/tpot_ms, serving/queue_wait_ms,
              serving/e2e_ms           (log-bucketed -> cheap p50/p95/p99)
  counters    serving/requests, serving/requests_finished,
              serving/readmissions, serving/slo_met, serving/slo_missed
  gauges      serving/goodput, serving/tokens_per_s,
              serving/preemption_rate  (rolling ``slo.window_s`` windows)

The engine adds the unlabelled process-level scheduler/pool series at chain
boundaries (``serving/queue_depth``, ``serving/batch_occupancy``,
``serving/kv_pool_free_blocks``, ``serving/kv_pool_utilization``, and the
``serving/preemptions`` counter).

SLO targets come from the ``serving_slo`` config block
(``inference/config.py:ServingSLOConfig``); goodput = fraction of finished
requests meeting both targets, over the rolling window and cumulatively
(the ``serving/slo_met``/``serving/slo_missed`` counters).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

# virtual Perfetto track ids for per-request tracks: far above any real
# thread id's low bits, stable per request index
TRACK_BASE = 0x5E51_0000


class RequestRecord:
    """One request's phase stamps + accounting (plain floats/ints only)."""

    __slots__ = ("rid", "uid", "arrival", "admit", "first_admit", "first_token",
                 "last_emit", "finish", "tokens", "chains", "preemptions",
                 "readmissions", "decode_s", "dispatch_stamps", "phase",
                 "last_preempt", "last_migrate", "migrations", "replica",
                 "flow_id", "flow_name")

    def __init__(self, rid: int, arrival: float):
        self.rid = rid
        self.uid: Optional[int] = None
        # cross-process Chrome flow identity (fleet.TraceContext): when
        # set, the request's flow arrows use the context's (name, id)
        # instead of the local rid — Chrome binds arrows on BOTH, so a
        # replica in ANOTHER process emitting a flow step with the same
        # context binds into this track in the merged trace
        self.flow_id: Optional[int] = None
        self.flow_name: Optional[str] = None
        self.arrival = arrival
        self.admit: Optional[float] = None  # most recent admission
        self.first_admit: Optional[float] = None
        self.last_preempt: Optional[float] = None  # readmit-wait anchor
        self.last_migrate: Optional[float] = None  # migration-wait anchor
        self.migrations = 0  # completed prefill->decode pool migrations
        self.replica: Optional[int] = None  # router affinity (None = local)
        self.first_token: Optional[float] = None
        self.last_emit: Optional[float] = None  # previous boundary stamp
        self.finish: Optional[float] = None
        self.tokens = 0  # output tokens emitted
        self.chains = 0  # decode-chain dispatches that served this request
        self.preemptions = 0
        self.readmissions = 0
        self.decode_s = 0.0  # summed post-first-token boundary deltas
        # perf_counter stamp per dispatch that carried this request (the
        # dispatch thread id lives on the tracker) — flow-arrow targets,
        # emitted at finish
        self.dispatch_stamps: List[float] = []
        self.phase = "queued"

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token is None:
            return None
        return self.first_token - self.arrival

    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.first_admit is None:
            return None
        return self.first_admit - self.arrival

    @property
    def mean_tpot_s(self) -> Optional[float]:
        # per-output-token latency AFTER the first token (the TTFT token)
        n = self.tokens - 1
        if n <= 0:
            return None
        return self.decode_s / n

    def snapshot(self) -> Dict[str, Any]:
        """Flight-recorder view: what a post-mortem needs to name this
        request and see where it was."""
        return {
            "rid": self.rid, "uid": self.uid, "phase": self.phase,
            "arrival": self.arrival, "admit": self.admit,
            "first_token": self.first_token, "finish": self.finish,
            "tokens": self.tokens, "chains": self.chains,
            "preemptions": self.preemptions, "readmissions": self.readmissions,
            "migrations": self.migrations,
        }


class LifecycleTracker:
    """Stamps request lifecycles, feeds the labelled SLO metrics, and emits
    per-request Perfetto tracks + flow events at finish.

    ``clock`` is injectable (tests pin TTFT/TPOT against a fake clock); every
    method also takes an explicit ``now`` so callers can reuse one stamp
    across a batch. ``emit_metrics=False`` (flight-recorder-only mode, tracer
    disabled) keeps the registry and trace untouched.
    """

    def __init__(self, tracer, slo=None, labels: Optional[Dict[str, Any]] = None,
                 clock=time.perf_counter, recorder=None, emit_metrics: bool = True):
        self._tracer = tracer
        self._slo = slo
        self._clock = clock
        self._recorder = recorder
        self._labels = {k: str(v) for k, v in (labels or {}).items()}
        self._records: Dict[int, RequestRecord] = {}
        # rid -> fleet.TraceContext, kept off RequestRecord (records hold
        # plain floats/ints only; the fabric needs the full context back)
        self._contexts: Dict[int, Any] = {}
        self._emit = emit_metrics and getattr(tracer, "enabled", False)
        window = float(getattr(slo, "window_s", 30.0) or 30.0)
        self._window_s = window
        # rolling windows with running sums — pruning and reading are O(1)
        # amortized per chain boundary, never a scan
        self._win_tokens: deque = deque()  # (t, n)
        self._win_tokens_sum = 0
        self._win_preempts: deque = deque()  # t
        self._win_slo: deque = deque()  # (t, 1|0)
        self._win_slo_met = 0
        self._dispatch_tid: Optional[int] = None
        if self._emit:
            reg = tracer.registry
            lb = self._labels
            self._h_ttft = reg.histogram("serving/ttft_ms", **lb)
            self._h_tpot = reg.histogram("serving/tpot_ms", **lb)
            self._h_queue = reg.histogram("serving/queue_wait_ms", **lb)
            self._h_e2e = reg.histogram("serving/e2e_ms", **lb)
            self._c_requests = reg.counter("serving/requests", **lb)
            self._c_finished = reg.counter("serving/requests_finished", **lb)
            self._c_readmit = reg.counter("serving/readmissions", **lb)
            self._h_readmit = reg.histogram("serving/readmit_wait_ms", **lb)
            # disaggregated serving (ISSUE 14): KV-block migration stamps
            self._h_migration = reg.histogram("serving/migration_ms", **lb)
            self._c_mig_blocks = reg.counter("serving/migrated_blocks", **lb)
            self._c_mig_fail = reg.counter("serving/migration_failures", **lb)
            self._c_slo_met = reg.counter("serving/slo_met", **lb)
            self._c_slo_missed = reg.counter("serving/slo_missed", **lb)
            self._g_goodput = reg.gauge("serving/goodput", **lb)
            self._g_tps = reg.gauge("serving/tokens_per_s", **lb)
            self._g_preempt_rate = reg.gauge("serving/preemption_rate", **lb)

    # ------------------------------------------------------------- helpers
    def _now(self, now: Optional[float]) -> float:
        return self._clock() if now is None else now

    def get(self, rid: int) -> Optional[RequestRecord]:
        return self._records.get(rid)

    def set_trace_context(self, rid: int, ctx) -> None:
        """Attach a ``fleet.TraceContext`` to a request: its flow arrows
        switch to the context's fleet-wide (name, id) — both sides of a
        process boundary derive the same pair from (run_id, request_id),
        and Chrome binds arrows on both fields."""
        rec = self._records.get(rid)
        if rec is not None:
            rec.flow_id = ctx.flow_id
            rec.flow_name = ctx.flow_name
            self._contexts[rid] = ctx

    def trace_context(self, rid: int):
        """The attached ``fleet.TraceContext`` (or None) — the serving
        fabric reads it back to forward the context on remote dispatches,
        so a replica daemon in another process can join the flow."""
        return self._contexts.get(rid)

    def records(self) -> Dict[int, RequestRecord]:
        return self._records

    def _record_to_recorder(self, rec: RequestRecord) -> None:
        if self._recorder is not None:
            snap = rec.snapshot()
            self._recorder.record_request(snap.pop("rid"), **snap)

    # ------------------------------------------------------------ lifecycle
    def arrive(self, rid: int, now: Optional[float] = None) -> RequestRecord:
        now = self._now(now)
        rec = self._records.get(rid)
        if rec is None:
            rec = self._records[rid] = RequestRecord(rid, now)
            if self._emit:
                self._c_requests.add(1.0)
            self._record_to_recorder(rec)
        return rec

    def admit(self, rid: int, uid: int, now: Optional[float] = None) -> None:
        now = self._now(now)
        rec = self._records[rid]
        rec.uid = uid
        rec.admit = now
        rec.phase = "prefill"
        if rec.first_admit is None:
            rec.first_admit = now
            if self._emit:
                self._h_queue.observe((now - rec.arrival) * 1e3)
        else:
            # re-admission after preemption or migration: the wait lands in
            # its OWN histogram; queue_wait stays pinned to the first
            # admission and TTFT stays measured from the ORIGINAL arrival
            # (never restarted — the fake-clock tests pin both). The anchor
            # is the LATEST hand-off stamp (preempt or migrate-start), never
            # the arrival when one exists: anchoring at arrival would
            # re-count the queue/defer window a deferred-then-migrated
            # request already spent before its first admission (ISSUE 14
            # small fix — defer and migration waits are disjoint intervals).
            rec.readmissions += 1
            if self._emit:
                self._c_readmit.add(1.0)
                stamps = [s for s in (rec.last_preempt, rec.last_migrate)
                          if s is not None]
                anchor = max(stamps) if stamps else rec.arrival
                self._h_readmit.observe((now - anchor) * 1e3)
        self._record_to_recorder(rec)

    def mark_dispatch(self, rids: Sequence[int], kind: str,
                      now: Optional[float] = None) -> None:
        """Stamp a dispatch that carries these requests — called INSIDE the
        engine's ``serve:dispatch`` span so the deferred flow arrows land
        within that slice. One float append per row; no trace events here."""
        now = self._now(now)
        if self._dispatch_tid is None:
            self._dispatch_tid = threading.get_ident()
        recs = self._records
        if kind == "chain":
            for rid in rids:
                rec = recs.get(rid)
                if rec is not None:
                    rec.dispatch_stamps.append(now)
                    rec.chains += 1
        else:
            for rid in rids:
                rec = recs.get(rid)
                if rec is not None:
                    rec.dispatch_stamps.append(now)

    def emitted_batch(self, rids: Sequence[int], counts: Sequence[int],
                      now: Optional[float] = None) -> None:
        """Record new output tokens for a whole boundary in one call — the
        chain fetch passes every live row. First emission per request stamps
        TTFT; later ones contribute per-token TPOT samples. Rows of one
        chain typically share (boundary delta, tokens), so their identical
        TPOT values collapse into grouped ``observe_n`` bucket hits."""
        now = self._now(now)
        recs = self._records
        emit = self._emit
        tpot_groups: Dict[float, int] = {}
        new_tokens = 0
        for rid, n in zip(rids, counts):
            n = int(n)
            if n <= 0:
                continue
            rec = recs.get(rid)
            if rec is None:
                continue
            if rec.first_token is None:
                rec.first_token = now
                rec.phase = "decoding"
                if emit:
                    self._h_ttft.observe((now - rec.arrival) * 1e3)
                # the TTFT token itself is not a TPOT sample
                n_tpot = n - 1
            else:
                n_tpot = n
            if n_tpot > 0 and rec.last_emit is not None:
                dt = now - rec.last_emit
                rec.decode_s += dt
                if emit:
                    v = dt / n_tpot * 1e3
                    tpot_groups[v] = tpot_groups.get(v, 0) + 1
            rec.tokens += n
            rec.last_emit = now
            new_tokens += n
        if emit:
            for v, c in tpot_groups.items():
                self._h_tpot.observe_n(v, c)
            if new_tokens:
                self._win_tokens.append((now, new_tokens))
                self._win_tokens_sum += new_tokens

    def emitted(self, rid: int, n_tokens: int, now: Optional[float] = None) -> None:
        """Single-request convenience wrapper over ``emitted_batch``."""
        self.emitted_batch((rid,), (n_tokens,), now=now)

    def preempt(self, rid: int, now: Optional[float] = None) -> None:
        now = self._now(now)
        rec = self._records.get(rid)
        if rec is None:
            return
        rec.preemptions += 1
        rec.phase = "preempted"
        rec.last_preempt = now  # anchor for serving/readmit_wait_ms
        # decode pauses while re-queued: break the TPOT chain so queue time
        # is charged to the (re)admission wait, not to per-token latency
        rec.last_emit = None
        if self._emit:
            self._win_preempts.append(now)
        self._record_to_recorder(rec)

    # ------------------------------------------------------------ migration
    def migrate_start(self, rid: int, now: Optional[float] = None) -> None:
        """Stamp the start of a post-prefill KV-block migration (the export
        dispatch). The TPOT chain breaks here — decode pauses while the
        pages stream, and that pause is charged to ``serving/migration_ms``
        / ``serving/readmit_wait_ms`` (anchored at this stamp), never to
        per-token latency."""
        now = self._now(now)
        rec = self._records.get(rid)
        if rec is None:
            return
        rec.phase = "migrating"
        rec.last_migrate = now
        rec.last_emit = None  # TPOT chain restarts on the decode replica
        self._record_to_recorder(rec)

    def transfer(self, rid: int, dst: "LifecycleTracker"
                 ) -> Optional[RequestRecord]:
        """Hand a request's record to the destination replica's tracker (the
        in-process analog of the trace context crossing a process boundary):
        TTFT/queue-wait history travels with it — finish-side metrics land
        under the DESTINATION's labels, arrival-side ones already landed
        under the source's."""
        rec = self._records.pop(rid, None)
        if rec is None:
            return None
        dst._records[rid] = rec
        dst._record_to_recorder(rec)
        return rec

    def migrated(self, rid: int, n_blocks: int,
                 now: Optional[float] = None) -> None:
        """Record a COMPLETED migration on the destination tracker:
        ``serving/migration_ms`` = export-dispatch -> import-committed,
        ``serving/migrated_blocks`` counts the pages moved."""
        now = self._now(now)
        rec = self._records.get(rid)
        if rec is None:
            return
        rec.migrations += 1
        rec.phase = "decoding" if rec.first_token is not None else "prefill"
        if self._emit:
            anchor = rec.last_migrate if rec.last_migrate is not None else now
            self._h_migration.observe((now - anchor) * 1e3)
            self._c_mig_blocks.add(float(n_blocks))
        self._record_to_recorder(rec)

    def migrate_retry(self, rid: int) -> None:
        """A failed import attempt whose migration will be RETRIED (the
        source pool cannot host the request's decode window): counts in
        ``serving/migration_failures`` — one per attempt, matching the
        router's attempt-level accounting — with the request's phase
        staying ``migrating``."""
        if self._emit:
            self._c_mig_fail.add(1.0)

    def migrate_failed(self, rid: int) -> None:
        """A migration that could not import: the request resumes decoding
        on its SOURCE replica (mixed-mode fallback — never dropped)."""
        rec = self._records.get(rid)
        if rec is not None:
            rec.phase = "decoding" if rec.first_token is not None else "prefill"
            self._record_to_recorder(rec)
        if self._emit:
            self._c_mig_fail.add(1.0)

    def _meets_slo_counted(self, rec: RequestRecord, now: float) -> None:
        met = self._meets_slo(rec)
        if met is not None:
            (self._c_slo_met if met else self._c_slo_missed).add(1.0)
            self._win_slo.append((now, 1 if met else 0))
            self._win_slo_met += 1 if met else 0

    def finish(self, rid: int, now: Optional[float] = None) -> None:
        now = self._now(now)
        rec = self._records.get(rid)
        if rec is None:
            return
        rec.finish = now
        rec.phase = "finished"
        self._record_to_recorder(rec)
        if not self._emit:
            return
        self._c_finished.add(1.0)
        self._h_e2e.observe((now - rec.arrival) * 1e3)
        self._meets_slo_counted(rec, now)
        self._emit_request_track(rec)

    def _meets_slo(self, rec: RequestRecord) -> Optional[bool]:
        """True/False against the configured targets; None when no target is
        configured (goodput undefined — never counted)."""
        slo = self._slo
        ttft_t = getattr(slo, "ttft_ms", None) if slo is not None else None
        tpot_t = getattr(slo, "tpot_ms", None) if slo is not None else None
        if ttft_t is None and tpot_t is None:
            return None
        ok = True
        if ttft_t is not None:
            ttft = rec.ttft_s
            ok &= ttft is not None and ttft * 1e3 <= ttft_t
        if tpot_t is not None:
            tpot = rec.mean_tpot_s
            if tpot is not None:  # single-token requests have no TPOT
                ok &= tpot * 1e3 <= tpot_t
        return bool(ok)

    # -------------------------------------------------------------- gauges
    def sample_gauges(self, now: Optional[float] = None) -> None:
        """Refresh the rolling-window gauges (called at chain boundaries).
        Running sums make this O(expired entries), not a window scan."""
        if not self._emit:
            return
        now = self._now(now)
        horizon = now - self._window_s
        wt = self._win_tokens
        while wt and wt[0][0] < horizon:
            self._win_tokens_sum -= wt.popleft()[1]
        wp = self._win_preempts
        while wp and wp[0] < horizon:
            wp.popleft()
        ws = self._win_slo
        while ws and ws[0][0] < horizon:
            self._win_slo_met -= ws.popleft()[1]
        if wt:
            span = max(now - wt[0][0], 1e-6)
            self._g_tps.set(self._win_tokens_sum / span)
        self._g_preempt_rate.set(len(wp) / self._window_s)
        if ws:
            self._g_goodput.set(self._win_slo_met / len(ws))

    # ------------------------------------------------------ trace emission
    def _emit_request_track(self, rec: RequestRecord) -> None:
        """Materialize the request's Perfetto track + flow arrows (deferred
        to finish — the whole batch lands under ONE tracer lock; the
        steady-state loop appends zero trace events per row)."""
        tr = self._tracer
        rid = rec.rid
        tid = TRACK_BASE + rid
        tr.name_track(tid, f"req {rid}")
        o = tr.origin()
        # one shared args dict referenced by all three phase slices (the
        # exporter only reads it); flat literals — no closures, no merges
        args = {"rid": rid, "tokens": rec.tokens, "chains": rec.chains,
                "preemptions": rec.preemptions}
        fa, ft, fin = rec.first_admit, rec.first_token, rec.finish
        # fleet-wide flow (name, id) when a trace context was attached (the
        # merged multi-process trace binds on BOTH); local rid otherwise
        fid = rec.flow_id if rec.flow_id is not None else rid
        flow_name = rec.flow_name if rec.flow_name is not None else f"req-{rid}"
        evs: List[Dict[str, Any]] = []
        if fa is not None:
            evs.append({"kind": "span", "name": "queue", "cat": "serve_req",
                        "ts": rec.arrival - o, "dur": max(fa - rec.arrival, 0.0),
                        "tid": tid, "args": args})
            if ft is not None:
                evs.append({"kind": "span", "name": "prefill", "cat": "serve_req",
                            "ts": fa - o, "dur": max(ft - fa, 0.0),
                            "tid": tid, "args": args})
        if ft is not None and fin is not None:
            evs.append({"kind": "span", "name": "decode", "cat": "serve_req",
                        "ts": ft - o, "dur": max(fin - ft, 0.0), "tid": tid,
                        "args": {"ttft_ms": round((ft - rec.arrival) * 1e3, 3),
                                 **args}})
        # flow: start on the request track at admission, one step inside every
        # dispatch span that carried the request, end back on the track
        if fa is not None:
            evs.append({"kind": "flow", "name": flow_name, "cat": "flow",
                        "ph": "s", "id": fid, "ts": fa + 1e-7 - o, "tid": tid})
        dtid = self._dispatch_tid or tid
        for t in rec.dispatch_stamps:
            evs.append({"kind": "flow", "name": flow_name, "cat": "flow",
                        "ph": "t", "id": fid, "ts": t - o, "tid": dtid})
        if fin is not None:
            evs.append({"kind": "flow", "name": flow_name, "cat": "flow",
                        "ph": "f", "id": fid, "ts": fin - 1e-7 - o, "tid": tid})
        tr.append_events(evs)
