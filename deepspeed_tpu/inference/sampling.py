"""Token sampling for generation (greedy / temperature / top-k / top-p).

The reference delegates sampling to HF ``generate`` (its engines only guard it,
``inference/engine.py:583``); FastGen's serving layer (MII) samples outside the
engine. Here sampling compiles INTO the serving step programs: the v1 engine
jits it alongside its scan decode, and the v2 engine fuses it into both the
ragged prefill step and the K-step decode chain
(``paged.ragged_decode_chain``), so decode dispatches return int32 token ids
and the ``[rows, vocab]`` logits never leave the device. All knobs are static
(compile-time) arguments; the PRNG key is threaded through the step carry.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def greedy_tokens(logits: jax.Array) -> jax.Array:
    """logits [..., V] -> argmax ids (int32), any leading dims. The single
    definition of "the target's greedy choice" — shared by plain sampling
    and the speculative verify-and-accept step (``paged.
    ragged_spec_decode_chain``), so acceptance compares against exactly the
    tokens the plain chain would have emitted."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def sample_logits(
    logits: jax.Array,
    rng: jax.Array,
    *,
    do_sample: bool = False,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """logits [B, V] -> token ids [B] (int32)."""
    if not do_sample:
        return greedy_tokens(logits)

    logits = logits.astype(jnp.float32)
    if temperature != 1.0:
        logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p (always keep 1)
        keep = cum - probs < top_p
        cutoff = jnp.where(keep, sorted_logits, jnp.inf).min(axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
