"""ZeRO++ wiring: quantized weight-gather / gradient-reduce inside the step.

Reference: ``deepspeed/runtime/comm/coalesced_collectives.py:31``
(``all_to_all_quant_reduce``, qgZ), ``zero/partition_parameters.py:1200``
(``all_gather_coalesced(quantize=True)``, qwZ) and the CUDA kernels under
``csrc/quantization/``. There the two are separate subsystems hooked into the
fetch coordinator and the gradient reducer.

TPU-native redesign: one differentiable collective. The stage-3 weight
all-gather IS the forward of a ``jax.custom_vjp`` op whose backward IS the
gradient reduce-scatter — so turning on qwZ quantizes the forward/backward
weight gathers and turning on qgZ quantizes the gradient reduction, both at
exactly one place in the compiled step. The engine runs its micro-batch
gradient computation inside a partial-manual ``shard_map`` over the data axes
(dp/fsdp manual, tp/sp/... auto) so the collectives are addressable; XLA still
schedules/overlaps them over ICI.

Int8 block quantization is the shared wire codec
(``collectives/codecs.py`` — one format across the hop algorithms, the
all_to_all helpers, and these custom-vjp gathers); comm volume per
gather/reduce is ~2x less than bf16, ~4x less than fp32 — the ZeRO++
headline (``docs/_tutorials/zeropp.md:6-17``). The weight gather optionally
splits its wire into chunks double-buffered through
``collectives/overlap.py`` so dequantize of chunk k overlaps the gather of
chunk k+1 (T3-style).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from deepspeed_tpu.collectives.codecs import Int8BlockCodec
from deepspeed_tpu.collectives.overlap import double_buffered
from deepspeed_tpu.comm import comm as dist
from deepspeed_tpu.parallel.quant_collectives import exchange_wire, gather_wire
from deepspeed_tpu.utils.compat import axis_size as _axis_size

DEFAULT_BLOCK = 2048


class CommPlan:
    """Per-leaf gather/scatter plan. A plain object (NOT a pytree node) so a
    plans tree zips against a params tree without being traversed into."""

    __slots__ = ("dim", "axes")

    def __init__(self, dim: Optional[int], axes: Tuple[str, ...] = ()):
        self.dim = dim
        self.axes = axes

    @property
    def sharded(self) -> bool:
        return self.dim is not None

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"CommPlan(dim={self.dim}, axes={self.axes})"


def leaf_comm_plan(spec: Optional[PartitionSpec], live_axes: Tuple[str, ...]) -> CommPlan:
    """Plan for one leaf: the data-axis-sharded dimension (if any).

    ``spec`` is the leaf's master/grad PartitionSpec; entries naming live data
    axes mark the dimension the weight gather / grad scatter works along.
    """
    if spec is None:
        return CommPlan(None)
    for dim, entry in enumerate(spec):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        hit = tuple(a for a in names if a in live_axes)
        if hit:
            return CommPlan(dim, hit)
    return CommPlan(None)


def _int8_all_gather_dim(x: jax.Array, dim: int, axes, block: int,
                         overlap_chunks: int = 1) -> jax.Array:
    """Encode the local shard once, gather the int8 wire, decode.

    ``overlap_chunks > 1`` splits the wire into that many chunks and runs
    them through the T3-style double buffer (``collectives/overlap.py``):
    the decode of chunk k and the gather of chunk k+1 have no data
    dependence, so XLA may overlap them — hiding dequantize time behind the
    next chunk's transfer on an async-collective backend."""
    moved = jnp.moveaxis(x, dim, 0)
    rest = moved.shape[1:]
    flat = moved.reshape(-1)
    M = flat.shape[0]
    codec = Int8BlockCodec(block_size=min(block, M))
    n = _axis_size(axes)

    chunks = max(int(overlap_chunks), 1)
    blk = codec.block_size
    blocks_total = -(-M // blk)
    chunks = min(chunks, blocks_total)  # a chunk is a whole number of blocks
    if chunks <= 1:
        wire = codec.encode_rows(flat[None])
        deq = codec.decode_rows(gather_wire(wire, axes), M, x.dtype)  # [n, M]
    else:
        wire = codec.encode_rows(flat[None])  # q [1, Mp], s [1, Mp//blk]
        Mp = wire.q.shape[1]
        blocks_per = -(-blocks_total // chunks)
        per = blocks_per * blk
        chunks = -(-Mp // per)
        pieces = [
            type(wire)(q=wire.q[:, k * per:(k + 1) * per],
                       s=wire.s[:, k * blocks_per:(k + 1) * blocks_per])
            for k in range(chunks)
        ]
        gathered = double_buffered(
            pieces,
            comm_fn=lambda w: gather_wire(w, axes),
            compute_fn=lambda wg: codec.decode_rows(wg, wg.q.shape[1], x.dtype),
        )
        deq = jnp.concatenate(gathered, axis=1)[:, :M]  # [n, M]
    full = deq.reshape((n * moved.shape[0],) + rest)
    return jnp.moveaxis(full, 0, dim)


def _int8_rs_core(g: jax.Array, err, dim: int, axes, err_beta: float,
                  block: int) -> Tuple[jax.Array, Optional[jax.Array]]:
    """The ONE qgZ wire format (quantize per destination shard -> a2a ->
    dequant -> mean), with optional LoCo error feedback (reference
    ``coalesced_collectives.py:81 all_to_all_loco_quant_reduce`` +
    ``csrc/quantization/pt_binding.cpp loco_*``):

        v       = g + err_beta * err          (when err is carried)
        wire    = Q(v)                        (int8 rows, as plain qgZ)
        new_err = v - dequant(Q(v))           (what the wire dropped)
    """
    n = _axis_size(axes)
    v = g if err is None else g.astype(jnp.float32) + err_beta * err
    moved = jnp.moveaxis(v, dim, 0)
    D, rest = moved.shape[0], moved.shape[1:]
    flat = moved.reshape(-1)
    shard = flat.shape[0] // n
    codec = Int8BlockCodec(block_size=min(block, shard))
    rows = flat.reshape(n, shard)
    wire = codec.encode_rows(rows)

    new_err = None
    if err is not None:
        # local residual: exactly what this rank's wire payload dropped
        local_deq = codec.decode_rows(wire, shard, jnp.float32)
        new_err = (rows - local_deq).reshape(moved.shape)
        new_err = jnp.moveaxis(new_err, 0, dim).astype(err.dtype)

    deq = codec.decode_rows(exchange_wire(wire, axes), shard, jnp.float32)
    red = jnp.mean(deq, axis=0)
    out = red.reshape((D // n,) + rest).astype(g.dtype)
    return jnp.moveaxis(out, 0, dim), new_err


def _int8_reduce_scatter_dim(g: jax.Array, dim: int, axes, block: int) -> jax.Array:
    """Plain qgZ mean-reduce-scatter (coalesced_collectives.py:31)."""
    out, _ = _int8_rs_core(g, None, dim, axes, 0.0, block)
    return out


def _int8_reduce_scatter_dim_loco(g: jax.Array, err: jax.Array, dim: int, axes,
                                  err_beta: float, block: int
                                  ) -> Tuple[jax.Array, jax.Array]:
    """LoCo qgZ: error-feedback compensation + refreshed residual."""
    return _int8_rs_core(g, err, dim, axes, err_beta, block)


def _exact_all_gather_dim(x: jax.Array, dim: int, axes) -> jax.Array:
    return dist.all_gather(x, axes, concat_axis=dim)


def _exact_reduce_scatter_dim(g: jax.Array, dim: int, axes) -> jax.Array:
    n = _axis_size(axes)
    return dist.reduce_scatter(g, axes, scatter_axis=dim) / n


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6, 7))
def sharded_weight_gather(
    shard: jax.Array,
    dim: int,
    gather_axes: Tuple[str, ...],
    other_axes: Tuple[str, ...],
    quantize_weights: bool,
    quantize_grads: bool,
    block: int,
    overlap_chunks: int = 1,
) -> jax.Array:
    """Differentiable ZeRO weight gather (must run inside shard_map).

    forward : shard -> full weight over ``gather_axes`` (int8 wire when
              ``quantize_weights`` — qwZ)
    backward: full-weight grads -> mean-reduced shard grads (int8 all-to-all
              when ``quantize_grads`` — qgZ), plus a mean over ``other_axes``
              (data axes the weight was replicated over).
    """
    if quantize_weights:
        return _int8_all_gather_dim(shard, dim, gather_axes, block, overlap_chunks)
    return _exact_all_gather_dim(shard, dim, gather_axes)


def _swg_fwd(shard, dim, gather_axes, other_axes, qw, qg, block, overlap_chunks):
    return sharded_weight_gather(shard, dim, gather_axes, other_axes, qw, qg,
                                 block, overlap_chunks), None


def _swg_bwd(dim, gather_axes, other_axes, qw, qg, block, overlap_chunks, _res, g):
    if qg:
        gs = _int8_reduce_scatter_dim(g, dim, gather_axes, block)
    else:
        gs = _exact_reduce_scatter_dim(g, dim, gather_axes)
    if other_axes:
        gs = jax.lax.pmean(gs, other_axes)
    return (gs,)


sharded_weight_gather.defvjp(_swg_fwd, _swg_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def sharded_weight_gather_loco(
    shard: jax.Array,
    err: jax.Array,
    inv: jax.Array,
    dim: int,
    gather_axes: Tuple[str, ...],
    other_axes: Tuple[str, ...],
    qw: bool,
    err_beta: float,
    block: int,
    overlap_chunks: int = 1,
) -> jax.Array:
    """LoCo form of :func:`sharded_weight_gather`: same forward, but the
    backward's quantized reduce-scatter carries error feedback. The updated
    residual is smuggled out as ``err``'s cotangent — the engine reads the
    error buffer's "gradient" as the next step's buffer (the same trick the
    1-bit path uses to thread state through a compiled grad program).

    ``err`` is stored in TRUE gradient units; ``inv`` (= 1/loss_scale)
    converts to/from the scaled-loss wire units inside the backward, so a
    dynamic loss-scale change between steps cannot corrupt the residuals
    (same invariant as the 1-bit path)."""
    if qw:
        return _int8_all_gather_dim(shard, dim, gather_axes, block, overlap_chunks)
    return _exact_all_gather_dim(shard, dim, gather_axes)


def _swgl_fwd(shard, err, inv, dim, gather_axes, other_axes, qw, err_beta, block,
              overlap_chunks):
    out = sharded_weight_gather_loco(shard, err, inv, dim, gather_axes,
                                     other_axes, qw, err_beta, block, overlap_chunks)
    return out, (err, inv)


def _swgl_bwd(dim, gather_axes, other_axes, qw, err_beta, block, overlap_chunks, res, g):
    err_true, inv = res
    gs, new_err_wire = _int8_reduce_scatter_dim_loco(
        g, err_true / inv, dim, gather_axes, err_beta, block)
    if other_axes:
        gs = jax.lax.pmean(gs, other_axes)
    return gs, new_err_wire * inv, jnp.zeros_like(inv)


sharded_weight_gather_loco.defvjp(_swgl_fwd, _swgl_bwd)


def gather_params_for_compute(params, plans, qw: bool, qg: bool, block: int = DEFAULT_BLOCK,
                              live_axes: Tuple[str, ...] = (),
                              errors=None, err_beta: float = 0.8, inv=None,
                              overlap_chunks: int = 1):
    """Map ``sharded_weight_gather`` over a param pytree inside shard_map.

    ``plans`` mirrors ``params`` with a ``CommPlan`` per leaf; replicated
    leaves pass through (their grads get a pmean in the caller instead).
    ``errors`` (a mirror pytree of per-leaf residual buffers) switches the
    sharded leaves to the LoCo gather — their grads then compensate with and
    refresh the residuals (reference all_to_all_loco_quant_reduce); ``inv``
    (1/loss_scale) is required with it.
    """

    if errors is None:
        def one(leaf, plan):
            if not plan.sharded:
                return leaf
            other = tuple(a for a in live_axes if a not in plan.axes)
            return sharded_weight_gather(leaf, plan.dim, plan.axes, other, qw, qg,
                                         block, overlap_chunks)

        return jax.tree_util.tree_map(one, params, plans)

    def one_loco(leaf, err, plan):
        if not plan.sharded:
            return leaf
        other = tuple(a for a in live_axes if a not in plan.axes)
        return sharded_weight_gather_loco(leaf, err, inv, plan.dim, plan.axes,
                                          other, qw, err_beta, block, overlap_chunks)

    return jax.tree_util.tree_map(one_loco, params, errors, plans)


# --------------------------------------------------------- fused-gather GEMM


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def sharded_matmul(x: jax.Array, w_shard: jax.Array, axis: str,
                   quantize: bool = False, block: int = DEFAULT_BLOCK) -> jax.Array:
    """``x [M, K] @ W [K, N]`` with ``W`` row-sharded over ``axis`` and the
    stage-3 weight gather fused INTO the GEMM (T3): the forward never
    materializes the full weight — each fused ring hop contracts the held
    shard against ``x`` while its wire is in flight
    (:func:`deepspeed_tpu.collectives.fused_gemm.all_gather_matmul`).

    backward: ``dw_shard`` comes back through the fused
    matmul+reduce-scatter (``reduce_scatter(x^T @ g, rows)`` — SUM over the
    axis, matching per-rank-batch partials), and ``dx = g @ W^T`` through
    the fused gather's independent-column-block form — neither direction
    materializes the full weight or the full gradient.

    ``quantize`` puts the int8 block wire (qwZ/qgZ) on every fused hop.
    With ``fused_gemm.configure(enabled=False)`` (the default; engine knob
    ``collectives.fused_gemm_collectives``) every path lowers to the plain
    lax composition — programs byte-identical to a build without the fused
    kernels. Must run inside full-manual shard_map; returns fp32.
    """
    from deepspeed_tpu.collectives import fused_gemm

    return fused_gemm.all_gather_matmul(
        x, w_shard, axis, codec="int8" if quantize else None, block_size=block)


def _smm_fwd(x, w_shard, axis, quantize, block):
    return sharded_matmul(x, w_shard, axis, quantize, block), (x, w_shard)


def _smm_bwd(axis, quantize, block, res, g):
    from deepspeed_tpu.collectives import fused_gemm

    x, w_shard = res
    codec = "int8" if quantize else None
    dx = fused_gemm.all_gather_matmul(g, w_shard, axis, codec=codec,
                                      block_size=block, out_block=True)
    dw = fused_gemm.matmul_reduce_scatter(
        jnp.swapaxes(x, 0, 1), g, axis, codec=codec, block_size=block)
    return dx.astype(x.dtype), dw.astype(w_shard.dtype)


sharded_matmul.defvjp(_smm_fwd, _smm_bwd)
