"""Instruction-stream executor for the pipeline schedules (simulation).

Reference analog: ``runtime/pipe/engine.py:1396`` ``_exec_schedule`` — the
instruction interpreter that walks a :class:`PipeSchedule`'s per-tick command
lists and dispatches ``_exec_*`` handlers, with ``pipe/p2p.py`` blocking
sends/recvs between stage ranks.

On TPU the production path is the compiled SPMD pipeline
(``pipeline_spmd.spmd_pipeline``): one XLA program, ppermute between stages.
This executor interprets the SAME instruction streams single-process — every
stage's generator advanced in lockstep, Send/Recv as queues, BackwardPass via
``jax.vjp`` residuals — so schedules are executable and checkable:

- parity: executing ``TrainSchedule`` must reproduce the unpipelined model's
  loss and gradients exactly (pinned in tests against ``spmd_pipeline`` too);
- buffer safety: a ``ForwardPass`` into a buffer whose previous microbatch
  has not completed its ``BackwardPass`` raises — validating
  ``num_pipe_buffers`` (the reference's in-flight memory contract);
- deadlock detection: a ``Recv*`` whose peer never sent raises instead of
  hanging the way real p2p would.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel.pipe_schedule import (
    BackwardPass,
    ForwardPass,
    LoadMicroBatch,
    OptimizerStep,
    RecvActivation,
    RecvGrad,
    ReduceGrads,
    ReduceTiedGrads,
    SendActivation,
    SendGrad,
)


class ScheduleExecutor:
    """Execute a schedule class across all stages of a staged model.

    Args:
      stage_fns: one ``fn(params, x) -> y`` per stage.
      stage_params: one params pytree per stage.
      loss_fn: ``loss_fn(last_stage_output, microbatch_target) -> scalar``;
        the per-microbatch losses are averaged (grad seeds are scaled by 1/M,
        matching the reference's gas-style loss scaling).
    """

    def __init__(self, stage_fns: Sequence[Callable], stage_params: Sequence[Any],
                 loss_fn: Callable):
        assert len(stage_fns) == len(stage_params)
        self.stage_fns = list(stage_fns)
        self.stage_params = list(stage_params)
        self.loss_fn = loss_fn
        self.stages = len(stage_fns)

    def run(self, schedule_cls, inputs: Sequence[Any], targets: Sequence[Any]):
        """Interpret ``schedule_cls(M, S, stage_id)`` for every stage.

        Returns ``(mean_loss, per_stage_param_grads)``.
        """
        S, M = self.stages, len(inputs)
        scheds = [iter(schedule_cls(micro_batches=M, stages=S, stage_id=s).steps())
                  for s in range(S)]
        # p2p queues between neighbors; (kind, from_stage) -> FIFO of (mb, value)
        act_q: List[deque] = [deque() for _ in range(S)]   # act_q[s]: s-1 -> s
        grad_q: List[deque] = [deque() for _ in range(S)]  # grad_q[s]: s+1 -> s
        # per-stage buffer slots: buffer_id -> microbatch occupying it
        buffers: List[Dict[int, int]] = [dict() for _ in range(S)]
        # saved forward state per (stage, microbatch)
        vjps: Dict[Tuple[int, int], Any] = {}
        # pending outbound value per (s, mb): the stage's forward OUTPUT —
        # consumed by SendActivation; on the last stage it is replaced by the
        # loss-gradient seed that BackwardPass consumes
        outbox: Dict[Tuple[int, int], Any] = {}
        out_grads: List[Any] = [jax.tree.map(jnp.zeros_like, p) for p in self.stage_params]
        losses: List[Any] = []
        fwd_count = [0] * S
        bwd_count = [0] * S
        optimizer_stepped = [False] * S

        def fwd(s: int, mb: int, buf: int, x: Any):
            prev = buffers[s].get(buf)
            if prev is not None:
                raise RuntimeError(
                    f"stage {s}: ForwardPass(mb={mb}) into buffer {buf} still "
                    f"holding microbatch {prev} (backward not yet run) — "
                    f"schedule violates num_pipe_buffers")
            buffers[s][buf] = mb
            y, vjp = jax.vjp(self.stage_fns[s], self.stage_params[s], x)
            vjps[(s, mb)] = vjp
            fwd_count[s] += 1
            return y

        def bwd(s: int, mb: int, buf: int, gy: Any):
            if buffers[s].get(buf) != mb:
                raise RuntimeError(
                    f"stage {s}: BackwardPass(mb={mb}) buffer {buf} holds "
                    f"{buffers[s].get(buf)}")
            del buffers[s][buf]
            gparams, gx = vjps.pop((s, mb))(gy)
            out_grads[s] = jax.tree.map(jnp.add, out_grads[s], gparams)
            bwd_count[s] += 1
            return gx

        tick = 0
        done = [False] * S
        while not all(done):
            tick += 1
            if tick > 4 * (M + S) + 8:
                raise RuntimeError("schedule did not terminate (deadlock?)")
            # Sends issued during this tick are buffered and published only
            # after EVERY stage has processed the tick: with stages advanced
            # in ascending order, stage s's send would otherwise be visible
            # to stage s+1's recv within the SAME tick — laxer than real
            # one-tick p2p latency, letting a schedule pass here yet deadlock
            # on real async sends (round-3 advice, pipe_executor.py:120).
            pending_sends: List[Tuple[deque, Tuple[int, Any]]] = []
            for s in range(S):
                if done[s]:
                    continue
                try:
                    cmds = next(scheds[s])
                except StopIteration:
                    done[s] = True
                    continue
                # track the microbatch flowing through this tick's cmd list
                cur_mb = None
                cur_x = None
                cur_g = None
                for cmd in cmds:
                    if isinstance(cmd, LoadMicroBatch):
                        cur_mb = fwd_count[s]
                        cur_x = inputs[cur_mb]
                    elif isinstance(cmd, RecvActivation):
                        if not act_q[s]:
                            raise RuntimeError(
                                f"stage {s} tick {tick}: RecvActivation on an "
                                f"empty queue — peer never sent (deadlock)")
                        cur_mb, cur_x = act_q[s].popleft()
                    elif isinstance(cmd, ForwardPass):
                        y = fwd(s, cur_mb, cmd.buffer_id, cur_x)
                        outbox[(s, cur_mb)] = y
                        if s == S - 1:
                            # last stage: loss + immediate grad seed
                            loss, loss_vjp = jax.vjp(
                                lambda o: self.loss_fn(o, targets[cur_mb]), y)
                            losses.append(loss)
                            (seed,) = loss_vjp(jnp.ones_like(loss) / M)
                            outbox[(s, cur_mb)] = seed
                    elif isinstance(cmd, SendActivation):
                        mb = buffers[s].get(cmd.buffer_id)
                        pending_sends.append((act_q[s + 1], (mb, outbox.pop((s, mb)))))
                    elif isinstance(cmd, RecvGrad):
                        if not grad_q[s]:
                            raise RuntimeError(
                                f"stage {s} tick {tick}: RecvGrad on an empty "
                                f"queue — peer never sent (deadlock)")
                        cur_mb, cur_g = grad_q[s].popleft()
                    elif isinstance(cmd, BackwardPass):
                        if s == S - 1:
                            cur_mb = buffers[s].get(cmd.buffer_id)
                            cur_g = outbox.pop((s, cur_mb))
                        gx = bwd(s, cur_mb, cmd.buffer_id, cur_g)
                        cur_g = gx
                    elif isinstance(cmd, SendGrad):
                        pending_sends.append((grad_q[s - 1], (cur_mb, cur_g)))
                    elif isinstance(cmd, (ReduceGrads, ReduceTiedGrads)):
                        pass  # dp reduction — single-replica simulation
                    elif isinstance(cmd, OptimizerStep):
                        optimizer_stepped[s] = True
                    else:
                        raise RuntimeError(f"unknown instruction {cmd!r}")
            for queue, item in pending_sends:
                queue.append(item)

        if any(c != M for c in fwd_count) or any(c != M for c in bwd_count):
            raise RuntimeError(
                f"schedule incomplete: fwd {fwd_count} bwd {bwd_count} (want {M})")
        if not all(optimizer_stepped):
            raise RuntimeError("schedule never issued OptimizerStep on some stage")
        mean_loss = jnp.mean(jnp.stack(losses))
        return mean_loss, out_grads
