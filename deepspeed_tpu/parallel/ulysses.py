"""Ulysses sequence parallelism.

TPU-native re-design of DeepSpeed-Ulysses (reference ``sequence/layer.py``:
``DistributedAttention`` :311, ``_SeqAllToAll`` :257, ``single_all_to_all``
:221). The algorithm: activations arrive sequence-sharded; an all-to-all over
the sp group re-shards them head-wise so each rank computes exact attention
over the full sequence for a head subset; a second all-to-all restores
sequence sharding. Comm volume O(N/P) per device vs ring attention's O(N).

Two implementations:

  1. ``ulysses_shard``/``ulysses_unshard``: sharding *constraints* that XLA
     lowers to the optimal all-to-all on the ICI mesh — the idiomatic SPMD
     form used by the CausalLM model. GQA/uneven head counts need no special
     path (the reference needs ``uneven_heads_all2all`` :111); the partitioner
     handles non-divisible head axes by local replication.

  2. ``DistributedAttention``: explicit ``shard_map`` + ``jax.lax.all_to_all``
     wrapper around any local attention callable — API parity with the
     reference class, useful when the caller manages its own mesh axes.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.topology.mesh import BATCH_AXES, get_mesh, has_mesh


def _live_batch_axes(mesh: Mesh) -> Optional[Tuple[str, ...]]:
    axes = tuple(a for a in BATCH_AXES if mesh.shape[a] > 1)
    return axes or None


def sp_active() -> bool:
    return has_mesh() and get_mesh().shape["sp"] > 1


def _auto_only(entry):
    """Drop axes that are MANUAL in the current trace context (inside a
    shard_map, e.g. the ZeRO++/1-bit micro fn's manual data axes): a
    with_sharding_constraint there may only name the remaining auto axes —
    the manual ones are already local. (jax raises otherwise.)"""
    from jax._src import mesh as mesh_lib

    manual = set(getattr(mesh_lib.get_abstract_mesh(), "manual_axes", ()) or ())
    if not manual or entry is None:
        return entry
    names = entry if isinstance(entry, tuple) else (entry,)
    keep = tuple(a for a in names if a not in manual)
    return keep if len(keep) > 1 else (keep[0] if keep else None)


def ulysses_shard(x: jax.Array) -> jax.Array:
    """[B, S, H, D] seq-sharded -> head-sharded (the first all-to-all)."""
    if not sp_active():
        return x
    mesh = get_mesh()
    spec = P(_auto_only(_live_batch_axes(mesh)), None, _auto_only("sp"), None)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def ulysses_unshard(x: jax.Array) -> jax.Array:
    """[B, S, H, D] head-sharded -> seq-sharded (the second all-to-all)."""
    if not sp_active():
        return x
    mesh = get_mesh()
    spec = P(_auto_only(_live_batch_axes(mesh)), _auto_only("sp"), None, None)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


class DistributedAttention:
    """Explicit all-to-all wrapper (reference ``DistributedAttention`` :311).

    ``local_attn(q, k, v, *args)`` operates on [B, S_full, H_local, D].
    Inputs to ``__call__`` are [B, S_local, H, D] per sp rank. scatter_idx /
    gather_idx follow the reference convention (head dim scattered, seq dim
    gathered on the way in; reversed on the way out).
    """

    def __init__(
        self,
        local_attn: Callable,
        mesh: Optional[Mesh] = None,
        scatter_idx: int = 2,
        gather_idx: int = 1,
    ):
        self.local_attn = local_attn
        self.mesh = mesh
        self.scatter_idx = scatter_idx
        self.gather_idx = gather_idx

    def __call__(self, query: jax.Array, key: jax.Array, value: jax.Array, *args, **kwargs):
        mesh = self.mesh if self.mesh is not None else get_mesh()
        sp = mesh.shape["sp"]
        if sp == 1:
            return self.local_attn(query, key, value, *args, **kwargs)
        if query.shape[self.scatter_idx] % sp:
            raise ValueError(
                f"head dim {query.shape[self.scatter_idx]} not divisible by sp={sp}; "
                "use the constraint-based ulysses_shard path for uneven heads"
            )

        from deepspeed_tpu.utils.compat import shard_map

        batch_axes = _live_batch_axes(mesh)
        in_spec = P(batch_axes, "sp", None, None)
        out_spec = P(batch_axes, "sp", None, None)

        def per_rank(q, k, v):
            # q: [B_local, S_local, H, D] -> a2a -> [B_local, S_full, H/sp, D]
            a2a = lambda t: jax.lax.all_to_all(
                t, "sp", split_axis=self.scatter_idx, concat_axis=self.gather_idx, tiled=True
            )
            q, k, v = a2a(q), a2a(k), a2a(v)
            o = self.local_attn(q, k, v, *args, **kwargs)
            return jax.lax.all_to_all(
                o, "sp", split_axis=self.gather_idx, concat_axis=self.scatter_idx, tiled=True
            )

        return shard_map(
            per_rank,
            mesh=mesh,
            in_specs=(in_spec, in_spec, in_spec),
            out_specs=out_spec,
            check_vma=False,
        )(query, key, value)


def sequence_parallel_cross_entropy_valid() -> bool:
    """The loss in models/transformer computes token NLL locally and reduces
    with a global mean — under jit the sp-sharded sum is exact, so no special
    vocab/sequence-parallel CE (reference ``sequence/cross_entropy.py``) is
    needed. Kept as documentation hook."""
    return True
