"""Compiled SPMD pipeline parallelism.

TPU-native replacement for the reference's interpreted 1F1B instruction
schedule (``runtime/pipe/engine.py:61`` ``PipelineEngine._exec_schedule``,
``runtime/pipe/schedule.py:189`` ``TrainSchedule``, ``runtime/pipe/p2p.py``).

The reference runs a per-rank Python loop issuing torch p2p sends/recvs per
microbatch. On TPU the whole pipeline is ONE jitted program: stage parameters
are sharded over the ``pp`` mesh axis, and a ``lax.scan`` over schedule ticks
moves activations between neighbor stages with ``lax.ppermute`` (collective
permute rides the ICI torus). Backward-through-the-scan gives the reverse
pipeline schedule automatically — XLA schedules the backward ppermutes the
same way the reference interprets ``SendGrad/RecvGrad`` instructions.

Schedule: GPipe-style fill-and-drain over ``T = M + S - 1`` ticks (M
microbatches, S stages). At tick ``t`` stage ``i`` processes microbatch
``t - i`` (when valid). Activation memory matches 1F1B's steady state when
``stage_fn`` is rematerialized (``jax.checkpoint``), because XLA frees
per-tick activations after each backward tick.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

# stage_fn(stage_params, carry, rng) -> carry
StageFn = Callable[[Any, Any, jax.Array], Any]


def spmd_pipeline(
    stage_fn: StageFn,
    stage_params: Any,
    stream: Any,
    *,
    mesh: Mesh,
    rng: jax.Array,
    side_stream: Any = None,
) -> Any:
    """Run ``stage_fn`` as a pipeline over the ``pp`` mesh axis.

    Args:
      stage_fn: processes ONE microbatch through ONE stage's layers. Called as
        ``stage_fn(local_params, carry, rng)`` — or, when ``side_stream`` is
        given, ``stage_fn(local_params, carry, side, rng)``. Receives the
        stage-local slice of ``stage_params`` (leading layer dim divided by
        the number of stages).
      stage_params: pytree whose leaves are stacked per-layer ``[L, ...]``;
        dim 0 is sharded over ``pp`` (L % pp_size == 0).
      stream: microbatch carry stream pytree, leaves ``[M, ...]``; replicated
        over ``pp`` (may be sharded over other mesh axes, e.g. batch over dp).
        These leaves travel stage-to-stage through the ring.
      mesh: the device mesh with a ``pp`` axis.
      rng: base PRNG key; folded per tick for dropout.
      side_stream: optional pytree of per-microbatch inputs ``[M, ...]`` that
        are *invariant across stages* (e.g. attention masks, positions). They
        are indexed locally per tick instead of riding the ppermute ring, so
        they cost no inter-stage communication.

    Returns:
      Pytree of ``[M, ...]`` last-stage outputs (of the carry stream only),
      replicated over ``pp``.

    Must be called under ``jax.jit`` (the engine always does): eager dispatch
    of partial-manual shard_map trips an upstream jax check in this version.
    """
    S = mesh.shape["pp"]
    M = jax.tree_util.tree_leaves(stream)[0].shape[0]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] % S:
            raise ValueError(
                f"stacked layer dim {leaf.shape[0]} not divisible by pp={S}; "
                f"choose num_layers divisible by the pp mesh axis"
            )

    def call_stage(params, carry, side, r):
        if side_stream is None:
            return stage_fn(params, carry, r)
        return stage_fn(params, carry, side, r)

    def side_at(side, idx):
        return jax.tree_util.tree_map(lambda v: v[jnp.clip(idx, 0, M - 1)], side)

    if S == 1:
        def body(_, xs):
            mb, t = xs
            side = side_at(side_stream, t) if side_stream is not None else None
            return (), call_stage(stage_params, mb, side, jax.random.fold_in(rng, t))

        _, out = lax.scan(body, (), (stream, jnp.arange(M)))
        return out

    T = M + S - 1
    perm = [(j, (j + 1) % S) for j in range(S)]

    def run(params, stream, side_stream, rng):
        i = lax.axis_index("pp")

        # Pad the stream with S-1 drain ticks (zeros; dead compute is masked).
        def pad(x):
            return jnp.concatenate([x, jnp.zeros((S - 1,) + x.shape[1:], x.dtype)], axis=0)

        padded = jax.tree_util.tree_map(pad, stream)
        zero_carry = jax.tree_util.tree_map(lambda x: jnp.zeros(x.shape[1:], x.dtype), stream)
        out_init = jax.tree_util.tree_map(jnp.zeros_like, stream)

        def tick(carry, xs):
            recv, out_buf = carry
            mb, t = xs
            # Stage 0 ingests the next microbatch; others consume the permuted
            # activation from their predecessor (reference RecvActivation).
            x = jax.tree_util.tree_map(lambda a, b: jnp.where(i == 0, a, b), mb, recv)
            # Stage i processes microbatch t-i: index its side inputs locally.
            side = side_at(side_stream, t - i) if side_stream is not None else None
            y = call_stage(params, x, side, jax.random.fold_in(rng, t))
            # Last stage commits microbatch t-(S-1) to the output buffer.
            mb_idx = t - (S - 1)
            write = (i == S - 1) & (mb_idx >= 0)
            idx = jnp.maximum(mb_idx, 0)
            out_buf = jax.tree_util.tree_map(
                lambda buf, yv: jnp.where(
                    write,
                    lax.dynamic_update_slice_in_dim(buf, yv[None].astype(buf.dtype), idx, 0),
                    buf,
                ),
                out_buf,
                y,
            )
            # Shift activations to the next stage (reference SendActivation).
            recv = jax.tree_util.tree_map(lambda v: lax.ppermute(v, "pp", perm), y)
            return (recv, out_buf), None

        (_, out_buf), _ = lax.scan(tick, (zero_carry, out_init), (padded, jnp.arange(T)))
        # Only the last stage holds real outputs; broadcast to all pp ranks.
        return jax.tree_util.tree_map(
            lambda v: lax.psum(jnp.where(i == S - 1, v, jnp.zeros_like(v)), "pp"), out_buf
        )

    return jax.shard_map(
        run,
        mesh=mesh,
        axis_names={"pp"},
        in_specs=(P("pp"), P(), P(), P()),
        out_specs=P(),
        check_vma=False,
    )(stage_params, stream, side_stream, rng)


def pipeline_bubble_fraction(num_microbatches: int, num_stages: int) -> float:
    """Idle fraction of the fill-and-drain schedule: (S-1)/(M+S-1)."""
    return (num_stages - 1) / (num_microbatches + num_stages - 1)
