"""Compiled SPMD pipeline parallelism.

TPU-native replacement for the reference's interpreted 1F1B instruction
schedule (``runtime/pipe/engine.py:61`` ``PipelineEngine._exec_schedule``,
``runtime/pipe/schedule.py:189`` ``TrainSchedule``, ``runtime/pipe/p2p.py``).

The reference runs a per-rank Python loop issuing torch p2p sends/recvs per
microbatch. On TPU the whole pipeline is ONE jitted program: stage parameters
are sharded over the ``pp`` mesh axis, and a ``lax.scan`` over schedule ticks
moves activations between neighbor stages with ``lax.ppermute`` (collective
permute rides the ICI torus). Backward-through-the-scan gives the reverse
pipeline schedule automatically — XLA schedules the backward ppermutes the
same way the reference interprets ``SendGrad/RecvGrad`` instructions.

Schedule: GPipe-style fill-and-drain over ``T = M + S - 1`` ticks (M
microbatches, S stages). At tick ``t`` stage ``i`` processes microbatch
``t - i`` (when valid). Activation memory matches 1F1B's steady state when
``stage_fn`` is rematerialized (``jax.checkpoint``), because XLA frees
per-tick activations after each backward tick.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

# stage_fn(stage_params, carry, rng) -> carry
StageFn = Callable[[Any, Any, jax.Array], Any]


def _make_call_stage(stage_fn, side_stream):
    def call_stage(params, carry, side, r):
        if side_stream is None:
            return stage_fn(params, carry, r)
        return stage_fn(params, carry, side, r)
    return call_stage


def _make_side_at(M):
    def side_at(side, idx):
        return jax.tree_util.tree_map(lambda v: v[jnp.clip(idx, 0, M - 1)], side)
    return side_at


def _check_layer_dims(stage_params, div: int, what: str):
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] % div:
            raise ValueError(
                f"stacked layer dim {leaf.shape[0]} not divisible by {what}={div}; "
                f"choose num_layers divisible by it")


def spmd_pipeline(
    stage_fn: StageFn,
    stage_params: Any,
    stream: Any,
    *,
    mesh: Mesh,
    rng: jax.Array,
    side_stream: Any = None,
) -> Any:
    """Run ``stage_fn`` as a pipeline over the ``pp`` mesh axis.

    Args:
      stage_fn: processes ONE microbatch through ONE stage's layers. Called as
        ``stage_fn(local_params, carry, rng)`` — or, when ``side_stream`` is
        given, ``stage_fn(local_params, carry, side, rng)``. Receives the
        stage-local slice of ``stage_params`` (leading layer dim divided by
        the number of stages).
      stage_params: pytree whose leaves are stacked per-layer ``[L, ...]``;
        dim 0 is sharded over ``pp`` (L % pp_size == 0).
      stream: microbatch carry stream pytree, leaves ``[M, ...]``; replicated
        over ``pp`` (may be sharded over other mesh axes, e.g. batch over dp).
        These leaves travel stage-to-stage through the ring.
      mesh: the device mesh with a ``pp`` axis.
      rng: base PRNG key; folded per tick for dropout.
      side_stream: optional pytree of per-microbatch inputs ``[M, ...]`` that
        are *invariant across stages* (e.g. attention masks, positions). They
        are indexed locally per tick instead of riding the ppermute ring, so
        they cost no inter-stage communication.

    Returns:
      Pytree of ``[M, ...]`` last-stage outputs (of the carry stream only),
      replicated over ``pp``.

    Must be called under ``jax.jit`` (the engine always does): eager dispatch
    of partial-manual shard_map trips an upstream jax check in this version.
    """
    S = mesh.shape["pp"]
    M = jax.tree_util.tree_leaves(stream)[0].shape[0]
    _check_layer_dims(stage_params, S, "pp")
    call_stage = _make_call_stage(stage_fn, side_stream)
    side_at = _make_side_at(M)

    if S == 1:
        def body(_, xs):
            mb, t = xs
            side = side_at(side_stream, t) if side_stream is not None else None
            return (), call_stage(stage_params, mb, side, jax.random.fold_in(rng, t))

        _, out = lax.scan(body, (), (stream, jnp.arange(M)))
        return out

    T = M + S - 1
    perm = [(j, (j + 1) % S) for j in range(S)]

    def run(params, stream, side_stream, rng):
        i = lax.axis_index("pp")

        # Pad the stream with S-1 drain ticks (zeros; dead compute is masked).
        def pad(x):
            return jnp.concatenate([x, jnp.zeros((S - 1,) + x.shape[1:], x.dtype)], axis=0)

        padded = jax.tree_util.tree_map(pad, stream)
        zero_carry = jax.tree_util.tree_map(lambda x: jnp.zeros(x.shape[1:], x.dtype), stream)
        out_init = jax.tree_util.tree_map(jnp.zeros_like, stream)

        def tick(carry, xs):
            recv, out_buf = carry
            mb, t = xs
            # Stage 0 ingests the next microbatch; others consume the permuted
            # activation from their predecessor (reference RecvActivation).
            x = jax.tree_util.tree_map(lambda a, b: jnp.where(i == 0, a, b), mb, recv)
            # Stage i processes microbatch t-i: index its side inputs locally.
            side = side_at(side_stream, t - i) if side_stream is not None else None
            y = call_stage(params, x, side, jax.random.fold_in(rng, t))
            # Last stage commits microbatch t-(S-1) to the output buffer.
            mb_idx = t - (S - 1)
            write = (i == S - 1) & (mb_idx >= 0)
            idx = jnp.maximum(mb_idx, 0)
            out_buf = jax.tree_util.tree_map(
                lambda buf, yv: jnp.where(
                    write,
                    lax.dynamic_update_slice_in_dim(buf, yv[None].astype(buf.dtype), idx, 0),
                    buf,
                ),
                out_buf,
                y,
            )
            # Shift activations to the next stage (reference SendActivation).
            recv = jax.tree_util.tree_map(lambda v: lax.ppermute(v, "pp", perm), y)
            return (recv, out_buf), None

        (_, out_buf), _ = lax.scan(tick, (zero_carry, out_init), (padded, jnp.arange(T)))
        # Only the last stage holds real outputs; broadcast to all pp ranks.
        return jax.tree_util.tree_map(
            lambda v: lax.psum(jnp.where(i == S - 1, v, jnp.zeros_like(v)), "pp"), out_buf
        )

    from deepspeed_tpu.utils.compat import shard_map

    return shard_map(
        run,
        mesh=mesh,
        axis_names={"pp"},
        in_specs=(P("pp"), P(), P(), P()),
        out_specs=P(),
        check_vma=False,
    )(stage_params, stream, side_stream, rng)


def pipeline_bubble_fraction(num_microbatches: int, num_stages: int) -> float:
    """Idle fraction of the fill-and-drain schedule: (S-1)/(M+S-1)."""
    return (num_stages - 1) / (num_microbatches + num_stages - 1)


def spmd_pipeline_interleaved(
    stage_fn: StageFn,
    stage_params: Any,
    stream: Any,
    *,
    mesh: Mesh,
    rng: jax.Array,
    virtual: int,
    side_stream: Any = None,
    chunk_remat: bool = True,
) -> Any:
    """Interleaved (virtual-stage) pipeline: bubble shrinks by ``virtual``.

    ``chunk_remat`` (default on) wraps the per-tick (chunk-select + stage)
    in ``jax.checkpoint``: the dynamic chunk gather would otherwise be a
    per-tick residual — the FULL chunk's bytes saved every tick, O(T x
    chunk) HBM — while under checkpoint the gather is recomputed in the
    backward from the scan-invariant local stack (saved once). The cost is
    one extra stage forward in the backward, i.e. exactly standard remat;
    set False only for small models where activation memory is free.

    Megatron-style interleaving the reference does NOT have (its
    ``TrainSchedule`` is plain 1F1B): each device owns ``virtual`` chunks of
    ``1/(S*virtual)`` of the layers, placed round-robin so virtual stage
    ``j = c*S + i`` lives on device ``i``. Every j -> j+1 hop is a ring
    neighbor, so ONE ppermute per tick still suffices.

    The lockstep schedule is closed-form and conflict-free: microbatch ``m``
    runs virtual stage ``j = c*S + i`` at tick

        t(m, j) = (m // S) * S * V + c * S + (m % S) + i

    (per device-tick the decomposition ``t - i = m' + S*(c + V*g)`` is a
    base-S digit expansion, so at most one (m, c) is active, and consecutive
    stages differ by exactly one tick — activations arrive exactly when
    consumed, no buffering). Fill is ``S - 1`` CHUNK-ticks, i.e. ``(S-1)/V``
    stage-times: bubble ``(S-1)/(M*V + S - 1)`` vs GPipe's ``(S-1)/(M+S-1)``.

    Requires ``M % S == 0`` and ``L % (S * virtual) == 0``.

    PERF NOTE: the round-robin layer permutation below runs per call on the
    pp-sharded stack, so XLA reshards O(param bytes) over the pp axis each
    step (plus the transposed scatter in backward) — comparable to one
    ZeRO-3-style allgather. Storing the engine's stacked params pre-permuted
    (and adjusting checkpoint canonicalization) would eliminate it; measure
    on real hardware before taking that complexity.
    """
    S = mesh.shape["pp"]
    V = int(virtual)
    if V <= 1:
        return spmd_pipeline(stage_fn, stage_params, stream, mesh=mesh, rng=rng,
                             side_stream=side_stream)
    M = jax.tree_util.tree_leaves(stream)[0].shape[0]
    L = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    _check_layer_dims(stage_params, S * V, "pp*virtual")
    if M % S:
        raise ValueError(f"microbatches {M} not divisible by pp={S} (interleaved schedule)")
    Lc = L // (S * V)

    # Reorder layers so device i's contiguous P("pp") shard holds its V chunks
    # [c=0..V-1] stacked: global layer order = vstage (c*S + i) blocks.
    order = jnp.asarray(
        [(c * S + i) * Lc + l for i in range(S) for c in range(V) for l in range(Lc)],
        jnp.int32,
    )
    params_z = jax.tree_util.tree_map(lambda p: jnp.take(p, order, axis=0), stage_params)

    call_stage = _make_call_stage(stage_fn, side_stream)
    side_at = _make_side_at(M)

    def chunked_call(local, c, x, side, r):
        chunk = jax.tree_util.tree_map(
            lambda p: lax.dynamic_index_in_dim(p, c, 0, keepdims=False), local)
        return call_stage(chunk, x, side, r)

    if chunk_remat:
        # See docstring: keeps the per-tick residual at the boundary carry
        # (x) instead of the gathered chunk's full bytes.
        chunked_call = jax.checkpoint(chunked_call, prevent_cse=False)

    T = M * V + S - 1
    perm = [(j, (j + 1) % S) for j in range(S)]

    def run(params, stream, side_stream, rng):
        i = lax.axis_index("pp")
        # local params: [S-shard of L] -> [V, Lc, ...]
        local = jax.tree_util.tree_map(
            lambda p: p.reshape((V, Lc) + p.shape[1:]), params)
        zero_carry = jax.tree_util.tree_map(lambda x: jnp.zeros(x.shape[1:], x.dtype), stream)
        out_init = jax.tree_util.tree_map(jnp.zeros_like, stream)

        def tick(carry, t):
            recv, out_buf = carry
            x_rel = t - i
            g = x_rel // (S * V)
            r = x_rel % (S * V)
            c = r // S
            m = g * S + (r % S)
            valid = (x_rel >= 0) & (m >= 0) & (m < M)
            ingest = valid & (i == 0) & (c == 0)
            commit = valid & (i == S - 1) & (c == V - 1)

            m_safe = jnp.clip(m, 0, M - 1)
            mb = jax.tree_util.tree_map(lambda v: v[m_safe], stream)
            x = jax.tree_util.tree_map(lambda a, b: jnp.where(ingest, a, b), mb, recv)
            side = side_at(side_stream, m_safe) if side_stream is not None else None
            y = chunked_call(local, jnp.clip(c, 0, V - 1), x, side,
                             jax.random.fold_in(rng, t))
            out_buf = jax.tree_util.tree_map(
                lambda buf, yv: jnp.where(
                    commit,
                    lax.dynamic_update_slice_in_dim(buf, yv[None].astype(buf.dtype), m_safe, 0),
                    buf,
                ),
                out_buf,
                y,
            )
            recv = jax.tree_util.tree_map(lambda v: lax.ppermute(v, "pp", perm), y)
            return (recv, out_buf), None

        (_, out_buf), _ = lax.scan(tick, (zero_carry, out_init), jnp.arange(T))
        return jax.tree_util.tree_map(
            lambda v: lax.psum(jnp.where(i == S - 1, v, jnp.zeros_like(v)), "pp"), out_buf
        )

    from deepspeed_tpu.utils.compat import shard_map

    return shard_map(
        run,
        mesh=mesh,
        axis_names={"pp"},
        in_specs=(P("pp"), P(), P(), P()),
        out_specs=P(),
        check_vma=False,
    )(params_z, stream, side_stream, rng)


def pipeline_bubble_fraction_interleaved(num_microbatches: int, num_stages: int,
                                         virtual: int) -> float:
    """Idle fraction with virtual-stage interleaving: (S-1)/(M*V + S-1)."""
    return (num_stages - 1) / (num_microbatches * virtual + num_stages - 1)
