"""Pipeline-parallel training engine.

TPU-native analog of ``deepspeed/runtime/pipe/engine.py`` (``PipelineEngine``
:61). The reference interprets a 1F1B instruction schedule with torch p2p
sends; on TPU the plan is a compiled microbatch loop over the ``pp`` mesh axis
(collective_permute between stage neighbors inside one jitted program).

Current state: with ``pp == 1`` the PipelineModule executes as a plain layer
chain through the standard engine (sequential composition + loss_fn), which is
the reference's degenerate single-stage path. The multi-stage 1F1B schedule is
implemented in ``parallel/pipe_schedule.py`` (see TrainSchedule) and wired here
as it lands.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from deepspeed_tpu.runtime.engine import DeepSpeedTPUEngine
from deepspeed_tpu.runtime.model import ModelSpec
from deepspeed_tpu.parallel.pipeline import PipelineModule


def _spec_from_pipeline_module(module: PipelineModule) -> ModelSpec:
    """Sequentially compose layer specs into one ModelSpec (pp=1 path)."""
    layers = [spec.build() for spec in module.layer_specs]
    loss_fn = module.loss_fn

    def init_fn(rng):
        params = []
        carry_shape = None
        for i, layer in enumerate(layers):
            layer_rng = jax.random.fold_in(rng, i)
            if hasattr(layer, "init"):
                raise ValueError(
                    "Flax modules inside PipelineModule need explicit example "
                    "activations; use LayerSpec with pure (init, apply) pairs "
                    "or pass model_parameters to initialize()"
                )
            params.append(None)
        return params

    def loss(params, batch, rng):
        h = batch
        for i, layer in enumerate(layers):
            h = layer(h) if params[i] is None else layer(params[i], h)
        if loss_fn is not None:
            if isinstance(batch, dict) and "labels" in batch:
                return loss_fn(h, batch["labels"])
            return loss_fn(h, batch)
        return h

    return ModelSpec(init_fn=init_fn, loss_fn=loss, name="pipeline")


class PipelineEngine(DeepSpeedTPUEngine):
    """Engine for PipelineModule models (reference ``pipe/engine.py:61``)."""

    def __init__(self, module: PipelineModule, config, mesh=None, **kwargs):
        import deepspeed_tpu.topology.mesh as mesh_mod

        self.pipeline_module = module
        pp = mesh.shape["pp"] if mesh is not None else getattr(config.mesh_config, "pp", 1)
        if pp > 1:
            raise NotImplementedError(
                "multi-stage pipeline execution (pp > 1) is under construction: "
                "the 1F1B schedule lives in parallel/pipe_schedule.py and is not "
                "yet wired into a compiled stage loop. Use pp=1 (layer chaining) "
                "or shard via dp/fsdp/tp/sp for now."
            )
        spec = _spec_from_pipeline_module(module)
        super().__init__(model=spec, config=config, mesh=mesh, **kwargs)

    def train_batch(self, batch: Any = None, data_iter: Optional[Any] = None):
        return super().train_batch(batch=batch, data_iter=data_iter)
