"""Pipeline-parallel training engine for the LayerSpec API.

TPU-native analog of ``deepspeed/runtime/pipe/engine.py`` (``PipelineEngine``
:61) + ``runtime/pipe/module.py`` (``PipelineModule.forward`` :340). The
reference interprets a 1F1B instruction schedule with torch p2p sends per
microbatch; here the WHOLE pipeline is one jitted SPMD program: the repeated
layer stack's parameters are stacked ``[L, ...]`` and sharded over the ``pp``
mesh axis, and ``parallel/pipeline_spmd.spmd_pipeline`` runs the fill-and-
drain microbatch loop with ``lax.ppermute`` between stage neighbors.

Layer conventions (``LayerSpec.build()`` result):
  - ``(init, apply)`` pair: ``init(rng, x) -> params``, ``apply(params, x)``
    (or ``apply(params, x, rng)``) ``-> y``
  - a Flax linen module: ``module.init(rng, x)`` / ``module.apply``
  - a plain callable ``x -> y`` (no parameters)

Stage mapping: the longest contiguous run of layers with identical parameter
structure (the repeated transformer blocks in every real pipeline model) is
stacked and pipelined over ``pp``; the layers before/after it (embedding,
norm, LM head — a few % of FLOPs) run replicated on every pp rank. This
differs from the reference's contiguous layer partition (``_partition_layers``
pipe/module.py:393) but computes the same function: replicating the cheap
boundary layers costs far less than the ppermute hops they would otherwise
need, and XLA DCEs the copies' gradients into one psum.

``TiedLayerSpec`` layers share one parameter subtree keyed by ``key``
(reference tied-weight groups, ``pipe/module.py:454``): reuse falls out of
autodiff instead of a ReduceTiedGrads instruction.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.engine import DeepSpeedTPUEngine
from deepspeed_tpu.runtime.model import ModelSpec
from deepspeed_tpu.parallel.pipeline import PipelineModule, TiedLayerSpec
from jax.sharding import PartitionSpec as P


class _Layer:
    """Uniform adapter over the three layer forms."""

    def __init__(self, spec, built):
        self.spec = spec
        self.tied_key = spec.key if isinstance(spec, TiedLayerSpec) else None
        self.typename = getattr(spec.typename, "__name__", str(spec.typename))
        if isinstance(built, (tuple, list)) and len(built) == 2 and all(callable(f) for f in built):
            self._init, self._apply = built
            self.has_params = True
            self._wants_rng = len(inspect.signature(self._apply).parameters) >= 3
        elif hasattr(built, "init") and hasattr(built, "apply"):  # flax module
            module = built

            def finit(rng, x):
                return module.init({"params": rng}, x)["params"]

            def fapply(params, x, rng=None):
                rngs = {"dropout": rng} if rng is not None else None
                return module.apply({"params": params}, x, rngs=rngs)

            self._init, self._apply = finit, fapply
            self.has_params = True
            self._wants_rng = True
        elif callable(built):
            fn = built
            self._init = None
            self._apply = lambda params, x, rng=None: fn(x)
            self.has_params = False
            self._wants_rng = False
        else:
            raise TypeError(
                f"LayerSpec built {type(built)}; expected (init, apply) pair, "
                f"flax module, or callable"
            )
        # TiedLayerSpec.forward_fn: alternate forward over the shared params
        # (reference pipe/module.py:77 — e.g. the LM head reusing the
        # embedding matrix).
        if isinstance(spec, TiedLayerSpec) and spec.forward_fn is not None:
            fwd = spec.forward_fn
            self._apply = lambda params, x, rng=None: fwd(params, x)
            self._wants_rng = False

    def init(self, rng, x):
        return self._init(rng, x) if self.has_params else None

    def apply(self, params, x, rng):
        if self._wants_rng:
            return self._apply(params, x, rng)
        return self._apply(params, x)


def _adapt_layers(module: PipelineModule) -> List[_Layer]:
    return [_Layer(spec, spec.build()) for spec in module.layer_specs]


def _discover(layers: List[_Layer], example_input, seed: int):
    """Abstract-init every layer to learn param/activation structure.

    Returns (param_shapes per layer [abstract], activation shapes) without
    running any real compute (jax.eval_shape end-to-end).
    """
    rng = jax.random.PRNGKey(seed)

    def chain(rng, x):
        tied: dict = {}
        per_layer = []
        for i, layer in enumerate(layers):
            lrng = jax.random.fold_in(rng, i)
            if not layer.has_params:
                per_layer.append(None)
                x = layer.apply(None, x, lrng)
                continue
            if layer.tied_key is not None:
                # tied layers never join the stacked run; record None
                if layer.tied_key not in tied:
                    tied[layer.tied_key] = layer.init(lrng, x)
                p = tied[layer.tied_key]
                per_layer.append(None)
            else:
                p = layer.init(lrng, x)
                per_layer.append(p)
            x = layer.apply(p, x, lrng)
        return per_layer, tied

    shapes, tied_shapes = jax.eval_shape(chain, rng, example_input)
    return shapes, tied_shapes


def _stackable_run(layers: List[_Layer], shapes) -> Tuple[int, int]:
    """Longest contiguous run of same-structure, untied, param'd layers."""

    def sig(i):
        layer, shp = layers[i], shapes[i]
        if not layer.has_params or layer.tied_key is not None:
            return None
        leaves, treedef = jax.tree_util.tree_flatten(shp)
        return (layer.typename, str(treedef), tuple((l.shape, str(l.dtype)) for l in leaves))

    best = (0, 0)
    i = 0
    n = len(layers)
    while i < n:
        s = sig(i)
        if s is None:
            i += 1
            continue
        j = i
        while j < n and sig(j) == s:
            j += 1
        if j - i > best[1] - best[0]:
            best = (i, j)
        i = j
    return best


def spec_from_pipeline_module(module: PipelineModule, pp: int, seed: int = 0) -> ModelSpec:
    """ModelSpec executing the PipelineModule, pipelined over ``pp`` stages."""
    layers = _adapt_layers(module)
    loss_fn = module.loss_fn
    any_params = any(l.has_params for l in layers)
    if any_params and module.example_input is None:
        raise ValueError(
            "PipelineModule has parameterized layers: pass example_input= "
            "(the activation pytree fed to the first layer) so shapes can be "
            "inferred at construction"
        )

    shapes = tied_shapes = None
    lo = hi = 0
    if any_params:
        shapes, tied_shapes = _discover(layers, module.example_input, seed)
        lo, hi = _stackable_run(layers, shapes)
    if pp > 1:
        if hi - lo < pp:
            raise ValueError(
                f"pipeline over pp={pp} needs a contiguous run of >= pp layers "
                f"with identical parameter structure (found {hi - lo}); the "
                f"repeated block stack is what gets partitioned over stages"
            )
        # Trim the run so it divides evenly; leftover layers join the epilogue.
        usable = ((hi - lo) // pp) * pp
        hi = lo + usable

    def init_fn(rng):
        x = module.example_input
        tied: dict = {}
        pre: dict = {}
        stack: list = []
        post: dict = {}
        for i, layer in enumerate(layers):
            lrng = jax.random.fold_in(rng, i)
            p = None
            if layer.has_params:
                if layer.tied_key is not None:
                    if layer.tied_key not in tied:
                        tied[layer.tied_key] = layer.init(lrng, x)
                    p = tied[layer.tied_key]
                else:
                    p = layer.init(lrng, x)
                    if lo <= i < hi and pp > 1:
                        stack.append(p)
                    elif i < hi:
                        pre[str(i)] = p
                    else:
                        post[str(i)] = p
            x = layer.apply(p, x, lrng)
        params = {"tied": tied, "pre": pre, "post": post}
        if stack:
            params["stack"] = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *stack)
        return params

    def _layer_params(params, i):
        layer = layers[i]
        if not layer.has_params:
            return None
        if layer.tied_key is not None:
            return params["tied"][layer.tied_key]
        key = str(i)
        if key in params["pre"]:
            return params["pre"][key]
        if key in params["post"]:
            return params["post"][key]
        # stacked run member (sequential fallback on a stack-layout tree)
        return jax.tree_util.tree_map(lambda v: v[i - lo], params["stack"])

    def _finish(h, batch):
        if loss_fn is None:
            return h
        if isinstance(batch, dict) and "labels" in batch:
            return loss_fn(h, batch["labels"])
        return loss_fn(h, batch)

    ckpt_interval = module.activation_checkpoint_interval

    def sequential_loss(params, batch, rng):
        # activation_checkpoint_interval=k: save activations only at every
        # k-th layer boundary, rematerialize inside each group (reference
        # PipelineModule.forward exec_range + checkpoint_interval,
        # pipe/module.py:340).
        h = batch
        n = len(layers)
        i = 0
        while i < n:
            j = min(i + ckpt_interval, n) if ckpt_interval > 0 else i + 1

            def seg(p, h, i=i, j=j):
                for t in range(i, j):
                    h = layers[t].apply(_layer_params(p, t), h, jax.random.fold_in(rng, t))
                return h

            if ckpt_interval > 0:
                seg = jax.checkpoint(seg, prevent_cse=False)
            h = seg(params, h)
            i = j
        return _finish(h, batch)

    def _apply_stack(stack, h, srng, apply_one):
        """Scan the stacked layer run, checkpointing every k layers."""
        n_local = jax.tree_util.tree_leaves(stack)[0].shape[0]
        rngs = jax.random.split(srng, n_local)

        def body(c, xs):
            lp, r = xs
            return apply_one(lp, c, r), None

        k = ckpt_interval
        if k <= 0:
            out, _ = jax.lax.scan(body, h, (stack, rngs))
            return out
        k = min(k, n_local)

        def gbody(c, xs):
            lp, rs = xs
            out, _ = jax.lax.scan(body, c, (lp, rs))
            return out, None

        gbody = jax.checkpoint(gbody, prevent_cse=False)
        main = (n_local // k) * k
        if main:
            gstack = jax.tree_util.tree_map(
                lambda v: v[:main].reshape((main // k, k) + v.shape[1:]), stack
            )
            grngs = rngs[:main].reshape((main // k, k) + rngs.shape[1:])
            h, _ = jax.lax.scan(gbody, h, (gstack, grngs))
        if n_local % k:
            # trailing partial group: one extra checkpoint boundary, honoring
            # the configured interval for the rest (reference exec_range tail)
            rest = jax.tree_util.tree_map(lambda v: v[main:][None], stack)
            h, _ = jax.lax.scan(gbody, h, (rest, rngs[main:][None]))
        return h

    def pipelined_loss(params, batch, rng):
        from deepspeed_tpu.topology.mesh import get_mesh, has_mesh

        mesh = get_mesh() if has_mesh() else None
        if mesh is None or "stack" not in params:
            return sequential_loss(params, batch, rng)
        # pp == 1 still flows through spmd_pipeline's degenerate scan branch so
        # the stacked-params layout stays valid on any mesh.
        M = module.num_microbatches or max(mesh.shape["pp"], 1)

        h = batch
        for i in range(lo):
            h = layers[i].apply(_layer_params(params, i), h, jax.random.fold_in(rng, i))

        leaves = jax.tree_util.tree_leaves(h)
        B = leaves[0].shape[0]
        if B % M:
            raise ValueError(f"micro-batch dim {B} not divisible by pipeline microbatches {M}")
        split = lambda v: v.reshape((M, B // M) + v.shape[1:])
        stream = jax.tree_util.tree_map(split, h)

        apply_mid = layers[lo].apply  # all stack layers share one apply

        def stage_fn(stage_stack, carry, srng):
            return _apply_stack(stage_stack, carry, srng, apply_mid)

        from deepspeed_tpu.parallel.pipeline_spmd import spmd_pipeline_interleaved

        h = spmd_pipeline_interleaved(
            stage_fn, params["stack"], stream, mesh=mesh, rng=rng,
            virtual=getattr(module, "virtual_stages", 1))
        h = jax.tree_util.tree_map(lambda v: v.reshape((B,) + v.shape[2:]), h)

        for i in range(hi, len(layers)):
            h = layers[i].apply(_layer_params(params, i), h, jax.random.fold_in(rng, i))
        return _finish(h, batch)

    def partition_rules(path: str, shape: tuple):
        if "'stack'" in path:
            return P(*(["pp"] + [None] * (len(shape) - 1)))
        return None

    return ModelSpec(
        init_fn=init_fn,
        loss_fn=pipelined_loss if pp > 1 else sequential_loss,
        name="pipeline",
        partition_rules=partition_rules if pp > 1 else None,
    )


class PipelineEngine(DeepSpeedTPUEngine):
    """Engine for PipelineModule models (reference ``pipe/engine.py:61``)."""

    def __init__(self, module: PipelineModule, config, mesh=None, **kwargs):
        self.pipeline_module = module
        pp = mesh.shape["pp"] if mesh is not None else getattr(config.mesh_config, "pp", 1)
        spec = spec_from_pipeline_module(module, pp)
        super().__init__(model=spec, config=config, mesh=mesh, **kwargs)
        # diagnostics ride the base engine (the pipelined loss is traced into
        # the same fused step the health probes/recompile detector watch);
        # stamp the pipeline topology into any crash dump's header so a
        # post-mortem names the schedule, not just the mesh
        if self.diagnostics is not None and self.diagnostics.flight_recorder is not None:
            self.diagnostics.flight_recorder.set_context(
                engine="pipeline",
                pipeline_stages=pp,
                num_layers=len(module.layer_specs),
                num_microbatches=getattr(module, "num_microbatches", None),
                virtual_stages=getattr(module, "virtual_stages", 1),
            )

    def train_batch(self, batch: Any = None, data_iter: Optional[Any] = None):
        return super().train_batch(batch=batch, data_iter=data_iter)
