"""Pipeline instruction schedules (host-side, for parity/introspection).

API parity with ``runtime/pipe/schedule.py`` (``PipeSchedule`` :189 base,
``InferenceSchedule``, ``TrainSchedule``): generators yielding per-tick
instruction lists for a given (micro_batches, stages, stage_id). On TPU the
compiled SPMD pipeline (``pipeline_spmd.spmd_pipeline``) executes the whole
schedule inside one XLA program, so these classes are NOT an execution engine;
they exist to (a) document/verify the tick→microbatch mapping the compiled
loop implements, (b) drive schedule-visualization and debugging tools, and
(c) keep the reference's public schedule API importable.
"""

from __future__ import annotations

from typing import Iterator, List


class PipeInstruction:
    """Base instruction (reference ``schedule.py:327``)."""

    def __init__(self, **kwargs):
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{type(self).__name__}({args})"

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class LoadMicroBatch(PipeInstruction):
    pass


class ForwardPass(PipeInstruction):
    pass


class BackwardPass(PipeInstruction):
    pass


class SendActivation(PipeInstruction):
    pass


class RecvActivation(PipeInstruction):
    pass


class SendGrad(PipeInstruction):
    pass


class RecvGrad(PipeInstruction):
    pass


class PipeSchedule:
    """Schedule generator base (reference ``schedule.py:11``)."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        if not 0 <= stage_id < stages:
            raise ValueError(f"stage_id {stage_id} out of range for {stages} stages")
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    def steps(self) -> Iterator[List[PipeInstruction]]:
        raise NotImplementedError

    @property
    def num_pipe_buffers(self) -> int:
        return 2

    @property
    def stage(self) -> int:
        return self.stage_id

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.stages - 1

    def __iter__(self):
        return self.steps()


class InferenceSchedule(PipeSchedule):
    """Forward-only fill-and-drain (reference ``schedule.py:86``).

    This is exactly the tick mapping of the compiled SPMD pipeline: at tick t,
    stage i runs forward on microbatch t - i when 0 <= t - i < M.
    """

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        for t in range(total_steps):
            cmds: List[PipeInstruction] = []
            micro_batch_id = t - self.stage_id
            active = 0 <= micro_batch_id < self.micro_batches
            if active:
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buffer_id=micro_batch_id % 2))
                else:
                    cmds.append(RecvActivation(buffer_id=micro_batch_id % 2))
                cmds.append(ForwardPass(buffer_id=micro_batch_id % 2))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buffer_id=micro_batch_id % 2))
            yield cmds


class TrainSchedule(PipeSchedule):
    """1F1B train schedule (reference ``schedule.py:189``).

    Forward ticks fill, then forwards and backwards interleave one-for-one,
    then backwards drain; ends with grad reduction + optimizer step. The
    compiled pipeline realizes the same dependency order via XLA's reverse-mode
    scan; peak live microbatches per stage matches ``num_pipe_buffers``.
    """

    def steps(self):
        # 1F1B tick mapping (reference ``_step_to_micro_batch`` schedule.py:262):
        # stage s runs FORWARD of microbatch m at tick s + 2m (activations
        # arrive one tick after the upstream send) and BACKWARD of m at tick
        # 2S - 1 + 2m - s (one tick after the downstream stage's backward).
        # Forward ticks have parity s, backward ticks parity s+1 — never both.
        S, M, s = self.stages, self.micro_batches, self.stage_id
        total_steps = 2 * (M + S - 1)
        for step_id in range(total_steps):
            cmds: List[PipeInstruction] = []
            fwd_mb, rem = divmod(step_id - s, 2)
            if rem == 0 and 0 <= fwd_mb < M:
                buf = fwd_mb % self.num_pipe_buffers
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buffer_id=buf))
                else:
                    cmds.append(RecvActivation(buffer_id=buf))
                cmds.append(ForwardPass(buffer_id=buf))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buffer_id=buf))
            bwd_mb, rem = divmod(step_id - (2 * S - 1 - s), 2)
            if rem == 0 and 0 <= bwd_mb < M:
                buf = bwd_mb % self.num_pipe_buffers
                if not self.is_last_stage:
                    cmds.append(RecvGrad(buffer_id=buf))
                cmds.append(BackwardPass(buffer_id=buf))
                if not self.is_first_stage:
                    cmds.append(SendGrad(buffer_id=buf))
            yield cmds
        yield [ReduceTiedGrads(), ReduceGrads(), OptimizerStep()]

    @property
    def num_pipe_buffers(self) -> int:
        # In-flight microbatches at this stage's steady state (reference :236).
        return min(self.stages - self.stage_id + 1, self.micro_batches)
