"""Ring attention over the ``sp`` mesh axis.

The reference has NO ring attention (SURVEY §2.3: Ulysses all-to-all + FPDT
chunking is its long-context answer) — this is a TPU-native addition: K/V
blocks rotate around the sp ring via ``ppermute`` while each device keeps its
query shard resident, giving exact attention with O(S/P) memory and comm that
rides neighbor ICI links (vs Ulysses' all-to-all). Comm volume per device is
O(S) vs Ulysses' O(S/P); use ring when heads < sp or when per-hop overlap
with the block compute wins (long S), Ulysses otherwise — both compose with
the same mesh.

Math: classic online-softmax (flash) accumulation per incoming block:
  m' = max(m, rowmax(s));  l' = l*e^(m-m') + rowsum(e^(s-m'))
  o' = o*e^(m-m') + e^(s-m') v
Causality across blocks is decided by the SOURCE block's global position:
blocks from later positions are masked entirely, the diagonal block gets the
intra-block triangular mask.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from deepspeed_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.topology.mesh import get_mesh

_NEG_INF = -1e30


def _block_attend(q, k, v, m, l, o, q_start, k_start, causal: bool,
                  slopes=None):
    """Online-softmax accumulate one K/V block into (m, l, o).

    q: [B, Sq, Hkv, G, D] (pre-scaled); k/v: [B, Sk, Hkv, D];
    m/l: [B, Hkv, G, Sq]; o: [B, Sq, Hkv, G, D]. Positions are global.
    ``slopes`` [Hkv, G] adds the ALiBi bias slope * GLOBAL key position
    (bloom convention — softmax cancels the per-row shift), so k_start must
    be the block's true global offset whenever slopes are used.
    """
    # HIGHEST: TPU einsum otherwise accumulates in bf16 and near-ties in the
    # softmax flip attention weights (catastrophic for long sequences)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", q, k.astype(jnp.float32),
                   precision=jax.lax.Precision.HIGHEST)
    if slopes is not None:
        kpos = (k_start + jnp.arange(k.shape[1])).astype(jnp.float32)
        s = s + slopes[None, :, :, None, None] * kpos[None, None, None, None, :]
    if causal:
        Sq, Sk = q.shape[1], k.shape[1]
        qpos = q_start + jnp.arange(Sq)
        kpos = k_start + jnp.arange(Sk)
        keep = qpos[:, None] >= kpos[None, :]
        s = jnp.where(keep[None, None, None], s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard fully-masked rows: e^(m - m_new) with m = -inf stays 0
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    # A fully-masked FIRST block has m == m_new == _NEG_INF, making
    # exp(s - m_new) == 1 for every masked entry — zero masked probabilities
    # explicitly so accumulation is correct for any caller's block order
    # (this helper is shared with sequence/fpdt.py).
    p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha.transpose(0, 3, 1, 2)[..., None] + jnp.einsum(
        "bhgqk,bkhd->bqhgd", p, v.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    )
    return m_new, l_new, o_new


def ring_attention(
    q: jax.Array,  # [B, S, H, D] sequence-sharded over sp
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,
    mesh: Optional[Mesh] = None,
    axis: str = "sp",
    causal: bool = True,
    alibi_slopes: Optional[jax.Array] = None,  # [H] bloom ALiBi
) -> jax.Array:
    """Exact attention with K/V rotating around the ``axis`` ring.

    Inputs/outputs are GLOBAL arrays; sharding over (batch, seq) is applied
    via shard_map specs — S must divide by the axis size.
    """
    mesh = mesh or get_mesh()
    P_ring = mesh.shape[axis]
    if P_ring == 1:
        if causal:
            from deepspeed_tpu.ops.attention import causal_attention

            return causal_attention(q, k, v, alibi_slopes=alibi_slopes)
        from deepspeed_tpu.sequence.fpdt import chunked_attention

        return chunked_attention(q, k, v, chunk_size=k.shape[1], causal=False,
                                 alibi_slopes=alibi_slopes)
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    slopes2 = (None if alibi_slopes is None
               else alibi_slopes.astype(jnp.float32).reshape(Hkv, H // Hkv))
    if S % P_ring:
        raise ValueError(f"seq {S} not divisible by ring size {P_ring}")
    if causal and S % (2 * P_ring) == 0:
        return _ring_zigzag(q, k, v, mesh, axis, P_ring, slopes2)
    G = H // Hkv
    S_loc = S // P_ring

    def local(qb, kb, vb):
        # qb: [B_loc, S_loc, H, D]; kb/vb: [B_loc, S_loc, Hkv, D] — batch is
        # dp-sharded too, so take every dim from the LOCAL shard
        B_loc = qb.shape[0]
        idx = jax.lax.axis_index(axis)
        qg = (qb.reshape(B_loc, S_loc, Hkv, G, D).astype(jnp.float32)) * (D ** -0.5)
        # derive accumulators from qg so they carry the same varying-axis type
        # as the rotating kb/vb (shard_map's typed-replication rules)
        o = jnp.zeros_like(qg)
        m = o[..., 0].transpose(0, 2, 3, 1) + _NEG_INF  # [B, Hkv, G, S_loc]
        l = o[..., 0].transpose(0, 2, 3, 1)
        q_start = idx * S_loc

        perm = [(i, (i + 1) % P_ring) for i in range(P_ring)]

        # hop 0: attend the resident block (no comm), then P_ring-1
        # permute-then-attend rounds — exactly P_ring-1 rotations total
        m, l, o = _block_attend(qg, kb, vb, m, l, o, q_start, idx * S_loc, causal,
                                slopes=slopes2)

        def body(carry, hop):
            kb, vb, m, l, o = carry
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            # after `hop` rotations we hold the block born on device idx - hop
            src = (idx - hop) % P_ring
            if causal:
                # blocks born strictly later than this device's queries are
                # fully masked — skip their matmuls. NOTE: the ring is
                # synchronous, so this saves energy/occupancy, not wall-clock
                # (devices that do attend set each hop's critical path); the
                # balanced answer is the zigzag placement (_ring_zigzag),
                # which handles every 2P-divisible causal case — this path
                # only runs for odd-shaped fallbacks.
                m, l, o = jax.lax.cond(
                    src <= idx,
                    lambda m, l, o, kb, vb, ks: _block_attend(
                        qg, kb, vb, m, l, o, q_start, ks, causal, slopes=slopes2),
                    lambda m, l, o, kb, vb, ks: (m, l, o),
                    m, l, o, kb, vb, src * S_loc,
                )
            else:
                m, l, o = _block_attend(qg, kb, vb, m, l, o, q_start, src * S_loc,
                                        causal, slopes=slopes2)
            return (kb, vb, m, l, o), None

        (kb, vb, m, l, o), _ = jax.lax.scan(
            body, (kb, vb, m, l, o), jnp.arange(1, P_ring)
        )
        out = o / jnp.maximum(l.transpose(0, 3, 1, 2)[..., None], 1e-30)
        return out.reshape(B_loc, S_loc, H, D).astype(q.dtype)

    from deepspeed_tpu.parallel.ulysses import _live_batch_axes

    batch_axes = _live_batch_axes(mesh)
    spec_q = P(batch_axes, axis, None, None)
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(spec_q, spec_q, spec_q),
        out_specs=spec_q,
        # the masked-hop lax.cond trips 0.4.x replication checking (upstream
        # suggests exactly this flag); the math is replication-safe
        check_vma=False,
    )
    return fn(q, k, v)


def _ring_zigzag(q, k, v, mesh, axis: str, P_ring: int, slopes2=None):
    """Causal ring attention with zigzag (striped) block placement.

    Contiguous placement under causality is pathologically imbalanced: device
    0's queries see one K/V block while device P-1's see all P — and the ring
    is synchronous, so every hop runs at the slowest device's pace. Splitting
    the sequence into 2P blocks and giving device i blocks (i, 2P-1-i) makes
    every device's visible work per hop identical (cf. Striped Attention,
    arXiv:2311.09431): per hop exactly one of the (early-half, incoming-early)
    / (late-half, incoming-late) pairs is live, plus the always-visible
    (late-half, incoming-early) pair.

    The zigzag redistribution happens INSIDE the shard_map as half-block
    ``ppermute``s (O(S/P) comm, ~one extra ring hop each way) — a global
    gather on the sp-sharded axis would lower to full-S all-gathers. The
    live-pair choice is made by SELECTING the pair's inputs/accumulators with
    the ring-position predicate rather than ``lax.cond``: an earlier
    cond-based zigzag intermittently hard-aborted the XLA CPU runtime under
    scan+shard_map+grad, and selects cost the same here since both branches'
    operands are resident. (The contiguous fallback still uses a cond, where
    the false branch genuinely skips work; its grad path is pinned by
    ``test_ring_attention_contiguous_fallback``.)
    """
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    G = H // Hkv
    Sb = S // (2 * P_ring)

    def z(b):  # zigzag owner of global block b
        return b if b < P_ring else 2 * P_ring - 1 - b

    # contiguous device d holds blocks (2d, 2d+1); both maps are bijections
    perm0 = [(d, z(2 * d)) for d in range(P_ring)]
    perm1 = [(d, z(2 * d + 1)) for d in range(P_ring)]
    inv0 = [(z(2 * d), d) for d in range(P_ring)]
    inv1 = [(z(2 * d + 1), d) for d in range(P_ring)]

    def to_zigzag(x, idx):
        """[B, 2Sb(contiguous), ...] -> (early block idx, late block 2P-1-idx)."""
        got0 = jax.lax.ppermute(x[:, :Sb], axis, perm0)  # the even block of the pair
        got1 = jax.lax.ppermute(x[:, Sb:], axis, perm1)  # the odd block
        even_is_early = (idx % 2 == 0)  # pair {i, 2P-1-i}: i is the even one iff i even
        early = jnp.where(even_is_early, got0, got1)
        late = jnp.where(even_is_early, got1, got0)
        return jnp.concatenate([early, late], axis=1)

    def from_zigzag(x, idx):
        even_is_early = (idx % 2 == 0)
        send0 = jnp.where(even_is_early, x[:, :Sb], x[:, Sb:])  # the even block
        send1 = jnp.where(even_is_early, x[:, Sb:], x[:, :Sb])
        slot0 = jax.lax.ppermute(send0, axis, inv0)
        slot1 = jax.lax.ppermute(send1, axis, inv1)
        return jnp.concatenate([slot0, slot1], axis=1)

    def local(qb, kb, vb):
        B_loc = qb.shape[0]
        idx = jax.lax.axis_index(axis)
        qb = to_zigzag(qb, idx)
        kb = to_zigzag(kb, idx)
        vb = to_zigzag(vb, idx)
        qg = (qb.reshape(B_loc, 2 * Sb, Hkv, G, D).astype(jnp.float32)) * (D ** -0.5)
        qa, qz = qg[:, :Sb], qg[:, Sb:]  # early block i, late block 2P-1-i
        a_start = idx * Sb
        z_start = (2 * P_ring - 1 - idx) * Sb

        def fresh(qh):
            o = jnp.zeros_like(qh)
            m = o[..., 0].transpose(0, 2, 3, 1) + _NEG_INF  # [B, Hkv, G, Sb]
            return m, o[..., 0].transpose(0, 2, 3, 1), o

        ma, la, oa = fresh(qa)
        mz, lz, oz = fresh(qz)

        # hop 0: resident halves. (a,a) and (z,z) are diagonal; (z,a) is
        # fully visible (late rows always see early keys); (a,z) fully masked.
        kc, kd = kb[:, :Sb], kb[:, Sb:]
        vc, vd = vb[:, :Sb], vb[:, Sb:]
        ma, la, oa = _block_attend(qa, kc, vc, ma, la, oa, a_start, a_start, True,
                                   slopes=slopes2)
        mz, lz, oz = _block_attend(qz, kd, vd, mz, lz, oz, z_start, z_start, True,
                                   slopes=slopes2)
        mz, lz, oz = _block_attend(qz, kc, vc, mz, lz, oz, z_start, a_start, False,
                                   slopes=slopes2)

        ring = [(i, (i + 1) % P_ring) for i in range(P_ring)]

        def body(carry, hop):
            kb, vb, ma, la, oa, mz, lz, oz = carry
            kb = jax.lax.ppermute(kb, axis, ring)
            vb = jax.lax.ppermute(vb, axis, ring)
            src = (idx - hop) % P_ring
            kc, kd = kb[:, :Sb], kb[:, Sb:]
            vc, vd = vb[:, :Sb], vb[:, Sb:]

            # late half vs incoming early block: always fully visible
            mz, lz, oz = _block_attend(qz, kc, vc, mz, lz, oz, z_start, src * Sb,
                                       False, slopes=slopes2)

            # exactly one of (early-half, incoming-early) / (late-half,
            # incoming-late) is visible, decided by ring position — select the
            # live pair's inputs and accumulators (all same-shaped), attend
            # once, and scatter the result back into the live accumulator
            pred = idx > src
            q_sel = jnp.where(pred, qa, qz)
            k_sel = jnp.where(pred, kc, kd)
            v_sel = jnp.where(pred, vc, vd)
            m_sel = jnp.where(pred, ma, mz)
            l_sel = jnp.where(pred, la, lz)
            o_sel = jnp.where(pred, oa, oz)
            # ALiBi needs the TRUE global key offset of whichever block was
            # selected (the bias is position-dependent; visibility is not)
            k_start_sel = jnp.where(pred, src * Sb, (2 * P_ring - 1 - src) * Sb)
            m2, l2, o2 = _block_attend(q_sel, k_sel, v_sel, m_sel, l_sel, o_sel,
                                       0, k_start_sel, False, slopes=slopes2)
            ma = jnp.where(pred, m2, ma)
            la = jnp.where(pred, l2, la)
            oa = jnp.where(pred, o2, oa)
            mz = jnp.where(pred, mz, m2)
            lz = jnp.where(pred, lz, l2)
            oz = jnp.where(pred, oz, o2)
            return (kb, vb, ma, la, oa, mz, lz, oz), None

        (kb, vb, ma, la, oa, mz, lz, oz), _ = jax.lax.scan(
            body, (kb, vb, ma, la, oa, mz, lz, oz), jnp.arange(1, P_ring)
        )

        def norm(o, l):
            return o / jnp.maximum(l.transpose(0, 3, 1, 2)[..., None], 1e-30)

        out = jnp.concatenate([norm(oa, la), norm(oz, lz)], axis=1)
        out = out.reshape(B_loc, 2 * Sb, H, D).astype(q.dtype)
        return from_zigzag(out, idx)

    from deepspeed_tpu.parallel.ulysses import _live_batch_axes

    batch_axes = _live_batch_axes(mesh)
    spec = P(batch_axes, axis, None, None)
    fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                   check_vma=False)
    return fn(q, k, v)
