"""Ring attention over the ``sp`` mesh axis.

The reference has NO ring attention (SURVEY §2.3: Ulysses all-to-all + FPDT
chunking is its long-context answer) — this is a TPU-native addition: K/V
blocks rotate around the sp ring via ``ppermute`` while each device keeps its
query shard resident, giving exact attention with O(S/P) memory and comm that
rides neighbor ICI links (vs Ulysses' all-to-all). Comm volume per device is
O(S) vs Ulysses' O(S/P); use ring when heads < sp or when per-hop overlap
with the block compute wins (long S), Ulysses otherwise — both compose with
the same mesh.

Math: classic online-softmax (flash) accumulation per incoming block:
  m' = max(m, rowmax(s));  l' = l*e^(m-m') + rowsum(e^(s-m'))
  o' = o*e^(m-m') + e^(s-m') v
Causality across blocks is decided by the SOURCE block's global position:
blocks from later positions are masked entirely, the diagonal block gets the
intra-block triangular mask.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.topology.mesh import get_mesh

_NEG_INF = -1e30


def _block_attend(q, k, v, m, l, o, q_start, k_start, causal: bool):
    """Online-softmax accumulate one K/V block into (m, l, o).

    q: [B, Sq, Hkv, G, D] (pre-scaled); k/v: [B, Sk, Hkv, D];
    m/l: [B, Hkv, G, Sq]; o: [B, Sq, Hkv, G, D]. Positions are global.
    """
    # HIGHEST: TPU einsum otherwise accumulates in bf16 and near-ties in the
    # softmax flip attention weights (catastrophic for long sequences)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", q, k.astype(jnp.float32),
                   precision=jax.lax.Precision.HIGHEST)
    if causal:
        Sq, Sk = q.shape[1], k.shape[1]
        qpos = q_start + jnp.arange(Sq)
        kpos = k_start + jnp.arange(Sk)
        keep = qpos[:, None] >= kpos[None, :]
        s = jnp.where(keep[None, None, None], s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard fully-masked rows: e^(m - m_new) with m = -inf stays 0
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    # A fully-masked FIRST block has m == m_new == _NEG_INF, making
    # exp(s - m_new) == 1 for every masked entry — zero masked probabilities
    # explicitly so accumulation is correct for any caller's block order
    # (this helper is shared with sequence/fpdt.py).
    p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha.transpose(0, 3, 1, 2)[..., None] + jnp.einsum(
        "bhgqk,bkhd->bqhgd", p, v.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    )
    return m_new, l_new, o_new


def ring_attention(
    q: jax.Array,  # [B, S, H, D] sequence-sharded over sp
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,
    mesh: Optional[Mesh] = None,
    axis: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """Exact attention with K/V rotating around the ``axis`` ring.

    Inputs/outputs are GLOBAL arrays; sharding over (batch, seq) is applied
    via shard_map specs — S must divide by the axis size.
    """
    mesh = mesh or get_mesh()
    P_ring = mesh.shape[axis]
    if P_ring == 1:
        if causal:
            from deepspeed_tpu.ops.attention import causal_attention

            return causal_attention(q, k, v)
        from deepspeed_tpu.sequence.fpdt import chunked_attention

        return chunked_attention(q, k, v, chunk_size=k.shape[1], causal=False)
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    if S % P_ring:
        raise ValueError(f"seq {S} not divisible by ring size {P_ring}")
    G = H // Hkv
    S_loc = S // P_ring

    def local(qb, kb, vb):
        # qb: [B_loc, S_loc, H, D]; kb/vb: [B_loc, S_loc, Hkv, D] — batch is
        # dp-sharded too, so take every dim from the LOCAL shard
        B_loc = qb.shape[0]
        idx = jax.lax.axis_index(axis)
        qg = (qb.reshape(B_loc, S_loc, Hkv, G, D).astype(jnp.float32)) * (D ** -0.5)
        # derive accumulators from qg so they carry the same varying-axis type
        # as the rotating kb/vb (shard_map's typed-replication rules)
        o = jnp.zeros_like(qg)
        m = o[..., 0].transpose(0, 2, 3, 1) + _NEG_INF  # [B, Hkv, G, S_loc]
        l = o[..., 0].transpose(0, 2, 3, 1)
        q_start = idx * S_loc

        perm = [(i, (i + 1) % P_ring) for i in range(P_ring)]

        # hop 0: attend the resident block (no comm), then P_ring-1
        # permute-then-attend rounds — exactly P_ring-1 rotations total
        m, l, o = _block_attend(qg, kb, vb, m, l, o, q_start, idx * S_loc, causal)

        def body(carry, hop):
            kb, vb, m, l, o = carry
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            # after `hop` rotations we hold the block born on device idx - hop
            src = (idx - hop) % P_ring
            m, l, o = _block_attend(qg, kb, vb, m, l, o, q_start, src * S_loc, causal)
            return (kb, vb, m, l, o), None

        (kb, vb, m, l, o), _ = jax.lax.scan(
            body, (kb, vb, m, l, o), jnp.arange(1, P_ring)
        )
        out = o / jnp.maximum(l.transpose(0, 3, 1, 2)[..., None], 1e-30)
        return out.reshape(B_loc, S_loc, H, D).astype(q.dtype)

    from deepspeed_tpu.parallel.ulysses import _live_batch_axes

    batch_axes = _live_batch_axes(mesh)
    spec_q = P(batch_axes, axis, None, None)
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(spec_q, spec_q, spec_q),
        out_specs=spec_q,
    )
    return fn(q, k, v)
