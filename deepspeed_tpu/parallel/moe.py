"""Mixture-of-Experts with expert parallelism.

TPU-native re-design of the reference MoE stack (``deepspeed/moe/``):
``TopKGate`` (``moe/sharded_moe.py:449``; top1/top2/topk gating fns :183, :290,
:374 with capacity factor, load-balancing aux loss, random token priority),
``Experts`` (``moe/experts.py:13``) and ``MOELayer`` (``sharded_moe.py:533``)
whose einsum dispatch/combine the TPU version keeps, replacing the explicit
``_AllToAll`` autograd op (:96) with sharding constraints over the ``ep`` mesh
axis that XLA lowers to all-to-all on ICI.

Data layout: tokens [T, M] -> dispatch einsum -> [E, C, M] (expert, capacity,
model). Expert weights are stacked [E, M, H]/[E, H, M] and sharded over ``ep``,
so the [E, C, M] activation resharding onto ``ep`` IS the dispatch all-to-all.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.topology.mesh import get_mesh, has_mesh


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None  # None | 'RSample' | 'Jitter'
    drop_tokens: bool = True
    use_rts: bool = True  # random token selection for priority under drops
    aux_loss_weight: float = 0.01
    # token distribution follows the reference's expert-data decomposition:
    # experts shard over 'ep'; dp ranks inside an ep group replicate experts.
    # Emit device-computed dispatch stats (MOE_STAT_KEYS) alongside the aux
    # loss — the telemetry moe/* gauges. Changes the layer's return arity.
    collect_metrics: bool = False


# Dispatch-health gauges the gating math can compute for free (ROADMAP item
# 4's instrumentation). All fp32 scalars, device-computed, fetched only at
# the engine's existing monitor sync points:
#   moe/capacity_factor     realized capacity demand — the factor that would
#                           have kept every token (busiest expert's pre-drop
#                           load x E / (T*k)); above the configured
#                           capacity_factor means tokens dropped
#   moe/token_drop_rate     fraction of (token, choice) slots dropped at the
#                           capacity cutoff
#   moe/expert_load_balance E * sum_e(share_e^2) of pre-drop routing: 1.0 =
#                           perfectly uniform, E = total collapse onto one
MOE_STAT_KEYS = ("moe/capacity_factor", "moe/token_drop_rate",
                 "moe/expert_load_balance")


def _ep_constrain(x: jax.Array, spec: P) -> jax.Array:
    if not has_mesh():
        return x
    mesh = get_mesh()
    if mesh.shape["ep"] <= 1:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def _capacity(num_tokens: int, num_experts: int, factor: float, min_capacity: int, top_k: int) -> int:
    import math

    # ceil, matching the reference's _capacity (sharded_moe.py ceil semantics)
    cap = math.ceil(num_tokens * top_k * factor / num_experts)
    return max(cap, min_capacity)


def top_k_gating(
    logits: jax.Array,  # [T, E]
    top_k: int,
    capacity: int,
    rng: Optional[jax.Array] = None,
    use_rts: bool = True,
    drop_tokens: bool = True,
    collect_stats: bool = False,
) -> Tuple[jax.Array, ...]:
    """Generic top-k gating (covers the reference's top1/top2/topk gates).

    Returns (l_aux, combine_weights [T, E, C], dispatch_mask [T, E, C], exp_counts [E]).
    Load-balancing aux loss is the standard me*ce formulation
    (``sharded_moe.py`` top1gating): E * sum_e mean_prob_e * frac_tokens_e.
    With ``collect_stats`` a fifth element is appended: a ``MOE_STAT_KEYS``
    dict of fp32 scalar dispatch-health gauges (see the key docs above) —
    a handful of reductions over masks the gate already built.
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    topk_vals, topk_idx = jax.lax.top_k(probs, top_k)  # [T, k]
    masks = jax.nn.one_hot(topk_idx, E, dtype=jnp.float32)  # [T, k, E]

    # aux loss from the top-1 assignment (reference top1gating/top2gating)
    me = probs.mean(axis=0)  # [E]
    ce = masks[:, 0, :].mean(axis=0)  # fraction routed per expert (1st choice)
    l_aux = jnp.sum(me * ce) * E

    # Without drops, capacity must still be static under jit: the worst case
    # is every (token, choice) slot routed to one expert. (The reference grows
    # capacity to max(exp_counts) dynamically — impossible in XLA.)
    if not drop_tokens:
        capacity = T * top_k

    # position of each token within its expert's capacity, priority by order
    # (optionally randomized: random token selection, ``use_rts``)
    if use_rts and rng is not None:
        priority = jax.random.uniform(rng, (T,))
        order = jnp.argsort(priority)
        inv_order = jnp.argsort(order)
        masks = masks[order]
    # cumulative count per expert across (choice, token) slots — second
    # choices queue behind first choices for the same expert (reference top2)
    flat = jnp.concatenate([masks[:, j, :] for j in range(top_k)], axis=0)  # [k*T, E]
    route_counts = flat.sum(axis=0)  # [E] pre-drop demand per expert
    positions = jnp.cumsum(flat, axis=0) - flat  # [k*T, E]
    pos_in_expert = (positions * flat).sum(axis=-1)  # [k*T]
    keep = pos_in_expert < capacity
    flat = flat * keep[:, None]

    # back to [T, k, E]
    per_k = jnp.stack(jnp.split(flat, top_k, axis=0), axis=1)  # [T, k, E]
    per_k_pos = jnp.stack(jnp.split(pos_in_expert, top_k, axis=0), axis=1)  # [T, k]
    if use_rts and rng is not None:
        per_k = per_k[inv_order]
        per_k_pos = per_k_pos[inv_order]

    # renormalize kept gate values over k (reference top2: normalize by sum)
    kept_gate = (per_k.sum(axis=-1) * topk_vals).astype(jnp.float32)  # [T, k]
    denom = jnp.clip(kept_gate.sum(axis=-1, keepdims=True), 1e-9, None)
    gate_w = kept_gate / denom

    cap_oh = jax.nn.one_hot(per_k_pos.astype(jnp.int32), capacity, dtype=jnp.float32)  # [T,k,C]
    combine = jnp.einsum("tk,tke,tkc->tec", gate_w, per_k, cap_oh)
    dispatch = (combine > 0).astype(logits.dtype)
    exp_counts = flat.sum(axis=0).astype(jnp.int32)
    out = (l_aux.astype(jnp.float32), combine.astype(logits.dtype), dispatch, exp_counts)
    if not collect_stats:
        return out
    slots = jnp.float32(T * top_k)  # every (token, choice) routes somewhere
    share = route_counts / slots  # [E], sums to 1
    stats = {
        "moe/capacity_factor": route_counts.max() * E / slots,
        "moe/token_drop_rate": 1.0 - exp_counts.sum() / slots,
        "moe/expert_load_balance": E * jnp.sum(share * share),
    }
    return out + ({k: v.astype(jnp.float32) for k, v in stats.items()},)


class TopKGate(nn.Module):
    """Gate module (reference ``TopKGate`` sharded_moe.py:449)."""

    config: MoEConfig
    model_dim: int

    @nn.compact
    def __call__(self, x: jax.Array, train: bool) -> Tuple[jax.Array, jax.Array, jax.Array]:
        cfg = self.config
        T = x.shape[0]
        if cfg.noisy_gate_policy not in (None, "RSample", "Jitter"):
            raise ValueError(f"unknown noisy_gate_policy {cfg.noisy_gate_policy!r}")
        gate_in = x.astype(jnp.float32)
        noisy = train and self.has_rng("dropout")
        if noisy and cfg.noisy_gate_policy == "Jitter":
            # multiplicative uniform jitter on the gate input (reference
            # ``multiplicative_jitter`` in sharded_moe.py)
            eps = 1e-2
            gate_in = gate_in * jax.random.uniform(
                self.make_rng("dropout"), gate_in.shape, minval=1.0 - eps, maxval=1.0 + eps
            )
        # gate math in fp32 (reference casts wg to fp32)
        logits = nn.Dense(cfg.num_experts, use_bias=False, dtype=jnp.float32, name="wg")(gate_in)
        if noisy and cfg.noisy_gate_policy == "RSample":
            noise = jax.random.normal(self.make_rng("dropout"), logits.shape)
            logits = logits + noise / cfg.num_experts
        factor = cfg.capacity_factor if train else cfg.eval_capacity_factor
        capacity = _capacity(T, cfg.num_experts, factor, cfg.min_capacity, cfg.top_k)
        rng = self.make_rng("dropout") if (train and cfg.use_rts and self.has_rng("dropout")) else None
        gated = top_k_gating(
            logits, cfg.top_k, capacity, rng=rng, use_rts=cfg.use_rts and train,
            drop_tokens=cfg.drop_tokens, collect_stats=cfg.collect_metrics,
        )
        l_aux, combine, dispatch = gated[0], gated[1], gated[2]
        if cfg.collect_metrics:
            return l_aux, combine, dispatch, gated[4]
        return l_aux, combine, dispatch


class Experts(nn.Module):
    """Stacked expert FFNs (reference ``Experts`` moe/experts.py:13).

    Weights: [E, M, H] / [E, H, M], sharded over the ``ep`` mesh axis via the
    partition rules below — grouped matmul over experts maps to one einsum.
    """

    num_experts: int
    model_dim: int
    hidden_dim: int
    activation: str = "silu_glu"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:  # x: [E, C, M]
        E, M, H = self.num_experts, self.model_dim, self.hidden_dim
        init = nn.initializers.lecun_normal()
        if self.activation == "silu_glu":
            w_gate = self.param("w_gate", init, (E, M, H))
            w_up = self.param("w_up", init, (E, M, H))
            w_down = self.param("w_down", init, (E, H, M))
            h = jax.nn.silu(jnp.einsum("ecm,emh->ech", x, w_gate.astype(self.dtype)))
            h = h * jnp.einsum("ecm,emh->ech", x, w_up.astype(self.dtype))
        else:
            from deepspeed_tpu.models.transformer import act_fn

            w_up = self.param("w_up", init, (E, M, H))
            w_down = self.param("w_down", init, (E, H, M))
            h = act_fn(self.activation)(jnp.einsum("ecm,emh->ech", x, w_up.astype(self.dtype)))
        return jnp.einsum("ech,ehm->ecm", h, w_down.astype(self.dtype))


class MoELayer(nn.Module):
    """MoE feed-forward layer (reference ``MoE`` moe/layer.py:17 + ``MOELayer``).

    Input [B, S, M] -> (l_aux, output [B, S, M]). The einsum dispatch/combine
    masks follow the reference; the all-to-all is the ``ep`` resharding of the
    [E, C, M] activations.
    """

    config: MoEConfig
    model_dim: int
    hidden_dim: int
    activation: str = "silu_glu"
    dtype: jnp.dtype = jnp.float32
    train: bool = False
    # PR-MoE (reference moe/layer.py use_residual + the DeepSpeed-MoE paper's
    # Pyramid-Residual design): a dense residual MLP acts as a shared expert,
    # mixed with the routed output by a learned per-token coefficient.
    use_residual: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> Tuple[jax.Array, ...]:
        B, S, M = x.shape
        tokens = x.reshape(B * S, M)
        gated = TopKGate(self.config, M, name="gate")(tokens, self.train)
        if self.config.collect_metrics:
            l_aux, combine, dispatch, stats = gated
        else:
            (l_aux, combine, dispatch), stats = gated, None
        # dispatch: [T, E, C] x [T, M] -> [E, C, M], then shard E over ep
        expert_in = jnp.einsum("tec,tm->ecm", dispatch.astype(self.dtype), tokens)
        expert_in = _ep_constrain(expert_in, P("ep", None, None))  # all-to-all in
        expert_out = Experts(
            self.config.num_experts, M, self.hidden_dim, self.activation, self.dtype, name="experts"
        )(expert_in)
        expert_out = _ep_constrain(expert_out, P("ep", None, None))
        out = jnp.einsum("tec,ecm->tm", combine.astype(self.dtype), expert_out)
        if self.use_residual:
            # residual expert: a dense FFN every token takes; the 2-way
            # coefficient gate decides the routed/residual mix per token
            res = Experts(1, M, self.hidden_dim, self.activation, self.dtype,
                          name="residual_mlp")(tokens[None])[0]
            coef = nn.Dense(2, use_bias=True, dtype=jnp.float32, name="coefficient")(
                tokens.astype(jnp.float32))
            c = jax.nn.softmax(coef, axis=-1).astype(self.dtype)
            out = out * c[:, 0:1] + res * c[:, 1:2]
        # returned aux loss is already weighted — callers add it to their loss
        weighted = self.config.aux_loss_weight * l_aux
        if self.config.collect_metrics:
            return weighted, out.reshape(B, S, M), stats
        return weighted, out.reshape(B, S, M)


def moe_partition_rules(path: str, shape: tuple) -> Optional[P]:
    """Expert weights shard over 'ep'; gate stays replicated."""

    def has(token: str) -> bool:
        return f"'{token}'" in path

    if has("experts") and (has("w_gate") or has("w_up") or has("w_down")):
        pad = len(shape) - 3
        return P(*([None] * pad + ["ep", None, None])) if pad >= 0 else None
    return None
