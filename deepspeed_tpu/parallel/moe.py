"""Mixture-of-Experts with expert parallelism.

TPU-native re-design of the reference MoE stack (``deepspeed/moe/``):
``TopKGate`` (``moe/sharded_moe.py:449``; top1/top2/topk gating fns :183, :290,
:374 with capacity factor, load-balancing aux loss, random token priority),
``Experts`` (``moe/experts.py:13``) and ``MOELayer`` (``sharded_moe.py:533``)
whose einsum dispatch/combine the TPU version keeps, replacing the explicit
``_AllToAll`` autograd op (:96) with sharding constraints over the ``ep`` mesh
axis that XLA lowers to all-to-all on ICI.

Data layout: tokens [T, M] -> dispatch einsum -> [E, C, M] (expert, capacity,
model). Expert weights are stacked [E, M, H]/[E, H, M] and sharded over ``ep``,
so the [E, C, M] activation resharding onto ``ep`` IS the dispatch all-to-all.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.topology.mesh import get_mesh, has_mesh
from deepspeed_tpu.utils.logging import logger


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None  # None | 'RSample' | 'Jitter'
    drop_tokens: bool = True
    use_rts: bool = True  # random token selection for priority under drops
    aux_loss_weight: float = 0.01
    # token distribution follows the reference's expert-data decomposition:
    # experts shard over 'ep'; dp ranks inside an ep group replicate experts.
    # Emit device-computed dispatch stats (MOE_STAT_KEYS) alongside the aux
    # loss — the telemetry moe/* gauges. Changes the layer's return arity.
    collect_metrics: bool = False
    # How the [E, C, M] dispatch/combine reshards onto the ep axis (ISSUE 15):
    #   "auto"       — explicit collective dispatch on ep x tp meshes (where
    #                  constraint-based resharding is the unverified path the
    #                  engine used to refuse), GSPMD constraints elsewhere
    #   "collective" — force the shard_map + facade all_to_all dispatch on
    #                  any ep>1 mesh
    #   "gspmd"      — force constraint-based resharding everywhere
    # The collective path is the reference moe/mappings.py shape: tokens are
    # gathered across the tp group at region entry (token specs never name
    # tp, so tp ranks see the full token set) and the duplicate outputs are
    # dropped at region exit; the dispatch and combine each cross the wire
    # as ONE facade all_to_all over ep, so algorithm/codec routing, hop
    # spans, and observatory signatures all apply to MoE token traffic.
    dispatch: str = "auto"
    # facade all_to_all routing of the collective dispatch: None = facade
    # defaults / selector ("auto" when the collectives block is enabled);
    # a concrete name ("ring" / "bidir" / "ring2d" / "pallas_ring" /
    # "pallas_ring2d") forces that schedule
    dispatch_algorithm: Optional[str] = None
    # wire codec of the dispatch/combine all-to-all: "int8"/"fp8" quantize
    # the token wire (EQuARX-style on the pallas backend: requantize ->
    # remote DMA -> dequantize in one kernel per hop); None = exact wire
    dispatch_codec: Optional[str] = None
    # Capacity-factor autotuning support (runtime moe_autotune block): when
    # set, the capacity ARRAYS are sized by this ceiling factor and the
    # factor actually enforced is a traced scalar clipped into
    # [capacity_factor bounds, ceiling] — so the host-side controller can
    # move the effective capacity between steps without a recompile.
    max_capacity_factor: Optional[float] = None


# Dispatch-health gauges the gating math can compute for free (ROADMAP item
# 4's instrumentation). All fp32 scalars, device-computed, fetched only at
# the engine's existing monitor sync points:
#   moe/capacity_factor     realized capacity demand — the factor that would
#                           have kept every token (busiest expert's pre-drop
#                           load x E / (T*k)); above the configured
#                           capacity_factor means tokens dropped
#   moe/token_drop_rate     fraction of (token, choice) slots dropped at the
#                           capacity cutoff
#   moe/expert_load_balance E * sum_e(share_e^2) of pre-drop routing: 1.0 =
#                           perfectly uniform, E = total collapse onto one
MOE_STAT_KEYS = ("moe/capacity_factor", "moe/token_drop_rate",
                 "moe/expert_load_balance")

# With dynamic capacity (``MoEConfig.max_capacity_factor``) the gate also
# reports the factor it actually ENFORCED this step — the autotuning
# controller's feedback that its knob reached the program:
#   moe/capacity_factor_applied   effective_capacity * E / (T * k)
MOE_DYNAMIC_STAT_KEYS = MOE_STAT_KEYS + ("moe/capacity_factor_applied",)


def _ep_constrain(x: jax.Array, spec: P) -> jax.Array:
    if not has_mesh():
        return x
    mesh = get_mesh()
    if mesh.shape["ep"] <= 1:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def _capacity(num_tokens: int, num_experts: int, factor: float, min_capacity: int, top_k: int) -> int:
    import math

    # ceil, matching the reference's _capacity (sharded_moe.py ceil semantics)
    cap = math.ceil(num_tokens * top_k * factor / num_experts)
    return max(cap, min_capacity)


def top_k_gating(
    logits: jax.Array,  # [T, E]
    top_k: int,
    capacity: int,
    rng: Optional[jax.Array] = None,
    use_rts: bool = True,
    drop_tokens: bool = True,
    collect_stats: bool = False,
    effective_capacity: Optional[jax.Array] = None,
) -> Tuple[jax.Array, ...]:
    """Generic top-k gating (covers the reference's top1/top2/topk gates).

    Returns (l_aux, combine_weights [T, E, C], dispatch_mask [T, E, C], exp_counts [E]).
    Load-balancing aux loss is the standard me*ce formulation
    (``sharded_moe.py`` top1gating): E * sum_e mean_prob_e * frac_tokens_e.
    With ``collect_stats`` a fifth element is appended: a ``MOE_STAT_KEYS``
    dict of fp32 scalar dispatch-health gauges (see the key docs above) —
    a handful of reductions over masks the gate already built.

    ``effective_capacity`` (int scalar, traced or static, <= ``capacity``)
    makes the drop cutoff dynamic while the array dims stay padded to the
    static ``capacity`` bound — the capacity-autotuning contract: one
    compiled program, a data-dependent cutoff. Adds the
    ``moe/capacity_factor_applied`` stat when stats are collected.
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    topk_vals, topk_idx = jax.lax.top_k(probs, top_k)  # [T, k]
    masks = jax.nn.one_hot(topk_idx, E, dtype=jnp.float32)  # [T, k, E]

    # aux loss from the top-1 assignment (reference top1gating/top2gating)
    me = probs.mean(axis=0)  # [E]
    ce = masks[:, 0, :].mean(axis=0)  # fraction routed per expert (1st choice)
    l_aux = jnp.sum(me * ce) * E

    # Without drops, capacity must still be static under jit: the worst case
    # is every (token, choice) slot routed to one expert. (The reference grows
    # capacity to max(exp_counts) dynamically — impossible in XLA.)
    if not drop_tokens:
        capacity = T * top_k
        effective_capacity = None

    # position of each token within its expert's capacity, priority by order
    # (optionally randomized: random token selection, ``use_rts``)
    if use_rts and rng is not None:
        priority = jax.random.uniform(rng, (T,))
        order = jnp.argsort(priority)
        inv_order = jnp.argsort(order)
        masks = masks[order]
    # cumulative count per expert across (choice, token) slots — second
    # choices queue behind first choices for the same expert (reference top2)
    flat = jnp.concatenate([masks[:, j, :] for j in range(top_k)], axis=0)  # [k*T, E]
    route_counts = flat.sum(axis=0)  # [E] pre-drop demand per expert
    positions = jnp.cumsum(flat, axis=0) - flat  # [k*T, E]
    pos_in_expert = (positions * flat).sum(axis=-1)  # [k*T]
    cutoff = capacity if effective_capacity is None else effective_capacity
    keep = pos_in_expert < cutoff
    flat = flat * keep[:, None]

    # back to [T, k, E]
    per_k = jnp.stack(jnp.split(flat, top_k, axis=0), axis=1)  # [T, k, E]
    per_k_pos = jnp.stack(jnp.split(pos_in_expert, top_k, axis=0), axis=1)  # [T, k]
    if use_rts and rng is not None:
        per_k = per_k[inv_order]
        per_k_pos = per_k_pos[inv_order]

    # renormalize kept gate values over k (reference top2: normalize by sum)
    kept_gate = (per_k.sum(axis=-1) * topk_vals).astype(jnp.float32)  # [T, k]
    denom = jnp.clip(kept_gate.sum(axis=-1, keepdims=True), 1e-9, None)
    gate_w = kept_gate / denom

    cap_oh = jax.nn.one_hot(per_k_pos.astype(jnp.int32), capacity, dtype=jnp.float32)  # [T,k,C]
    combine = jnp.einsum("tk,tke,tkc->tec", gate_w, per_k, cap_oh)
    dispatch = (combine > 0).astype(logits.dtype)
    exp_counts = flat.sum(axis=0).astype(jnp.int32)
    out = (l_aux.astype(jnp.float32), combine.astype(logits.dtype), dispatch, exp_counts)
    if not collect_stats:
        return out
    slots = jnp.float32(T * top_k)  # every (token, choice) routes somewhere
    share = route_counts / slots  # [E], sums to 1
    stats = {
        "moe/capacity_factor": route_counts.max() * E / slots,
        "moe/token_drop_rate": 1.0 - exp_counts.sum() / slots,
        "moe/expert_load_balance": E * jnp.sum(share * share),
    }
    if effective_capacity is not None:
        # the factor the cutoff actually enforced — the controller's
        # feedback that its between-steps knob reached the program
        stats["moe/capacity_factor_applied"] = (
            jnp.asarray(effective_capacity, jnp.float32) * E / slots)
    return out + ({k: v.astype(jnp.float32) for k, v in stats.items()},)


class TopKGate(nn.Module):
    """Gate module (reference ``TopKGate`` sharded_moe.py:449)."""

    config: MoEConfig
    model_dim: int

    @nn.compact
    def __call__(self, x: jax.Array, train: bool,
                 capacity_scale: Optional[jax.Array] = None
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        cfg = self.config
        T = x.shape[0]
        if cfg.noisy_gate_policy not in (None, "RSample", "Jitter"):
            raise ValueError(f"unknown noisy_gate_policy {cfg.noisy_gate_policy!r}")
        gate_in = x.astype(jnp.float32)
        noisy = train and self.has_rng("dropout")
        if noisy and cfg.noisy_gate_policy == "Jitter":
            # multiplicative uniform jitter on the gate input (reference
            # ``multiplicative_jitter`` in sharded_moe.py)
            eps = 1e-2
            gate_in = gate_in * jax.random.uniform(
                self.make_rng("dropout"), gate_in.shape, minval=1.0 - eps, maxval=1.0 + eps
            )
        # gate math in fp32 (reference casts wg to fp32)
        logits = nn.Dense(cfg.num_experts, use_bias=False, dtype=jnp.float32, name="wg")(gate_in)
        if noisy and cfg.noisy_gate_policy == "RSample":
            noise = jax.random.normal(self.make_rng("dropout"), logits.shape)
            logits = logits + noise / cfg.num_experts
        factor = cfg.capacity_factor if train else cfg.eval_capacity_factor
        effective = None
        if train and cfg.max_capacity_factor is not None and cfg.drop_tokens:
            # dynamic capacity: the arrays are padded to the CEILING bound
            # (jit-cache-stable), the enforced cutoff follows the traced
            # factor scalar the engine's autotuning controller threads in
            capacity = _capacity(T, cfg.num_experts, cfg.max_capacity_factor,
                                 cfg.min_capacity, cfg.top_k)
            f = (jnp.float32(factor) if capacity_scale is None
                 else jnp.asarray(capacity_scale, jnp.float32))
            effective = jnp.clip(
                jnp.ceil(T * cfg.top_k * f / cfg.num_experts),
                cfg.min_capacity, capacity).astype(jnp.int32)
        else:
            capacity = _capacity(T, cfg.num_experts, factor, cfg.min_capacity, cfg.top_k)
        rng = self.make_rng("dropout") if (train and cfg.use_rts and self.has_rng("dropout")) else None
        gated = top_k_gating(
            logits, cfg.top_k, capacity, rng=rng, use_rts=cfg.use_rts and train,
            drop_tokens=cfg.drop_tokens, collect_stats=cfg.collect_metrics,
            effective_capacity=effective,
        )
        l_aux, combine, dispatch = gated[0], gated[1], gated[2]
        if cfg.collect_metrics:
            return l_aux, combine, dispatch, gated[4]
        return l_aux, combine, dispatch


def experts_ffn(x: jax.Array, w_gate: Optional[jax.Array], w_up: jax.Array,
                w_down: jax.Array, activation: str, dtype) -> jax.Array:
    """The stacked-expert FFN math on ``[E, C, M]`` slots — ONE definition
    shared by the :class:`Experts` module and the collective dispatch path
    (which runs it on the LOCAL expert shard inside shard_map). Biasless by
    construction: an all-zero capacity slot maps to an all-zero output,
    the invariant the partial-sum dispatch relies on."""
    if activation == "silu_glu":
        h = jax.nn.silu(jnp.einsum("ecm,emh->ech", x, w_gate.astype(dtype)))
        h = h * jnp.einsum("ecm,emh->ech", x, w_up.astype(dtype))
    else:
        from deepspeed_tpu.models.transformer import act_fn

        h = act_fn(activation)(jnp.einsum("ecm,emh->ech", x, w_up.astype(dtype)))
    return jnp.einsum("ech,ehm->ecm", h, w_down.astype(dtype))


class Experts(nn.Module):
    """Stacked expert FFNs (reference ``Experts`` moe/experts.py:13).

    Weights: [E, M, H] / [E, H, M], sharded over the ``ep`` mesh axis via the
    partition rules below — grouped matmul over experts maps to one einsum.
    Declared in ``setup`` (not compact) so the collective dispatch path can
    read the kernels via :meth:`kernels` and run :func:`experts_ffn` on the
    LOCAL expert shard inside its shard_map region.
    """

    num_experts: int
    model_dim: int
    hidden_dim: int
    activation: str = "silu_glu"
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        E, M, H = self.num_experts, self.model_dim, self.hidden_dim
        init = nn.initializers.lecun_normal()
        if self.activation == "silu_glu":
            self.w_gate = self.param("w_gate", init, (E, M, H))
        self.w_up = self.param("w_up", init, (E, M, H))
        self.w_down = self.param("w_down", init, (E, H, M))

    def kernels(self) -> Tuple[Optional[jax.Array], jax.Array, jax.Array]:
        """(w_gate | None, w_up, w_down) — raw stacked kernels."""
        return (getattr(self, "w_gate", None) if self.activation == "silu_glu" else None,
                self.w_up, self.w_down)

    def __call__(self, x: jax.Array) -> jax.Array:  # x: [E, C, M]
        w_gate, w_up, w_down = self.kernels()
        return experts_ffn(x, w_gate, w_up, w_down, self.activation, self.dtype)


# ------------------------------------------------- collective token dispatch


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def _routed_all_to_all(x, axis, split_axis, concat_axis, algorithm, codec):
    """Facade all_to_all with the reference ``_AllToAll`` autograd contract
    (``moe/sharded_moe.py:96``): the backward pass is the REVERSE exchange
    through the same algorithm/codec — so a lossy dispatch wire quantizes
    the gradient tokens exactly like the forward tokens, instead of AD
    differentiating through the rounding (zero gradients)."""
    from deepspeed_tpu.comm import comm as dist

    return dist.all_to_all(x, axis, split_axis=split_axis,
                           concat_axis=concat_axis, algorithm=algorithm,
                           codec=codec)


def _routed_a2a_fwd(x, axis, split_axis, concat_axis, algorithm, codec):
    return _routed_all_to_all(x, axis, split_axis, concat_axis, algorithm, codec), None


def _routed_a2a_bwd(axis, split_axis, concat_axis, algorithm, codec, _res, g):
    from deepspeed_tpu.comm import comm as dist

    return (dist.all_to_all(g, axis, split_axis=concat_axis,
                            concat_axis=split_axis, algorithm=algorithm,
                            codec=codec),)


_routed_all_to_all.defvjp(_routed_a2a_fwd, _routed_a2a_bwd)


def _token_axes(mesh) -> Tuple[str, ...]:
    """The mesh axes token shards split over inside the collective dispatch
    region: the batch axes, ep (the expert-data decomposition) AND tp.

    The cross-tp token mapping (reference ``moe/mappings.py``
    gather_tokens/drop_tokens): the gate runs on the full GATHERED token
    set outside the region, and inside it the token dim shards across tp
    too — each tp rank dispatches a distinct token slice, so the duplicate
    work (and the duplicate outputs the reference drops) never exists.
    Naming EVERY >1 mesh axis in the token specs is also deliberate
    hygiene: on this jax 0.4.37, a shard_map output spec that leaves a >1
    manual axis unmentioned (replication-assumed) mis-assembles the global
    result when the region's inputs are traced intermediates — observed as
    deterministic garbage on ep x tp meshes; fully-named specs sidestep
    the bug entirely (sp rides along for the same reason — a flattened
    [B*S] token dim slices over it like any other)."""
    return tuple(a for a in ("dp", "fsdp", "ep", "sp", "tp")
                 if mesh.shape[a] > 1) or ("ep",)


def collective_dispatch_blocker(cfg: MoEConfig, mesh, num_tokens: int) -> Optional[str]:
    """Why the collective dispatch CANNOT serve this (mesh, shape) — None
    when it can. Static trace-time answer."""
    ep = mesh.shape["ep"]
    if cfg.num_experts % ep:
        return f"num_experts {cfg.num_experts} not divisible by ep={ep}"
    shards = 1
    for a in _token_axes(mesh):
        shards *= mesh.shape[a]
    if num_tokens % shards:
        return (f"{num_tokens} tokens not divisible by the "
                f"{shards} token shards (dp x fsdp x ep x sp x tp)")
    if mesh.shape["pp"] > 1:
        return "pp>1 runs layers inside the pipeline's own shard_map regions"
    return None


def resolve_dispatch_mode(cfg: MoEConfig, num_tokens: int) -> str:
    """'collective' | 'gspmd' for this trace (see ``MoEConfig.dispatch``).

    "auto" routes collective whenever tp > 1 — ep present or not: driving
    the constraint path end-to-end on tp meshes showed its MoE einsum
    lowering deviating from the global math on this jax/XLA (step-1 loss
    off by ~0.5% on a dp2 x ep2 x tp2 CPU mesh, ep=1 x tp=2 likewise —
    the "silent mis-routing" the engine's old ep x tp refusal guarded
    against, now reproduced). The collective region matches the global
    math to fp rounding."""
    if not has_mesh():
        return "gspmd"
    mesh = get_mesh()
    ep, tp = mesh.shape["ep"], mesh.shape["tp"]
    if cfg.dispatch == "gspmd":
        return "gspmd"
    if cfg.dispatch not in ("auto", "collective"):
        raise ValueError(
            f"MoEConfig.dispatch must be auto|collective|gspmd, got {cfg.dispatch!r}")
    if cfg.dispatch == "auto" and tp <= 1:
        return "gspmd"
    if cfg.dispatch == "collective" and ep <= 1 and tp <= 1:
        return "gspmd"  # nothing to dispatch over — the region would be a no-op
    reason = collective_dispatch_blocker(cfg, mesh, num_tokens)
    if reason is None:
        return "collective"
    if mesh.shape["pp"] > 1:
        # a pipeline mesh can NEVER host the collective region (layers run
        # inside the pipeline's own shard_map) — raising would leave
        # pipelined MoE no path at all, so keep the pre-PR GSPMD behavior
        # and say loudly what that means on tp meshes
        logger.warning(
            f"moe: collective dispatch unavailable ({reason}); falling back "
            "to GSPMD constraint resharding"
            + (" — KNOWN to deviate ~0.5% from global math on tp>1 meshes "
               "(set moe_dispatch='gspmd' to acknowledge and silence)"
               if tp > 1 else ""))
        return "gspmd"
    if tp > 1:
        # tp meshes NEED the explicit dispatch — the GSPMD constraint path
        # mis-routes there (~0.5% loss deviation, ep present or not; the
        # corruption the engine's old ep x tp refusal guarded against) —
        # so an unservable shape must fail loudly, never silently fall
        # back onto the known-bad lowering
        raise ValueError(
            f"ep={ep} x tp={tp} MoE requires the collective token dispatch, "
            f"which cannot serve this shape: {reason}")
    # ep-only meshes: the GSPMD resharding is the verified path there
    logger.warning(f"moe: collective dispatch unavailable ({reason}); "
                   "falling back to GSPMD constraint resharding")
    return "gspmd"


def collective_moe_apply(tokens: jax.Array, combine: jax.Array,
                         dispatch: jax.Array, kernels, *, activation: str,
                         dtype, algorithm: Optional[str] = None,
                         codec: Optional[str] = None) -> jax.Array:
    """The explicit expert-parallel dispatch (reference ``moe/mappings.py``
    + ``_AllToAll``): one full-manual shard_map region where

    1. each token shard (dp x fsdp x ep x sp x tp — the gate saw the full
       GATHERED token set outside; inside, every rank dispatches a distinct
       slice, so the reference's post-combine duplicate drop never exists)
       builds its PARTIAL ``[E, C, M]`` dispatch einsum — global capacity
       slots, so shard contributions are disjoint and all-zero elsewhere;
    2. ONE facade ``all_to_all`` over ep (split E, concat C) lands every
       shard's slots on the owning expert rank — the quantized-routable
       dispatch wire;
    3. the local expert FFN runs on ``[E/ep, ep*C, M]`` (biasless: zero
       slots stay zero, so disjoint partials stay disjoint);
    4. the reverse ``all_to_all`` returns each shard its slots' outputs;
    5. the local combine einsum reads only the shard's own tokens' slots.
    """
    from deepspeed_tpu.utils.compat import shard_map

    mesh = get_mesh()
    w_gate, w_up, w_down = kernels
    tok = _token_axes(mesh)
    tok_entry = tok if len(tok) > 1 else tok[0]
    n_ws = 3 if w_gate is not None else 2
    ws = [w for w in (w_gate, w_up, w_down) if w is not None]

    def shard_fn(tok_l, comb_l, disp_l, *ws_l):
        wg, wu, wd = ws_l if n_ws == 3 else (None,) + ws_l
        expert_in = jnp.einsum("tec,tm->ecm", disp_l, tok_l)  # partial [E, C, M]
        expert_in = _routed_all_to_all(expert_in, "ep", 0, 1, algorithm, codec)
        h = experts_ffn(expert_in, wg, wu, wd, activation, dtype)  # [E/ep, ep*C, M]
        expert_out = _routed_all_to_all(h, "ep", 1, 0, algorithm, codec)
        return jnp.einsum("tec,ecm->tm", comb_l, expert_out)  # [T_l, M]

    f = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(tok_entry, None), P(tok_entry, None, None),
                  P(tok_entry, None, None)) + tuple(P("ep", None, None) for _ in ws),
        out_specs=P(tok_entry, None), check_vma=False)
    return f(tokens, combine, dispatch, *ws)


class MoELayer(nn.Module):
    """MoE feed-forward layer (reference ``MoE`` moe/layer.py:17 + ``MOELayer``).

    Input [B, S, M] -> (l_aux, output [B, S, M]). The einsum dispatch/combine
    masks follow the reference; the all-to-all is the ``ep`` resharding of the
    [E, C, M] activations.
    """

    config: MoEConfig
    model_dim: int
    hidden_dim: int
    activation: str = "silu_glu"
    dtype: jnp.dtype = jnp.float32
    train: bool = False
    # PR-MoE (reference moe/layer.py use_residual + the DeepSpeed-MoE paper's
    # Pyramid-Residual design): a dense residual MLP acts as a shared expert,
    # mixed with the routed output by a learned per-token coefficient.
    use_residual: bool = False

    @nn.compact
    def __call__(self, x: jax.Array,
                 capacity_scale: Optional[jax.Array] = None) -> Tuple[jax.Array, ...]:
        B, S, M = x.shape
        tokens = x.reshape(B * S, M)
        gated = TopKGate(self.config, M, name="gate")(tokens, self.train, capacity_scale)
        if self.config.collect_metrics:
            l_aux, combine, dispatch, stats = gated
        else:
            (l_aux, combine, dispatch), stats = gated, None
        experts = Experts(
            self.config.num_experts, M, self.hidden_dim, self.activation,
            self.dtype, name="experts")
        mode = resolve_dispatch_mode(self.config, B * S)
        if mode == "collective":
            # explicit expert-parallel dispatch: cross-tp token gather/drop
            # + facade all_to_all over ep (quantized routing, hop spans)
            out = collective_moe_apply(
                tokens, combine.astype(self.dtype), dispatch.astype(self.dtype),
                experts.kernels(), activation=self.activation, dtype=self.dtype,
                algorithm=self.config.dispatch_algorithm,
                codec=self.config.dispatch_codec)
        else:
            # dispatch: [T, E, C] x [T, M] -> [E, C, M], then shard E over ep
            expert_in = jnp.einsum("tec,tm->ecm", dispatch.astype(self.dtype), tokens)
            expert_in = _ep_constrain(expert_in, P("ep", None, None))  # all-to-all in
            expert_out = experts(expert_in)
            expert_out = _ep_constrain(expert_out, P("ep", None, None))
            out = jnp.einsum("tec,ecm->tm", combine.astype(self.dtype), expert_out)
        if self.use_residual:
            # residual expert: a dense FFN every token takes; the 2-way
            # coefficient gate decides the routed/residual mix per token
            res = Experts(1, M, self.hidden_dim, self.activation, self.dtype,
                          name="residual_mlp")(tokens[None])[0]
            coef = nn.Dense(2, use_bias=True, dtype=jnp.float32, name="coefficient")(
                tokens.astype(jnp.float32))
            c = jax.nn.softmax(coef, axis=-1).astype(self.dtype)
            out = out * c[:, 0:1] + res * c[:, 1:2]
        # returned aux loss is already weighted — callers add it to their loss
        weighted = self.config.aux_loss_weight * l_aux
        if self.config.collect_metrics:
            return weighted, out.reshape(B, S, M), stats
        return weighted, out.reshape(B, S, M)


def moe_partition_rules(path: str, shape: tuple) -> Optional[P]:
    """Expert weights shard over 'ep'; gate stays replicated."""

    def has(token: str) -> bool:
        return f"'{token}'" in path

    if has("experts") and (has("w_gate") or has("w_up") or has("w_down")):
        pad = len(shape) - 3
        return P(*([None] * pad + ["ep", None, None])) if pad >= 0 else None
    return None
