"""1-bit compressed gradient allreduce with error feedback.

TPU-native analog of the reference's compressed-allreduce backends
(``runtime/comm/nccl.py:51 NcclBackend.compressed_allreduce`` — sign+scale
compression with worker/server error feedback driving OnebitAdam/OnebitLamb/
ZeroOneAdam, ``runtime/comm/compressed.py`` packbits path).

Scheme (single-stage compensation, executed inside ``shard_map`` over the
data axes):
  comp_i  = g_i + e_i                      (error-compensated local gradient)
  scale_i = mean(|comp_i|)                 (per-tensor fp32 scale)
  wire    = packbits(sign(comp_i)) + scale (n/8 bytes + 4, vs 4n for fp32)
  g_mean  = (1/W) sum_i sign_i * scale_i   (decompressed average)
  e_i'    = comp_i - sign_i * scale_i      (residual kept locally)

The wire format is an uint8 all_gather — 1/32 the bytes of an fp32
ring-allreduce's payload per hop (the reference claims the same 32x for its
NCCL path). Signs unpack and reduce locally after the gather.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp


def _ceil_to(n: int, k: int) -> int:
    return (n + k - 1) // k * k


def pack_signs(x: jax.Array) -> jax.Array:
    """Flattened float array -> uint8 bitmap (1 = non-negative)."""
    n = x.size
    bits = (x.reshape(-1) >= 0).astype(jnp.uint8)
    pad = _ceil_to(n, 8) - n
    if pad:
        bits = jnp.concatenate([bits, jnp.zeros((pad,), jnp.uint8)])
    bits = bits.reshape(-1, 8)
    weights = (1 << jnp.arange(8, dtype=jnp.uint8)).astype(jnp.uint8)
    return jnp.sum(bits * weights[None, :], axis=1, dtype=jnp.uint8)


def unpack_signs(packed: jax.Array, n: int) -> jax.Array:
    """uint8 bitmap -> {-1, +1} float32 array of length n."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (packed[:, None] >> shifts[None, :]) & 1
    signs = bits.reshape(-1)[:n].astype(jnp.float32) * 2.0 - 1.0
    return signs


def _compress_leaf(g: jax.Array, e: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """-> (packed_u8, scale, new_error). g, e: same shape (e may lead with 1s)."""
    comp = g.astype(jnp.float32) + e.reshape(g.shape).astype(jnp.float32)
    scale = jnp.mean(jnp.abs(comp))
    packed = pack_signs(comp)
    signs = unpack_signs(packed, g.size).reshape(g.shape)
    new_e = comp - signs * scale
    return packed, scale, new_e


def compressed_grad_mean(grads: Any, errors: Any, axis_names: Tuple[str, ...]) -> Tuple[Any, Any]:
    """Inside shard_map: exact-mean of per-rank sign-compressed gradients.

    ``grads`` leaves: local per-rank gradients (full tensor shape).
    ``errors`` leaves: [1, *shape] local slice of the persistent buffer.
    Returns (mean gradients, new error slices).
    """
    def leaf(g, e):
        packed, scale, new_e = _compress_leaf(g, e)
        # ship u8 signs + fp32 scale; W = product of axis sizes
        all_packed = jax.lax.all_gather(packed, axis_names)  # [W, n/8] u8
        all_scale = jax.lax.all_gather(scale, axis_names)  # [W]
        W = all_scale.shape[0]

        def one(i, acc):
            signs = unpack_signs(all_packed[i], g.size).reshape(g.shape)
            return acc + signs * all_scale[i]

        mean = jax.lax.fori_loop(0, W, one, jnp.zeros(g.shape, jnp.float32)) / W
        return mean, new_e[None]

    flat_g, tree = jax.tree_util.tree_flatten(grads)
    flat_e = jax.tree_util.tree_leaves(errors)
    out = [leaf(g, e) for g, e in zip(flat_g, flat_e)]
    means = jax.tree_util.tree_unflatten(tree, [m for m, _ in out])
    new_errs = jax.tree_util.tree_unflatten(tree, [e for _, e in out])
    return means, new_errs
