"""Tensor-parallel linear helpers over the fused GEMM⇄collective kernels.

Megatron-style row/column-parallel linear layers expressed at the
shard_map level (``autotp.py`` handles parameter *placement*; this module
is the matching *execution* path). The row-parallel boundary — the one
that actually moves bytes — routes through
:mod:`deepspeed_tpu.collectives.fused_gemm` when the
``collectives.fused_gemm_collectives`` knob is on, so the partial-product
reduce-scatter overlaps the GEMM inside one Pallas kernel per hop (T3);
with the knob off both helpers lower to the plain lax composition,
byte-identical to hand-written layers.

All helpers must run inside full-manual shard_map with ``axis`` bound.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.collectives import fused_gemm
from deepspeed_tpu.utils.compat import axis_size


def column_parallel_linear(x: jax.Array, w_col: jax.Array) -> jax.Array:
    """``x [M, K] @ w_col [K, N/n] -> [M, N/n]``: the column-parallel half
    moves no bytes (input replicated, output column-sharded) — it exists so
    a col->row pair reads as a pair. fp32 out like the fused ops."""
    return lax.dot_general(x.astype(jnp.float32), w_col.astype(jnp.float32),
                           (((1,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)


def row_parallel_linear(x_shard: jax.Array, w_row: jax.Array, axis: str, *,
                        scatter_output: bool = True,
                        quantize: bool = False,
                        block: Optional[int] = None) -> jax.Array:
    """Row-parallel linear: ``x_shard [M, K/n] @ w_row [K/n, N]`` summed
    over ``axis``.

    ``scatter_output=True`` returns the sequence-parallel form — rank ``i``
    gets row block ``i`` of ``[M/n, N]`` (fused: every ring hop's partial
    GEMM computes while the previous chunk's wire flies; unfused: one dot
    + ``psum_scatter``). ``False`` returns the replicated ``[M, N]``
    (always the plain dot + ``psum`` — there is no wire to hide a GEMM
    behind when every rank needs every row). ``quantize`` puts the int8
    block wire on the fused hops. fp32 out; full-manual shard_map only."""
    if not scatter_output:
        p = lax.dot_general(x_shard.astype(jnp.float32),
                            w_row.astype(jnp.float32),
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
        return lax.psum(p, axis) if axis_size(axis) > 1 else p
    return fused_gemm.matmul_reduce_scatter(
        x_shard, w_row, axis, codec="int8" if quantize else None,
        block_size=block)
