"""Quantized collectives (ZeRO++ analogs) composed inside shard_map.

Reference analog: ``deepspeed/runtime/comm/coalesced_collectives.py`` —
``all_to_all_quant_reduce`` (:31, qgZ: quantize grads, 2-hop all-to-all,
dequant-reduce) and the qwZ quantized weight allgather
(``zero/partition_parameters.py:1200`` ``all_gather_coalesced(quantize=True)``),
backed by ``csrc/quantization/swizzled_quantize.cu`` / ``quant_reduce.cu``.

TPU-native redesign: quantization is the Pallas/XLA int8 block quantizer
(``ops/quant.py``) and the communication is a plain ``jax.lax`` collective the
compiler schedules over ICI — the "2-hop intra-then-inter node" trick in the
reference exists because NCCL trees are latency-bound across nodes; on a TPU
slice XLA already routes all_to_all over ICI optimally, and on multi-slice
meshes the hierarchical hop falls out of splitting the axis (ici x dcn) in the
mesh rather than hand-written kernels.

Blocking invariant: quantization blocks never straddle a shard boundary — each
destination shard is padded up to a whole number of blocks before quantization
so the (values, scales) pairs stay aligned through the collective.

These functions must run inside ``shard_map`` (axis names bound). Comm volume:
int8 values + one f32 scale per block ~= 4x reduction vs f32, 2x vs bf16.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deepspeed_tpu.comm import comm as dist
from deepspeed_tpu.utils.compat import axis_size as _axis_size_compat
from deepspeed_tpu.ops.quant import dequantize_int8, quantize_int8

DEFAULT_BLOCK = 2048


def _padded(n: int, block: int) -> int:
    return -(-n // block) * block


def quantized_reduce_scatter(grad: jax.Array, axis: str, block_size: int = DEFAULT_BLOCK) -> jax.Array:
    """qgZ analog: int8-quantized gradient reduce-scatter over ``axis``.

    Input: full local gradient [N] (N divisible by axis size). Output: this
    rank's reduced shard [N / world], averaged over ranks. Exact math:
    quantize per destination shard -> all_to_all -> dequantize -> mean.
    """
    n = _axis_size_compat(axis)
    flat = grad.reshape(-1)
    N = flat.shape[0]
    assert N % n == 0, f"grad numel {N} not divisible by axis size {n}"
    shard = N // n
    block = min(block_size, shard)
    shard_p = _padded(shard, block)  # blocks stay within one destination shard
    rows = flat.reshape(n, shard)
    if shard_p != shard:
        rows = jnp.pad(rows, ((0, 0), (0, shard_p - shard)))

    vals, scales = quantize_int8(rows, block_size=block)  # row-aligned: shard_p % block == 0
    vals = vals.reshape(n, shard_p)
    scales = scales.reshape(n, shard_p // block)

    # Each rank receives every peer's int8 copy of *its* shard (+ scales).
    vals_t = dist.all_to_all(vals, axis, split_axis=0, concat_axis=0)  # [n, shard_p]
    scales_t = dist.all_to_all(scales, axis, split_axis=0, concat_axis=0)

    deq = dequantize_int8(
        vals_t.reshape(-1), scales_t.reshape(-1), (n, shard_p), dtype=jnp.float32,
        block_size=block,
    )
    return jnp.mean(deq[:, :shard], axis=0).astype(grad.dtype)


def quantized_all_gather(x: jax.Array, axis: str, block_size: int = DEFAULT_BLOCK) -> jax.Array:
    """qwZ analog: int8-quantized weight allgather over ``axis``.

    Input: local shard [M]; output: dequantized full buffer [world * M] in
    x.dtype. Halves (vs bf16) the allgather bytes on the wire.
    """
    flat = x.reshape(-1)
    M = flat.shape[0]
    block = min(block_size, M)
    M_p = _padded(M, block)
    if M_p != M:
        flat = jnp.pad(flat, (0, M_p - M))

    vals, scales = quantize_int8(flat, block_size=block)
    # Gather the *padded* blocked buffers so per-rank block boundaries survive.
    vals_g = dist.all_gather(vals.reshape(1, M_p), axis, concat_axis=0)  # [n, M_p]
    scales_g = dist.all_gather(scales.reshape(1, -1), axis, concat_axis=0)
    n = _axis_size_compat(axis)
    deq = dequantize_int8(
        vals_g.reshape(-1), scales_g.reshape(-1), (n, M_p), dtype=x.dtype,
        block_size=block,
    )
    return deq[:, :M].reshape(n * M)
