"""Quantized collectives (ZeRO++ analogs) composed inside shard_map.

Reference analog: ``deepspeed/runtime/comm/coalesced_collectives.py`` —
``all_to_all_quant_reduce`` (:31, qgZ: quantize grads, 2-hop all-to-all,
dequant-reduce) and the qwZ quantized weight allgather
(``zero/partition_parameters.py:1200`` ``all_gather_coalesced(quantize=True)``),
backed by ``csrc/quantization/swizzled_quantize.cu`` / ``quant_reduce.cu``.

These are now thin wrappers over the shared wire codec layer
(``collectives/codecs.py``): the int8 blockwise format (values + per-block
fp32 scales, blocks never straddling a shard boundary) is defined exactly
once there and reused by the hop-composed algorithms, the zeropp custom-vjp
gathers, and these all_to_all helpers. Comm volume: int8 values + one f32
scale per block ~= 4x reduction vs f32, 2x vs bf16.

These functions must run inside ``shard_map`` (axis names bound).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deepspeed_tpu.collectives.codecs import get_codec
from deepspeed_tpu.comm import comm as dist
from deepspeed_tpu.utils.compat import axis_size

DEFAULT_BLOCK = 2048


def gather_wire(wire, axis):
    """All-gather every non-empty leaf of a wire pytree (concat axis 0),
    pinned to the plain lowering: an already-encoded wire must never route
    back through the algorithmic/codec path. THE wire-movement idiom shared
    with the zeropp custom-vjp gathers."""
    return jax.tree_util.tree_map(
        lambda w: w if w.size == 0 else dist.all_gather(
            w, axis, concat_axis=0, algorithm="lax"), wire)


def exchange_wire(wire, axis):
    """All-to-all every non-empty leaf of a wire pytree (split/concat axis 0
    — the qgZ destination-shard exchange), pinned to the plain lowering
    like :func:`gather_wire`: an already-encoded wire must never route back
    through the algorithmic/codec path."""
    return jax.tree_util.tree_map(
        lambda w: w if w.size == 0 else dist.all_to_all(
            w, axis, split_axis=0, concat_axis=0, algorithm="lax"), wire)


def quantized_reduce_scatter(grad: jax.Array, axis: str, block_size: int = DEFAULT_BLOCK,
                             codec: str = "int8") -> jax.Array:
    """qgZ analog: quantized gradient reduce-scatter over ``axis``.

    Input: full local gradient [N] (N divisible by axis size). Output: this
    rank's reduced shard [N / world], averaged over ranks. Exact math:
    encode per destination shard -> all_to_all -> decode -> mean.
    """
    n = axis_size(axis)
    flat = grad.reshape(-1)
    N = flat.shape[0]
    assert N % n == 0, f"grad numel {N} not divisible by axis size {n}"
    shard = N // n
    c = get_codec(codec, min(block_size, shard))
    wire = c.encode_rows(flat.reshape(n, shard))  # row-aligned blocks per dest shard

    # Each rank receives every peer's encoded copy of *its* shard (+ scales).
    deq = c.decode_rows(exchange_wire(wire, axis), shard, jnp.float32)  # [n, shard]
    return jnp.mean(deq, axis=0).astype(grad.dtype)


def quantized_all_gather(x: jax.Array, axis: str, block_size: int = DEFAULT_BLOCK,
                         codec: str = "int8") -> jax.Array:
    """qwZ analog: quantized weight allgather over ``axis``.

    Input: local shard [M]; output: decoded full buffer [world * M] in
    x.dtype. Halves (vs bf16) the allgather bytes on the wire.
    """
    flat = x.reshape(-1)
    M = flat.shape[0]
    c = get_codec(codec, min(block_size, M))
    wire = c.encode_rows(flat[None])  # [1, M] -> padded blocked wire
    # Gather the *padded* blocked wire so per-rank block boundaries survive.
    wire_g = gather_wire(wire, axis)
    n = axis_size(axis)
    return c.decode_rows(wire_g, M, x.dtype).reshape(n * M)
