"""Pipeline-parallel model description.

TPU-native analog of ``deepspeed/runtime/pipe/module.py`` (``LayerSpec`` :30,
``TiedLayerSpec`` :77, ``PipelineModule`` :86). A model is declared as an
ordered list of layer specs; the pipeline engine partitions them into stages
over the ``pp`` mesh axis. Execution (1F1B) lives in pipeline_engine.py.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import numpy as np


class LayerSpec:
    """Deferred layer constructor (reference ``pipe/module.py:30``)."""

    def __init__(self, typename: Callable, *args, **kwargs):
        self.typename = typename
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.typename(*self.args, **self.kwargs)

    def __repr__(self) -> str:
        return f"LayerSpec({getattr(self.typename, '__name__', self.typename)})"


class TiedLayerSpec(LayerSpec):
    """Layer whose parameters are shared across stages (reference :77)."""

    def __init__(self, key: str, typename: Callable, *args, forward_fn=None, tied_weight_attr="embedding", **kwargs):
        super().__init__(typename, *args, **kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Balanced contiguous partition bounds (len = num_parts + 1)."""
    bounds = [0]
    for p in range(num_parts):
        bounds.append(bounds[-1] + num_items // num_parts + (1 if p < num_items % num_parts else 0))
    return bounds


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Weight-balanced contiguous partition via prefix-sum bisection
    (the reference's ``ds_utils.partition_balanced`` strategy)."""
    prefix = np.concatenate([[0.0], np.cumsum(np.asarray(weights, dtype=np.float64))])
    total = prefix[-1]
    bounds = [0]
    for p in range(1, num_parts):
        target = total * p / num_parts
        idx = int(np.searchsorted(prefix, target))
        idx = max(bounds[-1] + 1, min(idx, len(weights) - (num_parts - p)))
        bounds.append(idx)
    bounds.append(len(weights))
    return bounds


class PipelineModule:
    """Ordered layer-spec model for pipeline execution (reference :86).

    ``layers`` is a list of LayerSpec / callables / Flax modules. Each layer's
    ``__call__(carry, train=...)`` maps the activation pytree through; the
    first layer receives the batch.
    """

    def __init__(
        self,
        layers: Sequence[Any],
        num_stages: Optional[int] = None,
        loss_fn: Optional[Callable] = None,
        partition_method: str = "uniform",
        activation_checkpoint_interval: int = 0,
        seed_layers: bool = False,
        example_input: Any = None,
        num_microbatches: Optional[int] = None,
        virtual_stages: int = 1,
    ):
        self.layer_specs = [l if isinstance(l, LayerSpec) else LayerSpec(lambda l=l: l) for l in layers]
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.seed_layers = seed_layers
        # example_input: activation fed to the first layer at init time (the
        # reference infers shapes lazily from the first batch; the compiled
        # SPMD engine needs them at construction). Required when any layer
        # has parameters.
        self.example_input = example_input
        # pipeline microbatches per engine micro-batch (default: pp world).
        self.num_microbatches = num_microbatches
        # Megatron-style interleaving: chunks per device; bubble shrinks by V
        # (spmd_pipeline_interleaved). Requires stack % (pp*V) == 0 and
        # microbatches % pp == 0.
        self.virtual_stages = virtual_stages

    def __len__(self) -> int:
        return len(self.layer_specs)

    def partition_layers(self, num_stages: int, weights: Optional[Sequence[float]] = None) -> List[int]:
        """Stage bounds (reference ``_partition_layers`` pipe/module.py:393)."""
        method = self.partition_method.lower()
        if method == "uniform" or weights is None:
            return partition_uniform(len(self.layer_specs), num_stages)
        if method in ("parameters", "balanced"):
            return partition_balanced(weights, num_stages)
        raise ValueError(f"Unknown partition_method {self.partition_method!r}")
