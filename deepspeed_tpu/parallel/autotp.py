"""AutoTP: automatic tensor-parallel placement of parameter pytrees.

TPU-native analog of reference AutoTP (``module_inject/auto_tp.py:193``
``tp_parser``/``_replace`` + ``module_inject/layers.py`` LinearLayer/
LinearAllreduce): instead of swapping modules and slicing weights rank by
rank, placement is a PartitionSpec per parameter — XLA inserts the
all-reduces a row-parallel linear needs. Rules are name-based functions
``(keystr_path, shape) -> PartitionSpec | None`` (see
``models/transformer.py:causal_lm_partition_rules``); this module applies
them with the uneven-shard fallback the reference handles in
``module_inject/tp_shard.py:get_shard_size`` (here: replicate any dim the
mesh axis does not divide, since XLA requires even shards).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rules = Callable[[str, tuple], Optional[P]]


def divisible_spec(spec: Optional[P], shape: tuple, mesh: Mesh) -> P:
    """Drop spec entries whose mesh-axis product does not divide the dim."""
    if spec is None:
        return P()
    entries = []
    for dim, entry in enumerate(spec):
        if entry is None:
            entries.append(None)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        size = int(np.prod([mesh.shape[a] for a in axes]))
        entries.append(entry if shape[dim] % size == 0 else None)
    return P(*entries)


def place_parameters(params: Any, mesh: Mesh, rules: Rules, dtype: Any = None) -> Any:
    """device_put every leaf by its rule's spec (floats cast to ``dtype``)."""

    def _place(path, leaf):
        arr = jnp.asarray(leaf)
        spec = divisible_spec(rules(jax.tree_util.keystr(path), arr.shape), arr.shape, mesh)
        if dtype is not None and jnp.issubdtype(arr.dtype, jnp.floating):
            arr = arr.astype(dtype)
        return jax.device_put(arr, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(_place, params)
