"""AutoTP: automatic tensor-parallel placement of parameter pytrees.

TPU-native analog of reference AutoTP (``module_inject/auto_tp.py:193``
``tp_parser``/``_replace`` + ``module_inject/layers.py`` LinearLayer/
LinearAllreduce): instead of swapping modules and slicing weights rank by
rank, placement is a PartitionSpec per parameter — XLA inserts the
all-reduces a row-parallel linear needs. Rules are name-based functions
``(keystr_path, shape) -> PartitionSpec | None`` (see
``models/transformer.py:causal_lm_partition_rules``); this module applies
them with the uneven-shard fallback the reference handles in
``module_inject/tp_shard.py:get_shard_size`` (here: replicate any dim the
mesh axis does not divide, since XLA requires even shards).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rules = Callable[[str, tuple], Optional[P]]


def divisible_spec(spec: Optional[P], shape: tuple, mesh: Mesh) -> P:
    """Drop spec entries whose mesh-axis product does not divide the dim."""
    if spec is None:
        return P()
    entries = []
    for dim, entry in enumerate(spec):
        if entry is None:
            entries.append(None)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        size = int(np.prod([mesh.shape[a] for a in axes]))
        entries.append(entry if shape[dim] % size == 0 else None)
    return P(*entries)


def place_parameters(params: Any, mesh: Mesh, rules: Rules, dtype: Any = None) -> Any:
    """device_put every leaf by its rule's spec (floats cast to ``dtype``).

    Pre-quantized WOQ leaves (``inference/woq.WOQTensor`` — quantized BEFORE
    placement so the dense weights never materialize on device) place
    replicated: the packed [blocks]-flat layout doesn't line up with the
    name-based dim rules, and under GSPMD replication only costs memory, not
    correctness. The inference engines therefore only pre-quantize on tp=1
    meshes (where replicated == the whole model anyway, and the pre-flight
    guard's quantized estimate is exact); tp>1 places dense shards and
    quantizes in place instead. Scales stay fp32 (never cast — dequant math
    needs them).
    """
    from deepspeed_tpu.inference.woq import WOQTensor

    def _place(path, leaf):
        if isinstance(leaf, WOQTensor):
            rep = NamedSharding(mesh, P())
            return WOQTensor(jax.device_put(leaf.q, rep),
                             jax.device_put(leaf.scale, rep),
                             leaf.fmt, leaf.shape, stacked=leaf.stacked)
        arr = jnp.asarray(leaf)
        spec = divisible_spec(rules(jax.tree_util.keystr(path), arr.shape), arr.shape, mesh)
        if dtype is not None and jnp.issubdtype(arr.dtype, jnp.floating):
            arr = arr.astype(dtype)
        return jax.device_put(arr, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(
        _place, params, is_leaf=lambda x: isinstance(x, WOQTensor))


# ---------------------------------------------------------------------------
# Automatic rule inference (the tp_parser analog)
# ---------------------------------------------------------------------------
# Name patterns across HF model families (reference ``auto_tp.py`` carries the
# same knowledge as per-arch policy lists + tp_parser graph analysis; flax
# kernels are [in, out], so column-parallel = shard LAST dim, row-parallel =
# shard FIRST weight dim).
_COLUMN_PATTERNS = (
    "q_proj", "k_proj", "v_proj", "query", "key", "value", "c_attn", "qkv",
    "qkv_proj", "query_key_value", "gate_proj", "up_proj", "fc1", "fc_in",
    "wi", "wi_0", "wi_1", "w1", "w3", "c_fc", "intermediate", "wq", "wk",
    "wv", "w_gate", "w_up", "lin1",
)
_ROW_PATTERNS = (
    "o_proj", "out_proj", "dense_4h_to_h", "down_proj", "fc2", "fc_out", "wo",
    "w2", "c_proj", "attention_output", "w_down", "lin2",
)
_EMBED_PATTERNS = ("wte", "embed_tokens", "word_embeddings", "shared")
_HEAD_PATTERNS = ("lm_head", "embed_out", "score", "classifier")
# attention-output projections that share a name with generic 'dense' need
# position context: '...attention...dense' is row-parallel (BERT-style)
_ROW_IF_ATTN = ("dense",)


def _has(path_lower: str, token: str) -> bool:
    """Whole-quoted-name match against a keystr path — substring matching
    misclassifies (e.g. 'shared_expert' is not 'shared'); same idiom as
    ``causal_lm_partition_rules.has`` in models/transformer.py."""
    return f"'{token}'" in path_lower


def infer_tp_spec(path: str, shape: tuple) -> Optional[P]:
    """Infer the tensor-parallel PartitionSpec for one parameter.

    Reference analog: ``AutoTP.tp_parser`` + the per-arch policy classes
    (module_inject/auto_tp.py:193, containers/*) collapsed into one
    name-pattern classifier over pytree paths. Handles both pytree layouts:
    flax (leaf 'kernel', [in, out]) and torch state dicts (leaf 'weight',
    [out, in]) — the output dim is LAST for flax, FIRST for torch.
    """
    # normalize flat torch state-dict keys ('self_attn.q_proj.weight' as one
    # component) into quoted components so whole-name matching applies
    p = path.lower().replace(".", "']['")
    is_flax_kernel = _has(p, "kernel")
    is_torch_weight = _has(p, "weight")
    is_kernel = is_flax_kernel or is_torch_weight
    is_bias = _has(p, "bias")
    ndim = len(shape)
    out_dim_last = not is_torch_weight  # torch Linear.weight is [out, in]

    def col_spec():
        if ndim == 1:
            return P("tp") if is_bias else None
        if is_bias:
            # DenseGeneral-style [heads, head_dim] bias: shard the heads axis
            return P(*(["tp"] + [None] * (ndim - 1)))
        if ndim >= 3:
            # flax DenseGeneral kernel [in, heads, head_dim]
            return P(*([None] * (ndim - 2) + ["tp", None]))
        return P(None, "tp") if out_dim_last else P("tp", None)

    def row_spec():
        if ndim == 1:
            return None  # row-parallel bias is replicated (added after reduce)
        if ndim >= 3:
            return P(*([None] * (ndim - 3) + ["tp", None, None]))
        return P("tp", None) if out_dim_last else P(None, "tp")

    for tok in _HEAD_PATTERNS:
        if _has(p, tok):
            if is_kernel:
                return col_spec()
            return P("tp") if is_bias and ndim == 1 else None
    for tok in _EMBED_PATTERNS:
        if _has(p, tok) and ndim == 2:
            return P("tp", None)  # vocab dim (same layout flax & torch)
    for tok in _ROW_PATTERNS:
        if _has(p, tok):
            return row_spec() if is_kernel else None
    for tok in _COLUMN_PATTERNS:
        if _has(p, tok):
            return col_spec() if (is_kernel or is_bias) else None
    for tok in _ROW_IF_ATTN:
        if _has(p, tok) and ("attention" in p or "attn" in p):
            return row_spec() if is_kernel else None
    return None


def tp_model_init(params: Any, mesh: Optional[Mesh] = None, dtype: Any = None,
                  extra_rules: Optional[Rules] = None) -> Any:
    """Shard ANY HF-style param pytree over the mesh's ``tp`` axis
    (reference ``deepspeed.tp_model_init`` __init__.py:369 +
    ``TpTrainingManager`` runtime/tensor_parallel/tp_manager.py:12).

    ``extra_rules`` runs first for model-specific overrides; unknown params
    replicate. XLA inserts the row-parallel all-reduces the reference
    implements as ``LinearAllreduce`` modules.
    """
    if mesh is None:
        from deepspeed_tpu.topology.mesh import get_mesh

        mesh = get_mesh()

    def rules(path: str, shape: tuple) -> Optional[P]:
        if extra_rules is not None:
            spec = extra_rules(path, shape)
            if spec is not None:
                return spec
        return infer_tp_spec(path, shape)

    return place_parameters(params, mesh, rules, dtype=dtype)
