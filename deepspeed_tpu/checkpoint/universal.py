"""Universal (mesh-reshapeable) checkpoints + fp32 consolidation.

TPU-native analog of the reference universal-checkpoint suite:
  - ``checkpoint/ds_to_universal.py`` (:112 extract_zero_shards, :232
    merge_tp_slices): offline conversion of a sharded checkpoint into
    mesh-independent fp32 "atoms" reloadable under ANY parallel layout
  - ``checkpoint/universal_checkpoint.py:16 load_hp_checkpoint_state``:
    loading those atoms into a differently-sharded run
  - ``utils/zero_to_fp32.py`` (:533,:598): consolidating a ZeRO checkpoint
    into a single fp32 state dict offline

On TPU the hard part disappears by construction: the training state is one
global pytree (sharding is a placement property, not a storage property), so
"extract shards + merge slices" reduces to device_get → one .npz of fp32
arrays keyed by pytree path. Loading re-places every atom with the *target*
engine's shardings — any mesh, any ZeRO stage, any tp/pp/dp split.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger

UNIVERSAL_DIR = "universal"


def _tag_step(tag: str) -> int:
    """Numeric sort key for global_stepN tags (lexicographic misorders 9 vs 10)."""
    digits = "".join(c for c in tag if c.isdigit())
    return int(digits) if digits else -1


def write_npz_atomic(path: str, atoms: Dict[str, Any]) -> str:
    """``np.savez`` to a same-directory tmp file + ``os.replace``: readers
    see a whole file or none."""
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez(f, **atoms)
    os.replace(tmp, path)
    return path


def _flatten(tree: Any) -> Dict[str, Any]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        flat[jax.tree_util.keystr(path)] = leaf
    return flat


def state_to_atoms(state) -> Dict[str, np.ndarray]:
    """TrainState -> {path: fp32/int numpy atom} (the merge_tp_slices analog)."""
    atoms = {}
    for key, leaf in _flatten(state._asdict()).items():
        if leaf is None:
            continue
        arr = np.asarray(jax.device_get(leaf))
        if arr.dtype in (np.dtype(jnp.bfloat16), np.float16):
            arr = arr.astype(np.float32)
        atoms[key] = arr
    return atoms


def _fp32_state_tree(state) -> Dict[str, Any]:
    """State dict with 16-bit floats widened to fp32 atoms, device-side.

    ``comm_error`` (1-bit error-feedback residuals) is per-run, per-mesh
    scratch and is deliberately NOT part of a mesh-independent checkpoint —
    its leaves are shaped [dp_world, ...], so a cross-mesh restore could never
    consume it (checkpointing.py treats it the same way on regular loads)."""

    def widen(x):
        if x is None:
            return None
        if hasattr(x, "dtype") and x.dtype in (jnp.bfloat16, jnp.float16):
            return x.astype(jnp.float32)
        return x

    d = dict(state._asdict())
    d.pop("comm_error", None)
    # health-probe EMAs are per-run scratch too (checkpointing.py treats them
    # the same on regular loads): a universal checkpoint written with
    # diagnostics off must restore into an engine with them on, and vice versa
    d.pop("health", None)
    return jax.tree_util.tree_map(widen, d)


def save_universal(engine, save_dir: str, tag: Optional[str] = None,
                   sidecar: bool = True) -> str:
    """Write a mesh-independent checkpoint (ds_to_universal done online).

    v2 format: the fp32 atom tree streams through orbax/tensorstore — each
    host writes its own shards in parallel and no consolidated host copy is
    ever built (the round-2 verdict's scalability fix; the reference keeps
    per-param atom FILES for the same reason, ``ds_to_universal.py:112``).

    ``sidecar=True`` (default) additionally writes ``atoms_host.npz`` — the
    payload ``load_universal(placement='fresh')`` restores with plain numpy,
    never running orbax in the restoring process (an in-process tensorstore
    restore + persistent-compilation-cache reads corrupt the heap on this
    jax/orbax stack — see ``checkpointing._restore_placement``). The sidecar
    is one consolidated host copy; disable it for models too large to ever
    consolidate (those restores must then run cache-free).
    """
    tag = tag or f"global_step{engine.global_steps}"
    path = os.path.join(save_dir, UNIVERSAL_DIR, tag)
    os.makedirs(path, exist_ok=True)
    state = engine.state
    canon = getattr(engine, "canonical_opt_state", None)
    if canon is not None:
        # Twin-Flow masked partitions merge to the param-shaped moment tree:
        # atom paths must be partitioning-independent (the format's contract)
        state = state._replace(opt_state=canon(state.opt_state))
    atoms = _fp32_state_tree(state)
    if getattr(engine, "_twin_ratio", None) is not None:
        # mixed host/mesh placements -> host numpy atoms (same rationale as
        # checkpointing.save_checkpoint: a checkpoint must not encode
        # placement, and cross-placement orbax restores have bitten us)
        atoms = jax.tree_util.tree_map(
            lambda x: None if x is None else np.asarray(jax.device_get(x)),
            atoms, is_leaf=lambda x: x is None)
    n_atoms = len(jax.tree_util.tree_leaves(atoms))

    import orbax.checkpoint as ocp

    atom_path = os.path.join(os.path.abspath(path), "atoms")
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(atom_path, atoms, force=True)
    if sidecar and jax.process_index() == 0:
        # one consolidated host copy on process 0 only — the knob above is
        # the escape hatch for models too large to ever consolidate
        host_flat = {k: np.asarray(jax.device_get(v))
                     for k, v in _flatten(atoms).items() if v is not None}
        write_npz_atomic(os.path.join(path, "atoms_host.npz"), host_flat)
    meta = {
        "version": 2,
        "step": int(jax.device_get(engine.state.step)),
        "source_mesh": {k: int(v) for k, v in dict(engine.mesh.shape).items()},
        "zero_stage": engine.zero_config.stage,
        "n_atoms": n_atoms,
    }
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f)
    log_dist(f"saved universal checkpoint {path} ({n_atoms} atoms, streamed)", ranks=[0])
    return path


def load_universal(engine, load_dir: str, tag: Optional[str] = None,
                   strict: bool = True, placement: str = "fresh") -> str:
    """Restore a universal checkpoint into an engine on ANY mesh/stage.

    Every atom is device_put with the *current* engine's sharding for that
    leaf (reference ``load_hp_checkpoint_state`` re-slices per rank; XLA does
    the slicing here).

    ``placement='fresh'`` (default) restores the atoms from the
    ``atoms_host.npz`` sidecar with plain numpy — no orbax in the restoring
    process — and places each through ``utils.compat.device_put_unaliased``
    into buffers XLA owns exclusively (a zero-copy device_put of host numpy
    feeding the engine's donated steps is the PR-1 heap-corruption
    landmine; see ``checkpointing._restore_placement``). A sidecar-less
    checkpoint falls back to the in-process orbax host-read (same unaliased
    placement). ``placement='streamed'`` keeps the direct tensorstore→device
    restore (each host reads only its slices; orbax materializes the
    buffers itself, outside the unaliased fence — safe only for engines
    that never step afterwards).
    """
    if placement not in ("fresh", "streamed"):
        raise ValueError(f"placement={placement!r}: must be 'fresh' or 'streamed'")
    base = os.path.join(load_dir, UNIVERSAL_DIR)
    if tag is None:
        tags = sorted(os.listdir(base), key=_tag_step) if os.path.isdir(base) else []
        if not tags:
            raise FileNotFoundError(f"no universal checkpoints under {base}")
        tag = tags[-1]
    path = os.path.join(base, tag)
    npz_file = os.path.join(path, "atoms.npz")
    if os.path.exists(npz_file):  # v1 single-npz format
        return _load_universal_npz(engine, path, npz_file, strict)
    host_npz = os.path.join(path, "atoms_host.npz")
    if placement == "fresh" and os.path.exists(host_npz):
        # v2 fresh-restore sidecar: plain-numpy read + device_put with the
        # target engine's shardings — orbax never runs in this process
        return _load_universal_npz(engine, path, host_npz, strict)

    import orbax.checkpoint as ocp

    state_dict = dict(engine.state._asdict())
    comm_error = state_dict.pop("comm_error", None)  # per-run scratch, not saved
    health = state_dict.pop("health", None)  # per-run scratch, not saved
    canon = getattr(engine, "canonical_opt_state", None)
    if canon is not None:
        # restore against the canonical (partition-independent) structure;
        # re-partitioned into the target engine's Twin-Flow split below
        state_dict["opt_state"] = canon(state_dict["opt_state"])

    if placement == "fresh":
        # Sidecar-less checkpoint: in-process orbax host-read, then the same
        # unaliased placement (orbax only hands back host numpy here).
        logger.warning(
            f"universal checkpoint {path} has no atoms_host.npz sidecar "
            "(pre-PR-6 format): restoring via in-process orbax host-read; "
            "re-save to upgrade to the orbax-free restore payload")
        host_target = jax.tree_util.tree_map(lambda _x: 0, state_dict)
        host_args = jax.tree_util.tree_map(lambda _x: ocp.RestoreArgs(), state_dict)
        with ocp.PyTreeCheckpointer() as ckptr:
            atoms_host = ckptr.restore(
                os.path.join(os.path.abspath(path), "atoms"),
                item=host_target, restore_args=host_args)

        def place(atom, leaf):
            if atom is None or leaf is None:
                return leaf
            if isinstance(leaf, jax.Array):
                from deepspeed_tpu.utils.compat import device_put_unaliased

                arr = np.asarray(atom)
                if arr.dtype != leaf.dtype:
                    arr = arr.astype(leaf.dtype)
                return device_put_unaliased(arr, leaf.sharding)
            return atom

        restored = jax.tree_util.tree_map(
            place, atoms_host, state_dict, is_leaf=lambda x: x is None)
    else:
        # streamed: tensorstore restores directly into the TARGET engine's
        # shardings — every host reads only the slices it needs, so loading
        # scales with the local shard size, not the model.
        def widen_dtype(x):
            if x is None:
                return None
            dt = jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else x.dtype
            return jax.ShapeDtypeStruct(x.shape, dt, sharding=getattr(x, "sharding", None))

        target = jax.tree_util.tree_map(widen_dtype, state_dict)
        restore_args = jax.tree_util.tree_map(
            lambda t: ocp.ArrayRestoreArgs(sharding=t.sharding, global_shape=t.shape)
            if t is not None and t.sharding is not None else ocp.RestoreArgs(),
            target,
        )
        with ocp.PyTreeCheckpointer() as ckptr:
            restored = ckptr.restore(
                os.path.join(os.path.abspath(path), "atoms"), item=target, restore_args=restore_args
            )

        def narrow(atom, leaf):
            if atom is None or leaf is None:
                return leaf
            if isinstance(leaf, jax.Array) and atom.dtype != leaf.dtype:
                return atom.astype(leaf.dtype)
            return atom

        restored = jax.tree_util.tree_map(
            narrow, restored, state_dict, is_leaf=lambda x: x is None
        )
    restored["comm_error"] = comm_error  # fresh per-run residuals
    restored["health"] = health  # fresh per-run health baselines
    departition = getattr(engine, "opt_state_from_canonical", None)
    if departition is not None:
        restored["opt_state"] = departition(restored["opt_state"])
    engine.state = type(engine.state)(**restored)
    log_dist(f"loaded universal checkpoint {path} (streamed)", ranks=[0])
    return path


def _load_universal_npz(engine, path: str, npz_file: str, strict: bool) -> str:
    """v1 (single .npz) compatibility loader."""
    data = np.load(npz_file)
    state_dict = dict(engine.state._asdict())
    comm_error = state_dict.pop("comm_error", None)  # per-run scratch
    health = state_dict.pop("health", None)  # per-run scratch
    canon = getattr(engine, "canonical_opt_state", None)
    if canon is not None:
        state_dict["opt_state"] = canon(state_dict["opt_state"])
    flat_target = _flatten(state_dict)
    missing = [k for k in flat_target if k not in data.files and flat_target[k] is not None]
    # v1 checkpoints written before comm_error became per-run scratch may
    # carry its atoms; they are skipped, not a mismatch
    extra = [k for k in data.files
             if k not in flat_target
             and not k.startswith(("['comm_error']", "['health']"))]
    if (missing or extra) and strict:
        raise ValueError(f"universal checkpoint mismatch: missing={missing[:5]} extra={extra[:5]}")

    def _restore(path_keys, leaf):
        key = jax.tree_util.keystr(path_keys)
        if leaf is None or key not in data.files:
            return leaf
        atom = data[key]
        if isinstance(leaf, jax.Array):
            # unaliased: zero-copy device_put of host numpy + donated steps
            # is the PR-1 heap-corruption landmine (see utils.compat)
            from deepspeed_tpu.utils.compat import device_put_unaliased

            return device_put_unaliased(
                np.asarray(atom).astype(leaf.dtype, copy=False), leaf.sharding)
        return type(leaf)(atom) if np.isscalar(leaf) else atom

    restored = jax.tree_util.tree_map_with_path(_restore, state_dict)
    restored["comm_error"] = comm_error
    restored["health"] = health
    departition = getattr(engine, "opt_state_from_canonical", None)
    if departition is not None:
        restored["opt_state"] = departition(restored["opt_state"])
    engine.state = type(engine.state)(**restored)
    log_dist(f"loaded universal checkpoint {path}", ranks=[0])
    return path


# ------------------------------------------------------------ zero_to_fp32
def get_fp32_state_dict_from_checkpoint(ckpt_dir: str, tag: Optional[str] = None) -> Dict[str, np.ndarray]:
    """Offline: consolidated fp32 params from a saved checkpoint directory
    (reference ``zero_to_fp32.get_fp32_state_dict_from_zero_checkpoint``).

    Works on both universal checkpoints and regular Orbax ones.
    """
    upath = os.path.join(ckpt_dir, UNIVERSAL_DIR)
    if os.path.isdir(upath):
        tags = sorted(os.listdir(upath), key=_tag_step)
        tag = tag or (tags[-1] if tags else None)
        if tag and os.path.isdir(os.path.join(upath, tag)):
            for name in ("atoms.npz", "atoms_host.npz"):  # v1 / v2 sidecar
                npz_file = os.path.join(upath, tag, name)
                if os.path.exists(npz_file):
                    data = np.load(npz_file)
                    prefix = "['params']"
                    return {k[len(prefix):]: data[k].astype(np.float32)
                            for k in data.files if k.startswith(prefix)}
            import orbax.checkpoint as ocp  # v2: streamed atoms

            atom_dir = os.path.join(os.path.abspath(upath), tag, "atoms")
            with ocp.PyTreeCheckpointer() as ckptr:
                # partial restore: read ONLY the params subtree (the atom tree
                # also holds optimizer moments — ~3x the bytes for Adam)
                meta = ckptr.metadata(atom_dir).item_metadata.tree["params"]
                item = {"params": jax.tree_util.tree_map(lambda m: 0, meta)}
                restore_args = {"params": jax.tree_util.tree_map(lambda m: ocp.RestoreArgs(), meta)}
                restored = ckptr.restore(atom_dir, item=item, transforms={}, restore_args=restore_args)
            return {k: np.asarray(v, np.float32)
                    for k, v in _flatten(restored["params"]).items()}
    # regular checkpoint: prefer the numpy sidecar (orbax-free), else orbax
    if tag is None:
        latest = os.path.join(ckpt_dir, "latest")
        with open(latest) as f:
            tag = f.read().strip()
    from deepspeed_tpu.checkpoint.checkpointing import _sidecar_path

    sidecar = _sidecar_path(ckpt_dir, tag)
    if os.path.exists(sidecar):
        data = np.load(sidecar)
        prefix = "['params']"
        return {k[len(prefix):]: data[k].astype(np.float32)
                for k in data.files if k.startswith(prefix)}
    import orbax.checkpoint as ocp

    with ocp.PyTreeCheckpointer() as ckptr:
        restored = ckptr.restore(os.path.join(os.path.abspath(ckpt_dir), tag))
    flat = _flatten(restored["params"])
    return {k: np.asarray(v, np.float32) for k, v in flat.items()}


def convert_to_fp32_file(ckpt_dir: str, output_file: str, tag: Optional[str] = None) -> str:
    """CLI body (reference ``zero_to_fp32.py`` __main__): one .npz of fp32."""
    sd = get_fp32_state_dict_from_checkpoint(ckpt_dir, tag)
    np.savez(output_file, **sd)
    total = sum(v.size for v in sd.values())
    logger.info(f"wrote {len(sd)} tensors / {total/1e6:.1f}M params to {output_file}")
    return output_file


def main():  # pragma: no cover - CLI shim
    import argparse

    p = argparse.ArgumentParser(description="Consolidate a deepspeed_tpu checkpoint to fp32 (zero_to_fp32 analog)")
    p.add_argument("checkpoint_dir")
    p.add_argument("output_file")
    p.add_argument("--tag", default=None)
    a = p.parse_args()
    convert_to_fp32_file(a.checkpoint_dir, a.output_file, a.tag)


if __name__ == "__main__":  # pragma: no cover
    main()
