"""deepspeed_tpu.checkpoint: save/load, pluggable engines, universal reshape.

Reference analogs: ``runtime/engine.py:3274/:2928`` (save/load),
``runtime/checkpoint_engine/`` (engine ABC), ``checkpoint/ds_to_universal.py``
+ ``universal_checkpoint.py`` (mesh-independent atoms),
``utils/zero_to_fp32.py`` (fp32 consolidation CLI).
"""

from deepspeed_tpu.checkpoint.checkpointing import load_checkpoint, save_checkpoint
from deepspeed_tpu.checkpoint.engine import (
    AsyncCheckpointEngine,
    CheckpointEngine,
    OrbaxCheckpointEngine,
    get_checkpoint_engine,
)
from deepspeed_tpu.checkpoint.snapshot import (
    SnapshotCorruptionError,
    SnapshotError,
    SnapshotManager,
    restore_snapshot,
)
from deepspeed_tpu.checkpoint.universal import (
    convert_to_fp32_file,
    get_fp32_state_dict_from_checkpoint,
    load_universal,
    save_universal,
)
