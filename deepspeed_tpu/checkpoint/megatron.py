"""Legacy Megatron checkpoint ingestion + TP reshard (state-dict factory).

Reference analog: ``runtime/state_dict_factory.py:21 SDLoaderFactory`` /
``:190 MegatronSDLoader`` — load Megatron-LM GPT checkpoints saved at one
tensor-parallel degree and reshard them to another at load time (merge the
per-rank ``mp_rank_XX`` shards; optionally re-split). Also covers the fused
QKV handling of ``module_inject/fusedqkv_utils.py`` for the 'megatrontype'
blocked q|k|v ordering.

TPU mapping: merging to the FULL state is the only reshard primitive needed —
``parallel/autotp.place_parameters`` then lays the converted pytree onto any
mesh (tp degree is just a placement), so "reshard tp 4 -> 8" is merge + place
instead of the reference's merge + re-split + per-rank reload.
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.models.transformer import TransformerConfig

# --------------------------------------------------------------- categories
# Megatron-LM parallel layouts (reference state_dict_factory.py:190 and
# Megatron's ColumnParallelLinear/RowParallelLinear/VocabParallelEmbedding):
#   column-parallel: output dim sharded  -> merge cat(axis=0)
#   row-parallel:    input dim sharded   -> merge cat(axis=1); bias replicated
#   qkv:             column-parallel with blocked q|k|v per rank
#   replicated:      layernorms, position embeddings

_QKV = re.compile(r"attention\.query_key_value\.(weight|bias)$")
_COL_W = re.compile(r"(mlp\.dense_h_to_4h|word_embeddings)\.weight$")
_COL_B = re.compile(r"mlp\.dense_h_to_4h\.bias$")
_ROW_W = re.compile(r"(mlp\.dense_4h_to_h|attention\.dense)\.weight$")


def _category(key: str) -> str:
    if _QKV.search(key):
        return "qkv"
    if _COL_W.search(key) or _COL_B.search(key):
        return "col"
    if _ROW_W.search(key):
        return "row"
    return "replicated"


def merge_tp_state_dicts(sds: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    """Merge per-TP-rank Megatron state dicts into the full (tp=1) state.

    Reference ``MegatronSDLoader.merge_state_dict`` (state_dict_factory.py:190):
    qkv chunks are blocked q|k|v per rank, so each rank's tensor is split in
    3 and the thirds concatenated per category before recombining."""
    if len(sds) == 1:
        return dict(sds[0])
    out: Dict[str, np.ndarray] = {}
    for key in sds[0]:
        parts = [np.asarray(sd[key]) for sd in sds]
        cat = _category(key)
        if cat == "qkv":
            thirds = [np.split(p, 3, axis=0) for p in parts]  # per rank: q,k,v
            out[key] = np.concatenate(
                [np.concatenate([t[i] for t in thirds], axis=0) for i in range(3)],
                axis=0)
        elif cat == "col":
            out[key] = np.concatenate(parts, axis=0)
        elif cat == "row":
            out[key] = np.concatenate(parts, axis=1)
        else:
            if not all(np.array_equal(parts[0], p) for p in parts[1:]):
                raise ValueError(f"replicated tensor {key!r} differs across TP ranks")
            out[key] = parts[0]
    return out


def split_tp_state_dict(sd: Dict[str, np.ndarray], tp: int) -> List[Dict[str, np.ndarray]]:
    """Inverse of :func:`merge_tp_state_dicts` (reference ``split_state_dict``):
    produce ``tp`` Megatron-layout rank shards from the full state."""
    outs: List[Dict[str, np.ndarray]] = [dict() for _ in range(tp)]
    for key, val in sd.items():
        val = np.asarray(val)
        cat = _category(key)
        if cat == "qkv":
            q, k, v = np.split(val, 3, axis=0)
            for r, (qr, kr, vr) in enumerate(zip(np.split(q, tp, axis=0),
                                                 np.split(k, tp, axis=0),
                                                 np.split(v, tp, axis=0))):
                outs[r][key] = np.concatenate([qr, kr, vr], axis=0)
        elif cat == "col":
            for r, part in enumerate(np.split(val, tp, axis=0)):
                outs[r][key] = part
        elif cat == "row":
            for r, part in enumerate(np.split(val, tp, axis=1)):
                outs[r][key] = part
        else:
            for r in range(tp):
                outs[r][key] = val
    return outs


# ------------------------------------------------------------------- loading

def _strip_model_prefix(sd: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Flatten the megatron container nesting to transformer-relative keys."""
    # torch .pt files nest as {"model": {"language_model": {...}}}; the
    # language_model holds {"embedding": {...}, "transformer"|"encoder": {...}}
    if "model" in sd:
        sd = sd["model"]
    if "language_model" in sd:
        sd = sd["language_model"]
    flat: Dict[str, np.ndarray] = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}{k}." if not hasattr(v, "shape") else f"{prefix}{k}", v)
        else:
            flat[prefix.rstrip(".")] = np.asarray(node)

    walk("", sd)
    return flat


def load_megatron_checkpoint(ckpt_dir: str, tag: Optional[str] = None
                             ) -> Dict[str, np.ndarray]:
    """Read ``<dir>[/<tag>]/mp_rank_XX/model_optim_rng.pt`` shards and merge
    across the saved TP degree (reference SDLoaderFactory.get_sd_loader_json
    + MegatronSDLoader). Returns the FULL transformer-relative state dict."""
    import torch

    root = os.path.join(ckpt_dir, tag) if tag else ckpt_dir
    ranks = sorted(d for d in os.listdir(root) if d.startswith("mp_rank_"))
    if not ranks:
        raise FileNotFoundError(f"no mp_rank_* dirs under {root}")
    sds = []
    for r in ranks:
        fp = os.path.join(root, r, "model_optim_rng.pt")
        if not os.path.exists(fp):
            fp = os.path.join(root, r, "model_rng.pt")  # older layout
        raw = torch.load(fp, map_location="cpu", weights_only=False)
        sds.append({k: v.numpy() if hasattr(v, "numpy") else np.asarray(v)
                    for k, v in _strip_model_prefix(raw).items()})
    return merge_tp_state_dicts(sds)


# ------------------------------------------------------------------ convert

def config_from_megatron(state: Dict[str, np.ndarray], num_heads: int,
                         **overrides) -> TransformerConfig:
    """Infer a TransformerConfig from a merged Megatron GPT state dict
    (classic GPT-2 recipe: layernorm + gelu + learned positions + tied head)."""
    vocab, h = state["embedding.word_embeddings.weight"].shape
    max_seq = state["embedding.position_embeddings.weight"].shape[0]
    layer_ids = {int(m.group(1)) for k in state
                 if (m := re.search(r"layers\.(\d+)\.", k))}
    inter = state["transformer.layers.0.mlp.dense_h_to_4h.weight"].shape[0] \
        if "transformer.layers.0.mlp.dense_h_to_4h.weight" in state \
        else state["encoder.layers.0.mlp.dense_h_to_4h.weight"].shape[0]
    kw = dict(
        vocab_size=vocab, hidden_size=h, intermediate_size=inter,
        num_layers=max(layer_ids) + 1, num_heads=num_heads, max_seq_len=max_seq,
        norm="layernorm", activation="gelu", position="learned",
        tie_embeddings=True,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def convert_megatron_state(state: Dict[str, np.ndarray],
                           cfg: TransformerConfig) -> Dict[str, Any]:
    """Merged Megatron GPT state -> CausalLM stacked-scan param pytree."""
    from deepspeed_tpu.checkpoint.hf import _getter, _stack

    h, hd, H = cfg.hidden_size, cfg.dims_per_head, cfg.num_heads
    g = _getter(state, ("transformer.", "encoder.", ""))

    def layer(i):
        p = f"layers.{i}."
        qkv_w = g(p + "attention.query_key_value.weight")  # [3h, h] q|k|v
        qkv_b = g(p + "attention.query_key_value.bias")
        wq, wk, wv = np.split(qkv_w, 3, axis=0)
        bq, bk, bv = np.split(qkv_b, 3)
        return {
            "attn_norm": {"scale": g(p + "input_layernorm.weight"),
                          "bias": g(p + "input_layernorm.bias")},
            "mlp_norm": {"scale": g(p + "post_attention_layernorm.weight"),
                         "bias": g(p + "post_attention_layernorm.bias")},
            "attn": {
                "wq": {"kernel": wq.T.reshape(h, H, hd), "bias": bq.reshape(H, hd)},
                "wk": {"kernel": wk.T.reshape(h, H, hd), "bias": bk.reshape(H, hd)},
                "wv": {"kernel": wv.T.reshape(h, H, hd), "bias": bv.reshape(H, hd)},
                "wo": {"kernel": g(p + "attention.dense.weight").T.reshape(H, hd, h),
                       "bias": g(p + "attention.dense.bias")},
            },
            "mlp": {
                "w_up": {"kernel": g(p + "mlp.dense_h_to_4h.weight").T,
                         "bias": g(p + "mlp.dense_h_to_4h.bias")},
                "w_down": {"kernel": g(p + "mlp.dense_4h_to_h.weight").T,
                           "bias": g(p + "mlp.dense_4h_to_h.bias")},
            },
        }

    return {
        "embed": {"embedding": state["embedding.word_embeddings.weight"]},
        "pos_embed": state["embedding.position_embeddings.weight"],
        "final_norm": {"scale": g("final_layernorm.weight"),
                       "bias": g("final_layernorm.bias")},
        "layers": _stack(layer, cfg.num_layers),
    }


def load_megatron_model(ckpt_dir: str, num_heads: int, tag: Optional[str] = None,
                        **cfg_overrides) -> Tuple[TransformerConfig, Dict[str, Any]]:
    """One call: sharded Megatron checkpoint dir -> (config, params) at ANY
    target TP degree (placement decides — pass the params to
    ``initialize``/``init_inference`` on a mesh with the tp size you want)."""
    state = load_megatron_checkpoint(ckpt_dir, tag=tag)
    cfg = config_from_megatron(state, num_heads, **cfg_overrides)
    return cfg, convert_megatron_state(state, cfg)
