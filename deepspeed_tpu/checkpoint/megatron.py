"""Legacy Megatron checkpoint ingestion + TP reshard (state-dict factory).

Reference analog: ``runtime/state_dict_factory.py:21 SDLoaderFactory`` /
``:190 MegatronSDLoader`` — load Megatron-LM GPT checkpoints saved at one
tensor-parallel degree and reshard them to another at load time (merge the
per-rank ``mp_rank_XX`` shards; optionally re-split). Also covers the fused
QKV handling of ``module_inject/fusedqkv_utils.py`` for the 'megatrontype'
blocked q|k|v ordering.

TPU mapping: merging to the FULL state is the only reshard primitive needed —
``parallel/autotp.place_parameters`` then lays the converted pytree onto any
mesh (tp degree is just a placement), so "reshard tp 4 -> 8" is merge + place
instead of the reference's merge + re-split + per-rank reload.
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.models.transformer import TransformerConfig

# --------------------------------------------------------------- categories
# Megatron-LM parallel layouts (reference state_dict_factory.py:190 and
# Megatron's ColumnParallelLinear/RowParallelLinear/VocabParallelEmbedding):
#   column-parallel: output dim sharded  -> merge cat(axis=0)
#   row-parallel:    input dim sharded   -> merge cat(axis=1); bias replicated
#   qkv:             column-parallel with blocked q|k|v per rank
#   replicated:      layernorms, position embeddings

_QKV = re.compile(r"attention\.query_key_value\.(weight|bias)$")
_COL_W = re.compile(r"(mlp\.dense_h_to_4h|word_embeddings)\.weight$")
_COL_B = re.compile(r"mlp\.dense_h_to_4h\.bias$")
_ROW_W = re.compile(r"(mlp\.dense_4h_to_h|attention\.dense)\.weight$")


def _category(key: str) -> str:
    if _QKV.search(key):
        return "qkv"
    if _COL_W.search(key) or _COL_B.search(key):
        return "col"
    if _ROW_W.search(key):
        return "row"
    return "replicated"


#: Megatron Q/K/V row layouts by ``checkpoint_version`` (reference
#: state_dict_factory.py:220 merge_query_key_value docstring):
#:   0   — [(3 * np * hn), h]  globally-blocked q|k|v per rank
#:   1.0 — [(np * hn * 3), h]  per head: [hn, 3] interleaved
#:   2.0 — [(np * 3 * hn), h]  per head: [3, hn] blocked
_SUPPORTED_CKPT_VERSIONS = (0, 1.0, 2.0)
#: In-band metadata key carrying the Megatron ``checkpoint_version`` through
#: the Dict[str, ndarray] state (0-d float64). Stamped by the loader and by
#: merge/split; consumed (never treated as a weight) by merge/split/convert.
_VERSION_KEY = "_checkpoint_version"


def _check_version(version: float) -> float:
    if version not in _SUPPORTED_CKPT_VERSIONS:
        raise ValueError(
            f"Megatron checkpoint_version {version!r} is not supported "
            f"(known: {_SUPPORTED_CKPT_VERSIONS}); reference state_dict_factory.py:252")
    return version


def _resolve_version(sd: Dict[str, np.ndarray], version: Optional[float]) -> float:
    """Explicit ``version`` kwarg wins; else the state's in-band key; else v0."""
    if version is None:
        version = float(sd.get(_VERSION_KEY, 0))
    return _check_version(float(version))


def merge_tp_state_dicts(sds: List[Dict[str, np.ndarray]],
                         version: Optional[float] = None) -> Dict[str, np.ndarray]:
    """Merge per-TP-rank Megatron state dicts into the full (tp=1) state.

    Reference ``MegatronSDLoader.merge_state_dict`` (state_dict_factory.py:190)
    + ``merge_query_key_value`` (:220): version-0 qkv chunks are blocked q|k|v
    per rank, so each rank's tensor is split in 3 and the thirds concatenated
    per category; v1.0/v2.0 store per-HEAD-local q/k/v rows, so ranks merge by
    plain concat (heads are contiguous per rank)."""
    version = _resolve_version(sds[0], version)
    out: Dict[str, np.ndarray] = {}
    for key in sds[0]:
        if key == _VERSION_KEY:
            continue
        parts = [np.asarray(sd[key]) for sd in sds]
        cat = _category(key)
        if cat == "qkv" and version == 0 and len(sds) > 1:
            thirds = [np.split(p, 3, axis=0) for p in parts]  # per rank: q,k,v
            out[key] = np.concatenate(
                [np.concatenate([t[i] for t in thirds], axis=0) for i in range(3)],
                axis=0)
        elif cat in ("col", "qkv"):
            out[key] = np.concatenate(parts, axis=0)
        elif cat == "row":
            out[key] = np.concatenate(parts, axis=1)
        else:
            if not all(np.array_equal(parts[0], p) for p in parts[1:]):
                raise ValueError(f"replicated tensor {key!r} differs across TP ranks")
            out[key] = parts[0]
    out[_VERSION_KEY] = np.float64(version)
    return out


def split_tp_state_dict(sd: Dict[str, np.ndarray], tp: int,
                        version: Optional[float] = None) -> List[Dict[str, np.ndarray]]:
    """Inverse of :func:`merge_tp_state_dicts` (reference ``split_state_dict``
    + ``split_query_key_value`` state_dict_factory.py:257): produce ``tp``
    Megatron-layout rank shards from the full state. v1.0/v2.0 qkv rows are
    per-head-local, so their split is the plain 'col' row split."""
    version = _resolve_version(sd, version)
    outs: List[Dict[str, np.ndarray]] = [dict() for _ in range(tp)]
    for key, val in sd.items():
        if key == _VERSION_KEY:
            continue
        val = np.asarray(val)
        cat = _category(key)
        if cat == "qkv" and version == 0:
            q, k, v = np.split(val, 3, axis=0)
            for r, (qr, kr, vr) in enumerate(zip(np.split(q, tp, axis=0),
                                                 np.split(k, tp, axis=0),
                                                 np.split(v, tp, axis=0))):
                outs[r][key] = np.concatenate([qr, kr, vr], axis=0)
        elif cat in ("col", "qkv"):
            for r, part in enumerate(np.split(val, tp, axis=0)):
                outs[r][key] = part
        elif cat == "row":
            for r, part in enumerate(np.split(val, tp, axis=1)):
                outs[r][key] = part
        else:
            for r in range(tp):
                outs[r][key] = val
    for o in outs:
        o[_VERSION_KEY] = np.float64(version)
    return outs


# ------------------------------------------------------------------- loading

def _strip_model_prefix(sd: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Flatten the megatron container nesting to transformer-relative keys."""
    # torch .pt files nest as {"model": {"language_model": {...}}}; the
    # language_model holds {"embedding": {...}, "transformer"|"encoder": {...}}
    if "model" in sd:
        sd = sd["model"]
    if "language_model" in sd:
        sd = sd["language_model"]
    flat: Dict[str, np.ndarray] = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}{k}." if not hasattr(v, "shape") else f"{prefix}{k}", v)
        else:
            flat[prefix.rstrip(".")] = np.asarray(node)

    walk("", sd)
    return flat


def load_megatron_checkpoint(ckpt_dir: str, tag: Optional[str] = None
                             ) -> Dict[str, np.ndarray]:
    """Read ``<dir>[/<tag>]/mp_rank_XX/model_optim_rng.pt`` shards and merge
    across the saved TP degree (reference SDLoaderFactory.get_sd_loader_json
    + MegatronSDLoader). Returns the FULL transformer-relative state dict."""
    import torch

    root = os.path.join(ckpt_dir, tag) if tag else ckpt_dir
    ranks = sorted(d for d in os.listdir(root) if d.startswith("mp_rank_"))
    if not ranks:
        raise FileNotFoundError(f"no mp_rank_* dirs under {root}")
    sds, versions = [], []
    for r in ranks:
        fp = os.path.join(root, r, "model_optim_rng.pt")
        if not os.path.exists(fp):
            fp = os.path.join(root, r, "model_rng.pt")  # older layout
        raw = torch.load(fp, map_location="cpu", weights_only=False)
        # reference get_checkpoint_version (state_dict_factory.py:425):
        # absent == the pre-versioning (v0) blocked q|k|v layout.
        versions.append(float(raw.get("checkpoint_version", 0)) if isinstance(raw, dict) else 0)
        sds.append({k: v.numpy() if hasattr(v, "numpy") else np.asarray(v)
                    for k, v in _strip_model_prefix(raw).items()})
    if len(set(versions)) != 1:
        raise ValueError(f"mp_rank shards disagree on checkpoint_version: {versions}")
    # merge stamps _VERSION_KEY, consumed downstream by convert_megatron_state
    return merge_tp_state_dicts(sds, version=_check_version(versions[0]))


# ------------------------------------------------------------------ convert

def config_from_megatron(state: Dict[str, np.ndarray], num_heads: int,
                         **overrides) -> TransformerConfig:
    """Infer a TransformerConfig from a merged Megatron GPT state dict
    (classic GPT-2 recipe: layernorm + gelu + learned positions + tied head)."""
    vocab, h = state["embedding.word_embeddings.weight"].shape
    max_seq = state["embedding.position_embeddings.weight"].shape[0]
    layer_ids = {int(m.group(1)) for k in state
                 if (m := re.search(r"layers\.(\d+)\.", k))}
    inter = state["transformer.layers.0.mlp.dense_h_to_4h.weight"].shape[0] \
        if "transformer.layers.0.mlp.dense_h_to_4h.weight" in state \
        else state["encoder.layers.0.mlp.dense_h_to_4h.weight"].shape[0]
    kw = dict(
        vocab_size=vocab, hidden_size=h, intermediate_size=inter,
        num_layers=max(layer_ids) + 1, num_heads=num_heads, max_seq_len=max_seq,
        norm="layernorm", activation="gelu", position="learned",
        tie_embeddings=True,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def _split_qkv(arr: np.ndarray, version: float, H: int, hd: int
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """De-interleave a merged [3*H*hd, ...] qkv tensor into (q, k, v) per the
    checkpoint_version row layout (reference merge_query_key_value docstring)."""
    rest = arr.shape[1:]
    if version == 0:                       # [3, H, hd] — globally blocked
        q, k, v = np.split(arr, 3, axis=0)
    elif version == 1.0:                   # [H, hd, 3] — per-head interleaved
        a = arr.reshape(H, hd, 3, *rest)
        q, k, v = (a[:, :, i].reshape(H * hd, *rest) for i in range(3))
    else:                                  # 2.0: [H, 3, hd] — per-head blocked
        a = arr.reshape(H, 3, hd, *rest)
        q, k, v = (a[:, i].reshape(H * hd, *rest) for i in range(3))
    return q, k, v


def convert_megatron_state(state: Dict[str, np.ndarray],
                           cfg: TransformerConfig) -> Dict[str, Any]:
    """Merged Megatron GPT state -> CausalLM stacked-scan param pytree."""
    from deepspeed_tpu.checkpoint.hf import _getter, _stack

    h, hd, H = cfg.hidden_size, cfg.dims_per_head, cfg.num_heads
    version = _check_version(float(state.get(_VERSION_KEY, 0)))
    g = _getter(state, ("transformer.", "encoder.", ""))

    def layer(i):
        p = f"layers.{i}."
        qkv_w = g(p + "attention.query_key_value.weight")  # [3h, h]
        qkv_b = g(p + "attention.query_key_value.bias")
        wq, wk, wv = _split_qkv(qkv_w, version, H, hd)
        bq, bk, bv = _split_qkv(qkv_b, version, H, hd)
        return {
            "attn_norm": {"scale": g(p + "input_layernorm.weight"),
                          "bias": g(p + "input_layernorm.bias")},
            "mlp_norm": {"scale": g(p + "post_attention_layernorm.weight"),
                         "bias": g(p + "post_attention_layernorm.bias")},
            "attn": {
                "wq": {"kernel": wq.T.reshape(h, H, hd), "bias": bq.reshape(H, hd)},
                "wk": {"kernel": wk.T.reshape(h, H, hd), "bias": bk.reshape(H, hd)},
                "wv": {"kernel": wv.T.reshape(h, H, hd), "bias": bv.reshape(H, hd)},
                "wo": {"kernel": g(p + "attention.dense.weight").T.reshape(H, hd, h),
                       "bias": g(p + "attention.dense.bias")},
            },
            "mlp": {
                "w_up": {"kernel": g(p + "mlp.dense_h_to_4h.weight").T,
                         "bias": g(p + "mlp.dense_h_to_4h.bias")},
                "w_down": {"kernel": g(p + "mlp.dense_4h_to_h.weight").T,
                           "bias": g(p + "mlp.dense_4h_to_h.bias")},
            },
        }

    return {
        "embed": {"embedding": state["embedding.word_embeddings.weight"]},
        "pos_embed": state["embedding.position_embeddings.weight"],
        "final_norm": {"scale": g("final_layernorm.weight"),
                       "bias": g("final_layernorm.bias")},
        "layers": _stack(layer, cfg.num_layers),
    }


def load_megatron_model(ckpt_dir: str, num_heads: int, tag: Optional[str] = None,
                        **cfg_overrides) -> Tuple[TransformerConfig, Dict[str, Any]]:
    """One call: sharded Megatron checkpoint dir -> (config, params) at ANY
    target TP degree (placement decides — pass the params to
    ``initialize``/``init_inference`` on a mesh with the tp size you want)."""
    state = load_megatron_checkpoint(ckpt_dir, tag=tag)
    cfg = config_from_megatron(state, num_heads, **cfg_overrides)
    return cfg, convert_megatron_state(state, cfg)
