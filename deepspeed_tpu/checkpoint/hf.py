"""HuggingFace checkpoint ingestion: safetensors -> CausalLM param pytree.

TPU-native analog of the reference's model-implementation/checkpoint-loading
stack: ``module_inject/load_checkpoint.py`` (name-mapped weight copy into
injected modules), ``inference/v2/engine_factory.py`` (per-family policies:
llama/mistral/mixtral/...), and ``inference/engine.py:301``
(``load_model_with_checkpoint``, sharded/meta checkpoints). Instead of
surgically rewriting torch modules, we translate the HF state dict into the
framework's stacked-scan param tree once; AutoTP placement then shards it over
the mesh (``parallel/autotp.place_parameters``).

Supported families: llama (incl. mistral — same graph), qwen2 (llama graph
+ qkv biases), gpt2, opt, falcon (7b-style parallel block, MQA), phi (parallel
block + partial rotary), mixtral, gpt_neox (per-head fused QKV, parallel
residual with separate MLP norm), bloom (ALiBi + embedding layernorm), gptj
(interleaved rotary, parallel block, biased MLP/head), codegen (gptj graph +
mp_num-blocked fused QKV).
Sharded checkpoints (``model.safetensors.index.json``) are read shard-by-shard
into one host dict before conversion — peak host memory is the full fp* model
plus the stacked copy being built. A per-layer streaming path (convert and
free as each shard arrives) is the upgrade if host RAM ever binds.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.models.transformer import TransformerConfig


# --------------------------------------------------------------------- load

def load_safetensors_state(path: str) -> Dict[str, np.ndarray]:
    """Read a .safetensors file / HF checkpoint dir into {name: ndarray}."""
    from safetensors import safe_open

    def read_file(fp):
        out = {}
        with safe_open(fp, framework="np") as f:
            for k in f.keys():
                out[k] = f.get_tensor(k)
        return out

    if os.path.isfile(path):
        return read_file(path)
    index = os.path.join(path, "model.safetensors.index.json")
    if os.path.exists(index):
        with open(index) as f:
            weight_map = json.load(f)["weight_map"]
        state: Dict[str, np.ndarray] = {}
        for shard in sorted(set(weight_map.values())):
            state.update(read_file(os.path.join(path, shard)))
        return state
    single = os.path.join(path, "model.safetensors")
    if os.path.exists(single):
        return read_file(single)
    files = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {path}")
    state = {}
    for f in files:
        state.update(read_file(os.path.join(path, f)))
    return state


def config_from_hf(hf_config: Dict[str, Any]) -> TransformerConfig:
    """Map an HF ``config.json`` dict to a TransformerConfig."""
    mt = hf_config.get("model_type", "llama")
    if mt == "gpt2":
        h = hf_config["n_embd"]
        return TransformerConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=h,
            intermediate_size=hf_config.get("n_inner") or 4 * h,
            num_layers=hf_config["n_layer"],
            num_heads=hf_config["n_head"],
            max_seq_len=hf_config.get("n_positions", 1024),
            norm="layernorm",
            activation="gelu",
            position="learned",
            tie_embeddings=True,
        )
    if mt in ("llama", "mistral", "mixtral", "qwen2"):
        kw = dict(
            vocab_size=hf_config["vocab_size"],
            hidden_size=hf_config["hidden_size"],
            intermediate_size=hf_config["intermediate_size"],
            num_layers=hf_config["num_hidden_layers"],
            num_heads=hf_config["num_attention_heads"],
            num_kv_heads=hf_config.get("num_key_value_heads"),
            head_dim=hf_config.get("head_dim"),
            max_seq_len=hf_config.get("max_position_embeddings", 4096),
            norm="rmsnorm",
            activation="silu_glu",
            position="rope",
            rope_theta=float(hf_config.get("rope_theta", 10000.0)),
            norm_eps=float(hf_config.get("rms_norm_eps", 1e-5)),
            tie_embeddings=bool(hf_config.get("tie_word_embeddings", False)),
        )
        if mt == "mixtral":
            kw.update(
                num_experts=hf_config["num_local_experts"],
                moe_top_k=hf_config.get("num_experts_per_tok", 2),
            )
        # HF llama-format configs may carry qkv biases (attention_bias);
        # qwen2 always does
        kw["qkv_bias"] = True if mt == "qwen2" else bool(hf_config.get("attention_bias", False))
        return TransformerConfig(**kw)
    if mt == "opt":
        if not hf_config.get("do_layer_norm_before", True):
            raise ValueError("OPT post-layernorm variants (do_layer_norm_before=false) are unsupported")
        h = hf_config["hidden_size"]
        if hf_config.get("word_embed_proj_dim", h) != h:
            raise ValueError("OPT word_embed_proj_dim != hidden_size (e.g. opt-350m) is unsupported")
        act = hf_config.get("activation_function", "relu")
        if act not in ("relu", "gelu", "gelu_new"):
            raise ValueError(f"unsupported OPT activation_function {act!r}")
        return TransformerConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=h,
            intermediate_size=hf_config["ffn_dim"],
            num_layers=hf_config["num_hidden_layers"],
            num_heads=hf_config["num_attention_heads"],
            max_seq_len=hf_config.get("max_position_embeddings", 2048),
            norm="layernorm",
            # HF 'gelu' is exact erf-gelu; 'gelu_new' is the tanh approx
            activation={"relu": "relu", "gelu": "gelu_exact", "gelu_new": "gelu"}[act],
            position="learned",
            tie_embeddings=bool(hf_config.get("tie_word_embeddings", True)),
        )
    if mt == "falcon":
        if hf_config.get("new_decoder_architecture", False):
            raise ValueError("falcon new_decoder_architecture (40b/180b) is unsupported")
        if not hf_config.get("parallel_attn", True):
            raise ValueError("falcon without parallel_attn is unsupported")
        if hf_config.get("alibi", False):
            raise ValueError("falcon alibi position biases are unsupported (rope only)")
        if not hf_config.get("multi_query", True):
            raise ValueError(
                "falcon multi_query=False is unsupported (HF interleaves q/k/v per "
                "head in the fused projection for that variant)")
        if hf_config.get("bias", False):
            raise ValueError("falcon bias=True variants are unsupported")
        h = hf_config["hidden_size"]
        return TransformerConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=h,
            intermediate_size=hf_config.get("ffn_hidden_size") or 4 * h,
            num_layers=hf_config["num_hidden_layers"],
            num_heads=hf_config["num_attention_heads"],
            num_kv_heads=1,  # multi_query guaranteed by the guard above
            max_seq_len=hf_config.get("max_position_embeddings", 2048),
            norm="layernorm",
            activation="gelu_exact",
            position="rope",
            rope_theta=float(hf_config.get("rope_theta", 10000.0)),
            norm_eps=float(hf_config.get("layer_norm_epsilon", 1e-5)),
            qkv_bias=False,  # bias=True rejected above
            dense_bias=False,
            parallel_block=True,
            # falcon ties by default (FalconConfig.tie_word_embeddings=True)
            tie_embeddings=bool(hf_config.get("tie_word_embeddings", True)),
        )
    if mt == "phi":
        if hf_config.get("qk_layernorm", False):
            raise ValueError("phi qk_layernorm=True is unsupported")
        h = hf_config["hidden_size"]
        heads = hf_config["num_attention_heads"]
        kvh = hf_config.get("num_key_value_heads") or heads
        if kvh != heads:
            raise ValueError("phi with GQA (num_key_value_heads != num_attention_heads) is unsupported")
        act = hf_config.get("hidden_act", "gelu_new")
        if act not in ("gelu_new", "gelu", "relu"):
            raise ValueError(f"unsupported phi hidden_act {act!r}")
        return TransformerConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=h,
            intermediate_size=hf_config["intermediate_size"],
            num_layers=hf_config["num_hidden_layers"],
            num_heads=heads,
            max_seq_len=hf_config.get("max_position_embeddings", 2048),
            norm="layernorm",
            activation={"gelu_new": "gelu", "gelu": "gelu_exact", "relu": "relu"}[act],
            position="rope",
            rope_theta=float(hf_config.get("rope_theta", 10000.0)),
            rotary_dim=int(hf_config.get("partial_rotary_factor", 0.5) * (h // heads)),
            norm_eps=float(hf_config.get("layer_norm_eps", 1e-5)),
            qkv_bias=True,
            dense_bias=True,
            lm_head_bias=True,
            parallel_block=True,
            tie_embeddings=bool(hf_config.get("tie_word_embeddings", False)),
        )
    if mt == "gpt_neox":
        h = hf_config["hidden_size"]
        heads = hf_config["num_attention_heads"]
        act = hf_config.get("hidden_act", "gelu")
        if act not in ("gelu", "gelu_new", "relu"):
            raise ValueError(f"unsupported gpt_neox hidden_act {act!r}")
        parallel = bool(hf_config.get("use_parallel_residual", True))
        return TransformerConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=h,
            intermediate_size=hf_config["intermediate_size"],
            num_layers=hf_config["num_hidden_layers"],
            num_heads=heads,
            max_seq_len=hf_config.get("max_position_embeddings", 2048),
            norm="layernorm",
            # HF ACT2FN 'gelu' is the exact erf gelu; 'gelu_new' the tanh form
            activation={"gelu": "gelu_exact", "gelu_new": "gelu", "relu": "relu"}[act],
            position="rope",
            # newer transformers serialize rope_theta/partial_rotary_factor in
            # place of the legacy neox spellings — accept either, legacy first
            rope_theta=float(hf_config.get("rotary_emb_base")
                             or hf_config.get("rope_theta", 10000.0)),
            # neox ropes only the first rotary_pct of each head
            rotary_dim=int((hf_config.get("rotary_pct")
                            or hf_config.get("partial_rotary_factor", 0.25))
                           * (h // heads)),
            norm_eps=float(hf_config.get("layer_norm_eps", 1e-5)),
            qkv_bias=True,
            dense_bias=True,
            parallel_block=parallel,
            parallel_mlp_norm=parallel,  # neox parallel uses ln2 for the MLP
            tie_embeddings=bool(hf_config.get("tie_word_embeddings", False)),
        )
    if mt == "bloom":
        h = hf_config.get("hidden_size") or hf_config.get("n_embed")
        return TransformerConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=h,
            intermediate_size=4 * h,
            num_layers=hf_config.get("num_hidden_layers") or hf_config.get("n_layer"),
            num_heads=hf_config.get("num_attention_heads") or hf_config.get("n_head"),
            max_seq_len=hf_config.get("max_position_embeddings", 2048),
            norm="layernorm",
            activation="gelu",  # bloom uses the tanh-approx gelu
            position="alibi",
            norm_eps=float(hf_config.get("layer_norm_epsilon", 1e-5)),
            qkv_bias=True,
            dense_bias=True,
            embed_norm=True,  # word_embeddings_layernorm
            tie_embeddings=True,  # bloom always ties lm_head to embeddings
        )
    if mt in ("gptj", "codegen"):
        # codegen reuses the gpt-j graph (interleaved partial rotary, shared
        # ln_1 parallel block, biased MLP + untied biased head); only its
        # fused-QKV storage differs (mp_num blocking, handled in the converter)
        h = hf_config["n_embd"]
        heads = hf_config["n_head"]
        act = hf_config.get("activation_function", "gelu_new")
        if act not in ("gelu_new", "gelu", "relu"):
            raise ValueError(f"unsupported {mt} activation_function {act!r}")
        if hf_config.get("tie_word_embeddings", False):
            # the lm_head keeps its BIAS even when tied; our tied path
            # computes x @ embed.T with no bias, which would silently drop
            # it. Real GPT-J/CodeGen checkpoints are untied.
            raise ValueError(f"{mt} with tie_word_embeddings=true is unsupported "
                             "(the tied head would drop lm_head.bias)")
        return TransformerConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=h,
            intermediate_size=hf_config.get("n_inner") or 4 * h,
            num_layers=hf_config["n_layer"],
            num_heads=heads,
            max_seq_len=hf_config.get("n_positions", 2048),
            norm="layernorm",
            activation={"gelu_new": "gelu", "gelu": "gelu_exact", "relu": "relu"}[act],
            position="rope",
            rope_theta=10000.0,
            rotary_dim=hf_config.get("rotary_dim") or (h // heads),
            rope_interleaved=True,  # rotate_every_two convention
            norm_eps=float(hf_config.get("layer_norm_epsilon", 1e-5)),
            qkv_bias=False,
            dense_bias=False,   # attention projections are bias-free...
            mlp_bias=True,      # ...but fc_in/fc_out carry biases
            lm_head_bias=True,  # the lm_head carries a bias
            parallel_block=True,  # one shared ln_1 feeds attn AND mlp
            tie_embeddings=False,  # tied variant rejected above (bias drop)
        )
    if mt == "gpt_bigcode":
        # starcoder/santacoder (reference module_inject bigcode containers):
        # gpt2 graph but nn.Linear storage ([out, in]) and, with multi_query,
        # a single shared KV head fused into c_attn
        h = hf_config["n_embd"]
        act = hf_config.get("activation_function", "gelu_pytorch_tanh")
        if act not in ("gelu_pytorch_tanh", "gelu_new", "gelu", "relu"):
            raise ValueError(f"unsupported gpt_bigcode activation_function {act!r}")
        return TransformerConfig(
            vocab_size=hf_config["vocab_size"],
            hidden_size=h,
            intermediate_size=hf_config.get("n_inner") or 4 * h,
            num_layers=hf_config["n_layer"],
            num_heads=hf_config["n_head"],
            num_kv_heads=1 if hf_config.get("multi_query", True) else None,
            max_seq_len=hf_config.get("n_positions", 1024),
            norm="layernorm",
            # HF gelu_pytorch_tanh == gelu_new == the tanh approx
            activation={"gelu_pytorch_tanh": "gelu", "gelu_new": "gelu",
                        "gelu": "gelu_exact", "relu": "relu"}[act],
            position="learned",
            norm_eps=float(hf_config.get("layer_norm_epsilon", 1e-5)),
            tie_embeddings=bool(hf_config.get("tie_word_embeddings", True)),
        )
    raise ValueError(
        f"unsupported HF model_type {mt!r} (supported: llama/mistral/mixtral/"
        "qwen2/gpt2/opt/falcon/phi/gpt_neox/bloom/gptj/codegen/gpt_bigcode)")


def detect_family(state: Dict[str, np.ndarray]) -> str:
    keys = state.keys()
    if any("block_sparse_moe" in k for k in keys):
        return "mixtral"
    if any("decoder.embed_positions" in k for k in keys) and not any("encoder." in k for k in keys):
        return "opt"
    if any("word_embeddings_layernorm" in k for k in keys):
        return "bloom"
    if any("attention.query_key_value" in k and "self_attention" not in k for k in keys):
        return "gpt_neox"
    if any("self_attention.query_key_value" in k for k in keys):
        return "falcon"
    if any("self_attn.dense.weight" in k for k in keys):
        return "phi"
    if any("self_attn.q_proj.bias" in k for k in keys):
        return "qwen2"
    if any("self_attn.q_proj" in k for k in keys):
        return "llama"
    if any("attn.qkv_proj" in k for k in keys):
        return "codegen"
    if any("mlp.fc_in" in k for k in keys):
        return "gptj"
    for k in keys:
        if k.endswith("attn.c_attn.weight"):
            # gpt2 stores Conv1D [in, 3*in]; gpt_bigcode stores nn.Linear
            # [out, in] where out is 3*in (MHA) or in + 2*head_dim (MQA) —
            # the orientation/width separates them (np.shape also tolerates
            # non-array placeholders, treated as gpt2)
            shape = np.shape(state[k])
            if len(shape) == 2 and shape[1] != 3 * shape[0]:
                return "gpt_bigcode"
            return "gpt2"
    raise ValueError("cannot detect model family from checkpoint keys")


# ------------------------------------------------------------------ convert

def _getter(state: Dict[str, np.ndarray], prefixes: Tuple[str, ...]):
    """Tensor lookup tolerant of checkpoint-dependent top-level prefixes."""
    def g(name):
        for pre in prefixes:
            if pre + name in state:
                return np.asarray(state[pre + name])
        tried = ", ".join(repr(pre + name) for pre in prefixes)
        raise KeyError(f"checkpoint is missing tensor (tried {tried})")
    return g


def _stack(fn: Callable[[int], Dict[str, Any]], L: int) -> Dict[str, Any]:
    """Per-layer subtree -> stacked [L, ...] leaves (the nn.scan layout)."""
    import jax

    per = [fn(i) for i in range(L)]
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *per)


def _convert_llama(state, cfg: TransformerConfig) -> Dict[str, Any]:
    h, hd = cfg.hidden_size, cfg.dims_per_head
    H, Hkv = cfg.num_heads, cfg.kv_heads

    def g(name):
        return np.asarray(state[name])

    def layer(i):
        p = f"model.layers.{i}."
        attn = {
            # torch Linear stores [out, in]; flax DenseGeneral wants
            # [in, heads, head_dim]
            "wq": {"kernel": g(p + "self_attn.q_proj.weight").T.reshape(h, H, hd)},
            "wk": {"kernel": g(p + "self_attn.k_proj.weight").T.reshape(h, Hkv, hd)},
            "wv": {"kernel": g(p + "self_attn.v_proj.weight").T.reshape(h, Hkv, hd)},
            "wo": {"kernel": g(p + "self_attn.o_proj.weight").T.reshape(H, hd, h)},
        }
        if p + "self_attn.q_proj.bias" in state:  # qwen2-style qkv biases
            attn["wq"]["bias"] = g(p + "self_attn.q_proj.bias").reshape(H, hd)
            attn["wk"]["bias"] = g(p + "self_attn.k_proj.bias").reshape(Hkv, hd)
            attn["wv"]["bias"] = g(p + "self_attn.v_proj.bias").reshape(Hkv, hd)
        blk = {
            "attn_norm": {"scale": g(p + "input_layernorm.weight")},
            "mlp_norm": {"scale": g(p + "post_attention_layernorm.weight")},
            "attn": attn,
        }
        if cfg.num_experts > 0:
            ex = p + "block_sparse_moe."
            blk["moe"] = {
                "gate": {"wg": {"kernel": g(ex + "gate.weight").T}},
                "experts": {
                    "w_gate": np.stack([g(f"{ex}experts.{e}.w1.weight").T for e in range(cfg.num_experts)]),
                    "w_up": np.stack([g(f"{ex}experts.{e}.w3.weight").T for e in range(cfg.num_experts)]),
                    "w_down": np.stack([g(f"{ex}experts.{e}.w2.weight").T for e in range(cfg.num_experts)]),
                },
            }
        else:
            blk["mlp"] = {
                "w_gate": {"kernel": g(p + "mlp.gate_proj.weight").T},
                "w_up": {"kernel": g(p + "mlp.up_proj.weight").T},
                "w_down": {"kernel": g(p + "mlp.down_proj.weight").T},
            }
        return blk

    params: Dict[str, Any] = {
        "embed": {"embedding": g("model.embed_tokens.weight")},
        "final_norm": {"scale": g("model.norm.weight")},
        "layers": _stack(layer, cfg.num_layers),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = {"kernel": g("lm_head.weight").T}
    return params


def _convert_gpt2(state, cfg: TransformerConfig) -> Dict[str, Any]:
    h, hd, H = cfg.hidden_size, cfg.dims_per_head, cfg.num_heads

    # HF sometimes prefixes with "transformer."
    g = _getter(state, ("", "transformer."))

    def layer(i):
        p = f"h.{i}."
        # GPT-2 Conv1D stores [in, out] (already flax orientation)
        ca_w, ca_b = g(p + "attn.c_attn.weight"), g(p + "attn.c_attn.bias")
        q_w, k_w, v_w = np.split(ca_w, 3, axis=1)
        q_b, k_b, v_b = np.split(ca_b, 3)
        return {
            "attn_norm": {"scale": g(p + "ln_1.weight"), "bias": g(p + "ln_1.bias")},
            "mlp_norm": {"scale": g(p + "ln_2.weight"), "bias": g(p + "ln_2.bias")},
            "attn": {
                "wq": {"kernel": q_w.reshape(h, H, hd), "bias": q_b.reshape(H, hd)},
                "wk": {"kernel": k_w.reshape(h, H, hd), "bias": k_b.reshape(H, hd)},
                "wv": {"kernel": v_w.reshape(h, H, hd), "bias": v_b.reshape(H, hd)},
                "wo": {"kernel": g(p + "attn.c_proj.weight").reshape(H, hd, h),
                       "bias": g(p + "attn.c_proj.bias")},
            },
            "mlp": {
                "w_up": {"kernel": g(p + "mlp.c_fc.weight"), "bias": g(p + "mlp.c_fc.bias")},
                "w_down": {"kernel": g(p + "mlp.c_proj.weight"), "bias": g(p + "mlp.c_proj.bias")},
            },
        }

    return {
        "embed": {"embedding": g("wte.weight")},
        "pos_embed": g("wpe.weight"),
        "final_norm": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
        "layers": _stack(layer, cfg.num_layers),
    }


def _convert_opt(state, cfg: TransformerConfig) -> Dict[str, Any]:
    h, hd, H = cfg.hidden_size, cfg.dims_per_head, cfg.num_heads

    # checkpoints may or may not carry the top-level "model." prefix
    g = _getter(state, ("model.", ""))

    def layer(i):
        p = f"decoder.layers.{i}."
        return {
            "attn_norm": {"scale": g(p + "self_attn_layer_norm.weight"),
                          "bias": g(p + "self_attn_layer_norm.bias")},
            "mlp_norm": {"scale": g(p + "final_layer_norm.weight"),
                         "bias": g(p + "final_layer_norm.bias")},
            "attn": {
                "wq": {"kernel": g(p + "self_attn.q_proj.weight").T.reshape(h, H, hd),
                       "bias": g(p + "self_attn.q_proj.bias").reshape(H, hd)},
                "wk": {"kernel": g(p + "self_attn.k_proj.weight").T.reshape(h, H, hd),
                       "bias": g(p + "self_attn.k_proj.bias").reshape(H, hd)},
                "wv": {"kernel": g(p + "self_attn.v_proj.weight").T.reshape(h, H, hd),
                       "bias": g(p + "self_attn.v_proj.bias").reshape(H, hd)},
                "wo": {"kernel": g(p + "self_attn.out_proj.weight").T.reshape(H, hd, h),
                       "bias": g(p + "self_attn.out_proj.bias")},
            },
            "mlp": {
                "w_up": {"kernel": g(p + "fc1.weight").T, "bias": g(p + "fc1.bias")},
                "w_down": {"kernel": g(p + "fc2.weight").T, "bias": g(p + "fc2.bias")},
            },
        }

    params: Dict[str, Any] = {
        "embed": {"embedding": g("decoder.embed_tokens.weight")},
        # OPT's learned positions carry a legacy offset of 2 rows
        "pos_embed": g("decoder.embed_positions.weight")[2:],
        "final_norm": {"scale": g("decoder.final_layer_norm.weight"),
                       "bias": g("decoder.final_layer_norm.bias")},
        "layers": _stack(layer, cfg.num_layers),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = {"kernel": np.asarray(state["lm_head.weight"]).T}
    return params


def _convert_falcon(state, cfg: TransformerConfig) -> Dict[str, Any]:
    h, hd, H, Hkv = cfg.hidden_size, cfg.dims_per_head, cfg.num_heads, cfg.kv_heads
    g = _getter(state, ("transformer.", ""))

    def layer(i):
        p = f"h.{i}."
        # fused qkv rows: H query heads, then Hkv key heads, then Hkv value
        qkv = g(p + "self_attention.query_key_value.weight")  # [(H+2Hkv)*hd, h]
        wq = qkv[: H * hd]
        wk = qkv[H * hd: (H + Hkv) * hd]
        wv = qkv[(H + Hkv) * hd:]
        attn = {
            "wq": {"kernel": wq.T.reshape(h, H, hd)},
            "wk": {"kernel": wk.T.reshape(h, Hkv, hd)},
            "wv": {"kernel": wv.T.reshape(h, Hkv, hd)},
            "wo": {"kernel": g(p + "self_attention.dense.weight").T.reshape(H, hd, h)},
        }
        return {
            # parallel block: ONE shared input layernorm (no mlp_norm)
            "attn_norm": {"scale": g(p + "input_layernorm.weight"),
                          "bias": g(p + "input_layernorm.bias")},
            "attn": attn,
            "mlp": {
                "w_up": {"kernel": g(p + "mlp.dense_h_to_4h.weight").T},
                "w_down": {"kernel": g(p + "mlp.dense_4h_to_h.weight").T},
            },
        }

    params: Dict[str, Any] = {
        "embed": {"embedding": g("word_embeddings.weight")},
        "final_norm": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
        "layers": _stack(layer, cfg.num_layers),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = {"kernel": np.asarray(state["lm_head.weight"]).T}
    return params


def _convert_phi(state, cfg: TransformerConfig) -> Dict[str, Any]:
    h, hd, H = cfg.hidden_size, cfg.dims_per_head, cfg.num_heads
    g = _getter(state, ("model.", ""))

    def layer(i):
        p = f"layers.{i}."
        return {
            # parallel block: ONE shared input layernorm
            "attn_norm": {"scale": g(p + "input_layernorm.weight"),
                          "bias": g(p + "input_layernorm.bias")},
            "attn": {
                "wq": {"kernel": g(p + "self_attn.q_proj.weight").T.reshape(h, H, hd),
                       "bias": g(p + "self_attn.q_proj.bias").reshape(H, hd)},
                "wk": {"kernel": g(p + "self_attn.k_proj.weight").T.reshape(h, H, hd),
                       "bias": g(p + "self_attn.k_proj.bias").reshape(H, hd)},
                "wv": {"kernel": g(p + "self_attn.v_proj.weight").T.reshape(h, H, hd),
                       "bias": g(p + "self_attn.v_proj.bias").reshape(H, hd)},
                "wo": {"kernel": g(p + "self_attn.dense.weight").T.reshape(H, hd, h),
                       "bias": g(p + "self_attn.dense.bias")},
            },
            "mlp": {
                "w_up": {"kernel": g(p + "mlp.fc1.weight").T, "bias": g(p + "mlp.fc1.bias")},
                "w_down": {"kernel": g(p + "mlp.fc2.weight").T, "bias": g(p + "mlp.fc2.bias")},
            },
        }

    params: Dict[str, Any] = {
        "embed": {"embedding": g("embed_tokens.weight")},
        "final_norm": {"scale": g("final_layernorm.weight"),
                       "bias": g("final_layernorm.bias")},
        "layers": _stack(layer, cfg.num_layers),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = {"kernel": np.asarray(state["lm_head.weight"]).T,
                             "bias": np.asarray(state["lm_head.bias"])}
    return params


def _split_fused_qkv_per_head(w, b, H, Hkv, hd, h):
    """Split a per-head-interleaved fused QKV (gpt-neox/bloom pattern —
    reference ``module_inject/fusedqkv_utils.py:29`` ``prepare_tp_fused_qkvw``
    'glmtype'/'bloomtype' orderings): rows are [head0: q,k,v | head1: ...].
    Returns the attn param subtree in flax orientation."""
    if H != Hkv:
        raise ValueError("per-head fused QKV with GQA is not a pattern these families use")
    wr = w.reshape(H, 3, hd, h)
    attn = {
        "wq": {"kernel": wr[:, 0].reshape(H * hd, h).T.reshape(h, H, hd)},
        "wk": {"kernel": wr[:, 1].reshape(H * hd, h).T.reshape(h, H, hd)},
        "wv": {"kernel": wr[:, 2].reshape(H * hd, h).T.reshape(h, H, hd)},
    }
    if b is not None:
        br = b.reshape(H, 3, hd)
        attn["wq"]["bias"] = br[:, 0]
        attn["wk"]["bias"] = br[:, 1]
        attn["wv"]["bias"] = br[:, 2]
    return attn


def _neox_style_layers(state, cfg: TransformerConfig, g, layer_prefix: str,
                       attn_prefix: str) -> Dict[str, Any]:
    """Shared layer conversion for the gpt-neox/bloom graph (per-head fused
    QKV, biased dense/MLP, two layernorms); only key prefixes differ."""
    h, hd, H = cfg.hidden_size, cfg.dims_per_head, cfg.num_heads

    def layer(i):
        p = layer_prefix.format(i)
        a = p + attn_prefix
        attn = _split_fused_qkv_per_head(
            g(a + "query_key_value.weight"), g(a + "query_key_value.bias"),
            H, H, hd, h)
        attn["wo"] = {"kernel": g(a + "dense.weight").T.reshape(H, hd, h),
                      "bias": g(a + "dense.bias")}
        return {
            "attn_norm": {"scale": g(p + "input_layernorm.weight"),
                          "bias": g(p + "input_layernorm.bias")},
            "mlp_norm": {"scale": g(p + "post_attention_layernorm.weight"),
                         "bias": g(p + "post_attention_layernorm.bias")},
            "attn": attn,
            "mlp": {
                "w_up": {"kernel": g(p + "mlp.dense_h_to_4h.weight").T,
                         "bias": g(p + "mlp.dense_h_to_4h.bias")},
                "w_down": {"kernel": g(p + "mlp.dense_4h_to_h.weight").T,
                           "bias": g(p + "mlp.dense_4h_to_h.bias")},
            },
        }

    return _stack(layer, cfg.num_layers)


def _convert_gpt_neox(state, cfg: TransformerConfig) -> Dict[str, Any]:
    g = _getter(state, ("gpt_neox.", ""))
    params: Dict[str, Any] = {
        "embed": {"embedding": g("embed_in.weight")},
        "final_norm": {"scale": g("final_layer_norm.weight"),
                       "bias": g("final_layer_norm.bias")},
        "layers": _neox_style_layers(state, cfg, g, "layers.{}.", "attention."),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = {"kernel": np.asarray(state["embed_out.weight"]).T}
    return params


def _convert_bloom(state, cfg: TransformerConfig) -> Dict[str, Any]:
    g = _getter(state, ("transformer.", ""))
    return {
        "embed": {"embedding": g("word_embeddings.weight")},
        "embed_norm": {"scale": g("word_embeddings_layernorm.weight"),
                       "bias": g("word_embeddings_layernorm.bias")},
        "final_norm": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
        "layers": _neox_style_layers(state, cfg, g, "h.{}.", "self_attention."),
    }


def _convert_gptj(state, cfg: TransformerConfig) -> Dict[str, Any]:
    h, hd, H = cfg.hidden_size, cfg.dims_per_head, cfg.num_heads
    g = _getter(state, ("transformer.", ""))

    def layer(i):
        p = f"h.{i}."
        return {
            # parallel block: ONE shared ln_1 feeds attn and mlp
            "attn_norm": {"scale": g(p + "ln_1.weight"), "bias": g(p + "ln_1.bias")},
            "attn": {
                "wq": {"kernel": g(p + "attn.q_proj.weight").T.reshape(h, H, hd)},
                "wk": {"kernel": g(p + "attn.k_proj.weight").T.reshape(h, H, hd)},
                "wv": {"kernel": g(p + "attn.v_proj.weight").T.reshape(h, H, hd)},
                "wo": {"kernel": g(p + "attn.out_proj.weight").T.reshape(H, hd, h)},
            },
            "mlp": {
                "w_up": {"kernel": g(p + "mlp.fc_in.weight").T, "bias": g(p + "mlp.fc_in.bias")},
                "w_down": {"kernel": g(p + "mlp.fc_out.weight").T, "bias": g(p + "mlp.fc_out.bias")},
            },
        }

    params: Dict[str, Any] = {
        "embed": {"embedding": g("wte.weight")},
        "final_norm": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
        "layers": _stack(layer, cfg.num_layers),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = {"kernel": np.asarray(state["lm_head.weight"]).T,
                             "bias": np.asarray(state["lm_head.bias"])}
    return params


def _convert_codegen(state, cfg: TransformerConfig) -> Dict[str, Any]:
    """CodeGen = the GPT-J graph with an mp_num-blocked fused QKV (reference
    ``module_inject/fusedqkv_utils.py:29`` 'codegentype'): qkv_proj rows are
    mp_num groups of [q_local | V_LOCAL | k_local] (query, value, key order
    inside each group, matching HF CodeGenAttention's split). The fused
    projection is de-fused into gpt-j-style q/k/v keys and the rest of the
    conversion delegates to :func:`_convert_gptj` — one layer mapping."""
    h = cfg.hidden_size
    g = _getter(state, ("transformer.", ""))
    mp_num = 4  # fixed in HF CodeGenAttention
    local = h // mp_num

    defused = {k: v for k, v in state.items() if "attn.qkv_proj" not in k}
    for i in range(cfg.num_layers):
        grouped = g(f"h.{i}.attn.qkv_proj.weight").reshape(mp_num, 3 * local, h)
        p = f"transformer.h.{i}.attn."
        defused[p + "q_proj.weight"] = grouped[:, :local].reshape(h, h)
        defused[p + "v_proj.weight"] = grouped[:, local: 2 * local].reshape(h, h)
        defused[p + "k_proj.weight"] = grouped[:, 2 * local:].reshape(h, h)
    return _convert_gptj(defused, cfg)


def _convert_gpt_bigcode(state, cfg: TransformerConfig) -> Dict[str, Any]:
    """GPT-BigCode / starcoder (reference ``module_inject`` bigcode
    containers): the gpt2 graph, but projections are nn.Linear ([out, in] —
    transposed vs gpt2's Conv1D) and with ``multi_query`` the fused c_attn
    packs [q(H*hd) | k(hd) | v(hd)] rows sharing ONE kv head."""
    h, hd, H, Hkv = cfg.hidden_size, cfg.dims_per_head, cfg.num_heads, cfg.kv_heads
    g = _getter(state, ("", "transformer."))

    def layer(i):
        p = f"h.{i}."
        w, b = g(p + "attn.c_attn.weight"), g(p + "attn.c_attn.bias")
        if Hkv == 1:  # multi_query: [q(H*hd) | k(hd) | v(hd)] row blocks
            q_w, k_w, v_w = np.split(w, [H * hd, (H + 1) * hd], axis=0)
            q_b, k_b, v_b = np.split(b, [H * hd, (H + 1) * hd])
            q_w = q_w.T.reshape(h, H, hd)
            k_w, v_w = k_w.T.reshape(h, 1, hd), v_w.T.reshape(h, 1, hd)
        else:  # MHA: PER-HEAD [q_hd | k_hd | v_hd] blocks (HF comment: "the
            # memory layout is not the same as GPT2")
            per_head = w.reshape(H, 3 * hd, h)
            q_w, k_w, v_w = (per_head[:, s].transpose(2, 0, 1)
                             for s in (slice(0, hd), slice(hd, 2 * hd),
                                       slice(2 * hd, 3 * hd)))
            pb = b.reshape(H, 3 * hd)
            q_b, k_b, v_b = pb[:, :hd], pb[:, hd:2 * hd], pb[:, 2 * hd:]
        return {
            "attn_norm": {"scale": g(p + "ln_1.weight"), "bias": g(p + "ln_1.bias")},
            "mlp_norm": {"scale": g(p + "ln_2.weight"), "bias": g(p + "ln_2.bias")},
            "attn": {
                "wq": {"kernel": q_w, "bias": q_b.reshape(H, hd)},
                "wk": {"kernel": k_w, "bias": k_b.reshape(Hkv, hd)},
                "wv": {"kernel": v_w, "bias": v_b.reshape(Hkv, hd)},
                "wo": {"kernel": g(p + "attn.c_proj.weight").T.reshape(H, hd, h),
                       "bias": g(p + "attn.c_proj.bias")},
            },
            "mlp": {
                "w_up": {"kernel": g(p + "mlp.c_fc.weight").T, "bias": g(p + "mlp.c_fc.bias")},
                "w_down": {"kernel": g(p + "mlp.c_proj.weight").T, "bias": g(p + "mlp.c_proj.bias")},
            },
        }

    return {
        "embed": {"embedding": g("wte.weight")},
        "pos_embed": g("wpe.weight"),
        "final_norm": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
        "layers": _stack(layer, cfg.num_layers),
    }


_CONVERTERS = {
    "llama": _convert_llama,
    "mistral": _convert_llama,
    "mixtral": _convert_llama,
    "qwen2": _convert_llama,  # llama graph + qkv biases (handled by presence)
    "gpt2": _convert_gpt2,
    "opt": _convert_opt,
    "falcon": _convert_falcon,
    "phi": _convert_phi,
    "gpt_neox": _convert_gpt_neox,
    "bloom": _convert_bloom,
    "gptj": _convert_gptj,
    "codegen": _convert_codegen,
    "gpt_bigcode": _convert_gpt_bigcode,
}


def convert_hf_state(
    state: Dict[str, np.ndarray],
    config: TransformerConfig,
    family: Optional[str] = None,
) -> Dict[str, Any]:
    """HF state dict -> CausalLM stacked-scan param pytree."""
    family = family or detect_family(state)
    if family not in _CONVERTERS:
        raise ValueError(f"unsupported family {family!r}; supported: {sorted(_CONVERTERS)}")
    return _CONVERTERS[family](state, config)


def load_hf_checkpoint(
    path: str,
    config: Optional[TransformerConfig] = None,
    family: Optional[str] = None,
) -> Tuple[TransformerConfig, Dict[str, Any]]:
    """One-call ingestion: checkpoint dir (config.json + safetensors) ->
    (TransformerConfig, params) ready for ``initialize(model_parameters=...)``
    or ``init_inference(params=...)``."""
    if config is None:
        cfg_path = os.path.join(path, "config.json") if os.path.isdir(path) else None
        if cfg_path is None or not os.path.exists(cfg_path):
            raise ValueError("pass config= or point at a dir containing config.json")
        with open(cfg_path) as f:
            config = config_from_hf(json.load(f))
    state = load_safetensors_state(path)
    params = convert_hf_state(state, config, family=family)
    return config, params
