"""Elastic snapshots: async sharded saves, atomic commit, mesh-reshape restore.

The resilience half of the checkpoint subsystem (ROADMAP open item 5). The
orbax path (``checkpointing.py``) remains the interoperable format; this
module is the format the AUTO-RECOVERY loop (``elasticity/resilience.py``)
trusts its life to, so it trades orbax's generality for three hard
guarantees the reference's universal-checkpoint + Nebula pair provides
(``checkpoint/ds_to_universal.py``, ``runtime/checkpoint_engine/
nebula_checkpoint_engine.py``):

1. **The step clock never blocks on disk.** ``SnapshotManager.after_step``
   does one device→host copy of the (ZeRO-partitioned) train state into host
   buffers at a step boundary — the only synchronous cost — then hands the
   buffers to a background writer thread that serializes, checksums, fsyncs
   and commits. Training dispatches the next step while the write runs.

2. **A crash can never publish a torn snapshot.** Shards and the manifest are
   written into ``<tag>.tmp-<pid>/``; the manifest (with a sha256 per shard)
   is written and fsynced LAST, the directory is atomically renamed to
   ``<tag>``, and only then is the ``latest`` pointer rewritten (itself via
   tmp + fsync + rename). A writer killed between any two of those steps
   leaves ``latest`` naming the previous fully-durable snapshot.

3. **A snapshot taken on an M-chip mesh restores onto an N-chip mesh.** The
   payload is the partitioning-independent fp32 atom tree (the
   ``universal.py`` canonical form: Twin-Flow opt partitions merged,
   16-bit floats widened, per-run scratch dropped). Atoms are full logical
   arrays sliced into bounded shard files; restore reassembles each atom on
   host and places it with the TARGET engine's sharding via
   ``utils.compat.device_put_unaliased`` — XLA re-slices for whatever mesh
   the resumed job got, and every restored leaf lands in a buffer XLA owns
   EXCLUSIVELY (a zero-copy device_put of host numpy feeding the donated
   step programs is the PR-1 heap-corruption landmine).

Layout under ``<base_dir>/snapshots/``::

    latest                   # text: name of the newest committed tag
    step000042/
      manifest.json          # format/meta + per-shard {file, atom, slice, sha256}
      shards/00000.npy ...   # one logical-atom slice per file, bounded bytes
    step000064.tmp-12345/    # in-flight (or crashed) write; never loaded

Telemetry: ``ckpt:snapshot`` span (caller-side D2H + enqueue), ``ckpt:commit``
span (writer-side serialize→fsync→rename), ``ckpt/save_ms`` / ``ckpt/bytes``
/ ``ckpt/inflight`` gauges in the shared registry (scrapeable via the PR-5
``/metrics`` endpoint).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import shutil
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from deepspeed_tpu.checkpoint.universal import _tag_step
from deepspeed_tpu.utils.compat import host_copy_unaliased
from deepspeed_tpu.utils.logging import log_dist, logger

SNAPSHOT_DIR = "snapshots"
LATEST_FILE = "latest"
MANIFEST_FILE = "manifest.json"
FORMAT_VERSION = 1


class SnapshotError(RuntimeError):
    """Snapshot subsystem failure (write or restore)."""


class SnapshotCorruptionError(SnapshotError):
    """Manifest missing/invalid or a shard failed its checksum."""

    def __init__(self, message: str, tag: Optional[str] = None):
        super().__init__(message)
        self.tag = tag


# ------------------------------------------------------------------ helpers
def snapshot_root(base_dir: str) -> str:
    return os.path.join(os.path.abspath(base_dir), SNAPSHOT_DIR)


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platforms without directory fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_atomic(path: str, data: str, fsync: bool = True) -> None:
    """tmp + (fsync) + rename: readers see the old content or the new,
    never a partial write."""
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(data)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if fsync:
        _fsync_dir(os.path.dirname(path))


def list_snapshots(base_dir: str) -> List[str]:
    """Committed tags under ``base_dir``, oldest→newest by step number.
    In-flight/crashed ``*.tmp-*`` directories are never listed."""
    root = snapshot_root(base_dir)
    if not os.path.isdir(root):
        return []
    tags = [
        t for t in os.listdir(root)
        if ".tmp-" not in t
        and os.path.isfile(os.path.join(root, t, MANIFEST_FILE))
    ]
    return sorted(tags, key=_tag_step)


def latest_tag(base_dir: str) -> Optional[str]:
    """The tag the ``latest`` pointer names (None when it does not exist)."""
    p = os.path.join(snapshot_root(base_dir), LATEST_FILE)
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return f.read().strip() or None


def read_manifest(base_dir: str, tag: str) -> Dict[str, Any]:
    path = os.path.join(snapshot_root(base_dir), tag, MANIFEST_FILE)
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise SnapshotCorruptionError(
            f"snapshot {tag}: unreadable manifest {path}: {e}", tag=tag)
    if manifest.get("format_version") != FORMAT_VERSION:
        raise SnapshotCorruptionError(
            f"snapshot {tag}: unsupported format_version "
            f"{manifest.get('format_version')!r}", tag=tag)
    return manifest


# --------------------------------------------------------------- state <-> atoms
def engine_state_atoms(engine) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """(atoms, meta): the canonical fp32 atom tree as HOST numpy.

    Same canonical form as ``universal.py`` — Twin-Flow opt partitions merged
    to the param-shaped moment tree, 16-bit floats widened to fp32, per-run
    scratch (``comm_error`` EF residuals, ``health`` EMAs) dropped — so the
    payload is partitioning-independent and restores under ANY mesh/stage.
    The ``jax.device_get`` here is the snapshot's one synchronous cost: it
    waits for the step that produced the state, copies D2H, and returns; all
    serialization and IO happen off-thread.
    """
    from deepspeed_tpu.checkpoint.universal import _flatten, _fp32_state_tree

    materialize = getattr(engine, "materialize_state", None)
    if materialize is not None:
        materialize()  # NVMe-swapped moments must be in the snapshot
    state = engine.state
    canon = getattr(engine, "canonical_opt_state", None)
    if canon is not None:
        state = state._replace(opt_state=canon(state.opt_state))
    tree = _fp32_state_tree(state)
    # Exclusively-owned copies, NOT device_get views: the background writer
    # serializes these while the engine keeps stepping, and a donated step can
    # write through a zero-copy D2H view (utils.compat.host_copy_unaliased) —
    # the snapshot on disk would silently hold LATER state than its tag.
    host = host_copy_unaliased(tree)
    atoms = {k: np.asarray(v) for k, v in _flatten(host).items() if v is not None}
    meta = {
        "step": int(np.asarray(host["step"])),
        "source_mesh": {k: int(v) for k, v in dict(engine.mesh.shape).items()},
        "zero_stage": engine.zero_config.stage,
    }
    return atoms, meta


# ------------------------------------------------------------------- writing
def _iter_shards(atoms: Dict[str, np.ndarray], shard_bytes: int):
    """Yield (atom_key, slice_start, slice_stop, ndarray_slice).

    Large atoms are sliced along dim 0 into bounded shard files (the
    "sharded" in sharded snapshots): bounded writer memory, bounded loss on
    a torn write, and natural parallel-read units. slice (None, None) means
    the whole atom in one shard.
    """
    for key in sorted(atoms):
        arr = atoms[key]
        if arr.ndim == 0 or arr.nbytes <= shard_bytes or arr.shape[0] <= 1:
            yield key, None, None, arr
            continue
        rows = max(1, int(shard_bytes // max(arr.nbytes // arr.shape[0], 1)))
        for start in range(0, arr.shape[0], rows):
            stop = min(start + rows, arr.shape[0])
            yield key, start, stop, arr[start:stop]


def _npy_bytes(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    return buf.getvalue()


def partition_atoms(atoms: Dict[str, np.ndarray], process_count: int) -> List[List[str]]:
    """Deterministic atom → writer-process assignment for multi-host writes.

    Greedy largest-first into the currently lightest bin, ties broken by the
    lower process index and by sorted key order, so every process computes
    the IDENTICAL partition from the same canonical atom tree — no
    coordination round is needed to agree on ownership. Returns one sorted
    key list per process (some may be empty when atoms < processes).
    """
    if process_count < 1:
        raise ValueError(f"process_count must be >= 1, got {process_count}")
    bins: List[List[str]] = [[] for _ in range(process_count)]
    weights = [0] * process_count
    for key in sorted(atoms, key=lambda k: (-atoms[k].nbytes, k)):
        p = min(range(process_count), key=lambda i: (weights[i], i))
        bins[p].append(key)
        weights[p] += int(atoms[key].nbytes)
    return [sorted(b) for b in bins]


def _write_shard_files(
    atoms: Dict[str, np.ndarray],
    keys: Sequence[str],
    dest_dir: str,
    rel_dir: str,
    prefix: str,
    shard_bytes: int,
    fsync: bool,
    fault_hook: Optional[Callable[[str, int], None]],
) -> Tuple[List[Dict[str, Any]], int]:
    """Write shard files for ``keys`` into ``dest_dir``; records name files
    relative to the final snapshot dir (``rel_dir``/``prefix``NNNNN.npy)."""
    owned = {k: atoms[k] for k in keys}
    shards: List[Dict[str, Any]] = []
    total_bytes = 0
    for i, (key, start, stop, part) in enumerate(_iter_shards(owned, shard_bytes)):
        if fault_hook is not None:
            fault_hook("shard", i)
        # NOT ascontiguousarray: it promotes 0-d atoms to shape (1,);
        # np.save copies non-contiguous input itself
        payload = _npy_bytes(np.asarray(part))
        fname = f"{prefix}{i:05d}.npy"
        with open(os.path.join(dest_dir, fname), "wb") as f:
            f.write(payload)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        shards.append({
            "file": os.path.join(rel_dir, fname) if rel_dir else fname,
            "atom": key,
            "dtype": str(part.dtype),
            "shape": list(part.shape),
            "slice": None if start is None else [int(start), int(stop)],
            "sha256": hashlib.sha256(payload).hexdigest(),
            "bytes": len(payload),
        })
        total_bytes += len(payload)
    return shards, total_bytes


def _part_dir(root: str, tag: str, process_index: int) -> str:
    return os.path.join(root, f"{tag}.part{process_index}")


def _write_part(
    atoms: Dict[str, np.ndarray],
    keys: Sequence[str],
    root: str,
    tag: str,
    process_index: int,
    shard_bytes: int,
    fsync: bool,
    fault_hook: Optional[Callable[[str, int], None]],
) -> str:
    """Non-zero rank's half of a multi-process snapshot: write owned shards
    plus a ``part.json`` into ``<root>/<tag>.part<p>`` (tmp + rename, so
    rank 0 only ever observes a COMPLETE part). Part dirs hold no
    ``manifest.json`` and are therefore never listed as snapshots."""
    final_path = _part_dir(root, tag, process_index)
    tmp_path = f"{final_path}.tmp-{os.getpid()}"
    if os.path.exists(tmp_path):
        shutil.rmtree(tmp_path)
    os.makedirs(tmp_path)
    shards, total = _write_shard_files(
        atoms, keys, tmp_path, rel_dir="shards",
        prefix=f"p{process_index}_", shard_bytes=shard_bytes,
        fsync=fsync, fault_hook=fault_hook)
    part = {
        "format_version": FORMAT_VERSION,
        "tag": tag,
        "process_index": process_index,
        "shards": shards,
        "payload_bytes": total,
    }
    with open(os.path.join(tmp_path, "part.json"), "w") as f:
        json.dump(part, f)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    if os.path.exists(final_path):
        shutil.rmtree(final_path)
    os.replace(tmp_path, final_path)
    if fsync:
        _fsync_dir(root)
    return final_path


def _collect_parts(
    root: str,
    tag: str,
    tmp_shards_dir: str,
    process_count: int,
    part_timeout_s: float,
) -> Tuple[List[Dict[str, Any]], int, List[str]]:
    """Rank 0's merge: wait for every peer's part dir, move its shard files
    into the snapshot-in-progress, and return the merged shard records."""
    shards: List[Dict[str, Any]] = []
    total = 0
    part_paths: List[str] = []
    deadline = time.time() + part_timeout_s
    for p in range(1, process_count):
        path = _part_dir(root, tag, p)
        while not os.path.isfile(os.path.join(path, "part.json")):
            if time.time() > deadline:
                raise SnapshotError(
                    f"snapshot {tag}: timed out after {part_timeout_s:.0f}s "
                    f"waiting for part {p}/{process_count - 1} at {path} — "
                    f"a writer process died before publishing its shards")
            time.sleep(0.05)
        with open(os.path.join(path, "part.json")) as f:
            part = json.load(f)
        if part.get("tag") != tag or part.get("process_index") != p:
            raise SnapshotError(
                f"snapshot {tag}: part dir {path} holds "
                f"tag={part.get('tag')!r} process={part.get('process_index')!r}")
        for rec in part["shards"]:
            fname = os.path.basename(rec["file"])
            os.replace(os.path.join(path, fname),
                       os.path.join(tmp_shards_dir, fname))
            shards.append(rec)
            total += int(rec["bytes"])
        part_paths.append(path)
    return shards, total, part_paths


def write_snapshot(
    atoms: Dict[str, np.ndarray],
    meta: Dict[str, Any],
    base_dir: str,
    tag: str,
    shard_bytes: int = 64 << 20,
    fsync: bool = True,
    fault_hook: Optional[Callable[[str, int], None]] = None,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
    part_timeout_s: float = 120.0,
) -> str:
    """Write one snapshot with atomic commit; returns the committed path.

    ``fault_hook(event, index)`` is the fault-injection seam
    (``diagnostics/faultinject.py``): called before each shard write
    (``("shard", i)``), before the manifest (``("manifest", n)``) and before
    the commit rename (``("commit", n)``); a hook that raises simulates a
    writer crash at exactly that point.

    Multi-process writes (ISSUE 18, elastic training on multi-host meshes):
    ``process_index``/``process_count`` default to the jax runtime's. Every
    process passes the SAME canonical atom tree (``engine_state_atoms`` is
    partitioning-independent by construction) and :func:`partition_atoms`
    deterministically assigns each atom one writer, so shard IO scales with
    host count without any coordination round. Non-zero ranks publish their
    shards to ``<root>/<tag>.part<p>`` (tmp + rename) and return that path;
    rank 0 writes its own shards, waits up to ``part_timeout_s`` for every
    part, merges the files into one snapshot dir, and commits the single
    manifest — so loaders are unchanged and the commit stays atomic. With
    ``process_count == 1`` the layout is byte-identical to the
    single-process format.
    """
    if process_count is None:
        process_count = jax.process_count()
    if process_index is None:
        process_index = jax.process_index()
    if not 0 <= process_index < process_count:
        raise ValueError(
            f"process_index {process_index} out of range for "
            f"process_count {process_count}")
    root = snapshot_root(base_dir)
    os.makedirs(root, exist_ok=True)

    multi = process_count > 1
    owned = partition_atoms(atoms, process_count) if multi else [sorted(atoms)]
    if multi and process_index != 0:
        return _write_part(atoms, owned[process_index], root, tag,
                           process_index, shard_bytes, fsync, fault_hook)

    final_path = os.path.join(root, tag)
    tmp_path = f"{final_path}.tmp-{os.getpid()}"
    if os.path.exists(tmp_path):
        shutil.rmtree(tmp_path)
    tmp_shards = os.path.join(tmp_path, "shards")
    os.makedirs(tmp_shards)

    shards, total_bytes = _write_shard_files(
        atoms, owned[0], tmp_shards, rel_dir="shards",
        prefix="p0_" if multi else "", shard_bytes=shard_bytes,
        fsync=fsync, fault_hook=fault_hook)
    part_paths: List[str] = []
    if multi:
        peer_shards, peer_bytes, part_paths = _collect_parts(
            root, tag, tmp_shards, process_count, part_timeout_s)
        shards = sorted(shards + peer_shards, key=lambda r: r["file"])
        total_bytes += peer_bytes

    manifest = {
        "format_version": FORMAT_VERSION,
        "tag": tag,
        "written_unix": time.time(),
        "atoms": {k: {"dtype": str(v.dtype), "shape": list(v.shape)}
                  for k, v in atoms.items()},
        "shards": shards,
        "payload_bytes": total_bytes,
        "writer_processes": process_count,
        **meta,
    }
    if fault_hook is not None:
        fault_hook("manifest", len(shards))
    mpath = os.path.join(tmp_path, MANIFEST_FILE)
    with open(mpath, "w") as f:
        json.dump(manifest, f)
        if fsync:
            f.flush()
            os.fsync(f.fileno())

    if fault_hook is not None:
        fault_hook("commit", len(shards))
    # durability order: shards+manifest fsynced above -> dir rename -> dir
    # entry fsync -> 'latest'. A crash between any two leaves 'latest'
    # naming a fully durable snapshot.
    if os.path.exists(final_path):
        # Same-tag overwrite (re-snapshot after a rewind). The committed dir
        # must never be DELETED while 'latest' can still name it, so:
        # repoint 'latest' at the newest other committed tag (empty when
        # this is the only one), slide the old dir aside under a .tmp- name
        # (never listed/loaded), swap the new one in, then reclaim the old
        # bytes. A crash in the swap window leaves 'latest' naming a
        # durable OTHER tag — or, sole-snapshot case, empty with the old
        # bytes still on disk under the aside name.
        others = [t for t in list_snapshots(base_dir) if t != tag]
        _write_atomic(os.path.join(root, LATEST_FILE),
                      others[-1] if others else "", fsync=fsync)
        aside = f"{final_path}.old.tmp-{os.getpid()}"
        if os.path.exists(aside):
            shutil.rmtree(aside)
        os.replace(final_path, aside)
        os.replace(tmp_path, final_path)
        if fsync:
            _fsync_dir(root)
        shutil.rmtree(aside, ignore_errors=True)
    else:
        os.replace(tmp_path, final_path)
        if fsync:
            _fsync_dir(root)
    _write_atomic(os.path.join(root, LATEST_FILE), tag, fsync=fsync)
    for p in part_paths:  # shard files already moved in; reclaim the husks
        shutil.rmtree(p, ignore_errors=True)
    return final_path


def prune_snapshots(base_dir: str, keep: int, protect: Tuple[str, ...] = (),
                    stale_tmp_s: float = 3600.0) -> List[str]:
    """Delete committed snapshots beyond the newest ``keep`` (and crashed
    tmp dirs from OTHER pids once older than ``stale_tmp_s`` — the age gate
    keeps a live writer sharing the directory from losing its in-flight
    write); the ``latest`` target and ``protect`` tags are never deleted.
    Returns the removed tags."""
    root = snapshot_root(base_dir)
    if not os.path.isdir(root):
        return []
    keep_set = set(protect)
    cur = latest_tag(base_dir)
    if cur:
        keep_set.add(cur)
    tags = list_snapshots(base_dir)
    removed = []
    excess = [t for t in tags if t not in keep_set]
    n_extra = len(tags) - max(int(keep), 1)
    for t in excess:
        if n_extra <= 0:
            break
        shutil.rmtree(os.path.join(root, t), ignore_errors=True)
        removed.append(t)
        n_extra -= 1
    pid = os.getpid()
    now = time.time()
    for entry in os.listdir(root):
        stale_tmp = ".tmp-" in entry and not entry.endswith(f".tmp-{pid}")
        # committed multi-process part dirs are reclaimed by rank 0 at merge
        # time; one still present past the age gate was orphaned by a rank-0
        # death and will never be collected
        orphan_part = ".tmp-" not in entry and ".part" in entry
        if stale_tmp or orphan_part:
            path = os.path.join(root, entry)
            try:
                age = now - os.path.getmtime(path)
            except OSError:
                continue  # racing its owner: it is being committed/removed
            if age >= stale_tmp_s:
                shutil.rmtree(path, ignore_errors=True)
    return removed


# ------------------------------------------------------------------- loading
def load_snapshot_atoms(base_dir: str, tag: str) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """Read + VERIFY one snapshot: every shard is checksummed against the
    manifest before anything is returned, so corruption is detected before
    any device state is touched. Raises :class:`SnapshotCorruptionError`."""
    root = snapshot_root(base_dir)
    manifest = read_manifest(base_dir, tag)
    parts: Dict[str, List[Tuple[Optional[int], np.ndarray]]] = {}
    for shard in manifest["shards"]:
        fpath = os.path.join(root, tag, shard["file"])
        try:
            with open(fpath, "rb") as f:
                payload = f.read()
        except OSError as e:
            raise SnapshotCorruptionError(
                f"snapshot {tag}: missing shard {shard['file']}: {e}", tag=tag)
        if hashlib.sha256(payload).hexdigest() != shard["sha256"]:
            raise SnapshotCorruptionError(
                f"snapshot {tag}: checksum mismatch on {shard['file']} "
                f"(atom {shard['atom']})", tag=tag)
        arr = np.load(io.BytesIO(payload), allow_pickle=False)
        if list(arr.shape) != shard["shape"] or str(arr.dtype) != shard["dtype"]:
            raise SnapshotCorruptionError(
                f"snapshot {tag}: shard {shard['file']} decoded to "
                f"{arr.dtype}{arr.shape}, manifest says "
                f"{shard['dtype']}{shard['shape']}", tag=tag)
        start = None if shard["slice"] is None else shard["slice"][0]
        parts.setdefault(shard["atom"], []).append((start, arr))

    atoms: Dict[str, np.ndarray] = {}
    for key, decl in manifest["atoms"].items():
        got = parts.get(key)
        if not got:
            raise SnapshotCorruptionError(
                f"snapshot {tag}: atom {key} has no shards", tag=tag)
        if len(got) == 1 and got[0][0] is None:
            atom = got[0][1]
        else:
            atom = np.concatenate(
                [a for _, a in sorted(got, key=lambda sa: sa[0] or 0)], axis=0)
        if list(atom.shape) != decl["shape"]:
            raise SnapshotCorruptionError(
                f"snapshot {tag}: atom {key} reassembled to {atom.shape}, "
                f"manifest says {decl['shape']}", tag=tag)
        atoms[key] = atom
    return atoms, manifest


def _recover_aside(base_dir: str) -> Optional[str]:
    """Crash recovery for the same-tag-overwrite swap window: the committed
    dir was slid aside as ``<tag>.old.tmp-<pid>`` and the writer died before
    the replacement landed, leaving no listed tag and an empty ``latest``.
    Re-commit the newest aside copy under its original name, repoint
    ``latest`` at it, and return its tag (None when there is nothing to
    recover)."""
    root = snapshot_root(base_dir)
    if not os.path.isdir(root):
        return None
    asides = [e for e in os.listdir(root)
              if ".old.tmp-" in e
              and os.path.isfile(os.path.join(root, e, MANIFEST_FILE))]
    for entry in sorted(asides,
                        key=lambda e: _tag_step(e.split(".old.tmp-")[0]),
                        reverse=True):
        tag = entry.split(".old.tmp-")[0]
        final = os.path.join(root, tag)
        if os.path.exists(final):
            continue  # that tag was re-committed; the aside is just garbage
        try:
            os.replace(os.path.join(root, entry), final)
        except OSError:
            continue  # racing its owner mid-commit: leave it alone
        _write_atomic(os.path.join(root, LATEST_FILE), tag, fsync=False)
        logger.warning(
            f"snapshot recovery: re-committed {entry!r} as {tag!r} — a writer "
            "died mid same-tag overwrite leaving no listed snapshot")
        return tag
    return None


def load_latest_atoms(
    base_dir: str, tag: Optional[str] = None, fallback: bool = True,
) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """Atoms of ``tag`` (default: the ``latest`` pointer), falling back
    through OLDER committed tags on corruption — with a loud warning naming
    what was skipped — instead of crashing mid-materialization. Raises
    :class:`SnapshotCorruptionError` only when no tag survives validation."""
    tags = list_snapshots(base_dir)
    if tag is None:
        tag = latest_tag(base_dir)
        if tag is None and tags:
            # crashed before the first 'latest' write but after a commit
            tag = tags[-1]
    if tag is None:
        tag = _recover_aside(base_dir)
        if tag is not None:
            tags = list_snapshots(base_dir)
    if tag is None:
        raise SnapshotError(f"no snapshots under {snapshot_root(base_dir)}")
    candidates = [tag] + ([] if not fallback else
                          [t for t in reversed(tags) if _tag_step(t) < _tag_step(tag)])
    last_err: Optional[SnapshotCorruptionError] = None
    for cand in candidates:
        try:
            atoms, manifest = load_snapshot_atoms(base_dir, cand)
        except SnapshotCorruptionError as e:
            logger.warning(f"snapshot restore: {e}; "
                           + ("falling back to the previous tag"
                              if fallback else "no fallback requested"))
            last_err = e
            continue
        if cand != tag:
            logger.warning(
                f"snapshot restore: tag {tag!r} was corrupt/partial — restored "
                f"OLDER snapshot {cand!r} (step {manifest.get('step')}) instead")
        return atoms, manifest
    raise SnapshotCorruptionError(
        f"no loadable snapshot under {snapshot_root(base_dir)} "
        f"(last error: {last_err})", tag=tag)


def restore_snapshot(
    engine, base_dir: str, tag: Optional[str] = None, fallback: bool = True,
) -> str:
    """Restore a snapshot into ``engine`` — on ANY mesh/stage/partitioning.

    Every atom is validated (checksums) BEFORE any device state is touched,
    then placed with the TARGET engine's sharding for that leaf via
    ``jax.device_put`` from host numpy: XLA slices the logical array for the
    new mesh (the reshape restore), and each leaf lands in a freshly
    allocated committed buffer — restored state never aliases the donated
    fused engine's memory. Returns the tag restored.
    """
    atoms, manifest = load_latest_atoms(base_dir, tag=tag, fallback=fallback)

    materialize = getattr(engine, "materialize_state", None)
    if materialize is not None:
        materialize()  # the restored opt_state must land in state, not be
        # shadowed by stale NVMe-resident moments the next step swaps in
    state_dict = dict(engine.state._asdict())
    comm_error = state_dict.pop("comm_error", None)  # per-run scratch
    health = state_dict.pop("health", None)  # per-run scratch (re-armed by caller)
    canon = getattr(engine, "canonical_opt_state", None)
    if canon is not None:
        state_dict["opt_state"] = canon(state_dict["opt_state"])

    flat_target = {k: None for k, leaf in
                   _flatten_with_none(state_dict) if leaf is not None}
    missing = [k for k in flat_target if k not in atoms]
    extra = [k for k in atoms if k not in flat_target]
    if missing or extra:
        raise SnapshotError(
            f"snapshot {manifest['tag']} does not match the engine state tree: "
            f"missing={missing[:5]} extra={extra[:5]} (a snapshot restores "
            f"across meshes, not across models)")

    def _restore(path_keys, leaf):
        if leaf is None:
            return None
        key = jax.tree_util.keystr(path_keys)
        atom = atoms[key]
        if isinstance(leaf, jax.Array):
            # unaliased: zero-copy device_put of host numpy + donated steps
            # is the PR-1 heap-corruption landmine (see utils.compat)
            from deepspeed_tpu.utils.compat import device_put_unaliased

            return device_put_unaliased(atom.astype(leaf.dtype, copy=False),
                                        leaf.sharding)
        return np.asarray(atom, dtype=np.asarray(leaf).dtype)

    restored = jax.tree_util.tree_map_with_path(_restore, state_dict)
    restored["comm_error"] = comm_error
    restored["health"] = health
    departition = getattr(engine, "opt_state_from_canonical", None)
    if departition is not None:
        restored["opt_state"] = departition(restored["opt_state"])
    engine.state = type(engine.state)(**restored)
    if hasattr(engine, "_batch_count"):
        # the cadence hook keys on the host-side batch counter (a per-step
        # device fetch of state.step would block async dispatch): rewind it
        # with the state so post-restore snapshot boundaries stay aligned
        # with optimizer steps, as the config documents
        engine._batch_count = int(manifest.get("step", engine._batch_count))
    if getattr(engine, "offload_mode", None) in ("host-jit", "nvme"):
        engine._compute_dev = None  # params changed: bf16 view re-materializes
    log_dist(
        f"restored snapshot {manifest['tag']} (step {manifest.get('step')}, "
        f"saved on mesh {manifest.get('source_mesh')}, restored onto "
        f"{dict(engine.mesh.shape)})", ranks=[0])
    return manifest["tag"]


def _flatten_with_none(tree):
    from deepspeed_tpu.checkpoint.universal import _flatten

    return _flatten(tree).items()


# ------------------------------------------------------------------- manager
class SnapshotManager:
    """Cadenced async snapshots for one engine (``snapshot`` config block).

    One background writer thread, one in-flight snapshot at a time: if the
    previous write is still running at the next boundary, the boundary is
    skipped with a warning (cadence too aggressive for the disk) rather than
    queueing unbounded host copies. ``wait()`` is the durability barrier and
    re-raises any writer failure; a failed write never moves ``latest``, so
    ``last_good_tag`` stays truthful.
    """

    def __init__(self, engine, config, base_dir: Optional[str] = None):
        self.engine = engine
        self.config = config
        self.base_dir = base_dir or config.dir
        if not self.base_dir:
            raise ValueError("snapshot.enabled requires snapshot.dir")
        self.fault_hook: Optional[Callable[[str, int], None]] = None  # faultinject seam
        self.save_failures = 0  # cadenced-save failures swallowed by after_step
        self._inflight: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._lock = threading.Lock()
        self.last_good_tag: Optional[str] = latest_tag(self.base_dir)
        from deepspeed_tpu import telemetry as telemetry_mod

        self._tracer = telemetry_mod.get_tracer()
        reg = self._tracer.registry
        self._g_save_ms = reg.gauge("ckpt/save_ms")
        self._g_bytes = reg.gauge("ckpt/bytes")
        self._g_inflight = reg.gauge("ckpt/inflight")

    # ------------------------------------------------------------- lifecycle
    def after_step(self, step: int) -> None:
        """Engine hook at a step/chain boundary: snapshot every
        ``every_n_steps`` (and never while another write is in flight).

        A save failure here is COUNTED and logged, never raised — a cadenced
        snapshot must not kill healthy training from inside a later,
        unrelated ``train_batch`` (``latest`` still names the previous
        durable snapshot). Explicit :meth:`snapshot`/:meth:`wait` calls are
        the durability barriers and do raise."""
        every = max(int(self.config.every_n_steps), 1)
        if step % every != 0:
            return
        try:
            # drain a PREVIOUS async write's failure separately, so reporting
            # it does not consume this boundary's save (snapshot() raises
            # pending errors first — undrained, one transient disk failure
            # would silently double the rewind window)
            self._raise_pending()
        except SnapshotError as e:
            self.save_failures += 1
            logger.warning(
                f"snapshot: earlier async save failed ({e}); training "
                "continues — 'latest' still names the previous good snapshot")
        try:
            self.snapshot(blocking=self.config.blocking)
        except SnapshotError as e:
            self.save_failures += 1
            logger.warning(
                f"snapshot: cadenced save failed ({e}); training continues — "
                "'latest' still names the previous good snapshot")

    def snapshot(self, tag: Optional[str] = None, blocking: bool = False) -> Optional[str]:
        """Take one snapshot now. Returns the tag enqueued (None when skipped
        because a previous write is still in flight)."""
        self._raise_pending()
        if self._inflight is not None and self._inflight.is_alive():
            if blocking:
                self.wait()
            else:
                logger.warning(
                    "snapshot: previous write still in flight at the next "
                    "boundary — skipping this one (raise snapshot.every_n_steps "
                    "or speed up the disk)")
                return None
        if self._inflight is not None:
            self._inflight.join()  # reap the finished thread
            self._inflight = None
            self._raise_pending()

        with self._tracer.span("ckpt:snapshot", step=int(self.engine._batch_count)):
            atoms, meta = engine_state_atoms(self.engine)
        tag = tag or f"step{meta['step']:06d}"
        t_enqueue = time.perf_counter()
        self._g_inflight.set(1)

        def _write():
            try:
                with self._tracer.span("ckpt:commit", tag=tag):
                    write_snapshot(
                        atoms, meta, self.base_dir, tag,
                        shard_bytes=int(self.config.shard_megabytes) << 20,
                        fsync=self.config.fsync,
                        fault_hook=self.fault_hook,
                    )
                with self._lock:
                    self.last_good_tag = tag
                self._g_save_ms.set((time.perf_counter() - t_enqueue) * 1e3)
                self._g_bytes.set(float(sum(a.nbytes for a in atoms.values())))
                prune_snapshots(self.base_dir, keep=self.config.keep)
            except BaseException as e:  # noqa: BLE001 — surfaced at wait()
                with self._lock:
                    self._error = e
                logger.warning(
                    f"snapshot writer failed for {tag}: {type(e).__name__}: {e} "
                    f"('latest' still names the previous good snapshot)")
            finally:
                self._g_inflight.set(0)

        th = threading.Thread(target=_write, name=f"snapshot-writer-{tag}", daemon=True)
        self._inflight = th
        th.start()
        if blocking:
            self.wait()
        return tag

    def wait(self) -> None:
        """Durability barrier: block until the in-flight write finishes and
        re-raise its failure (once) if it had one."""
        th = self._inflight
        if th is not None:
            th.join()
            self._inflight = None
        self._raise_pending()

    def _raise_pending(self) -> None:
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise SnapshotError(f"async snapshot write failed: {err}") from err

    # --------------------------------------------------------------- restore
    def restore(self, tag: Optional[str] = None, fallback: bool = True) -> str:
        """Restore into this manager's engine (see :func:`restore_snapshot`);
        drains the writer first so a mid-write snapshot can't be half-read."""
        try:
            self.wait()
        except SnapshotError as e:
            logger.warning(f"snapshot restore: draining writer reported: {e}")
        return restore_snapshot(self.engine, self.base_dir, tag=tag, fallback=fallback)
