"""Pluggable checkpoint engines.

TPU-native analog of the reference ``CheckpointEngine`` ABC
(``runtime/checkpoint_engine/checkpoint_engine.py:9``) with a synchronous
Orbax engine (the ``TorchCheckpointEngine`` :12 analog) and an async engine
(the Nebula analog — reference ``NebulaCheckpointEngine`` tiers saves to a
background service; here a worker thread runs the Orbax write so the train
loop is not blocked, with ``commit()`` as the completion barrier).
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from deepspeed_tpu.utils.compat import host_copy_unaliased
from deepspeed_tpu.utils.logging import log_dist, logger


class CheckpointEngine:
    """save/load/commit surface (reference checkpoint_engine.py:9)."""

    async_save = False  # True => save() returns before durable; commit() is the barrier

    def create(self, tag: str) -> None:  # checkpoint transaction begin
        pass

    def save(self, payload: Any, path: str) -> None:
        raise NotImplementedError

    def load(self, path: str, target: Any = None, restore_args: Any = None) -> Any:
        raise NotImplementedError

    def commit(self, tag: str) -> bool:  # transaction end; True when durable
        return True

    def after_saved(self, fn) -> None:
        """Run ``fn`` once every save issued so far is durable.

        Synchronous engines call it inline. Async engines defer it behind the
        pending writes so publish actions (meta.json, the 'latest' pointer)
        never point at a checkpoint that is not yet on disk (the reference
        Nebula engine likewise only publishes the tag once persisted)."""
        fn()


class OrbaxCheckpointEngine(CheckpointEngine):
    """Blocking Orbax PyTree write/read (TorchCheckpointEngine analog)."""

    def save(self, payload: Any, path: str) -> None:
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(path, payload, force=True)

    def load(self, path: str, target: Any = None, restore_args: Any = None) -> Any:
        with ocp.PyTreeCheckpointer() as ckptr:
            return ckptr.restore(path, item=target, restore_args=restore_args)


class AsyncCheckpointEngine(CheckpointEngine):
    """Background-thread saves; ``commit`` waits for durability.

    ``async_save = True``: callers skip the immediate commit so training
    overlaps the write; ``load()`` and ``commit()`` are durability barriers.

    The device→host copy happens on the caller thread (cheap, async dispatch)
    so the training step can proceed; serialization/IO runs in the worker.
    """

    async_save = True

    def __init__(self):
        self._inner = OrbaxCheckpointEngine()
        self._queue: "queue.Queue" = queue.Queue()
        self._errors: list = []
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        while True:
            item = self._queue.get()
            if item is None:
                return
            kind, a, b = item
            try:
                if kind == "save":
                    self._inner.save(a, b)
                elif kind == "call" and not self._errors:
                    # publish actions are skipped when a prior write failed —
                    # never advertise a checkpoint that is not durable
                    a()
            except Exception as e:  # noqa: BLE001 - surfaced at commit()
                self._errors.append(e)
            finally:
                self._queue.task_done()

    def save(self, payload: Any, path: str) -> None:
        # Exclusively-owned host copies, not device_get views: the worker
        # serializes this payload while training keeps stepping, and a donated
        # step can write through a zero-copy D2H view
        # (utils.compat.host_copy_unaliased) — the checkpoint would hold state
        # from AFTER the save point.
        host = jax.tree_util.tree_map(
            lambda x: host_copy_unaliased(x) if isinstance(x, jax.Array) else x,
            payload,
        )
        self._queue.put(("save", host, path))

    def load(self, path: str, target: Any = None, restore_args: Any = None) -> Any:
        self.commit("")  # drain pending saves before reading
        return self._inner.load(path, target, restore_args)

    def commit(self, tag: str) -> bool:
        self._queue.join()
        if self._errors:
            err, self._errors = self._errors[:], []
            raise RuntimeError(f"async checkpoint save failed: {err[0]}") from err[0]
        return True

    def after_saved(self, fn) -> None:
        self._queue.put(("call", fn, None))

    def shutdown(self):
        self._queue.put(None)
        self._worker.join(timeout=10)


def get_checkpoint_engine(name: str = "orbax") -> CheckpointEngine:
    """Engine selection (reference ``engine._configure_checkpointing`` :354)."""
    if name in ("orbax", "torch", "default"):
        return OrbaxCheckpointEngine()
    if name in ("async", "nebula"):
        return AsyncCheckpointEngine()
    raise ValueError(f"unknown checkpoint engine {name!r}")
