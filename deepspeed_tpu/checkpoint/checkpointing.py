"""Checkpoint save/load on Orbax.

TPU-native analog of the reference checkpoint path
(``runtime/engine.py:3274 save_checkpoint`` / ``:2928 load_checkpoint`` and the
``CheckpointEngine`` ABC ``runtime/checkpoint_engine/checkpoint_engine.py:9``).
Layout parity: ``<dir>/<tag>/`` per checkpoint plus a ``latest`` file naming
the newest tag. Orbax stores sharding metadata, so a checkpoint written on one
mesh restores onto another (the "universal checkpoint" reshape the reference
needs an offline tool for — ``checkpoint/ds_to_universal.py`` — comes free).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from deepspeed_tpu import telemetry
from deepspeed_tpu.utils.logging import log_dist, logger

LATEST_FILE = "latest"


def _tag(step: int) -> str:
    return f"global_step{step}"


def _canonical_opt_state(engine, opt_state):
    """Partitioning-independent opt_state for the checkpoint boundary
    (Twin-Flow engines merge their masked partition pair; everyone else is
    identity — see ``engine.canonical_opt_state``)."""
    canon = getattr(engine, "canonical_opt_state", None)
    return canon(opt_state) if canon is not None else opt_state


def _departition_opt_state(engine, opt_state):
    canon = getattr(engine, "opt_state_from_canonical", None)
    return canon(opt_state) if canon is not None else opt_state


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[Dict] = None, save_latest: bool = True,
                    checkpoint_engine=None) -> str:
    tag = tag or _tag(engine.global_steps)
    with telemetry.span("checkpoint:save", tag=tag):
        return _save_checkpoint(engine, save_dir, tag, client_state, save_latest,
                                checkpoint_engine)


def _save_checkpoint(engine, save_dir, tag, client_state, save_latest,
                     checkpoint_engine) -> str:
    path = os.path.abspath(os.path.join(save_dir, tag))
    os.makedirs(save_dir, exist_ok=True)

    state = engine.state
    payload = {
        "step": state.step,
        "params": state.params,
        "opt_state": _canonical_opt_state(engine, state.opt_state),
        "loss_scale": state.loss_scale._asdict(),
        "rng": state.rng,
    }
    if getattr(engine, "_twin_ratio", None) is not None:
        # Twin-Flow leaves live on MIXED placements (host-committed masters +
        # mesh-sharded device partition). Save host numpy instead: restoring
        # a host-committed array into a donated mesh-sharded target corrupts
        # the heap on this jax/orbax stack (observed: glibc double-linked-
        # list corruption on the second post-restore step), and a checkpoint
        # should not encode placement anyway. The masters are host-resident
        # already, so this costs one D2H of the small device partition.
        payload = jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x)), payload)
    if checkpoint_engine is None:
        checkpoint_engine = getattr(engine, "checkpoint_engine", None)
    if checkpoint_engine is None:
        from deepspeed_tpu.checkpoint.engine import OrbaxCheckpointEngine

        checkpoint_engine = OrbaxCheckpointEngine()
    checkpoint_engine.create(tag)
    checkpoint_engine.save(payload, path)
    # async engines: the write continues in the background; durability is
    # guaranteed at the next load()/commit() barrier (Nebula tier semantics)

    meta = {
        "client_state": client_state or {},
        "mesh_shape": {k: int(v) for k, v in dict(engine.mesh.shape).items()},
        "zero_stage": engine.zero_config.stage,
        "version": 1,
    }

    def _publish():
        # Runs only once the payload is durable (inline for sync engines,
        # behind the queued write for async): 'latest' / meta never point at
        # a missing or partial checkpoint.
        with open(os.path.join(save_dir, f"{tag}.meta.json"), "w") as f:
            json.dump(meta, f)
        if save_latest:
            with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
                f.write(tag)

    if not getattr(checkpoint_engine, "async_save", False):
        # sync engines: finalize the transaction FIRST, then publish —
        # 'latest' must never precede durability
        checkpoint_engine.commit(tag)
        checkpoint_engine.after_saved(_publish)
    else:
        # async engines: publish is queued behind the payload write
        checkpoint_engine.after_saved(_publish)
    log_dist(f"saved checkpoint {path}", ranks=[0])
    return path


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_optimizer_states: bool = True,
                    checkpoint_engine=None) -> Tuple[Optional[str], Dict]:
    with telemetry.span("checkpoint:load", tag=tag or "latest"):
        return _load_checkpoint(engine, load_dir, tag, load_optimizer_states,
                                checkpoint_engine)


def _load_checkpoint(engine, load_dir, tag, load_optimizer_states,
                     checkpoint_engine) -> Tuple[Optional[str], Dict]:
    if checkpoint_engine is None:
        checkpoint_engine = getattr(engine, "checkpoint_engine", None)
    if checkpoint_engine is not None and getattr(checkpoint_engine, "async_save", False):
        checkpoint_engine.commit("")  # durability barrier before reading 'latest'
    if tag is None:
        latest = os.path.join(load_dir, LATEST_FILE)
        if not os.path.exists(latest):
            logger.warning(f"no '{LATEST_FILE}' file in {load_dir}; nothing loaded")
            return None, {}
        with open(latest) as f:
            tag = f.read().strip()
    path = os.path.abspath(os.path.join(load_dir, tag))
    if not os.path.isdir(path):
        logger.warning(f"checkpoint {path} not found; nothing loaded")
        return None, {}

    if checkpoint_engine is None:
        from deepspeed_tpu.checkpoint.engine import OrbaxCheckpointEngine

        checkpoint_engine = OrbaxCheckpointEngine()
    state = engine.state
    target = {
        "step": state.step,
        "params": state.params,
        # canonical (partition-independent) form; re-partitioned below
        "opt_state": _canonical_opt_state(engine, state.opt_state),
        "loss_scale": state.loss_scale._asdict(),
        "rng": state.rng,
    }
    restore_args = jax.tree_util.tree_map(
        lambda x: ocp.ArrayRestoreArgs(sharding=x.sharding) if isinstance(x, jax.Array) else ocp.RestoreArgs(),
        target,
    )
    restored = checkpoint_engine.load(path, target=target, restore_args=restore_args)

    from deepspeed_tpu.runtime.engine import TrainState
    from deepspeed_tpu.runtime.precision import LossScaleState

    engine.state = TrainState(
        step=restored["step"],
        params=restored["params"],
        opt_state=(_departition_opt_state(engine, restored["opt_state"])
                   if load_optimizer_states else state.opt_state),
        loss_scale=LossScaleState(**restored["loss_scale"]),
        rng=restored["rng"],
        # error-feedback residuals are per-run scratch (reference reinitializes
        # worker/server error buffers on load as well)
        comm_error=state.comm_error,
        # health-probe EMAs are per-run scratch too: the restored run re-warms
        # its spike baselines rather than trusting another run's statistics
        health=state.health,
    )

    client_state: Dict[str, Any] = {}
    meta_path = os.path.join(load_dir, f"{tag}.meta.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            client_state = json.load(f).get("client_state", {})
    log_dist(f"loaded checkpoint {path} (step {int(restored['step'])})", ranks=[0])
    return path, client_state
