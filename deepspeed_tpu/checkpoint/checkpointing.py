"""Checkpoint save/load on Orbax.

TPU-native analog of the reference checkpoint path
(``runtime/engine.py:3274 save_checkpoint`` / ``:2928 load_checkpoint`` and the
``CheckpointEngine`` ABC ``runtime/checkpoint_engine/checkpoint_engine.py:9``).
Layout parity: ``<dir>/<tag>/`` per checkpoint plus a ``latest`` file naming
the newest tag. Orbax stores sharding metadata, so a checkpoint written on one
mesh restores onto another (the "universal checkpoint" reshape the reference
needs an offline tool for — ``checkpoint/ds_to_universal.py`` — comes free).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from deepspeed_tpu import telemetry
from deepspeed_tpu.utils.compat import host_copy_unaliased
from deepspeed_tpu.utils.logging import log_dist, logger

LATEST_FILE = "latest"
HOST_SIDECAR_SUFFIX = ".host.npz"


def _tag(step: int) -> str:
    return f"global_step{step}"


def _canonical_opt_state(engine, opt_state):
    """Partitioning-independent opt_state for the checkpoint boundary
    (Twin-Flow engines merge their masked partition pair; everyone else is
    identity — see ``engine.canonical_opt_state``)."""
    canon = getattr(engine, "canonical_opt_state", None)
    return canon(opt_state) if canon is not None else opt_state


def _departition_opt_state(engine, opt_state):
    canon = getattr(engine, "opt_state_from_canonical", None)
    return canon(opt_state) if canon is not None else opt_state


def _sidecar_path(save_dir: str, tag: str) -> str:
    return os.path.join(save_dir, f"{tag}{HOST_SIDECAR_SUFFIX}")


def _write_sidecar(save_dir: str, tag: str, payload) -> str:
    """Write the fresh-restore payload: one .npz of host numpy atoms keyed by
    pytree path, next to the orbax checkpoint dir.

    This is what ``restore='fresh'`` reads — with plain numpy, no orbax — so
    a training process never runs tensorstore restore machinery in-process
    (see :func:`_restore_placement` for why that matters). 16-bit floats are
    widened to fp32 (np.savez stores ml_dtypes as raw void, losing the
    dtype); the restore casts back to the live leaf's dtype, value-exact.

    Cost: this consolidates the full logical state on ONE host (process 0)
    and writes synchronously — the price of the landmine-safe restore.
    ``checkpoint: {"sidecar": false}`` skips it for models too large to
    consolidate; their restores must then use ``restore='streamed'`` (or
    eat the in-process orbax host-read fallback). The async-off-the-step-
    clock save path is the elastic snapshot layer (docs/elastic.md), not
    this one.
    """
    from deepspeed_tpu.checkpoint.universal import _flatten

    atoms = {}
    for key, leaf in _flatten(payload).items():
        if leaf is None:
            continue
        arr = np.asarray(jax.device_get(leaf))
        if arr.dtype in (np.dtype(jnp.bfloat16), np.float16):
            arr = arr.astype(np.float32)
        atoms[key] = arr
    from deepspeed_tpu.checkpoint.universal import write_npz_atomic

    return write_npz_atomic(_sidecar_path(save_dir, tag), atoms)


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[Dict] = None, save_latest: bool = True,
                    checkpoint_engine=None) -> str:
    tag = tag or _tag(engine.global_steps)
    with telemetry.span("checkpoint:save", tag=tag):
        return _save_checkpoint(engine, save_dir, tag, client_state, save_latest,
                                checkpoint_engine)


def _save_checkpoint(engine, save_dir, tag, client_state, save_latest,
                     checkpoint_engine) -> str:
    path = os.path.abspath(os.path.join(save_dir, tag))
    os.makedirs(save_dir, exist_ok=True)

    state = engine.state
    payload = {
        "step": state.step,
        "params": state.params,
        "opt_state": _canonical_opt_state(engine, state.opt_state),
        "loss_scale": state.loss_scale._asdict(),
        "rng": state.rng,
    }
    if getattr(engine, "_twin_ratio", None) is not None:
        # Twin-Flow leaves live on MIXED placements (host-committed masters +
        # mesh-sharded device partition). Save host numpy instead: restoring
        # a host-committed array into a donated mesh-sharded target corrupts
        # the heap on this jax/orbax stack (observed: glibc double-linked-
        # list corruption on the second post-restore step), and a checkpoint
        # should not encode placement anyway. The masters are host-resident
        # already, so this costs one D2H of the small device partition.
        # host_copy_unaliased, not a device_get view: async engines serialize
        # this payload while training continues and a donated step can write
        # through the zero-copy view (utils.compat.host_copy_unaliased).
        payload = host_copy_unaliased(payload)
    if checkpoint_engine is None:
        checkpoint_engine = getattr(engine, "checkpoint_engine", None)
    if checkpoint_engine is None:
        from deepspeed_tpu.checkpoint.engine import OrbaxCheckpointEngine

        checkpoint_engine = OrbaxCheckpointEngine()
    checkpoint_engine.create(tag)
    checkpoint_engine.save(payload, path)
    # async engines: the write continues in the background; durability is
    # guaranteed at the next load()/commit() barrier (Nebula tier semantics)
    cfg = getattr(getattr(engine, "config", None), "model", None)
    if cfg is None or cfg.checkpoint.get("sidecar", True):
        if jax.process_count() > 1:
            # device_get cannot consolidate shards living on OTHER hosts —
            # multi-process saves keep the orbax payload only and restore
            # via the streamed path (ROADMAP: multi-host sharded writes)
            log_dist("checkpoint sidecar skipped: multi-process run cannot "
                     "consolidate cross-host shards; use restore='streamed'",
                     ranks=[0])
        else:
            # the orbax-free fresh-restore payload (one host copy; docstring)
            _write_sidecar(save_dir, tag, payload)

    meta = {
        "client_state": client_state or {},
        "mesh_shape": {k: int(v) for k, v in dict(engine.mesh.shape).items()},
        "zero_stage": engine.zero_config.stage,
        "version": 1,
    }

    def _publish():
        # Runs only once the payload is durable (inline for sync engines,
        # behind the queued write for async): 'latest' / meta never point at
        # a missing or partial checkpoint.
        with open(os.path.join(save_dir, f"{tag}.meta.json"), "w") as f:
            json.dump(meta, f)
        if save_latest:
            with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
                f.write(tag)

    if not getattr(checkpoint_engine, "async_save", False):
        # sync engines: finalize the transaction FIRST, then publish —
        # 'latest' must never precede durability
        checkpoint_engine.commit(tag)
        checkpoint_engine.after_saved(_publish)
    else:
        # async engines: publish is queued behind the payload write
        checkpoint_engine.after_saved(_publish)
    log_dist(f"saved checkpoint {path}", ranks=[0])
    return path


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_optimizer_states: bool = True,
                    checkpoint_engine=None) -> Tuple[Optional[str], Dict]:
    with telemetry.span("checkpoint:load", tag=tag or "latest"):
        return _load_checkpoint(engine, load_dir, tag, load_optimizer_states,
                                checkpoint_engine)


def _restore_placement(engine) -> str:
    """'fresh' (default) or 'streamed' — how restored leaves reach the device.

    ``fresh``: the restore reads the numpy sidecar payload
    (``<tag>.host.npz``) with plain numpy — no orbax/tensorstore runs in the
    restoring process — and every leaf is placed through
    ``utils.compat.device_put_unaliased`` into a buffer XLA owns EXCLUSIVELY.
    That unaliased placement is the actual fix for the PR-1 landmine, whose
    mechanism the PR-6 fault-injection work isolated: ``jax.device_put`` of
    64-byte-aligned host numpy is ZERO-COPY on the CPU backend, so a
    restored leaf aliases numpy-owned memory; the engine's compiled steps
    then DONATE that buffer, XLA reuses memory it does not exclusively own,
    and the glibc heap corrupts ("corrupted double-linked list" /
    segfaults, detected nondeterministically a few steps later — the
    nondeterminism is malloc alignment luck per array).

    ``streamed`` keeps the direct-to-device tensorstore restore (each host
    reads only its slices — scales with the local shard size, but orbax
    materializes the buffers itself, outside the unaliased fence); opt in
    via ``checkpoint: {"restore": "streamed"}`` only for engines that never
    step after restoring (export/eval)."""
    mode = "fresh"
    cfg = getattr(getattr(engine, "config", None), "model", None)
    if cfg is not None:
        mode = cfg.checkpoint.get("restore", "fresh")
    if mode not in ("fresh", "streamed"):
        raise ValueError(f"checkpoint.restore={mode!r}: must be 'fresh' or 'streamed'")
    if mode == "streamed":
        logger.warning(
            "checkpoint.restore='streamed': orbax materializes the restored "
            "device arrays itself, outside the unaliased-placement fence — "
            "do not step this engine afterwards (donated steps over "
            "host-aliased buffers corrupt the heap; see "
            "utils.compat.device_put_unaliased)")
    return mode


def _place_fresh(host_leaf, live_leaf):
    """One restored host atom -> a freshly allocated committed device buffer
    with the live leaf's sharding. Placement goes through
    ``device_put_unaliased``: a plain device_put of aligned host numpy is
    ZERO-COPY on CPU, and the engine's donated steps then reuse memory
    numpy still owns — the actual mechanism behind the PR-1 heap-corruption
    landmine."""
    if live_leaf is None or host_leaf is None:
        return host_leaf
    if isinstance(live_leaf, jax.Array):
        from deepspeed_tpu.utils.compat import device_put_unaliased

        arr = np.asarray(host_leaf)
        if arr.dtype != live_leaf.dtype:
            arr = arr.astype(live_leaf.dtype)
        return device_put_unaliased(arr, live_leaf.sharding)
    return host_leaf


def _load_fresh(checkpoint_engine, load_dir, tag, path, target):
    """Fresh-placement restore: numpy sidecar when present (orbax-free — the
    landmine-safe path), else the in-process orbax host-read with a loud
    warning (pre-sidecar checkpoints only; re-saving upgrades them)."""
    from deepspeed_tpu.checkpoint.universal import _flatten

    sidecar = _sidecar_path(load_dir, tag)
    if os.path.exists(sidecar):
        data = np.load(sidecar, allow_pickle=False)
        flat_target = _flatten(target)
        missing = [k for k, v in flat_target.items()
                   if v is not None and k not in data.files]
        if not missing:
            def place(path_keys, leaf):
                return _place_fresh(data[jax.tree_util.keystr(path_keys)], leaf)

            return jax.tree_util.tree_map_with_path(place, target)
        logger.warning(
            f"checkpoint sidecar {sidecar} does not match the engine state "
            f"tree (missing {missing[:3]}…) — falling back to the in-process "
            f"orbax host restore")
    else:
        logger.warning(
            f"checkpoint {path} has no {HOST_SIDECAR_SUFFIX} sidecar "
            "(pre-PR-6 format): restoring via in-process orbax host-read; "
            "re-save to upgrade to the orbax-free restore payload")
    host_target = jax.tree_util.tree_map(lambda _x: 0, target)
    host_args = jax.tree_util.tree_map(lambda _x: ocp.RestoreArgs(), target)
    restored_host = checkpoint_engine.load(path, target=host_target,
                                           restore_args=host_args)
    return jax.tree_util.tree_map(_place_fresh, restored_host, target)


def _load_checkpoint(engine, load_dir, tag, load_optimizer_states,
                     checkpoint_engine) -> Tuple[Optional[str], Dict]:
    if checkpoint_engine is None:
        checkpoint_engine = getattr(engine, "checkpoint_engine", None)
    if checkpoint_engine is not None and getattr(checkpoint_engine, "async_save", False):
        checkpoint_engine.commit("")  # durability barrier before reading 'latest'
    if tag is None:
        latest = os.path.join(load_dir, LATEST_FILE)
        if not os.path.exists(latest):
            logger.warning(f"no '{LATEST_FILE}' file in {load_dir}; nothing loaded")
            return None, {}
        with open(latest) as f:
            tag = f.read().strip()
    path = os.path.abspath(os.path.join(load_dir, tag))
    if not os.path.isdir(path):
        logger.warning(f"checkpoint {path} not found; nothing loaded")
        return None, {}

    if checkpoint_engine is None:
        from deepspeed_tpu.checkpoint.engine import OrbaxCheckpointEngine

        checkpoint_engine = OrbaxCheckpointEngine()
    state = engine.state
    target = {
        "step": state.step,
        "params": state.params,
        # canonical (partition-independent) form; re-partitioned below
        "opt_state": _canonical_opt_state(engine, state.opt_state),
        "loss_scale": state.loss_scale._asdict(),
        "rng": state.rng,
    }
    if _restore_placement(engine) == "fresh":
        restored = _load_fresh(checkpoint_engine, load_dir, tag, path, target)
    else:
        restore_args = jax.tree_util.tree_map(
            lambda x: ocp.ArrayRestoreArgs(sharding=x.sharding) if isinstance(x, jax.Array) else ocp.RestoreArgs(),
            target,
        )
        restored = checkpoint_engine.load(path, target=target, restore_args=restore_args)

    from deepspeed_tpu.runtime.engine import TrainState
    from deepspeed_tpu.runtime.precision import LossScaleState

    engine.state = TrainState(
        step=restored["step"],
        params=restored["params"],
        opt_state=(_departition_opt_state(engine, restored["opt_state"])
                   if load_optimizer_states else state.opt_state),
        loss_scale=LossScaleState(**restored["loss_scale"]),
        rng=restored["rng"],
        # error-feedback residuals are per-run scratch (reference reinitializes
        # worker/server error buffers on load as well)
        comm_error=state.comm_error,
        # health-probe EMAs are per-run scratch too: the restored run re-warms
        # its spike baselines rather than trusting another run's statistics
        health=state.health,
    )

    client_state: Dict[str, Any] = {}
    meta_path = os.path.join(load_dir, f"{tag}.meta.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            client_state = json.load(f).get("client_state", {})
    log_dist(f"loaded checkpoint {path} (step {int(restored['step'])})", ranks=[0])
    return path, client_state
