"""deepspeed_tpu: a TPU-native training & inference framework.

Brand-new JAX/XLA/Pallas implementation of the capability surface of the
reference DeepSpeed (Snowflake-Labs/DeepSpeed): engine + JSON config, ZeRO
1/2/3-equivalent sharding, mixed precision, pipeline/tensor/expert/sequence
parallelism, checkpointing, kernels, inference, and observability — designed
for SPMD over a named device mesh rather than ported from the reference's
CUDA/hook architecture.

Top-level API parity (reference ``deepspeed/__init__.py``):
  initialize()        -> (engine, optimizer, dataloader, lr_scheduler)
  init_inference()    -> InferenceEngine   (see deepspeed_tpu/inference)
"""

from deepspeed_tpu.version import __version__
from deepspeed_tpu.config import DeepSpeedTPUConfig
from deepspeed_tpu.topology import build_mesh, get_mesh, set_mesh

__git_hash__ = None
__git_branch__ = None


def initialize(*args, **kwargs):
    """Create a training engine (reference ``deepspeed.initialize`` __init__.py:69).

    Lazy import so that ``import deepspeed_tpu`` stays cheap.
    """
    from deepspeed_tpu.runtime.engine_builder import initialize as _initialize

    return _initialize(*args, **kwargs)


def init_inference(*args, **kwargs):
    """Create an inference engine (reference ``deepspeed.init_inference`` __init__.py:291)."""
    from deepspeed_tpu.inference.engine import init_inference as _init_inference

    return _init_inference(*args, **kwargs)


def build_hf_engine(*args, **kwargs):
    """HF checkpoint dir -> v2 continuous-batching engine (reference
    ``inference/v2/engine_factory.py:69``)."""
    from deepspeed_tpu.inference.engine_v2 import build_hf_engine as _build

    return _build(*args, **kwargs)


def init_distributed(*args, **kwargs):
    """Initialize the multi-host runtime (reference ``deepspeed.init_distributed``
    ``comm/comm.py:636``; here jax.distributed rendezvous — no-op single-host)."""
    from deepspeed_tpu.comm.comm import init_distributed as _initd

    return _initd(*args, **kwargs)


def add_config_arguments(parser):
    """Add the standard CLI arguments (reference ``deepspeed.add_config_arguments``
    ``__init__.py:268``): ``--deepspeed`` enable flag + ``--deepspeed_config``
    json path, consumable by ``initialize(args=...)``."""
    group = parser.add_argument_group("DeepSpeed", "deepspeed_tpu configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="enable deepspeed_tpu (helper flag for user code)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="deepspeed_tpu json configuration file")
    return parser


def default_inference_config():
    """Default inference config dict (reference ``default_inference_config``)."""
    from deepspeed_tpu.inference.config import InferenceConfig

    return InferenceConfig().model_dump()


def tp_model_init(*args, **kwargs):
    """Shard an HF-style param pytree over tp (reference ``deepspeed.tp_model_init``
    __init__.py:369; AutoTP rule inference in ``parallel/autotp.py``)."""
    from deepspeed_tpu.parallel.autotp import tp_model_init as _tp_model_init

    return _tp_model_init(*args, **kwargs)


def load_hf_checkpoint(*args, **kwargs):
    """Ingest a HuggingFace safetensors checkpoint into (TransformerConfig,
    params) for ``initialize``/``init_inference`` (reference
    ``module_inject/load_checkpoint.py`` + ``inference/v2/engine_factory.py``;
    implementation in ``checkpoint/hf.py``)."""
    from deepspeed_tpu.checkpoint.hf import load_hf_checkpoint as _load

    return _load(*args, **kwargs)
