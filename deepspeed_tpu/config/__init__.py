from deepspeed_tpu.config.config import (
    DeepSpeedTPUConfig,
    EngineConfig,
    FP16Config,
    BF16Config,
    ZeroConfig,
    OffloadConfig,
    MeshConfig,
    OptimizerConfig,
    SchedulerConfig,
)
from deepspeed_tpu.config.config_utils import DeepSpeedConfigModel
