"""The framework config: JSON/dict -> typed config tree.

TPU-native analog of the reference's ``deepspeed/runtime/config.py``
(``DeepSpeedConfig`` :708). Accepts the same JSON surface where it makes sense
on TPU (batch triad, optimizer, scheduler, fp16/bf16, zero_optimization,
gradient_clipping, monitors, flops profiler, activation checkpointing), plus a
TPU-specific ``mesh`` section declaring named parallelism axes
(dp/fsdp/tp/sp/ep/pp) in place of the reference's implicit world-size plumbing.

Batch triad arithmetic (reference ``runtime/config.py:983``):
``train_batch_size = micro_batch_per_device * gradient_accumulation_steps * dp_world``
where ``dp_world`` is the product of the data-like mesh axes (dp * fsdp).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple, Union

from pydantic import Field

from deepspeed_tpu.config.config_utils import DeepSpeedConfigModel
from deepspeed_tpu.utils.logging import logger

TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"


class FP16Config(DeepSpeedConfigModel):
    """fp16 section (reference ``runtime/fp16/loss_scaler.py`` semantics)."""

    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0.0  # 0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0

    @property
    def dynamic(self) -> bool:
        return self.loss_scale == 0.0


class BF16Config(DeepSpeedConfigModel):
    enabled: bool = False
    # fp32 gradient accumulation across microbatches (reference bf16_optimizer
    # immediate_grad_update analog — on TPU this picks the accum dtype).
    accumulate_grads_in_fp32: bool = True


class OffloadDeviceEnum:
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class OffloadConfig(DeepSpeedConfigModel):
    """offload_optimizer / offload_param sections (reference ``zero/offload_config.py``)."""

    device: str = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = 1.0
    max_in_cpu: int = 1_000_000_000


class ZeroConfig(DeepSpeedConfigModel):
    """zero_optimization section (reference ``runtime/zero/config.py:86``).

    On TPU, stages map to sharding placements of one jitted program:
      stage 0: params+grads+opt replicated (plain DP, psum grads)
      stage 1: optimizer state sharded over data axes
      stage 2: + gradients reduce-scattered / accumulated sharded
      stage 3: + parameters sharded over the ``fsdp`` mesh axis
    """

    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: bool = True  # XLA latency-hiding scheduler does this; kept for schema parity
    offload_param: Optional[OffloadConfig] = None
    offload_optimizer: Optional[OffloadConfig] = None
    sub_group_size: int = 1_000_000_000
    # stage-3 partitioning knobs
    param_persistence_threshold: int = 100_000  # params smaller than this stay replicated
    model_persistence_threshold: int = 9_223_372_036_854_775_807
    max_live_parameters: int = 1_000_000_000
    max_reuse_distance: int = 1_000_000_000
    stage3_gather_16bit_weights_on_model_save: bool = False
    # ZeRO++ analogs (quantized collectives)
    zero_quantized_weights: bool = False
    zero_quantized_gradients: bool = False
    # ZeRO++ LoCo (reference coalesced_collectives.py:81
    # all_to_all_loco_quant_reduce): error-feedback compensation on the qgZ
    # quantized gradient reduce. Requires zero_quantized_gradients.
    # e.g. {"err_beta": 0.8, "reset_T": 1024}
    loco_param: Optional[Dict[str, Any]] = None
    zero_hpz_partition_size: int = 1
    # MiCS analog: shard params over a sub-group of the fsdp axis, replicate across groups
    mics_shard_size: int = -1
    mics_hierarchical_params_gather: bool = False
    round_robin_gradients: bool = False
    ignore_unused_parameters: bool = True
    log_trace_cache_warnings: bool = False

    @property
    def offload_optimizer_device(self) -> str:
        return self.offload_optimizer.device if self.offload_optimizer else OffloadDeviceEnum.none

    @property
    def offload_param_device(self) -> str:
        return self.offload_param.device if self.offload_param else OffloadDeviceEnum.none


class OptimizerConfig(DeepSpeedConfigModel):
    type: str = "AdamW"
    params: Dict[str, Any] = Field(default_factory=dict)


class SchedulerConfig(DeepSpeedConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = Field(default_factory=dict)


class MeshConfig(DeepSpeedConfigModel):
    """TPU-specific: named parallelism axes over the device mesh.

    Replaces the reference's process-group plumbing (``utils/groups.py``,
    ``runtime/pipe/topology.py``). Sizes of -1 mean "absorb remaining devices".
    Axis order here is the physical layout order (outermost first): pp rides
    DCN when multi-slice; tp is innermost for fastest ICI.
    """

    pp: int = 1
    dp: int = -1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1
    # multi-slice: number of slices connected over DCN (1 = single slice)
    num_slices: int = 1
    dcn_axis: str = "dp"  # which axis spans DCN in multi-slice deployments


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    """reference ``runtime/activation_checkpointing/config``; on TPU this maps
    to jax.checkpoint (remat) policies applied to the compiled loss
    (``runtime/activation_checkpointing.py``)."""

    enabled: bool = False
    partition_activations: bool = False
    cpu_checkpointing: bool = False  # maps to XLA host-memory offload of residuals
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU extension: jax.checkpoint policy name (see runtime/activation_checkpointing.py)
    policy: str = "full"


class TensorboardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJob"


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJob"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed_tpu"


class FlopsProfilerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class HBMGuardConfig(DeepSpeedConfigModel):
    """hbm_guard section — pre-flight memory-fit check (``utils/hbm.py``).

    Before the engine materializes parameters on device it estimates the
    per-device state bytes (params + grads/accumulator + optimizer state +
    activations + logits, ``autotuning.estimate_state_memory``) against the
    device budget. Default: warn-only. ``enabled=True`` REFUSES over-budget
    configs with the estimate in the error — an oversized init on this
    platform wedges the device without raising (round-5 relay incident), so
    refusal is the only safe behavior on shared hardware."""

    enabled: bool = False  # True: raise HBMBudgetError instead of warning
    warn: bool = True  # False (with enabled=False): guard fully off
    # Override budget discovery (jax memory_stats / DSTPU_DEVICE_MEMORY_GB).
    device_memory_gb: Optional[float] = None
    headroom: float = 0.92  # fraction of the budget the estimate may use


class TelemetryConfig(DeepSpeedConfigModel):
    """telemetry section — the unified observability substrate
    (``deepspeed_tpu/telemetry``): span tracer + metrics registry + trace
    exporters. TPU-native; the closest reference analog is the union of
    ``wall_clock_breakdown``, the comms logger, and the monitor scalars,
    sharing one registry here. Zero overhead when disabled (the default)."""

    enabled: bool = False
    # Drain the device queue at span boundaries so spans measure true device
    # time instead of async dispatch. Serializes the dispatch pipeline — for
    # diagnosis runs, not production steps.
    sync_spans: bool = False
    # Bounded in-memory event buffer; overflow counts dropped_events.
    max_events: int = 100_000
    # Chrome trace-event JSON (open at https://ui.perfetto.dev), written at
    # monitor flushes and by explicit telemetry.export_chrome_trace() calls.
    trace_path: Optional[str] = None
    # Structured event log, one JSON object per line.
    jsonl_path: Optional[str] = None
    # Per-step device-memory gauges (PJRT memory_stats / jax.live_arrays).
    memory_watermarks: bool = True
    # Prometheus text exposition of the whole registry, rewritten at every
    # monitor flush (node-exporter textfile-collector style). None = off.
    prometheus_path: Optional[str] = None
    # Opt-in /metrics HTTP endpoint (stdlib thread, telemetry/exposition.py):
    # GET /metrics (Prometheus text) + /metrics.json (snapshot). 0 binds a
    # free port; None (default) starts no server.
    http_port: Optional[int] = None
    # Compiled-program registry (telemetry/programs.py): capture cost/memory/
    # collective analysis of every jitted program at the recompile-detector
    # wrap point, published as program/* + compile/* metrics and feeding the
    # hbm/estimate_ratio calibration. Follows `enabled`; set false to keep
    # spans/metrics without program capture (skips the one-time per-compile
    # AOT analysis pass).
    programs: bool = True
    # Fleet federation (telemetry/fleet.py + telemetry/collector.py): when
    # set, this process registers with the FleetCollector at this URL
    # (identity + clock handshake) and pushes mergeable registry snapshots,
    # heartbeats (step rate, HBM watermark, anomaly flags) and observatory
    # table rows on the cadence below, from a daemon thread. None = no
    # fleet client (single-process runs pay nothing).
    fleet_url: Optional[str] = None
    fleet_push_interval_s: float = 5.0
    # Identity override for this process's role in the fleet ledger
    # (train | router | replica | collector | worker); None keeps the
    # $DSTPU_ROLE / default resolution.
    fleet_role: Optional[str] = None
    # ---- incident plane (telemetry/events.py + telemetry/alerts.py) ----
    # Structured event stream: bounded ring of typed detector events
    # (always on — emission is a lock + deque append; the knobs below only
    # size the ring / route the JSONL export next to the trace stream).
    events_capacity: int = 2048
    events_dedup_window_s: float = 300.0
    # Event JSONL export path; None = $DSTPU_TELEMETRY_DIR/event_log.jsonl
    # when telemetry is enabled, written at monitor flushes.
    events_jsonl_path: Optional[str] = None
    # Declarative alert engine over the registry + event stream. When
    # enabled, the default rule pack (numerics divergence, collective
    # drift, perf regressions, dead replicas, RPC failures, health aborts,
    # recompile storms) evaluates on a daemon thread at this cadence.
    alerts_enabled: bool = False
    alerts_interval_s: float = 5.0
    # Optional sinks beyond the log: JSONL notification stream, and a
    # webhook POSTed from a worker thread that never raises (PR-13
    # push_async discipline).
    alerts_jsonl_path: Optional[str] = None
    alerts_webhook_url: Optional[str] = None


class HealthConfig(DeepSpeedConfigModel):
    """In-jit training-health probes (``diagnostics/health.py``): per-leaf-
    group nonfinite counts plus grad-norm/loss EMA z-score spike detection,
    traced into the compiled step next to the existing overflow/grad-norm
    math. Per-signal policy: ``log`` (record in metrics), ``skip_step`` (gate
    the optimizer update off inside the program — the fp16 overflow-skip
    select, extended), ``abort`` (skip AND raise ``TrainingHealthError``
    host-side; the one policy that syncs the dispatch pipeline per step)."""

    enabled: bool = True
    nonfinite_policy: str = "log"  # log | skip_step | abort
    grad_spike_policy: str = "log"
    loss_spike_policy: str = "log"
    grad_spike_zscore: float = 6.0
    loss_spike_zscore: float = 6.0
    ema_beta: float = 0.98
    # healthy steps absorbed into the EMAs before z-scores may fire
    warmup_steps: int = 20


class RecompileDetectConfig(DeepSpeedConfigModel):
    """Recompile detection on the engine's jitted callables
    (``diagnostics/recompile.py``): compile-cache growth tracking + argument
    shape-diff attribution, with storm escalation when recompiles cluster."""

    enabled: bool = True
    storm_threshold: int = 3  # recompiles within the window => storm error
    storm_window_s: float = 60.0


class StepTimeConfig(DeepSpeedConfigModel):
    """Step-time anomaly detection (``diagnostics/anomaly.py``): rolling
    median + MAD straggler flags and sustained-regression detection over the
    per-step wall times; results land as ``anomaly/`` registry gauges."""

    enabled: bool = True
    window: int = 64
    straggler_mads: float = 6.0
    regression_factor: float = 1.3
    min_samples: int = 8


class FlightRecorderConfig(DeepSpeedConfigModel):
    """Crash flight recorder (``diagnostics/flight_recorder.py``): bounded
    ring of recent step records (metric snapshot + health verdicts), dumped
    to JSONL + Perfetto on unhandled exception, SIGTERM/SIGUSR1, or an
    explicit ``engine.diagnostics.dump()``."""

    enabled: bool = True
    capacity: int = 16  # step records kept in the ring
    dump_dir: Optional[str] = None  # default: $DSTPU_TELEMETRY_DIR or ./telemetry_out
    install_signal_handlers: bool = True  # SIGTERM/SIGUSR1 -> dump (process-wide, once)
    dump_on_exception: bool = True  # sys.excepthook chain -> dump


class ProfilerCaptureConfig(DeepSpeedConfigModel):
    """Anomaly-triggered device-trace capture (``profiling/capture.py``).

    When the step-time anomaly detector flags a straggler or sustained
    regression (or on SIGUSR2, or an explicit
    ``engine.diagnostics.profiler_capture.arm()``), ``jax.profiler`` traces
    the next ``steps`` steps and drops the trace directory next to the
    flight record — so the post-mortem of a slow step holds the device
    timeline that explains it, not just the host-side flag. Opt-in:
    ``jax.profiler`` is heavyweight, so nothing starts unless this block is
    enabled AND a trigger fires; ``cooldown_steps`` bounds how often."""

    enabled: bool = False
    steps: int = 3  # steps traced per capture window
    on_anomaly: bool = True  # straggler/regression flags arm a capture
    signal: bool = True  # SIGUSR2 arms a capture (process-wide, once)
    cooldown_steps: int = 200  # min steps between capture windows
    dir: Optional[str] = None  # default: the flight recorder's dump dir


class DiagnosticsConfig(DeepSpeedConfigModel):
    """diagnostics section — the watching half of observability
    (``deepspeed_tpu/diagnostics``), built on the telemetry core. Disabled
    (the default) the engine compiles the identical program as without the
    block and every hook is one attribute check."""

    enabled: bool = False
    health: HealthConfig = Field(default_factory=HealthConfig)
    recompile: RecompileDetectConfig = Field(default_factory=RecompileDetectConfig)
    step_time: StepTimeConfig = Field(default_factory=StepTimeConfig)
    flight_recorder: FlightRecorderConfig = Field(default_factory=FlightRecorderConfig)
    profiler_capture: ProfilerCaptureConfig = Field(default_factory=ProfilerCaptureConfig)


class NumericsConfig(DeepSpeedConfigModel):
    """numerics section — the numerics observatory
    (``telemetry/numerics.py``): sampled wire-fidelity probes over every
    routed lossy codec, the in-jit cross-replica divergence sentinel
    (carried in ``TrainState.numerics`` like the health field), LoCo
    error-feedback residual gauges, and serving fidelity probes. Disabled
    (the default) the traced step program is jaxpr-identical to a build
    without the block (pinned by ``tests/unit/test_numerics.py``)."""

    enabled: bool = False
    # 1-in-N train steps runs the standalone wire/serving fidelity probes
    # (codec encode->decode round trips on deterministic payloads); <= 0
    # keeps route registration live but never probes
    sample_every: int = 16
    # in-jit divergence sentinel: digests the params on sampled steps and
    # compares replicas across the mesh axes each leaf is replicated over
    sentinel: bool = True
    sentinel_sample_every: int = 16
    # what a confirmed cross-replica divergence does: "log" (counter +
    # loud warning + profiler capture arm) or "abort" (raise
    # TrainingHealthError through the diagnostics manager, dumping the
    # flight recorder when one is live)
    divergence_policy: str = "log"  # log | abort
    max_probe_elems: int = 65536  # wire-probe payload cap (elements)
    # wire rel-err beyond drift_ratio x the codec's pinned bound
    # (numerics.WIRE_REL_ERR_BOUNDS) is a drift event
    drift_ratio: float = 2.0
    # spec-decode acceptance-rate trend alarm (PR-2 median+MAD, low side)
    spec_accept_window: int = 64
    spec_accept_mads: float = 6.0
    spec_accept_min_n: int = 8


class SnapshotConfig(DeepSpeedConfigModel):
    """snapshot section — elastic async sharded snapshots
    (``checkpoint/snapshot.py``). At every ``every_n_steps`` step boundary the
    engine copies the canonical fp32 train state device→host (the one
    synchronous cost) and a background thread serializes, checksums, fsyncs
    and atomically commits it under ``<dir>/snapshots/<tag>`` with a
    ``latest`` pointer updated only after durability — the step clock never
    blocks on disk, and a crash mid-save can never publish a torn snapshot.
    Snapshots restore onto ANY mesh (``engine.restore_snapshot`` /
    ``elasticity.run_resilient``). See ``docs/elastic.md``."""

    enabled: bool = False
    dir: Optional[str] = None  # snapshot base directory (required when enabled)
    every_n_steps: int = 100  # snapshot cadence in optimizer-step boundaries
    keep: int = 2  # committed snapshots retained (older ones pruned)
    shard_megabytes: int = 64  # per-shard-file ceiling (atoms sliced on dim 0)
    fsync: bool = True  # fsync shards+manifest before commit (durability)
    blocking: bool = False  # debug: write synchronously at the boundary


class RecoveryConfig(DeepSpeedConfigModel):
    """recovery section — the auto-recovery policy ``elasticity.run_resilient``
    applies when diagnostics abort a run (``TrainingHealthError``) or a
    snapshot turns out corrupt: dump the flight recorder, rewind to the
    last-good snapshot with exponential backoff, re-arm the health monitor,
    and give up (re-raise, naming the flight record) after
    ``max_rewinds_per_snapshot`` rewinds land on the SAME snapshot — a fault
    that reproduces from identical state is deterministic, not transient."""

    max_rewinds_per_snapshot: int = 2  # same-snapshot rewinds before giving up
    max_total_rewinds: int = 8  # across the whole run
    backoff_base_s: float = 1.0  # first-rewind sleep; doubles per consecutive rewind
    backoff_max_s: float = 60.0


class CollObserveConfig(DeepSpeedConfigModel):
    """collectives.observe section — the collective performance observatory
    (``collectives/observatory.py``): on sampled steps the routed hop-scope
    programs are re-dispatched standalone and host-clocked, observations
    EMA-merge into an on-disk decision table that warm-starts measured mode
    on the next run, a least-squares refit calibrates the per-backend
    alpha/beta constants live, and observed-vs-predicted drift warns loudly
    and arms the diagnostics profiler capture. Disabled (the default) the
    traced step programs and the facade are byte-identical to today's —
    and they stay identical when enabled too: probes are separate
    dispatches, never ops inside the step."""

    enabled: bool = False
    # 1-in-N train steps runs probe work (the steady-state path is untouched
    # between samples; amortized overhead guarded <2% by bench.py's
    # coll_observability extra); <= 0 disables sampling while keeping
    # route registration + the trace-time census live
    sample_every: int = 16
    probes_per_sample: int = 1
    iters: int = 1       # timed iterations per probe dispatch
    warmup: int = 1      # probe warmup (the first pays the probe compile)
    # also time candidate algorithms (lax baseline + the other families) so
    # the online table can CHANGE a decision, not just confirm one
    probe_alternatives: bool = True
    # compile new probe programs on a background worker and only time them
    # once warm — a multi-second XLA compile must never stall train_batch
    async_compile: bool = True
    # online table location (default <telemetry dir>/coll_table.json); the
    # engine feeds it back as the measured-mode decision table on the next
    # run when no explicit collectives.decision_table is configured
    table_path: Optional[str] = None
    persist: bool = True
    ema: float = 0.25          # EMA weight folding new samples into rows
    drift_ratio: float = 3.0   # observed/predicted beyond this (either way)
    refit_every: int = 8       # alpha/beta refit cadence (merged samples)
    # per-refit forgetting on the fit statistics (1.0 = never): lets the
    # calibration track an interconnect regime change on long runs
    fit_decay: float = 0.5
    max_probe_mb: float = 64.0  # never time payloads above this
    max_programs: int = 32     # probe program cache bound


class CollectivesConfig(DeepSpeedConfigModel):
    """collectives section — the algorithmic collective library
    (``deepspeed_tpu/collectives``): hop-composed ring / bidirectional-ring /
    recursive-halving-doubling / hierarchical-2D algorithms with per-hop wire
    codecs, selected per (op, bytes, axis-size) by an alpha-beta cost model
    or a measured decision table (``comm/benchmark.py --sweep``). Disabled
    (the default), the ``comm`` facade keeps its plain ``jax.lax`` lowering
    and the compiled program is unchanged."""

    enabled: bool = False
    # Facade default when a single-axis collective is issued without explicit
    # arguments ("auto" consults the selector; a concrete name forces one
    # algorithm). Installed process-wide by the engine when enabled, so ALL
    # facade collectives — including the zeropp gathers — route through it.
    # The pallas_* names run the same schedules over remote-DMA hop kernels
    # (TPU; interpret mode elsewhere — see docs/collectives.md).
    algorithm: str = "auto"  # auto | ring | bidir | rhd | ring2d | pallas_ring | pallas_ring2d | lax
    # "auto" lets the selector pick among `codecs`; any concrete name —
    # including "none" — FORCES that wire for every default-routed collective.
    codec: str = "auto"  # auto | none | fp32 | bf16 | int8 | fp8
    # Candidate codecs the selector may choose among in auto mode.
    codecs: List[str] = Field(default_factory=lambda: ["none"])
    # auto = measured when decision_table is set, alpha-beta model otherwise
    mode: str = "auto"  # auto | model (alpha-beta) | measured (decision table)
    decision_table: Optional[str] = None  # JSON from `benchmark --sweep`
    alpha_us: float = 1.0  # per-hop latency for the cost model
    beta_us_per_mb: float = 10.0  # inverse link bandwidth (~100 GB/s)
    block_size: int = 2048  # quantization block for int8/fp8 wire codecs
    # Payloads below this never auto-quantize (scale overhead dominates).
    min_quant_bytes: int = 65536
    # Payloads below this stay on the native lax lowering in model mode
    # (tiny collectives are latency-bound; serial hops lose to XLA's own).
    min_algorithmic_bytes: int = 4096
    # Cost-model alpha discount for pallas remote-DMA hops (one fused kernel
    # per hop vs encode+permute+decode programs); candidates enter the model
    # only when the backend is actually available (a real TPU).
    pallas_alpha_scale: float = 0.5
    # T3-style double buffering of the zeropp qwZ gather wire: chunk count
    # (1 = off). Chunk k's dequantize overlaps chunk k+1's gather.
    overlap_chunks: int = 1
    # Let model mode SYNTHESIZE hierarchical schedules (the GC3-style
    # compiler, collectives/schedule.py) as candidates next to the
    # hand-written menu, and accept `algorithm: "compiled"` /
    # "compiled:<sig>" as facade defaults. Off by default: a multi-level
    # schedule dominates ring on hop count under a flat alpha-beta model,
    # so turning this on shifts auto routing across the board.
    compiled_search: bool = False
    # Fuse the ZeRO-3/zeropp weight-gather and tp-boundary matmuls with
    # their collectives inside single Pallas kernels (all-gather+matmul /
    # matmul+reduce-scatter, collectives/fused_gemm.py): grid step j
    # computes output chunk j while chunk j-1's wire is in flight. Off by
    # default; config-off leaves every hot path byte-identical.
    fused_gemm_collectives: bool = False
    # The performance observatory: live hop timing, online calibration,
    # drift detection (active only when `enabled` above is too).
    observe: CollObserveConfig = Field(default_factory=CollObserveConfig)


class CommsLoggerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = Field(default_factory=list)


class PipelineConfig(DeepSpeedConfigModel):
    stages: Union[int, str] = "auto"
    partition: str = "parameters"
    seed_layers: bool = False
    activation_checkpoint_interval: int = 0
    use_reentrant: bool = True


class GradientCompressionConfig(DeepSpeedConfigModel):
    enabled: bool = False
    bits: int = 1  # 1-bit Adam analog via sign+error-feedback compression


class DataEfficiencyConfig(DeepSpeedConfigModel):
    enabled: bool = False
    seed: int = 1234
    data_sampling: Dict[str, Any] = Field(default_factory=dict)
    data_routing: Dict[str, Any] = Field(default_factory=dict)


class MoEAutotuneConfig(DeepSpeedConfigModel):
    """moe_autotune section — host-side capacity-factor controller
    (``runtime/engine.py``): consumes the ``moe/*`` dispatch gauges the MoE
    gate already computes (telemetry + ``moe_metrics``) at the existing
    ``steps_per_print`` sync cadence and moves the gate's *effective*
    capacity factor between steps, inside configured bounds. Jit-cache
    stable by construction: the capacity ARRAYS are padded to a static
    ceiling (``TransformerConfig.moe_capacity_factor_max``, which the
    engine installs from ``max_factor`` via the same rebuild hook the moe
    gauges use) and the controller only moves the traced drop cutoff
    WITHIN that preallocated bucket — one compiled program, a scalar knob
    threaded through the batch (key ``moe_capacity_factor``)."""

    enabled: bool = False
    # drop rate above this raises capacity (the controller's error signal);
    # at-or-below it, a balanced dispatch lowers capacity to reclaim the
    # dead padding FLOPs
    target_drop_rate: float = 0.01
    # controller bounds on the effective factor. ``max_factor`` is also the
    # static padding ceiling the capacity arrays are sized by (the bucket).
    min_factor: float = 1.0
    max_factor: float = 2.0
    # asymmetric steps (raise fast on drops, decay slowly when balanced —
    # drops hurt the loss, slack only hurts the step time)
    increase_step: float = 0.25
    decrease_step: float = 0.0625
    # only lower capacity while expert load balance (E * sum(share^2), 1.0
    # = uniform) is below this — an imbalanced dispatch needs its headroom
    balance_threshold: float = 1.25


class EngineConfig(DeepSpeedConfigModel):
    """Top-level typed config (reference ``DeepSpeedConfig`` runtime/config.py:708)."""

    train_batch_size: Optional[int] = None
    train_micro_batch_size_per_gpu: Optional[int] = None
    gradient_accumulation_steps: Optional[int] = None

    steps_per_print: int = 10
    wall_clock_breakdown: bool = False
    memory_breakdown: bool = False
    dump_state: bool = False
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    gradient_clipping: float = 0.0
    sparse_gradients: bool = False
    disable_allgather: bool = False

    seed: int = 1234

    optimizer: Optional[OptimizerConfig] = None
    scheduler: Optional[SchedulerConfig] = None
    fp16: FP16Config = Field(default_factory=FP16Config)
    bf16: BF16Config = Field(default_factory=BF16Config)
    zero_optimization: ZeroConfig = Field(default_factory=ZeroConfig)
    mesh: MeshConfig = Field(default_factory=MeshConfig)
    activation_checkpointing: ActivationCheckpointingConfig = Field(
        default_factory=ActivationCheckpointingConfig
    )
    tensorboard: TensorboardConfig = Field(default_factory=TensorboardConfig)
    csv_monitor: CSVConfig = Field(default_factory=CSVConfig)
    wandb: WandbConfig = Field(default_factory=WandbConfig)
    flops_profiler: FlopsProfilerConfig = Field(default_factory=FlopsProfilerConfig)
    comms_logger: CommsLoggerConfig = Field(default_factory=CommsLoggerConfig)
    collectives: CollectivesConfig = Field(default_factory=CollectivesConfig)
    telemetry: TelemetryConfig = Field(default_factory=TelemetryConfig)
    diagnostics: DiagnosticsConfig = Field(default_factory=DiagnosticsConfig)
    numerics: NumericsConfig = Field(default_factory=NumericsConfig)
    hbm_guard: HBMGuardConfig = Field(default_factory=HBMGuardConfig)
    snapshot: SnapshotConfig = Field(default_factory=SnapshotConfig)
    recovery: RecoveryConfig = Field(default_factory=RecoveryConfig)
    pipeline: PipelineConfig = Field(default_factory=PipelineConfig)
    data_efficiency: DataEfficiencyConfig = Field(default_factory=DataEfficiencyConfig)
    moe_autotune: MoEAutotuneConfig = Field(default_factory=MoEAutotuneConfig)
    gradient_compression: GradientCompressionConfig = Field(default_factory=GradientCompressionConfig)

    # Inference / misc sections accepted for schema parity
    communication_data_type: Optional[str] = None
    checkpoint: Dict[str, Any] = Field(default_factory=dict)
    elasticity: Dict[str, Any] = Field(default_factory=dict)
    autotuning: Dict[str, Any] = Field(default_factory=dict)
    compression_training: Dict[str, Any] = Field(default_factory=dict)


class DeepSpeedTPUConfig:
    """Parsed + resolved config. The runtime-facing object.

    Resolves the batch-size triad against the mesh's data-parallel world size
    exactly as the reference does (``runtime/config.py:938-1045``).
    """

    def __init__(self, config: Union[str, Dict[str, Any], None] = None, dp_world_size: Optional[int] = None):
        if config is None:
            config = {}
        if isinstance(config, str):
            with open(config, "r") as f:
                config = json.load(f)
        if not isinstance(config, dict):
            raise ValueError(f"Expected a dict or a path to a JSON file, got {type(config)}")
        self.raw: Dict[str, Any] = dict(config)
        self.model = EngineConfig(**config)
        self._dp_world_size = dp_world_size
        self._resolve_batch_triad()

    # -- batch triad -------------------------------------------------------
    def _resolve_batch_triad(self) -> None:
        m = self.model
        train = m.train_batch_size
        micro = m.train_micro_batch_size_per_gpu
        gas = m.gradient_accumulation_steps
        dp = self._dp_world_size or 1

        if train is not None and micro is not None and gas is not None:
            if train != micro * gas * dp:
                raise ValueError(
                    f"Inconsistent batch config: train_batch_size={train} != "
                    f"micro_batch({micro}) * gas({gas}) * dp_world({dp})"
                )
        elif train is not None and micro is not None:
            gas = train // (micro * dp)
            if train % (micro * dp) != 0 or gas == 0:
                raise ValueError(
                    f"train_batch_size={train} not divisible by micro_batch({micro}) * dp_world({dp})"
                )
        elif train is not None and gas is not None:
            micro = train // (gas * dp)
            if train % (gas * dp) != 0 or micro == 0:
                raise ValueError(
                    f"train_batch_size={train} not divisible by gas({gas}) * dp_world({dp})"
                )
        elif micro is not None:
            gas = gas or 1
            train = micro * gas * dp
        elif train is not None:
            micro = train // dp
            gas = 1
            if train % dp != 0 or micro == 0:
                raise ValueError(f"train_batch_size={train} not divisible by dp_world({dp})")
        else:
            # only gas given (or nothing): micro defaults to 1
            micro = 1
            gas = gas or 1
            train = micro * gas * dp

        m.train_batch_size = train
        m.train_micro_batch_size_per_gpu = micro
        m.gradient_accumulation_steps = gas

    # -- convenience accessors --------------------------------------------
    @property
    def train_batch_size(self) -> int:
        return self.model.train_batch_size

    @property
    def train_micro_batch_size_per_gpu(self) -> int:
        return self.model.train_micro_batch_size_per_gpu

    @property
    def gradient_accumulation_steps(self) -> int:
        return self.model.gradient_accumulation_steps

    @property
    def zero_config(self) -> ZeroConfig:
        return self.model.zero_optimization

    @property
    def zero_enabled(self) -> bool:
        return self.model.zero_optimization.stage > 0

    @property
    def fp16_enabled(self) -> bool:
        return self.model.fp16.enabled

    @property
    def bf16_enabled(self) -> bool:
        return self.model.bf16.enabled

    @property
    def compute_dtype(self):
        import jax.numpy as jnp

        if self.model.bf16.enabled:
            return jnp.bfloat16
        if self.model.fp16.enabled:
            return jnp.float16
        return jnp.float32

    @property
    def gradient_clipping(self) -> float:
        return self.model.gradient_clipping

    @property
    def mesh_config(self) -> MeshConfig:
        return self.model.mesh

    def print_config(self, name: str = "DeepSpeedTPUConfig") -> None:
        logger.info(f"{name}:\n{json.dumps(self.model.model_dump(), indent=2, default=str)}")
