"""Pydantic config base machinery.

TPU-native analog of the reference's ``deepspeed/runtime/config_utils.py``
(``DeepSpeedConfigModel`` :17): a pydantic BaseModel that supports deprecated
field migration (``deprecated=True, new_param=...`` in ``json_schema_extra``)
and the ``"auto"`` sentinel for autotunable values.
"""

from __future__ import annotations

from typing import Any, Dict

from pydantic import BaseModel, ConfigDict, model_validator

from deepspeed_tpu.utils.logging import logger

AUTO_VALUE = "auto"


class DeepSpeedConfigModel(BaseModel):
    """Base for all config sections.

    Like the reference, unknown keys are tolerated (collected, warned about)
    rather than fatal, so configs written for the reference largely parse.
    """

    model_config = ConfigDict(
        extra="allow",
        populate_by_name=True,
        validate_assignment=True,
        arbitrary_types_allowed=True,
        protected_namespaces=(),
    )

    def __init__(self, _ds_strict: bool = False, **data):
        # _ds_strict is underscore-prefixed so it cannot collide with a config
        # key (unknown keys are tolerated and must pass through to the model).
        if not _ds_strict:  # drop "auto" values so field defaults apply
            data = {k: v for k, v in data.items() if v != AUTO_VALUE}
        super().__init__(**data)

    @model_validator(mode="before")
    @classmethod
    def _migrate_deprecated(cls, values: Any) -> Any:
        if not isinstance(values, dict):
            return values
        for name, field in cls.model_fields.items():
            extra = getattr(field, "json_schema_extra", None) or {}
            if not extra.get("deprecated", False):
                continue
            keys = {name}
            if field.alias:
                keys.add(field.alias)
            hit = next((k for k in keys if k in values), None)
            if hit is None:
                continue
            new_param = extra.get("new_param", "")
            logger.warning(f"Config parameter {hit} is deprecated" + (f"; use {new_param} instead" if new_param else ""))
            if new_param and new_param not in values:
                values[new_param] = values.pop(hit)
        return values

    def extra_fields(self) -> Dict[str, Any]:
        return dict(self.__pydantic_extra__ or {})
