"""Collectives facade over named mesh axes, with telemetry.

Reference analog: ``deepspeed/comm/comm.py`` — a torch.distributed-compatible
module API where every collective runs through the ``timed_op`` decorator and
``CommsLogger`` aggregates counts/bytes/bandwidth (``utils/comms_logging.py:67``,
``calc_bw_log`` :34, ``log_summary`` ``comm/comm.py:428``).

TPU-native redesign: collectives are *in-program* ``jax.lax`` ops over named
mesh axes, scheduled by XLA — there is no host-side call to time. Telemetry is
therefore **trace-time**: every facade call records (op, axis, bytes, dtype)
when the traced program is built, so after one compiled step the logger holds
the exact collective workload of that step (count x size per op). Bus-bandwidth
estimates use the standard algo->bus factors (allreduce 2(n-1)/n, allgather /
reducescatter (n-1)/n, alltoall (n-1)/n) from the reference's ``calc_bw_log``.

Host control-plane (multi-host rendezvous) maps to ``jax.distributed`` —
``init_distributed()`` here is the analog of ``deepspeed.init_distributed``
(``comm/comm.py:636``): idempotent, env-driven, no-op in single-process runs.
"""

from __future__ import annotations

import collections
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu import telemetry
from deepspeed_tpu.utils.logging import logger


# --------------------------------------------------------------------------
# telemetry
# --------------------------------------------------------------------------


@dataclass
class _OpRecord:
    count: int = 0
    total_bytes: int = 0
    sizes: collections.Counter = field(default_factory=collections.Counter)


class CommsLogger:
    """Trace-time collective telemetry (reference ``CommsLogger``
    ``utils/comms_logging.py:67``)."""

    def __init__(self, enabled: bool = False, verbose: bool = False, debug: bool = False):
        self.enabled = enabled
        self.verbose = verbose
        self.debug = debug
        self._lock = threading.Lock()
        self._records: Dict[str, _OpRecord] = collections.defaultdict(_OpRecord)

    def configure(self, enabled: bool = True, verbose: bool = False, debug: bool = False):
        self.enabled, self.verbose, self.debug = enabled, verbose, debug

    def reset(self):
        with self._lock:
            self._records.clear()

    def record(self, op_name: str, axis: str, nbytes: int, world: int):
        if not self.enabled:
            return
        key = f"{op_name}@{axis}"
        with self._lock:
            rec = self._records[key]
            rec.count += 1
            rec.total_bytes += nbytes
            rec.sizes[(nbytes, world)] += 1
        if self.verbose:
            logger.info(f"comm: {key} size={nbytes}B world={world}")

    @staticmethod
    def _bus_factor(op_name: str, n: int) -> float:
        if n <= 1:
            return 0.0
        if op_name.startswith("all_reduce"):
            return 2 * (n - 1) / n
        return (n - 1) / n  # all_gather / reduce_scatter / all_to_all

    def summary(self) -> List[dict]:
        rows = []
        with self._lock:
            for key, rec in sorted(self._records.items()):
                op, _, axis = key.partition("@")
                rows.append(
                    {
                        "op": op,
                        "axis": axis,
                        "count": rec.count,
                        "total_bytes": rec.total_bytes,
                        "bus_bytes": int(
                            sum(self._bus_factor(op, w) * b * c for (b, w), c in rec.sizes.items())
                        ),
                    }
                )
        return rows

    def log_summary(self):
        rows = self.summary()
        if not rows:
            logger.info("comm summary: no collectives recorded")
            return rows
        width = max(len(r["op"] + r["axis"]) for r in rows) + 4
        logger.info(f"{'op@axis':<{width}} {'count':>8} {'total':>12} {'bus-traffic':>12}")
        for r in rows:
            logger.info(
                f"{r['op'] + '@' + r['axis']:<{width}} {r['count']:>8} "
                f"{_fmt_bytes(r['total_bytes']):>12} {_fmt_bytes(r['bus_bytes']):>12}"
            )
        return rows


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n:.1f}TB"


comms_logger = CommsLogger(enabled=os.environ.get("DSTPU_COMMS_LOGGER", "") == "1")


def configure(enabled: bool = True, verbose: bool = False, debug: bool = False):
    comms_logger.configure(enabled=enabled, verbose=verbose, debug=debug)


def log_summary():
    """Reference ``deepspeed.comm.log_summary()`` (``comm/comm.py:428``)."""
    return comms_logger.log_summary()


def _axis_size(axis) -> int:
    from deepspeed_tpu.utils.compat import axis_size

    # compat resolves the axis-size API move (unit-psum fallback on older
    # jax); outside a bound axis context the size is unknowable -> 1
    return axis_size(axis, default=1)


def _axes_sig(axis):
    """((name, size), ...) for the selector's decision-cache key and the
    schedule compiler's search domain — two meshes with equal world size
    but different axis factorizations must take different decisions. None
    when any axis is unbound (size unknowable outside shard_map)."""
    from deepspeed_tpu.utils.compat import axis_size

    axes = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)
    sig = []
    for a in axes:
        n = axis_size(a, default=0)
        if n <= 0:
            return None
        sig.append((str(a), int(n)))
    return tuple(sig)


def _itemsize(x) -> int:
    try:
        return jnp.dtype(x.dtype).itemsize
    except Exception:
        return 4


def _nbytes(x) -> int:
    try:
        return int(np.prod(x.shape)) * jnp.dtype(x.dtype).itemsize
    except Exception:
        return 0


def _record(op_name: str, axis, x, **tags):
    """Record one collective into the comms logger AND the telemetry
    subsystem; returns a span context wrapping the ``jax.lax`` call.

    Collectives here are in-program ops, so both records happen at TRACE
    time: the span duration is host tracing time (one per compiled program,
    not per execution), while the (op, axis, dtype, bytes, world) tags are
    the exact per-execution collective workload of the traced step.
    ``tags`` carries extra span attributes (algorithm/codec on the
    algorithmic path) so routing decisions are visible in the trace.
    """
    axis_str = "+".join(axis) if isinstance(axis, (tuple, list)) else str(axis)
    nbytes, world = _nbytes(x), _axis_size(axis)
    comms_logger.record(op_name, axis_str, nbytes, world)
    if op_name in ("ppermute", "remote_dma"):
        # hop-wire census for the collective observatory: inside a routed
        # collective's trace scope these ARE the wire bytes the selector's
        # routing put on the interconnect (no-op outside a scope — pipeline
        # ppermutes etc. are not routed wires)
        from deepspeed_tpu.collectives import observatory as _coll_obs

        _coll_obs.on_wire(nbytes)
    tracer = telemetry.get_tracer()
    if not tracer.enabled:
        return telemetry.NOOP_SPAN
    tracer.count("comm/count")
    tracer.count("comm/bytes", nbytes)
    tracer.count(f"comm/bytes/{op_name}", nbytes)
    dtype = str(getattr(x, "dtype", "unknown"))
    return tracer.span(f"comm:{op_name}", cat="comm", op=op_name, axis=axis_str,
                       bytes=nbytes, dtype=dtype, world=world, **tags)


# --------------------------------------------------------------------------
# collectives (usable inside shard_map / jit with bound axis names)
# --------------------------------------------------------------------------
#
# ``algorithm=`` / ``codec=`` route through deepspeed_tpu.collectives (the
# hop-composed algorithmic library): algorithm None keeps the plain jax.lax
# lowering (XLA picks the implementation), "auto" asks collectives.selector
# for the best (algorithm, codec) per (op, bytes, axis size), and a concrete
# name ("ring" / "bidir" / "rhd" / "ring2d", or "pallas_ring" /
# "pallas_ring2d" for remote-DMA hop kernels with in-kernel fused int8/fp8
# reduction — collectives/pallas_backend.py) forces it. The algorithmic
# path must run inside FULL-MANUAL shard_map (see utils/compat.py).


def _algorithmic(op_name: str, x, axis, algorithm, codec, reduce_op: str = "sum"):
    """Resolve (algorithm, codec) — consulting the selector for "auto" —
    and tag the choice on the facade span.

    A call with no explicit algorithm/codec first picks up the process
    defaults the ``collectives`` config block installed
    (``selector.SelectorConfig.facade_algorithm/codec``). Default-routed
    calls stay on the lax lowering when the algorithmic path cannot serve
    them (multi-axis tuples, max/min reductions) and never apply a lossy
    codec to non-float payloads (token ids, the already-int8 zeropp wire);
    an EXPLICIT algorithm/codec argument is honored verbatim and surfaces
    the library's own errors instead."""
    from deepspeed_tpu.collectives import selector

    if isinstance(axis, (tuple, list)) and len(axis) == 0:
        # an empty axis tuple is the native no-op reduction (lax.pmean(x, ())
        # == x — e.g. grad means on a mesh with no >1 data axis): nothing
        # crosses a wire, so there is nothing to route or quantize
        return None, None
    explicit = algorithm is not None or codec is not None
    from_config = False
    if not explicit:
        cfg = selector.get_config()
        if cfg.facade_algorithm is None:
            return None, None
        if isinstance(axis, (tuple, list)) and len(axis) > 1:
            return None, None  # hierarchical tuples only when asked for
        if reduce_op not in ("sum", "mean", "avg"):
            return None, None  # algorithmic all_reduce has no max/min
        if not jnp.issubdtype(getattr(x, "dtype", jnp.float32), jnp.floating):
            # integer payloads (token ids, counters, the zeropp int8 wire)
            # keep the native lowering under default routing
            return None, None
        algorithm, codec = cfg.facade_algorithm, cfg.facade_codec
        from_config = True
        if op_name == "all_to_all" and algorithm == "rhd":
            # the configured default may be an algorithm this op has no
            # form of (rhd: every block has exactly one destination);
            # default routing keeps the lax lowering — only an EXPLICIT
            # rhd request surfaces the library's error
            return None, None
    if algorithm == "lax":
        return None, None
    if algorithm in (None, "auto"):
        if codec is None and not jnp.issubdtype(
                getattr(x, "dtype", jnp.float32), jnp.floating):
            codec = "none"
        d = selector.select(op_name, _nbytes(x), _axis_size(axis), codec,
                            itemsize=_itemsize(x), axes_sig=_axes_sig(axis))
        if d.algorithm == "lax":
            # measured mode's "don't bother" verdict: the baseline won
            return None, None
        return d.algorithm, d.codec
    if codec is None and from_config:
        # concrete configured algorithm + codec "auto": the selector still
        # picks the wire among the configured candidates
        codec = selector.pick_codec(op_name, _nbytes(x), _axis_size(axis),
                                    algorithm, itemsize=_itemsize(x))
    return algorithm, codec or "none"


def _observe_route(op_name: str, x, axis, algorithm: str, codec: str,
                   block_size: Optional[int]):
    """Trace-time observatory registration of one ROUTED collective: the
    returned context collects this trace's hop/wire census
    (``collectives/observatory.py``). A nullcontext when the observatory is
    disabled — the traced program is identical either way (the observatory
    never adds operations; its timings come from standalone probe
    dispatches)."""
    from deepspeed_tpu.collectives import observatory as _coll_obs
    from deepspeed_tpu.telemetry import numerics as _numerics_obs

    # the numerics observatory registers the same signature for its
    # wire-fidelity probes (lossy codecs only; a no-op when disabled)
    _numerics_obs.note_route(
        op_name, algorithm, codec, _nbytes(x), _itemsize(x),
        _axis_size(axis), axis, str(getattr(x, "dtype", "unknown")),
        block_size)
    return _coll_obs.note_route(
        op_name, algorithm, codec, _nbytes(x), _itemsize(x),
        _axis_size(axis), axis, str(getattr(x, "dtype", "unknown")),
        block_size)


def _resolved_block_size(block_size: Optional[int]) -> Optional[int]:
    """The configured quantization block for auto-routed collectives (the
    caller's explicit block_size wins)."""
    if block_size is not None:
        return block_size
    from deepspeed_tpu.collectives import selector

    return selector.get_config().block_size


def all_reduce(x, axis, op: str = "sum", *, algorithm: Optional[str] = None,
               codec: Optional[str] = None, block_size: Optional[int] = None):
    """psum/pmax/pmin over a named axis (reference ``all_reduce`` ``comm/comm.py``)."""
    alg, cd = _algorithmic("all_reduce", x, axis, algorithm, codec, reduce_op=op)
    if alg is not None:
        from deepspeed_tpu import collectives

        bs = _resolved_block_size(block_size)
        with _record(f"all_reduce_{op}", axis, x, algorithm=alg, codec=cd), \
                _observe_route("all_reduce", x, axis, alg, cd, bs):
            return collectives.all_reduce(x, axis, algorithm=alg, codec=cd, op=op,
                                          block_size=bs)
    with _record(f"all_reduce_{op}", axis, x):
        if op == "sum":
            return jax.lax.psum(x, axis)
        if op == "max":
            return jax.lax.pmax(x, axis)
        if op == "min":
            return jax.lax.pmin(x, axis)
        if op in ("mean", "avg"):
            return jax.lax.pmean(x, axis)
        raise ValueError(f"unsupported reduce op {op!r}")


def all_gather(x, axis, *, concat_axis: int = 0, tiled: bool = True,
               algorithm: Optional[str] = None, codec: Optional[str] = None,
               block_size: Optional[int] = None):
    """all_gather over a named axis (reference ``all_gather_into_tensor``)."""
    if not tiled:
        # untiled gathers have no algorithmic form: explicit requests get a
        # clear error, default routing skips the selector entirely (no
        # cached decision / coll:select event for a path never taken)
        if algorithm is not None or codec is not None:
            raise ValueError("algorithmic all_gather supports tiled=True only")
        alg = cd = None
    else:
        alg, cd = _algorithmic("all_gather", x, axis, algorithm, codec)
    if alg is not None:
        from deepspeed_tpu import collectives

        bs = _resolved_block_size(block_size)
        with _record("all_gather", axis, x, algorithm=alg, codec=cd), \
                _observe_route("all_gather", x, axis, alg, cd, bs):
            return collectives.all_gather(x, axis, algorithm=alg, codec=cd,
                                          concat_axis=concat_axis, block_size=bs)
    with _record("all_gather", axis, x):
        return jax.lax.all_gather(x, axis, axis=concat_axis, tiled=tiled)


def reduce_scatter(x, axis, *, scatter_axis: int = 0, tiled: bool = True,
                   algorithm: Optional[str] = None, codec: Optional[str] = None,
                   block_size: Optional[int] = None):
    """psum_scatter (reference ``reduce_scatter_tensor``)."""
    if not tiled:
        # untiled scatters have no algorithmic form (see all_gather above)
        if algorithm is not None or codec is not None:
            raise ValueError("algorithmic reduce_scatter supports tiled=True only")
        alg = cd = None
    else:
        alg, cd = _algorithmic("reduce_scatter", x, axis, algorithm, codec)
    if alg is not None:
        from deepspeed_tpu import collectives

        bs = _resolved_block_size(block_size)
        with _record("reduce_scatter", axis, x, algorithm=alg, codec=cd), \
                _observe_route("reduce_scatter", x, axis, alg, cd, bs):
            return collectives.reduce_scatter(x, axis, algorithm=alg, codec=cd,
                                              scatter_axis=scatter_axis,
                                              block_size=bs)
    with _record("reduce_scatter", axis, x):
        return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=tiled)


def all_to_all(x, axis, *, split_axis: int, concat_axis: int, tiled: bool = True,
               algorithm: Optional[str] = None, codec: Optional[str] = None,
               block_size: Optional[int] = None):
    """all_to_all (reference ``all_to_all_single``; backbone of Ulysses + MoE).

    ``algorithm=``/``codec=`` route through the algorithmic collectives
    library like every other facade op: ``None`` defers to the process
    facade defaults the ``collectives`` config block installed (falling
    back to the byte-identical ``jax.lax`` lowering when none are set —
    callers moving already-encoded bytes must pin ``algorithm="lax"``,
    see ``quant_collectives.exchange_wire``), "auto" consults the
    selector, a concrete name
    ("ring" / "bidir" / "ring2d", or "pallas_ring"/"pallas_ring2d" for
    remote-DMA hops with the in-kernel fused int8/fp8 dispatch wire) forces
    it. The MoE token dispatch/combine (``parallel/moe.py``) and the
    expert-parallel inference path ride this entry point."""
    if not tiled:
        # untiled all_to_all has no algorithmic form (the block-exchange
        # schedules are tiled by construction); explicit requests get a
        # clear error, default routing skips the selector entirely
        if algorithm is not None or codec is not None:
            raise ValueError("algorithmic all_to_all supports tiled=True only")
        alg = cd = None
    else:
        alg, cd = _algorithmic("all_to_all", x, axis, algorithm, codec)
    if alg is not None:
        from deepspeed_tpu import collectives

        bs = _resolved_block_size(block_size)
        with _record("all_to_all", axis, x, algorithm=alg, codec=cd), \
                _observe_route("all_to_all", x, axis, alg, cd, bs):
            return collectives.all_to_all(x, axis, split_axis=split_axis,
                                          concat_axis=concat_axis,
                                          algorithm=alg, codec=cd,
                                          block_size=bs)
    with _record("all_to_all", axis, x):
        return jax.lax.all_to_all(x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled)


def ppermute(x, axis, perm):
    """collective_permute (reference p2p ``send``/``recv``, ``pipe/p2p.py``)."""
    with _record("ppermute", axis, x):
        return jax.lax.ppermute(x, axis, perm)


def broadcast(x, axis, root: int = 0):
    """Broadcast root's shard to all ranks of the axis.

    In-program equivalent of reference ``broadcast`` (``comm/comm.py``): select
    the root slice post-all_gather; XLA lowers this to a broadcast.
    """
    with _record("broadcast", axis, x):
        gathered = jax.lax.all_gather(x, axis, axis=0)
        return gathered[root]


# --------------------------------------------------------------------------
# host control-plane
# --------------------------------------------------------------------------

_initialized = False


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    timeout_s: int = 300,
) -> bool:
    """Multi-host rendezvous via ``jax.distributed`` (reference
    ``init_distributed`` ``comm/comm.py:636``).

    Env-driven like the reference's MASTER_ADDR/RANK/WORLD_SIZE discovery:
    honors ``COORDINATOR_ADDRESS``/``NUM_PROCESSES``/``PROCESS_ID`` or the
    jax-native auto-detection on TPU pods. Idempotent; returns True when a
    multi-process runtime is active.
    """
    global _initialized
    if _initialized:
        return jax.process_count() > 1
    coordinator_address = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    num_processes = num_processes or _int_env("NUM_PROCESSES")
    process_id = process_id if process_id is not None else _int_env("PROCESS_ID")
    try:
        if coordinator_address or num_processes:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                initialization_timeout=timeout_s,
            )
        elif jax.default_backend() == "tpu" and os.environ.get("TPU_WORKER_HOSTNAMES"):
            jax.distributed.initialize()  # auto-detect on TPU pods
    except RuntimeError as e:
        if "already initialized" in str(e).lower():
            logger.debug(f"init_distributed: runtime already initialized: {e}")
        else:
            # A requested multi-host rendezvous that fails must fail loudly
            # (reference deepspeed.init_distributed raises on bad rendezvous);
            # silently continuing would train on 1/N of the pod.
            raise
    _initialized = True
    return jax.process_count() > 1


def _int_env(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v else None


def get_world_size() -> int:
    """Host-process world size (reference ``get_world_size``)."""
    return jax.process_count()


def get_rank() -> int:
    """Host-process rank (reference ``get_rank``)."""
    return jax.process_index()


def barrier(name: str = "barrier", timeout_s: float = 120.0):
    """Cross-host barrier (reference ``barrier`` ``comm/comm.py``).

    Uses a tiny device psum when multiple processes exist; no-op otherwise.
    """
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
