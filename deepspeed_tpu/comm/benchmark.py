"""Collective micro-benchmark (the ``ds_bench`` analog).

Reference: ``bin/ds_bench`` -> DeepSpeedExamples' communication benchmarks
(allreduce/allgather/alltoall latency + busbw sweeps). Here each collective
runs inside a jitted ``shard_map`` over the requested mesh axis; algorithmic
bus bandwidth uses the standard ring-collective factors (the same formulas as
``utils/comms_logging.calc_bw_log``).

Timing note: syncs via scalar fetch, not ``block_until_ready`` (a no-op on
some experimental platforms — see PERF.md).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.comm import CommsLogger
from deepspeed_tpu.utils.compat import shard_map
from deepspeed_tpu.topology.mesh import build_mesh

OPS = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all")


def _collective_fn(op: str, axis: str):
    if op == "all_reduce":
        return lambda x: jax.lax.psum(x, axis)
    if op == "all_gather":
        return lambda x: jax.lax.all_gather(x, axis)
    if op == "reduce_scatter":
        return lambda x: jax.lax.psum_scatter(x, axis, tiled=True)
    if op == "all_to_all":
        return lambda x: jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=True)
    raise ValueError(f"unknown op {op!r} (one of {OPS})")


# Algorithmic bus-bandwidth factors are shared with the in-band comm telemetry.
_busbw_factor = CommsLogger._bus_factor


def _time_collective(f, x, iters: int, warmup: int) -> float:
    """Compile + warm up, then mean seconds/call. Syncs by fetching a scalar
    (block_until_ready is a no-op on some experimental platforms — PERF.md);
    the ONE timing idiom for bench and sweep rows."""
    r = f(x)  # compile + first run (counts as warmup)
    for _ in range(max(warmup - 1, 0)):
        r = f(x)
    np.asarray(jax.tree_util.tree_leaves(r)[0].ravel()[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        r = f(x)
    np.asarray(jax.tree_util.tree_leaves(r)[0].ravel()[0])
    return (time.perf_counter() - t0) / iters


def run_collective_bench(
    op: str,
    sizes_mb: List[float],
    axis: str = "dp",
    mesh: Optional[Mesh] = None,
    iters: int = 10,
    warmup: int = 3,
    dtype=jnp.bfloat16,
) -> List[Dict]:
    """Sweep payload sizes for one collective; returns rows of
    {size_mb, latency_ms, algbw_gbps, busbw_gbps}."""
    mesh = mesh if mesh is not None else build_mesh(axis_sizes={axis: -1})
    n = mesh.shape[axis]
    fn = _collective_fn(op, axis)
    itemsize = jnp.dtype(dtype).itemsize

    rows = []
    for size_mb in sizes_mb:
        elems = max(int(size_mb * 1e6 / itemsize), n)
        elems = (elems // (n * 128)) * (n * 128) or n * 128  # divisible, lane-aligned
        x = jax.device_put(
            jnp.ones((elems,), dtype), NamedSharding(mesh, P(axis))
        )
        f = jax.jit(
            shard_map(fn, mesh=mesh, in_specs=P(axis),
                          out_specs=P() if op == "all_reduce" else P(axis),
                          check_vma=False)
        )
        dt = _time_collective(f, x, iters, warmup)

        payload = elems * itemsize  # global payload bytes
        algbw = payload / dt
        busbw = algbw * _busbw_factor(op, n)
        rows.append({
            "op": op, "world": n, "size_mb": round(payload / 1e6, 3),
            "latency_ms": round(dt * 1e3, 4),
            "algbw_gbps": round(algbw / 1e9, 3),
            "busbw_gbps": round(busbw / 1e9, 3),
        })
    return rows


_SWEEP_OPS = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all")


def candidate_pairs(world: int, codecs, algorithms=None, op: Optional[str] = None,
                    axis: Optional[str] = None):
    """(algorithm, codec) measurement candidates for one axis size — THE
    enumeration shared by ``run_sweep`` and the observatory's probe queue,
    so online rows stay comparable with sweep rows: lax + the ppermute
    schedule families (+ the pallas algorithms when the backend is
    available), ``rhd`` only on power-of-two worlds (and never for
    ``all_to_all``, which has no recursive-halving form), the native
    lowering never paired with a wire codec. With ``axis`` (and an ``op``
    the schedule compiler covers), the compiler's top synthesized
    ``compiled:<sig>`` programs join the queue — measured mode then learns
    real latencies for searched schedules, not just the hand-written
    families; their codec column is the signature's lossiest level. An
    EXPLICIT ``algorithms`` list is honored verbatim (no compiled rows):
    a pinned sweep measures exactly what was asked."""
    from deepspeed_tpu.collectives import pallas_backend
    from deepspeed_tpu.collectives.algorithms import ALGORITHMS
    from deepspeed_tpu.collectives.pallas_backend import PALLAS_ALGORITHMS

    auto = algorithms is None
    if auto:
        algorithms = ["lax"] + list(ALGORITHMS)
        if pallas_backend.available():
            algorithms += list(PALLAS_ALGORITHMS)
    pow2 = world > 0 and not (world & (world - 1))
    out = []
    for alg in algorithms:
        if alg == "rhd" and (not pow2 or op == "all_to_all"):
            continue
        for cd in codecs:
            if alg == "lax" and cd != "none":
                continue  # the lax lowering has no wire codec
            if (alg, cd) not in out:
                out.append((alg, cd))
    if auto and axis is not None:
        from deepspeed_tpu.collectives import schedule as _schedule

        if op in _schedule.SCHEDULED_OPS:
            for sig in _schedule.candidate_signatures(op, axis, world,
                                                      codecs=tuple(codecs)):
                pair = (f"compiled:{sig}", _schedule.signature_codec(sig))
                if pair not in out:
                    out.append(pair)
    return out


def probe_elems(n: int, elems: int) -> int:
    """Round a global element count to the sweep's payload base (a multiple
    of ``n*n*128``): the per-device shard must itself divide by ``n`` for
    reduce_scatter and stay lane-aligned. Shared by ``run_sweep`` and the
    observatory's probe payloads so both measure the same shapes."""
    base = n * n * 128
    return (elems // base) * base or base


def _algorithmic_fn(op: str, axis: str, algorithm: str, codec: str, block_size: int):
    """Per-device body routing through the comm facade's algorithmic path
    (so the sweep measures exactly what ``selector`` will later dispatch)."""
    from deepspeed_tpu.comm import comm as dist

    if op == "all_reduce":
        return lambda x: dist.all_reduce(x, axis, algorithm=algorithm, codec=codec,
                                         block_size=block_size)
    if op == "all_gather":
        return lambda x: dist.all_gather(x, axis, algorithm=algorithm, codec=codec,
                                         block_size=block_size)
    if op == "reduce_scatter":
        return lambda x: dist.reduce_scatter(x, axis, algorithm=algorithm, codec=codec,
                                             block_size=block_size)
    if op == "all_to_all":
        return lambda x: dist.all_to_all(x, axis, split_axis=0, concat_axis=0,
                                         algorithm=algorithm, codec=codec,
                                         block_size=block_size)
    raise ValueError(f"sweep op {op!r} not algorithmic (one of {_SWEEP_OPS})")


def run_sweep(
    ops=_SWEEP_OPS,
    sizes_mb: Optional[List[float]] = None,
    axis: str = "dp",
    mesh: Optional[Mesh] = None,
    algorithms: Optional[List[str]] = None,
    codecs: Optional[List[str]] = None,
    iters: int = 5,
    warmup: int = 2,
    block_size: int = 2048,
    dtype=jnp.bfloat16,
) -> List[Dict]:
    """Measure every (op, size, algorithm, codec) combination and return the
    decision-table rows ``selector.configure(decision_table=...)`` consumes
    (one JSON row per measurement: op/world/size_mb/algorithm/codec/backend/
    latency_ms/busbw_gbps; ``size_mb`` is the PER-DEVICE payload, matching
    the local-shard bytes the selector is queried with; ``backend`` is the
    hop backend the row was measured with — measured mode never applies a
    ppermute row to a pallas algorithm or vice versa). The lax baseline
    rides along as ``algorithm="lax"`` so measured mode can conclude
    "don't bother"."""
    from deepspeed_tpu.collectives import pallas_backend
    from deepspeed_tpu.collectives.algorithms import ALGORITHMS
    from deepspeed_tpu.collectives.pallas_backend import PALLAS_ALGORITHMS
    from deepspeed_tpu.utils.logging import logger

    sizes_mb = sizes_mb if sizes_mb is not None else [0.25, 1.0, 4.0]
    if algorithms is None:
        # the pallas remote-DMA algorithms sweep themselves in on TPU only
        algorithms = ["lax"] + list(ALGORITHMS)
        if pallas_backend.available():
            algorithms += list(PALLAS_ALGORITHMS)
    pallas_req = [a for a in algorithms if pallas_backend.is_pallas(a)]
    if pallas_req and not pallas_backend.available():
        # an off-TPU sweep must not crash (CI boxes) — and must not emit
        # interpret-mode timings either: the interpreter's latencies say
        # nothing about remote-DMA hops, and a table holding them would
        # poison measured-mode routing on a real TPU
        logger.warning(
            f"collectives sweep: skipping {pallas_req} — the pallas "
            f"remote-DMA backend needs a TPU (backend is "
            f"{jax.default_backend()!r}; interpret-mode timings would "
            "poison the decision table)")
        algorithms = [a for a in algorithms if not pallas_backend.is_pallas(a)]
    codecs = codecs if codecs is not None else ["none"]
    mesh = mesh if mesh is not None else build_mesh(axis_sizes={axis: -1})
    n = mesh.shape[axis]
    itemsize = jnp.dtype(dtype).itemsize
    rows: List[Dict] = []
    for op in ops:
        for size_mb in sizes_mb:
            elems = probe_elems(n, max(int(size_mb * 1e6 / itemsize), n))
            x = jax.device_put(jnp.ones((elems,), dtype), NamedSharding(mesh, P(axis)))
            for alg, codec in candidate_pairs(n, codecs, algorithms, op=op,
                                              axis=axis):
                fn = (_collective_fn(op, axis) if alg == "lax"
                      else _algorithmic_fn(op, axis, alg, codec, block_size))
                out_spec = P() if op == "all_reduce" else P(axis)
                f = jax.jit(shard_map(fn, mesh=mesh, in_specs=P(axis),
                                      out_specs=out_spec, check_vma=False))
                dt = _time_collective(f, x, iters, warmup)
                payload = elems * itemsize
                busbw = payload / dt * _busbw_factor(op, n)
                # size_mb is the PER-DEVICE payload: selector.select is
                # queried at trace time with the local shard's bytes
                # (inside shard_map), so table rows must bucket the same
                # quantity or measured mode matches a world-x-off regime
                rows.append({
                    "op": op, "world": n, "size_mb": round(payload / n / 1e6, 4),
                    "algorithm": alg, "codec": codec,
                    # the hop backend these timings were measured with:
                    # selector measured mode only applies a row to
                    # algorithms of the same backend (a ppermute table
                    # must never route pallas hop counts, nor vice versa)
                    "backend": pallas_backend.hop_backend(alg),
                    "latency_ms": round(dt * 1e3, 4),
                    "busbw_gbps": round(busbw / 1e9, 3),
                    # payload element width: the observatory's alpha/beta
                    # refit reconstructs wire bytes from it (table.py v1)
                    "itemsize": itemsize,
                    "samples": 1,
                })
    return rows


def _emit_perf_ledger(rows: List[Dict]) -> None:
    """Append the sweep's decision rows to the unified perf ledger, suite
    ``coll-sweep`` (ISSUE 16): one latency + one busbw row per measured
    (op, world, size, algorithm, codec) point. The ledger row's ``backend``
    stays the ACCELERATOR (cpu / tpu-v5e — gate isolation is per chip);
    the hop backend the row was measured with (ppermute / pallas) rides
    inside the metric path. Best-effort: a read-only ledger dir must not
    fail the sweep."""
    try:
        from deepspeed_tpu.telemetry.perfledger import PerfLedger, make_row

        out = []
        for r in rows:
            stem = (f"{r['op']}/{r['algorithm']}/{r['codec']}/"
                    f"{r['backend']}/w{r['world']}/mb{r['size_mb']:g}")
            samples = int(r.get("samples", 1))
            out.append(make_row("coll-sweep", f"{stem}/latency_ms",
                                r["latency_ms"], "ms", direction="lower",
                                samples=samples))
            out.append(make_row("coll-sweep", f"{stem}/busbw_gbps",
                                r["busbw_gbps"], "GB/s", direction="higher",
                                samples=samples))
        PerfLedger().append(out)
    except Exception as e:  # noqa: BLE001 — evidence plane, not the sweep
        from deepspeed_tpu.utils.logging import logger

        logger.warning(f"collectives sweep: perf-ledger append skipped: {e}")


def main(argv=None) -> int:  # pragma: no cover - CLI body exercised via run_collective_bench
    import argparse
    import json

    p = argparse.ArgumentParser(description="Collective micro-benchmark (ds_bench analog)")
    p.add_argument("--op", default="all_reduce", choices=OPS + ("all",))
    p.add_argument("--axis", default="dp")
    p.add_argument("--sizes-mb", default="1,8,64,256")
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--sweep", action="store_true",
                   help="sweep algorithms x codecs and emit a selector decision table")
    p.add_argument("--codecs", default="none",
                   help="comma-separated wire codecs for --sweep (none,bf16,int8,fp8)")
    p.add_argument("--algorithms", default=None,
                   help="comma-separated algorithms for --sweep (default: lax + "
                        "the ppermute set, + pallas_ring/pallas_ring2d on TPU; "
                        "pallas algorithms are skipped with a logged reason "
                        "off-TPU rather than measured under the interpreter)")
    p.add_argument("--output", default=None,
                   help="write the --sweep decision table JSON here (default "
                        "stdout; versioned schema envelope — see "
                        "collectives/table.py)")
    p.add_argument("--merge", default=None, metavar="TABLE",
                   help="fold the sweep into an EXISTING decision table "
                        "(e.g. the observatory's online coll_table.json): "
                        "matching rows are replaced by the fresh sweep, rows "
                        "the sweep did not cover are kept; written to "
                        "--output (default: back onto TABLE)")
    a = p.parse_args(argv)
    sizes = [float(s) for s in a.sizes_mb.split(",")]
    if a.sweep:
        from deepspeed_tpu.collectives import table as table_mod
        from deepspeed_tpu.utils.logging import logger

        ops = _SWEEP_OPS if a.op == "all" else (a.op,)
        bad = [op for op in ops if op not in _SWEEP_OPS]
        if bad:
            p.error(f"--sweep supports {_SWEEP_OPS}, not {bad}")
        rows = run_sweep(ops=ops, sizes_mb=sizes, axis=a.axis, iters=a.iters,
                         algorithms=([s for s in a.algorithms.split(",") if s]
                                     if a.algorithms else None),
                         codecs=[c for c in a.codecs.split(",") if c])
        source = "sweep"
        out_path = a.output
        if a.merge:
            out_path = out_path or a.merge
            try:
                base = table_mod.load_table(a.merge, strict=True)
            except FileNotFoundError:
                base = []  # first merge into a table nobody persisted yet
            except (OSError, ValueError) as e:
                # unreadable or version-mismatched base: the (possibly
                # long, on-TPU) sweep that just ran must not be thrown
                # away — but neither may rows we cannot parse be DESTROYED
                # by overwriting the base file with sweep-only content
                base = []
                if out_path == a.merge:
                    out_path = a.merge + ".sweep.json"
                logger.warning(
                    f"--merge: base table {a.merge!r} unreadable or "
                    f"version-mismatched ({e}); leaving it untouched and "
                    f"writing the fresh sweep to {out_path}")
            rows = table_mod.merge_rows(base, rows)
            source = "merged"
        if out_path:
            table_mod.write_table(out_path, rows, source=source)
            print(f"wrote {len(rows)} decision rows to {out_path} "
                  f"(schema {table_mod.SCHEMA_VERSION}, source {source})")
        else:
            print(json.dumps({"schema": table_mod.SCHEMA_VERSION,
                              "source": source, "rows": rows}, indent=1))
        _emit_perf_ledger(rows)
        return 0
    ops = OPS if a.op == "all" else (a.op,)
    for op in ops:
        for row in run_collective_bench(op, sizes, axis=a.axis, iters=a.iters):
            print(json.dumps(row))
    return 0


if __name__ == "__main__":  # pragma: no cover - bin/ds_bench is the usual entry
    import sys

    sys.exit(main())
