"""Collective micro-benchmark (the ``ds_bench`` analog).

Reference: ``bin/ds_bench`` -> DeepSpeedExamples' communication benchmarks
(allreduce/allgather/alltoall latency + busbw sweeps). Here each collective
runs inside a jitted ``shard_map`` over the requested mesh axis; algorithmic
bus bandwidth uses the standard ring-collective factors (the same formulas as
``utils/comms_logging.calc_bw_log``).

Timing note: syncs via scalar fetch, not ``block_until_ready`` (a no-op on
some experimental platforms — see PERF.md).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.comm import CommsLogger
from deepspeed_tpu.utils.compat import shard_map
from deepspeed_tpu.topology.mesh import build_mesh

OPS = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all")


def _collective_fn(op: str, axis: str):
    if op == "all_reduce":
        return lambda x: jax.lax.psum(x, axis)
    if op == "all_gather":
        return lambda x: jax.lax.all_gather(x, axis)
    if op == "reduce_scatter":
        return lambda x: jax.lax.psum_scatter(x, axis, tiled=True)
    if op == "all_to_all":
        return lambda x: jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=True)
    raise ValueError(f"unknown op {op!r} (one of {OPS})")


# Algorithmic bus-bandwidth factors are shared with the in-band comm telemetry.
_busbw_factor = CommsLogger._bus_factor


def run_collective_bench(
    op: str,
    sizes_mb: List[float],
    axis: str = "dp",
    mesh: Optional[Mesh] = None,
    iters: int = 10,
    warmup: int = 3,
    dtype=jnp.bfloat16,
) -> List[Dict]:
    """Sweep payload sizes for one collective; returns rows of
    {size_mb, latency_ms, algbw_gbps, busbw_gbps}."""
    mesh = mesh if mesh is not None else build_mesh(axis_sizes={axis: -1})
    n = mesh.shape[axis]
    fn = _collective_fn(op, axis)
    itemsize = jnp.dtype(dtype).itemsize

    rows = []
    for size_mb in sizes_mb:
        elems = max(int(size_mb * 1e6 / itemsize), n)
        elems = (elems // (n * 128)) * (n * 128) or n * 128  # divisible, lane-aligned
        x = jax.device_put(
            jnp.ones((elems,), dtype), NamedSharding(mesh, P(axis))
        )
        f = jax.jit(
            shard_map(fn, mesh=mesh, in_specs=P(axis),
                          out_specs=P() if op == "all_reduce" else P(axis),
                          check_vma=False)
        )
        r = f(x)  # compile + first run (counts as warmup)
        for _ in range(max(warmup - 1, 0)):
            r = f(x)
        np.asarray(jax.tree_util.tree_leaves(r)[0].ravel()[0])
        t0 = time.perf_counter()
        for _ in range(iters):
            r = f(x)
        np.asarray(jax.tree_util.tree_leaves(r)[0].ravel()[0])
        dt = (time.perf_counter() - t0) / iters

        payload = elems * itemsize  # global payload bytes
        algbw = payload / dt
        busbw = algbw * _busbw_factor(op, n)
        rows.append({
            "op": op, "world": n, "size_mb": round(payload / 1e6, 3),
            "latency_ms": round(dt * 1e3, 4),
            "algbw_gbps": round(algbw / 1e9, 3),
            "busbw_gbps": round(busbw / 1e9, 3),
        })
    return rows


def main(argv=None) -> int:  # pragma: no cover - CLI body exercised via run_collective_bench
    import argparse
    import json

    p = argparse.ArgumentParser(description="Collective micro-benchmark (ds_bench analog)")
    p.add_argument("--op", default="all_reduce", choices=OPS + ("all",))
    p.add_argument("--axis", default="dp")
    p.add_argument("--sizes-mb", default="1,8,64,256")
    p.add_argument("--iters", type=int, default=10)
    a = p.parse_args(argv)
    sizes = [float(s) for s in a.sizes_mb.split(",")]
    ops = OPS if a.op == "all" else (a.op,)
    for op in ops:
        for row in run_collective_bench(op, sizes, axis=a.axis, iters=a.iters):
            print(json.dumps(row))
    return 0
