"""deepspeed_tpu.comm: collectives facade + telemetry.

Reference analog: ``deepspeed/comm`` (``comm/comm.py`` module-level collectives,
``utils/comms_logging.py`` CommsLogger). See ``comm/comm.py`` here for the
design mapping onto XLA in-program collectives.
"""

from deepspeed_tpu.comm.comm import (
    CommsLogger,
    all_gather,
    all_reduce,
    all_to_all,
    barrier,
    broadcast,
    comms_logger,
    get_rank,
    get_world_size,
    init_distributed,
    log_summary,
    ppermute,
    reduce_scatter,
)
