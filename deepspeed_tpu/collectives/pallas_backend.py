"""Pallas TPU remote-DMA collective backend.

The hop primitive behind ``algorithm="pallas_ring"`` / ``"pallas_ring2d"``:
instead of routing each neighbor exchange through ``lax.ppermute`` (one XLA
collective-permute per hop, with the wire codec's encode/decode as separate
programs around it), every hop is ONE Pallas kernel built on
``pltpu.make_async_remote_copy`` + DMA-semaphore signaling — the SNIPPETS
right-permute shape, with the neighbor resolved to a LOGICAL device id so
it works on any full-manual mesh.

Two kernel shapes:

- :func:`permute_wire` — the plain hop: remote-copy every wire leaf
  (quantized values + scales) HBM→HBM in one program. Used by the
  encode-once gather/relay paths, and by reduce paths whose codec cannot
  fuse (exact wires, integer payloads).
- :func:`fused_ring_reduce_scatter_rows` — the EQuARX fusion
  (arxiv 2506.17615): for int8/fp8 wires the whole
  quantize → remote-DMA → dequantize-accumulate hop runs inside ONE kernel,
  with the wire blocks staged in VMEM. The kernel grid double-buffers
  chunks (the ``overlap.py`` T3 pattern moved inside the kernel): the
  remote DMA of chunk ``j`` is in flight while chunk ``j-1`` is
  dequant-accumulated, on a 2-slot VMEM wire buffer. One program per hop
  where the ppermute path ran three (encode / permute / decode).

Quantization block math is shared with the ``ops.quant`` registry
(``int8_block_math`` / ``fp8_block_math``) so the fused wire is the same
format every other collective and the zeropp gathers speak.

Execution modes: compiled Mosaic on a real TPU backend; Pallas
``interpret=True`` everywhere else (the tier-1 equivalence tests run the
same kernels on the forced-CPU mesh). Interpret mode cannot express remote
``semaphore_signal`` — the credit-based sender flow control and the
kernel-entry barrier are therefore emitted only in compiled mode (the
interpreter's DMAs are synchronous, so the slot-reuse hazard they guard
against cannot occur there).
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.collectives.codecs import Codec

PALLAS_ALGORITHMS = ("pallas_ring", "pallas_ring2d")

# double-buffered chunk target (elements) for the fused hop kernel grid;
# rounded to a whole number of quantization blocks per chunk
_CHUNK_TARGET = 16384


def is_pallas(algorithm) -> bool:
    return isinstance(algorithm, str) and algorithm in PALLAS_ALGORITHMS


def base_algorithm(algorithm: str) -> str:
    """The schedule a pallas algorithm runs (``pallas_ring`` -> ``ring``):
    hop counts and link volumes are identical — only the hop primitive and
    the codec fusion move."""
    return algorithm[len("pallas_"):] if is_pallas(algorithm) else algorithm


def available() -> bool:
    """True when compiled remote-DMA hops can actually run (a real TPU
    backend). Off-TPU the kernels still execute under ``interpret=True``
    when explicitly requested (tests, smoke stages), but the selector and
    the benchmark sweep must never route production traffic there."""
    return jax.default_backend() == "tpu"


def backend_token() -> str:
    """The hop-backend family usable in this process — stamped into
    selector cache keys and matched against measured decision-table rows so
    a table swept with one backend never routes the other's algorithms."""
    return "pallas" if available() else "ppermute"


def hop_backend(algorithm: str) -> str:
    """The hop-backend family an algorithm name implies — THE one
    classification behind decision-table ``backend`` stamps, the selector's
    calibrated alpha/beta lookup, and the observatory's sample labels
    (``"xla"`` for the native lowering, ``"pallas"`` for the remote-DMA
    kernels, ``"ppermute"`` for everything else)."""
    if algorithm == "lax":
        return "xla"
    return "pallas" if is_pallas(algorithm) else "ppermute"


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def fusable(codec: Codec, dtype) -> bool:
    """The in-kernel dequant-accumulate-requant fusion speaks the 1-byte
    block-quant wires (int8/fp8) over float payloads; everything else runs
    the unfused wire with plain remote-copy hops."""
    return codec.name in ("int8", "fp8") and jnp.issubdtype(dtype, jnp.floating)


# ------------------------------------------------------------- hop routing

_hop_state = threading.local()


def hops_active() -> bool:
    return getattr(_hop_state, "active", False)


@contextlib.contextmanager
def hop_scope():
    """Trace-time scope marking that the current algorithm's hops run on
    the Pallas backend (``algorithms._permute_wire`` and the reduce-scatter
    row helper consult it, so the schedule layer stays hop-agnostic)."""
    prev = getattr(_hop_state, "active", False)
    _hop_state.active = True
    try:
        yield
    finally:
        _hop_state.active = prev


_warned_multiaxis = False


def remote_dma_supported() -> bool:
    """Whether the remote-DMA hop can actually express this trace context.

    Compiled Mosaic handles LOGICAL device ids on any mesh; the Pallas
    INTERPRETER only discharges them for single-named-axis shardings (jax
    0.4.x ``dma_start_discharge_rule``). Inside interpret mode on a
    multi-axis mesh the hops fall back to ppermute — the schedule, codec,
    and numerics are identical, only the transport differs, so tests on 2D
    CPU meshes still validate the algorithm while 1D meshes validate the
    kernels themselves."""
    global _warned_multiaxis
    if not _interpret():
        return True
    names, _ = _mesh_axes()
    if len(names) == 1:
        return True
    if not _warned_multiaxis:
        _warned_multiaxis = True
        from deepspeed_tpu.utils.logging import logger

        logger.warning(
            f"pallas collectives: interpret mode cannot express remote DMA "
            f"on a multi-axis mesh ({names}) — hops fall back to ppermute "
            "for this trace (compiled TPU runs use the kernels)")
    return False


# ------------------------------------------------------- device id resolution


def _mesh_axes() -> Tuple[List[str], List[int]]:
    """(names, sizes) of every bound mesh axis, in mesh order, from the
    trace-time axis env (full-manual shard_map binds them all)."""
    from deepspeed_tpu.utils.compat import axis_env_sizes

    sizes = axis_env_sizes()
    if not sizes:
        raise RuntimeError(
            "pallas collective hops need bound mesh axis names — call inside "
            "a full-manual shard_map (see utils/compat.shard_map)")
    return list(sizes.keys()), [int(v) for v in sizes.values()]


def _neighbor_logicals(axis, perm: Sequence[Tuple[int, int]]):
    """(dst, src) LOGICAL device ids (traced int32 scalars) of the ranks this
    device sends to / receives from under ``perm`` (a permutation of the
    ``axis`` indices). Logical ids are row-major over the mesh shape, so the
    neighbor differs from this device only along the hop axis' stride."""
    names, sizes = _mesh_axes()
    if axis not in names:
        raise ValueError(f"hop axis {axis!r} not bound in mesh axes {names}")
    ax = names.index(axis)
    n = sizes[ax]
    stride = int(np.prod(sizes[ax + 1:], dtype=np.int64)) if ax + 1 < len(sizes) else 1
    dst_t = np.full((n,), -1, np.int32)
    src_t = np.full((n,), -1, np.int32)
    for s, d in perm:
        dst_t[s] = d
        src_t[d] = s
    if (dst_t < 0).any() or (src_t < 0).any():
        raise ValueError(f"perm is not a full permutation of {n} ranks: {perm}")
    i = lax.axis_index(axis)
    my_logical = jnp.int32(0)
    for k, nm in enumerate(names):
        st = int(np.prod(sizes[k + 1:], dtype=np.int64)) if k + 1 < len(sizes) else 1
        my_logical = my_logical + lax.axis_index(nm).astype(jnp.int32) * np.int32(st)
    dst = my_logical + (jnp.asarray(dst_t)[i] - i).astype(jnp.int32) * np.int32(stride)
    src = my_logical + (jnp.asarray(src_t)[i] - i).astype(jnp.int32) * np.int32(stride)
    return dst, src


def _compiler_params():
    """Mosaic params for compiled mode (interpret mode takes none):
    collective kernels sharing the barrier semaphore need a
    ``collective_id`` (one id — every hop kernel of a step participates in
    the same gang). Routed through the compat shim so the
    TPUCompilerParams -> CompilerParams rename cannot break compiled hops."""
    if _interpret():
        return None
    from deepspeed_tpu.utils.compat import tpu_compiler_params

    return tpu_compiler_params(collective_id=0)


def _entry_barrier(dst, src, interpret: bool):
    """Compiled-mode rendezvous with both hop partners before touching
    comm buffers: a remote DMA may not land in a peer's buffer before that
    peer's kernel owns it. Interpret mode is synchronous — skip."""
    if interpret:
        return
    bar = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(bar, 1, device_id=dst,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_signal(bar, 1, device_id=src,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(bar, 2)


# ------------------------------------------------------------ plain hop kernel


def _permute_leaves_kernel(idx_ref, *refs, k: int, interpret: bool):
    """Remote-copy ``k`` HBM buffers to the ``dst`` rank in one program.
    refs = inputs[k] + outputs[k] + DMA sems[2k] (send/recv per leaf)."""
    ins, outs, sems = refs[:k], refs[k:2 * k], refs[2 * k:]
    dst, src = idx_ref[0], idx_ref[1]
    _entry_barrier(dst, src, interpret)
    ops = []
    for t in range(k):
        op = pltpu.make_async_remote_copy(
            src_ref=ins[t], dst_ref=outs[t],
            send_sem=sems[2 * t], recv_sem=sems[2 * t + 1],
            device_id=dst, device_id_type=pltpu.DeviceIdType.LOGICAL)
        op.start()
        ops.append(op)
    for op in ops:
        op.wait()


def remote_permute_leaves(leaves: Sequence[jax.Array], axis,
                          perm: Sequence[Tuple[int, int]]) -> List[jax.Array]:
    """One Pallas program moving every leaf one hop along ``perm`` (the
    ``ppermute`` replacement: same permutation semantics, remote DMA
    transport)."""
    leaves = list(leaves)
    if not leaves:
        return []
    interpret = _interpret()
    dst, src = _neighbor_logicals(axis, perm)
    idx = jnp.stack([dst, src])
    k = len(leaves)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY) for _ in range(k)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.ANY) for _ in range(k)],
        scratch_shapes=[pltpu.SemaphoreType.DMA] * (2 * k),
    )
    out = pl.pallas_call(
        functools.partial(_permute_leaves_kernel, k=k, interpret=interpret),
        out_shape=[jax.ShapeDtypeStruct(l.shape, l.dtype) for l in leaves],
        grid_spec=grid_spec,
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(idx, *leaves)
    return list(out) if isinstance(out, (list, tuple)) else [out]


def permute_wire(wire, axis, perm):
    """Permute a wire pytree one hop over remote DMA (the pallas analog of
    ``algorithms._permute_wire``); zero-size leaves (passthrough codec
    scale placeholders) pass through untouched. The transfer is recorded as
    a ``comm:remote_dma`` span so trace consumers see the hop's wire bytes
    exactly like a ``comm:ppermute``."""
    from deepspeed_tpu.comm import comm as dist

    leaves, treedef = jax.tree_util.tree_flatten(wire)
    live = [(i, l) for i, l in enumerate(leaves) if l.size > 0]
    if not live:
        return wire
    nbytes = sum(int(np.prod(l.shape)) * jnp.dtype(l.dtype).itemsize for _, l in live)
    proxy = jax.ShapeDtypeStruct((nbytes,), jnp.int8)
    with dist._record("remote_dma", axis, proxy, backend="pallas"):
        moved = remote_permute_leaves([l for _, l in live], axis, perm)
    out = list(leaves)
    for (i, _), m in zip(live, moved):
        out[i] = m
    return jax.tree_util.tree_unflatten(treedef, out)


# ------------------------------------------------------------ fused hop kernel


def _block_math(codec: Codec):
    """(encode, decode, wire_dtype) — the shared ``ops.quant`` block math
    the fused kernel runs in VMEM, identical to the unfused wire codecs."""
    from deepspeed_tpu.ops.quant import (fp8_block_dequant, fp8_block_math,
                                         int8_block_dequant, int8_block_math)

    if codec.name == "int8":
        return int8_block_math, int8_block_dequant, jnp.int8
    if codec.name == "fp8":
        return fp8_block_math, fp8_block_dequant, jnp.float8_e4m3fn
    raise ValueError(f"no fused kernel for codec {codec.name!r}")


def _fused_hop_kernel(idx_ref, send_blk, recv_blk, out_blk,
                      send_q, send_s, recv_q, recv_s,
                      sq_sem, ss_sem, rq_sem, rs_sem, cap_sem,
                      *, C: int, B: int, qb: int, encode, decode,
                      interpret: bool, accumulate: bool = True):
    """One ring hop, fused: grid step ``j`` requantizes chunk ``j`` of the
    accumulated send row into a VMEM wire slot and launches its remote DMA,
    then dequant-accumulates chunk ``j-1`` (whose DMA was launched last
    step) into the output row — chunk ``j``'s interconnect time hides
    behind chunk ``j-1``'s VMEM compute. 2-slot wire buffers; the last grid
    step (``j == C``) only drains."""
    j = pl.program_id(0)
    slot = lax.rem(j, 2)
    prev = lax.rem(j + 1, 2)  # == (j - 1) % 2
    dst, src = idx_ref[2], idx_ref[3]
    nb = B // qb

    def q_copy(s):
        return pltpu.make_async_remote_copy(
            src_ref=send_q.at[s], dst_ref=recv_q.at[s],
            send_sem=sq_sem.at[s], recv_sem=rq_sem.at[s],
            device_id=dst, device_id_type=pltpu.DeviceIdType.LOGICAL)

    def s_copy(s):
        return pltpu.make_async_remote_copy(
            src_ref=send_s.at[s], dst_ref=recv_s.at[s],
            send_sem=ss_sem.at[s], recv_sem=rs_sem.at[s],
            device_id=dst, device_id_type=pltpu.DeviceIdType.LOGICAL)

    @pl.when(j == 0)
    def _():
        _entry_barrier(dst, src, interpret)

    @pl.when(j < C)
    def _send():
        @pl.when(j >= 2)
        def _():
            # slot reuse: our previous DMAs out of this slot must have left
            # the buffer, and (compiled mode) the receiver must have drained
            # the chunk we sent into ITS slot two steps ago — the credit it
            # signals back when consuming
            q_copy(slot).wait_send()
            s_copy(slot).wait_send()
            if not interpret:
                pltpu.semaphore_wait(cap_sem, 1)
        x = send_blk[0].astype(jnp.float32).reshape(nb, qb)
        q, s = encode(x)
        send_q[slot] = q.reshape(B)
        send_s[slot] = s.reshape(nb)
        q_copy(slot).start()
        s_copy(slot).start()

    @pl.when(j > 0)
    def _recv():
        q_copy(prev).wait_recv()
        s_copy(prev).wait_recv()
        deq = decode(recv_q[prev].reshape(nb, qb), recv_s[prev].reshape(nb, 1))
        if accumulate:
            out_blk[0] = recv_blk[0] + deq.reshape(B).astype(jnp.float32)
        else:
            # the all-to-all hop: the PR-8 fused reduce hop MINUS the
            # accumulate — the dequantized wire IS the received row
            out_blk[0] = deq.reshape(B).astype(jnp.float32)
        if not interpret:
            # grant the sender upstream one wire-slot credit
            pltpu.semaphore_signal(cap_sem, 1, device_id=src,
                                   device_id_type=pltpu.DeviceIdType.LOGICAL)

    # semaphore balance: every DMA/credit semaphore must read zero at kernel
    # exit — consecutive hop kernels reuse the same physical scratch
    # semaphores, so a leftover send credit would let the NEXT hop's
    # wait_send pass before its own DMA drained the VMEM slot, corrupting
    # wire data. The send loop waits slot s only when a LATER send reuses it
    # (j in [2, C-1]), which leaves the final min(C, 2) sends outstanding;
    # wait them here. cap_sem (compiled mode only): the downstream receiver
    # signals C credits but the send loop consumes only C-2 (the first two
    # sends ride the free slots) — draining the rest doubles as
    # back-pressure: this hop cannot retire until the downstream rank
    # consumed every chunk.
    @pl.when(j == C)
    def _drain():
        for s in ([0] if C == 1 else [(C - 2) % 2, (C - 1) % 2]):
            q_copy(s).wait_send()
            s_copy(s).wait_send()
        if not interpret:
            pltpu.semaphore_wait(cap_sem, min(C, 2))


def _fused_hop(acc: jax.Array, send_idx, recv_idx, dst, src, *,
               C: int, B: int, qb: int, codec: Codec,
               accumulate: bool = True) -> jax.Array:
    """acc ``[n, Lp]`` fp32 (``Lp == C*B``) -> the updated receive row
    ``[Lp]``: ``acc[recv_idx] + dequant(wire(acc[send_idx]))`` where the
    wire crossed the interconnect quantized. ONE program.
    ``accumulate=False`` drops the add (the all-to-all dispatch hop): the
    returned row is ``dequant(wire(acc[send_idx]))`` from the upstream
    neighbor."""
    encode, decode, wdtype = _block_math(codec)
    interpret = _interpret()
    nb = B // qb
    idx = jnp.stack([send_idx.astype(jnp.int32), recv_idx.astype(jnp.int32),
                     dst, src])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(C + 1,),
        in_specs=[
            # chunk j of the row being sent (pipelined HBM->VMEM by pallas)
            pl.BlockSpec((1, B), lambda j, idx: (idx[0], jnp.minimum(j, C - 1))),
            # chunk j-1 of the row being accumulated into
            pl.BlockSpec((1, B), lambda j, idx: (idx[1], jnp.maximum(j - 1, 0))),
        ],
        out_specs=pl.BlockSpec((1, B), lambda j, idx: (0, jnp.maximum(j - 1, 0))),
        scratch_shapes=[
            pltpu.VMEM((2, B), wdtype),        # send wire values
            pltpu.VMEM((2, nb), jnp.float32),  # send wire scales
            pltpu.VMEM((2, B), wdtype),        # recv wire values
            pltpu.VMEM((2, nb), jnp.float32),  # recv wire scales
            pltpu.SemaphoreType.DMA((2,)), pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)), pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,       # sender flow-control credits
        ],
    )
    out = pl.pallas_call(
        functools.partial(_fused_hop_kernel, C=C, B=B, qb=qb,
                          encode=encode, decode=decode, interpret=interpret,
                          accumulate=accumulate),
        out_shape=jax.ShapeDtypeStruct((1, C * B), jnp.float32),
        grid_spec=grid_spec,
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(idx, acc, acc)
    return out[0]


def _chunk_geometry(L: int, block_size: int) -> Tuple[int, int, int]:
    """(C, B, qb): kernel chunks of B elements, each a whole number of
    quantization blocks of qb, covering L once padded to C*B."""
    qb = max(min(int(block_size), L), 1)
    per_chunk = max(_CHUNK_TARGET // qb, 1)
    B = qb * min(per_chunk, -(-L // qb))
    C = -(-L // B)
    return C, B, qb


def fused_ring_reduce_scatter_rows(rows: jax.Array, axis, codec: Codec, *,
                                   reverse: bool = False,
                                   sub: Optional[tuple] = None) -> jax.Array:
    """Ring reduce-scatter of ``[n, L]`` chunk rows with every hop a single
    fused dequant-accumulate-requant kernel — the same schedule as
    ``algorithms._ring_reduce_scatter_rows`` (including ring2d's
    ``sub``-ring form), EQuARX transport. Returns this rank's fully reduced
    chunk ``[L]`` in fp32 (the caller casts at the RS->AG boundary, like
    the unfused path)."""
    from deepspeed_tpu.collectives.algorithms import _hop_span, _ring_perm
    from deepspeed_tpu.comm import comm as dist
    from deepspeed_tpu.utils.compat import axis_size

    if sub is not None:
        n, i, perm, label = sub
        step = 1
    else:
        n = axis_size(axis)
        i = lax.axis_index(axis) if n > 1 else 0
        step = -1 if reverse else 1
        perm = _ring_perm(n, reverse)
        label = f"reduce_scatter:pallas_ring{'-' if reverse else ''}"
    L = rows.shape[1]
    if n == 1:
        return rows[0].astype(jnp.float32)
    C, B, qb = _chunk_geometry(L, codec.block_size)
    Lp = C * B
    acc = rows.astype(jnp.float32)
    if Lp != L:
        acc = jnp.pad(acc, ((0, 0), (0, Lp - L)))
    dst, src = _neighbor_logicals(axis, perm)
    wire_bytes = (Lp + 4 * (Lp // qb)) * 1  # 1B values + fp32 scales, per hop
    proxy = jax.ShapeDtypeStruct((wire_bytes,), jnp.int8)
    for k in range(n - 1):
        send_idx = jnp.asarray((i - step * (1 + k)) % n)
        recv_idx = jnp.asarray((i - step * (2 + k)) % n)
        with _hop_span(label, axis, k, codec, fused=True):
            with dist._record("remote_dma", axis, proxy, backend="pallas",
                              fused=codec.name):
                new_row = _fused_hop(acc, send_idx, recv_idx, dst, src,
                                     C=C, B=B, qb=qb, codec=codec)
        acc = lax.dynamic_update_index_in_dim(acc, new_row[None], recv_idx, axis=0)
    out = lax.dynamic_index_in_dim(acc, jnp.asarray(i), axis=0)[0]
    return out[:L]


def fused_ring_all_to_all_rows(rows: jax.Array, axis, codec: Codec, *,
                               n: int, i, perm_k, label: str) -> jax.Array:
    """All-to-all of ``[n, L]`` destination rows with every phase a single
    fused requantize -> remote-DMA -> dequantize kernel (the EQuARX fused
    reduce hop of :func:`fused_ring_reduce_scatter_rows` minus the
    accumulate) — the shift schedule of
    ``algorithms._ring_all_to_all_rows``: phase k moves the row destined
    ``k`` ranks ahead directly via the distance-k permutation ``perm_k(k)``.
    Each row crosses exactly one hop, so the wire quantizes exactly once,
    same as the unfused encode-once path. The own row never leaves HBM and
    stays raw. Returns ``[n, L]`` rows ordered by source rank, in the
    payload dtype."""
    from deepspeed_tpu.collectives.algorithms import _hop_span
    from deepspeed_tpu.comm import comm as dist

    L = rows.shape[1]
    if n == 1:
        return rows
    C, B, qb = _chunk_geometry(L, codec.block_size)
    Lp = C * B
    acc = rows.astype(jnp.float32)
    if Lp != L:
        acc = jnp.pad(acc, ((0, 0), (0, Lp - L)))
    out = jnp.zeros((n, Lp), jnp.float32)
    out = lax.dynamic_update_index_in_dim(
        out, lax.dynamic_index_in_dim(acc, jnp.asarray(i), axis=0),
        jnp.asarray(i), axis=0)  # own row: raw, no wire crossed
    wire_bytes = (Lp + 4 * (Lp // qb)) * 1  # 1B values + fp32 scales, per hop
    proxy = jax.ShapeDtypeStruct((wire_bytes,), jnp.int8)
    for k in range(1, n):
        dst, src = _neighbor_logicals(axis, perm_k(k))
        send_idx = jnp.asarray((i + k) % n)
        with _hop_span(label, axis, k - 1, codec, fused=True):
            with dist._record("remote_dma", axis, proxy, backend="pallas",
                              fused=codec.name):
                new_row = _fused_hop(acc, send_idx, send_idx, dst, src,
                                     C=C, B=B, qb=qb, codec=codec,
                                     accumulate=False)
        out = lax.dynamic_update_index_in_dim(
            out, new_row[None], jnp.asarray((i - k) % n), axis=0)
    return out[:, :L].astype(rows.dtype)
