"""Collective performance observatory: live hop timing, online cost-model
calibration, and selector drift detection.

The selector (``selector.py``) routes every facade collective from an
alpha-beta cost model or an offline ``comm/benchmark.py --sweep`` table —
and until now nothing ever checked whether the algorithm it picked is
actually the fastest one on the mesh it is running on. This module closes
that loop, GC3-style (PAPERS.md): schedules should be derived from
*measured* per-link costs, so the selector must observe its own decisions
in production runs and calibrate itself from what it sees.

Three legs, all host-side:

**Live hop timing.** Every routed facade collective registers its signature
at trace time (``note_route`` — op, algorithm, codec, backend, payload
bytes, world, plus a hop/wire census collected by ``algorithms._hop_span``
and the facade's ppermute/remote-DMA records inside ``trace_scope``). On
sampled steps (1-in-N, ``sample_every``) the observatory dispatches the
routed hop-scope program STANDALONE — the same ``jit(shard_map(...))``
probe shape and scalar-fetch sync fencing as ``benchmark._time_collective``,
host-clocked per dispatch — and feeds per-``(op, algorithm, codec,
backend, bytes-bucket, world)`` ``coll/hop_ms`` histograms and
``coll/achieved_gbps`` gauges. Because probes are their own dispatches,
the steady-state step program is untouched in EVERY mode: hop programs are
jaxpr-identical with the observatory on, off, or absent (pinned by test).
Works for ppermute, pallas remote-DMA, and fused-codec hops alike — the
probe runs whatever the signature routed.

**Online calibration.** Observed samples accumulate into the same versioned
row schema ``--sweep`` emits (``table.py``), EMA-merged so one noisy probe
cannot flip a decision, and persist to ``telemetry_out/coll_table.json`` —
which warm-starts the selector's measured mode on the next run (the engine
passes it as the decision table when no explicit one is configured). A
least-squares fit over the accumulated samples refits the per-backend
alpha/beta constants (``selector.calibrate``; ``coll/alpha_us`` /
``coll/beta_gbps`` gauges) so model mode improves even without a sweep.

**Drift detection.** Each probed routed signature reconciles its observed
latency against the selector's predicted cost: ``coll/model_ratio`` gauge,
a LOUD warning past ``drift_ratio`` (either direction), a ``coll:drift``
trace instant, and — when the engine wired one — arming the PR-7
anomaly-profiler capture so the next steps leave a device trace. The
trace-time wire census additionally feeds the ProgramRegistry: every
captured program reconciles the wire bytes the selector's routing traced
against the collective bytes extracted from its compiled HLO
(``coll/wire_bytes_ratio``; ``telemetry/programs.py``).

Process-global like the selector and the tracer; engines configure it from
the ``collectives.observe`` config block. Disabled (the default) every hook
is one attribute check and nothing is allocated.
"""

from __future__ import annotations

import contextlib
import math
import os
import threading
from collections import deque
from dataclasses import dataclass, replace as dc_replace
from typing import Callable, Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

# probe signatures larger than this are registered but never timed (a
# multi-GB all-gather probe would stall the run it is observing)
_MAX_SIGNATURES = 64


@dataclass
class ObservatoryConfig:
    """Tunables for the observatory (the ``collectives.observe`` config
    block mirrors these)."""

    enabled: bool = False
    sample_every: int = 16          # 1-in-N steps runs probe work; <=0 never
    probes_per_sample: int = 1      # timed probes per sampled step
    iters: int = 1                  # timed iterations per probe
    warmup: int = 1                 # warmup iterations (first pays compile)
    probe_alternatives: bool = True  # also time candidate algorithms
    # compile new probe programs on a background thread and only TIME them
    # once warm: a multi-second XLA compile must never stall train_batch
    # (the <2% overhead bound covers steady state, not compiles). False =
    # synchronous compile inside the sampled step — deterministic, for
    # tests and explicit tooling; sample_now() always compiles in line.
    async_compile: bool = True
    table_path: Optional[str] = None  # default: <telemetry dir>/coll_table.json
    persist: bool = True
    ema: float = 0.25               # online EMA weight for table merges
    drift_ratio: float = 3.0        # observed/predicted past this ⇒ drift
    refit_every: int = 8            # refit alpha/beta every N merged samples
    # per-refit forgetting factor on the fit statistics (1.0 = never
    # forget): without decay a long run's history outweighs a regime
    # change — an interconnect slowdown would take O(history) samples to
    # show in the calibrated constants
    fit_decay: float = 0.5
    max_probe_mb: float = 64.0      # skip timing payloads above this
    max_programs: int = 32          # probe program cache bound


@dataclass
class RouteInfo:
    """One routed facade signature, as registered at trace time."""

    op: str
    algorithm: str
    codec: str
    backend: str
    axis: str
    nbytes: int        # per-device payload bytes (the selector's query)
    itemsize: int
    world: int
    dtype: str
    block_size: Optional[int] = None
    hops: int = 0        # trace-time hop census (0 until a trace completes)
    wire_bytes: int = 0  # per-trace hop wire bytes (census)
    routes: int = 0      # how many traces registered this signature
    probes: int = 0      # how many timed probes ran for it


class _ScopeState:
    __slots__ = ("key", "hops", "wire")

    def __init__(self, key):
        self.key = key
        self.hops = 0
        self.wire = 0


def _backend_of(algorithm: str) -> str:
    from deepspeed_tpu.collectives.pallas_backend import hop_backend

    return hop_backend(algorithm)


def _bus_factor(op: str, n: int) -> float:
    from deepspeed_tpu.comm.comm import CommsLogger

    return CommsLogger._bus_factor(op, n)


def model_terms(op: str, algorithm: str, codec: str, nbytes: int,
                world: int, itemsize: int = 4,
                block_size: Optional[int] = None) -> Tuple[int, float]:
    """(hops, wire_mb) regressors the alpha/beta refit fits observed
    latencies against — delegates to ``selector.model_terms`` so they are
    BY CONSTRUCTION the same terms ``estimate_us`` charges."""
    from deepspeed_tpu.collectives import selector

    return selector.model_terms(op, algorithm, codec, nbytes, world,
                                itemsize, block_size)


class CollectiveObservatory:
    """Process-global observer of routed collectives (see module doc)."""

    def __init__(self):
        self.config = ObservatoryConfig()
        self._lock = threading.Lock()
        # shared warn-once helper (telemetry/events.py): its OWN lock —
        # callers (note_route's capacity branch) may already hold the
        # non-reentrant self._lock — and every first warning also lands on
        # the typed event stream
        from deepspeed_tpu.telemetry.events import WarnOnceSet

        self._warn_once_set = WarnOnceSet(subsystem="coll",
                                          default_kind="observatory_warning")
        self._tls = threading.local()
        self._routes: Dict[Tuple, RouteInfo] = {}
        self._mesh = None
        self.profiler_arm: Optional[Callable[..., None]] = None
        self._steps = 0
        self._merged_samples = 0
        self._pending_program_wire = 0
        self._probe_queue: deque = deque()
        # (op, alg, codec, axis, elems, dtype, block) -> [f, state]; state
        # is "cold" (never dispatched), "warming" (background compile in
        # flight), "warm" (timable), or "failed". Entries hold the jitted
        # fn only — payloads live solely in _payload_cache so its byte-cap
        # eviction actually frees device memory
        self._probe_cache: Dict[Tuple, List] = {}
        # device payloads shared ACROSS probe programs: every candidate of
        # a signature times the same (elems, dtype, axis) array — caching
        # per program would pin up to max_programs full-size duplicates
        self._payload_cache: Dict[Tuple, object] = {}
        self._warm_queue: deque = deque()
        self._warm_thread = None
        self._table_rows: List[dict] = []
        # per-backend running sufficient statistics of the alpha/beta fit:
        # [sum h*h, sum h*w, sum w*w, sum h*t, sum w*t, n] — O(1) memory
        # and refit cost no matter how long the run observes
        self._fit_stats: Dict[str, List[float]] = {}
        self.calibration: Dict[str, Tuple[float, float]] = {}
        self.drift_events = 0
        # the ONE timing idiom (bench + sweep + probes), resolved lazily at
        # first probe; monkeypatchable in tests to inject a slow hop
        # without slowing the suite
        self._timer: Optional[Callable] = None

    # ----------------------------------------------------------- configure
    @property
    def enabled(self) -> bool:
        return self.config.enabled

    def configure(self, config: Optional[ObservatoryConfig] = None,
                  **kwargs) -> "CollectiveObservatory":
        """Install tunables and reset accumulated state (process-global,
        same lifecycle as ``selector.configure``)."""
        with self._lock:
            cfg = (dc_replace(config, **kwargs) if config is not None
                   else ObservatoryConfig(**kwargs))
            self.config = cfg
            self._routes.clear()
            self._probe_queue.clear()
            self._probe_cache.clear()
            self._payload_cache.clear()
            self._warm_queue.clear()
            self._table_rows = []
            self._fit_stats = {}
            self.calibration = {}
            self._steps = 0
            self._merged_samples = 0
            self._pending_program_wire = 0
            self.drift_events = 0
            self._warn_once_set.reset()
            self._timer = None  # drop any injected test timer with the state
            # install() targets belong to the engine that configured us:
            # keeping a torn-down engine's mesh or diagnostics arm callable
            # would probe a dead mesh / arm a dead profiler (and pin its
            # object graph) from the next engine's drift events
            self._mesh = None
            self.profiler_arm = None
        if cfg.enabled and (cfg.persist or cfg.table_path):
            # warm-load the RESOLVED path (explicit or the default): the
            # first persist() must merge into prior runs' rows, not clobber
            # signatures this run happens not to re-probe (persist=False
            # with no explicit path observes in-memory only — nothing to
            # carry over)
            self._load_existing_table(self.table_path())
        return self

    def install(self, mesh=None, profiler_arm: Optional[Callable] = None) -> None:
        """Attach the live mesh probes run on (and, optionally, the
        diagnostics profiler-capture ``arm`` callable drift fires)."""
        if mesh is not None:
            self._mesh = mesh
        if profiler_arm is not None:
            self.profiler_arm = profiler_arm

    def table_path(self) -> str:
        return self.config.table_path or default_table_path()

    def _load_existing_table(self, path: str) -> None:
        """Warm-start the online table from a previous run's persisted rows
        (EMA continuity — a restart must not forget what it measured)."""
        from deepspeed_tpu.collectives import table as table_mod

        try:
            rows = table_mod.load_table(path)
        except (OSError, ValueError):
            return
        with self._lock:
            self._table_rows = rows

    # -------------------------------------------------- trace-time hooks
    def note_route(self, op: str, algorithm: str, codec: str, nbytes: int,
                   itemsize: int, world: int, axis, dtype: str,
                   block_size: Optional[int] = None):
        """Register one routed facade collective (called at trace time by
        ``comm.py``'s routed branches). Returns a scope context collecting
        the hop/wire census of this trace; the no-op context when disabled,
        unprobeable (tuple axis), or at capacity."""
        if not self.config.enabled:
            return contextlib.nullcontext()
        if getattr(self._tls, "probing", False):
            # the probe programs route through the same facade: observing
            # them would register phantom signatures (and feed back into the
            # probe queue forever)
            return contextlib.nullcontext()
        if isinstance(axis, (tuple, list)):
            if len(axis) != 1:
                return contextlib.nullcontext()  # hierarchical: unprobeable
            axis = axis[0]
        backend = _backend_of(algorithm)
        if backend == "pallas":
            from deepspeed_tpu.collectives import pallas_backend

            if not pallas_backend.available():
                # interpret-mode pallas hops: timings would poison the
                # table (same rule as the sweep) — observe nothing
                return contextlib.nullcontext()
        key = (op, algorithm, codec, backend, _bytes_bucket(nbytes),
               int(world), str(axis))
        with self._lock:
            info = self._routes.get(key)
            if info is None:
                if len(self._routes) >= _MAX_SIGNATURES:
                    self._warn_once(
                        "routes",
                        f"collectives observatory: signature capacity "
                        f"({_MAX_SIGNATURES}) reached; further routed "
                        "signatures are not observed")
                    return contextlib.nullcontext()
                info = self._routes[key] = RouteInfo(
                    op=op, algorithm=algorithm, codec=codec, backend=backend,
                    axis=str(axis), nbytes=int(nbytes), itemsize=int(itemsize),
                    world=int(world), dtype=str(dtype), block_size=block_size)
                self._probe_queue.extend(
                    (key, a, c) for a, c in self._candidates(info))
            info.routes += 1
        return self._scope(key)

    @contextlib.contextmanager
    def _scope(self, key):
        prev = getattr(self._tls, "scope", None)
        state = _ScopeState(key)
        self._tls.scope = state
        try:
            yield
        finally:
            self._tls.scope = prev
            with self._lock:
                info = self._routes.get(key)
                if info is not None:
                    # census SETS (idempotent across retraces), never adds
                    info.hops = state.hops or info.hops
                    info.wire_bytes = state.wire or info.wire_bytes
                self._pending_program_wire += state.wire

    def on_hop(self) -> None:
        """One hop traced inside an active scope (``algorithms._hop_span``)."""
        s = getattr(self._tls, "scope", None)
        if s is not None:
            s.hops += 1

    def on_wire(self, nbytes: int) -> None:
        """Wire bytes of one traced hop transfer (the facade's ppermute /
        remote-DMA records)."""
        s = getattr(self._tls, "scope", None)
        if s is not None:
            s.wire += int(nbytes)

    def drain_program_wire(self) -> int:
        """Routed-collective wire bytes traced since the last captured
        program — the ProgramRegistry attributes them to the program it
        just captured (sequential trace→compile makes this exact for the
        engines' build order; concurrent tracers would smear, documented)."""
        with self._lock:
            n = self._pending_program_wire
            self._pending_program_wire = 0
            return n

    # -------------------------------------------------------- step sampling
    def on_step(self, step: Optional[int] = None) -> int:
        """Per-step hook (engine ``train_batch``): on sampled steps, run up
        to ``probes_per_sample`` timed probes. Returns probes run."""
        if not self.config.enabled:
            return 0
        self._steps += 1
        n = self.config.sample_every
        if n <= 0:
            # sampling off (registration/census stay live) — a zero must
            # not read as "probe every step and blow the overhead bound"
            return 0
        if n > 1 and (self._steps % n):
            return 0
        ran = 0
        for _ in range(max(self.config.probes_per_sample, 1)):
            item = self._next_probe()
            if item is None:
                break
            if self._run_probe(*item):
                ran += 1
        if ran and self.config.persist:
            self.persist()
        return ran

    def sample_now(self) -> int:
        """Force one full probe round regardless of cadence (bench warmup,
        tools) — compiles in line: an explicit call IS the warmup."""
        if not self.config.enabled:
            return 0
        ran = 0
        while True:
            item = self._next_probe(refill=False)
            if item is None:
                break
            if self._run_probe(*item, sync_compile=True):
                ran += 1
        if ran and self.config.persist:
            self.persist()
        return ran

    def _candidates(self, info: RouteInfo) -> List[Tuple[str, str]]:
        """(algorithm, codec) pairs worth timing for one signature: the
        routed pair first (drift detection), then — when
        ``probe_alternatives`` — the sweep's candidate enumeration
        (``benchmark.candidate_pairs``, THE shared gate logic so online
        rows stay comparable with sweep rows), so the online table
        accumulates enough coverage for measured mode to CHANGE a
        decision, not just confirm one."""
        out = [(info.algorithm, info.codec)]
        if not self.config.probe_alternatives:
            return out
        from deepspeed_tpu.comm.benchmark import candidate_pairs

        for pair in candidate_pairs(info.world,
                                    tuple(dict.fromkeys((info.codec, "none"))),
                                    op=info.op, axis=info.axis):
            if pair not in out:
                out.append(pair)
        return out

    def _next_probe(self, refill: bool = True):
        with self._lock:
            if not self._probe_queue:
                if not refill:
                    return None
                # every pending probe ran: start a fresh round so steady
                # state keeps re-measuring (EMA tracks slow drift)
                for key, info in self._routes.items():
                    self._probe_queue.extend(
                        (key, a, c) for a, c in self._candidates(info))
                if not self._probe_queue:
                    return None
            key, alg, cd = self._probe_queue.popleft()
            info = self._routes.get(key)
        if info is None:
            return None
        return key, info, alg, cd

    # ------------------------------------------------------------- probing
    def _probe_payload(self, mesh, axis: str, elems: int, dtype):
        """The device payload probes time against — ONE array per
        (elems, dtype, axis), shared by every candidate program of a
        signature (per-program copies would pin max_programs full-size
        duplicates in device memory). The cache is BYTE-capped (~2x
        ``max_probe_mb`` per device, FIFO eviction): an observer must not
        pin GBs of resident payloads next to model state — an evicted
        shape just pays one host->device transfer on its next probe."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        pkey = (axis, elems, str(dtype))
        with self._lock:
            x = self._payload_cache.get(pkey)
        if x is not None:
            return x
        x = jax.device_put(jnp.ones((elems,), dtype),
                           NamedSharding(mesh, P(axis)))
        nbytes = elems * jnp.dtype(dtype).itemsize
        budget = 2 * self.config.max_probe_mb * 1e6 * max(
            int(mesh.shape[axis]), 1)
        # cache mutation under the lock: the train thread and the warm
        # worker both come through here, and an unguarded evict/iterate
        # would race an insert ("dict changed size during iteration")
        with self._lock:
            cur = self._payload_cache.get(pkey)
            if cur is not None:
                return cur  # the other thread won the transfer
            held = sum(k[1] * jnp.dtype(k[2]).itemsize
                       for k in self._payload_cache)
            while self._payload_cache and held + nbytes > budget:
                k = next(iter(self._payload_cache))
                self._payload_cache.pop(k)
                held -= k[1] * jnp.dtype(k[2]).itemsize
            self._payload_cache[pkey] = x
        return x

    def _probe_program(self, info: RouteInfo, algorithm: str, codec: str):
        """The cache entry ``[f, x, elems, state]`` for one probe — the
        standalone hop-scope program: the same ``jit(shard_map(facade
        call))`` shape the sweep measures, on the live mesh."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from deepspeed_tpu.comm.benchmark import (_algorithmic_fn,
                                                  _collective_fn, probe_elems)
        from deepspeed_tpu.utils.compat import shard_map as _shard_map

        mesh = self._mesh
        if mesh is None or info.axis not in mesh.axis_names:
            return None
        n = int(mesh.shape[info.axis])
        if n != info.world:
            return None  # stale signature from a previous mesh
        dtype = jnp.dtype(info.dtype) if info.dtype != "unknown" else jnp.float32
        itemsize = dtype.itemsize
        # per-device payload -> global elements, rounded to the sweep's
        # shared base so reduce_scatter shards stay divisible+lane-aligned
        # and probe rows land on the same shapes a sweep would measure
        elems = probe_elems(n, max(int(info.nbytes // itemsize), 1) * n)
        if elems * itemsize / n > self.config.max_probe_mb * 1e6:
            return None
        key = (info.op, algorithm, codec, info.axis, elems, str(dtype),
               info.block_size)
        with self._lock:
            cached = self._probe_cache.get(key)
            if cached is not None:
                return key, cached
            if len(self._probe_cache) >= self.config.max_programs:
                full = True
            else:
                full = False
        if full:
            self._warn_once(
                "programs",
                f"collectives observatory: probe program cache full "
                f"({self.config.max_programs}); new signatures are not timed")
            return None
        fn = (_collective_fn(info.op, info.axis) if algorithm == "lax" else
              _algorithmic_fn(info.op, info.axis, algorithm, codec,
                              info.block_size or 2048))
        out_spec = P() if info.op == "all_reduce" else P(info.axis)
        f = jax.jit(_shard_map(fn, mesh=mesh, in_specs=P(info.axis),
                               out_specs=out_spec, check_vma=False))
        entry = [f, "cold"]
        with self._lock:
            entry = self._probe_cache.setdefault(key, entry)
        return key, entry

    # ----------------------------------------------- background compile
    def _schedule_warm(self, key) -> None:
        """Queue a cold probe program for background compile + first
        dispatch; a daemon worker pays the (multi-second) XLA compile OFF
        the train loop, and the probe is only TIMED once warm."""
        with self._lock:
            entry = self._probe_cache.get(key)
            if entry is None or entry[1] != "cold":
                return
            entry[1] = "warming"
            self._warm_queue.append(key)
            # handshake against the worker's exit: the worker nulls
            # _warm_thread (under this lock) BEFORE returning on an empty
            # queue, so either it sees this append or we see None and spawn
            # — an is_alive() check would race thread teardown and strand
            # the entry in "warming" forever
            if self._warm_thread is None:
                self._warm_thread = threading.Thread(
                    target=self._warm_worker, name="coll-observatory-warm",
                    daemon=True)
                self._warm_thread.start()

    def _warm_worker(self) -> None:
        import numpy as np
        import jax

        while True:
            with self._lock:
                if not self._warm_queue:
                    self._warm_thread = None  # exit handshake (see above)
                    return
                key = self._warm_queue.popleft()
                entry = self._probe_cache.get(key)
            if entry is None:
                continue
            f = entry[0]
            try:
                mesh = self._mesh
                if mesh is None:
                    continue  # configure() tore the install down mid-warm
                # key = (op, alg, codec, axis, elems, dtype, block)
                x = self._probe_payload(mesh, key[3], key[4], key[5])
                self._tls.probing = True  # this thread's traces too
                try:
                    r = f(x)
                finally:
                    self._tls.probing = False
                # the sweep's sync idiom: fetch a scalar (block_until_ready
                # is a no-op on some platforms)
                np.asarray(jax.tree_util.tree_leaves(r)[0].ravel()[0])
                entry[1] = "warm"
            except Exception as e:  # noqa: BLE001 — must not kill the worker
                entry[1] = "failed"
                self._warn_once(("warm", key[:3]),
                                f"collectives observatory: probe compile "
                                f"failed for {key[0]}/{key[1]}/{key[2]}: {e}")

    def _run_probe(self, key, info: RouteInfo, algorithm: str, codec: str,
                   sync_compile: bool = False) -> bool:
        cfg = self.config
        self._tls.probing = True  # probe traces must not self-register
        try:
            try:
                prog = self._probe_program(info, algorithm, codec)
            except Exception as e:  # noqa: BLE001 — observing must not break the run
                self._warn_once(("build", algorithm, codec),
                                f"collectives observatory: probe build failed "
                                f"for {info.op}/{algorithm}/{codec}: {e}")
                return False
            if prog is None:
                return False
            pkey, entry = prog
            f, state = entry
            if state == "failed" or state == "warming":
                return False
            if state == "cold" and cfg.async_compile and not sync_compile:
                # never pay an XLA compile inside train_batch: warm on the
                # background worker; the re-arming queue brings this pair
                # back once it is timable
                self._schedule_warm(pkey)
                return False
            try:
                # payload fetch + timing in ONE guard: a RESOURCE_EXHAUSTED
                # device_put (or a configure() tearing the mesh down
                # between checks) must degrade to a warning, never abort
                # the train step that sampled this probe
                mesh = self._mesh
                if mesh is None:
                    return False
                # key = (op, alg, codec, axis, elems, dtype, block)
                elems = pkey[4]
                x = self._probe_payload(mesh, pkey[3], elems, pkey[5])
                if self._timer is None:
                    from deepspeed_tpu.comm.benchmark import _time_collective

                    self._timer = _time_collective
                dt = self._timer(f, x, cfg.iters, cfg.warmup)
                entry[1] = "warm"
            except Exception as e:  # noqa: BLE001
                self._warn_once(("time", algorithm, codec),
                                f"collectives observatory: probe failed for "
                                f"{info.op}/{algorithm}/{codec}: {e}")
                return False
        finally:
            self._tls.probing = False
        try:
            itemsize = max(int(x.dtype.itemsize), 1)
            size_mb = elems * itemsize / info.world / 1e6
            routed = (algorithm == info.algorithm and codec == info.codec)
            # the routed signature's own hop census beats the model's count
            hops = info.hops if (routed and info.hops) else None
            info.probes += 1
            self.record_sample(
                op=info.op, algorithm=algorithm, codec=codec,
                backend=_backend_of(algorithm), world=info.world,
                size_mb=size_mb, latency_ms=dt * 1e3, itemsize=itemsize,
                bucket=_bytes_bucket(info.nbytes), hops=hops,
                check_drift=routed, block_size=info.block_size)
        except Exception as e:  # noqa: BLE001 — same contract as above
            self._warn_once(("record", algorithm, codec),
                            f"collectives observatory: sample recording "
                            f"failed for {info.op}/{algorithm}/{codec}: {e}")
            return False
        return True

    # ------------------------------------------------------------- samples
    def record_sample(self, *, op: str, algorithm: str, codec: str,
                      backend: str, world: int, size_mb: float,
                      latency_ms: float, itemsize: int = 4,
                      bucket: Optional[int] = None, hops: Optional[int] = None,
                      check_drift: bool = False,
                      block_size: Optional[int] = None,
                      merge: bool = True) -> dict:
        """Fold one observed latency into the observatory: metrics, online
        table EMA merge, refit accumulation, and (for routed signatures)
        drift reconciliation. The probe path lands here; tests and external
        timers may call it directly (``merge=False`` observes without
        touching the table — the report tool's injected-drift check)."""
        from deepspeed_tpu.collectives import table as table_mod

        nbytes = size_mb * 1e6
        bucket = bucket if bucket is not None else _bytes_bucket(int(nbytes))
        payload_global = nbytes * world
        dt = latency_ms / 1e3
        busbw = (payload_global / dt) * _bus_factor(op, world) if dt > 0 else 0.0
        if hops is None:
            try:
                hops, _ = model_terms(op, algorithm, codec, int(nbytes),
                                      world, itemsize, block_size)
            except ValueError:
                hops = max(world - 1, 1)
        from deepspeed_tpu.telemetry.fleet import get_identity

        row = {
            "op": op, "world": int(world), "size_mb": round(size_mb, 4),
            "algorithm": algorithm, "codec": codec, "backend": backend,
            "latency_ms": round(latency_ms, 4),
            "busbw_gbps": round(busbw / 1e9, 3),
            "itemsize": int(itemsize), "samples": 1,
            # process identity stamp (fleet federation provenance; not part
            # of row_key — the same signature measured on two processes
            # still EMA-merges into one row at the collector)
            "proc": get_identity().key(),
        }
        self._publish_sample(row, hops, bucket)
        if check_drift:
            # BEFORE the merge: the prediction must come from what the
            # table/calibration said prior to this observation, not from a
            # row this very sample just dragged toward itself
            self._check_drift(op, algorithm, codec, backend, int(nbytes),
                              world, itemsize, latency_ms, bucket)
        if merge:
            with self._lock:
                self._table_rows = table_mod.merge_rows(
                    self._table_rows, [row], ema=self.config.ema)
                self._merged_samples += 1
                refit_due = (self.config.refit_every > 0 and
                             self._merged_samples % self.config.refit_every == 0)
            self._note_fit_sample(op, algorithm, codec, backend, int(nbytes),
                                  world, itemsize, latency_ms, block_size)
            if refit_due:
                self.refit()
        return row

    def _publish_sample(self, row: dict, hops: int, bucket: int) -> None:
        from deepspeed_tpu import telemetry

        tracer = telemetry.get_tracer()
        if not tracer.enabled:
            return
        labels = dict(op=row["op"], algorithm=row["algorithm"],
                      codec=row["codec"], backend=row["backend"],
                      bucket=bucket, world=row["world"])
        reg = tracer.registry
        reg.histogram("coll/hop_ms", **labels).observe(
            row["latency_ms"] / max(hops, 1))
        reg.gauge("coll/achieved_gbps", **labels).set(row["busbw_gbps"])
        reg.counter("coll/probes").add(1.0)
        with self._lock:
            reg.gauge("coll/table_rows").set(float(len(self._table_rows)))

    # --------------------------------------------------------------- drift
    def _predicted_us(self, op: str, algorithm: str, codec: str, backend: str,
                      nbytes: int, world: int, itemsize: int
                      ) -> Optional[float]:
        """The trusted cost for this signature, or ``None`` when no
        TRUSTWORTHY prediction exists yet. A drift alarm against the static
        (hand-set) alpha/beta would fire on every mesh whose constants were
        never tuned — noise, not drift — so predictions count only once
        they are measured or calibrated. A measured row counts only at a
        COMPARABLE size (within 2x of the query): the selector's
        nearest-by-log-distance routing may legitimately answer a 32 MB
        query from a 0.25 MB row, but that row's raw latency is no
        prediction for the 32 MB payload and would alarm forever."""
        size_mb = nbytes / 1e6
        with self._lock:
            rows = [r for r in self._table_rows
                    if r.get("op") == op and r.get("algorithm") == algorithm
                    and r.get("codec", "none") == codec
                    and (r.get("backend") or backend) == backend
                    and int(r.get("world", 0)) == world
                    and float(r.get("size_mb", 0.0)) > 0]
            calibrated = backend in self.calibration
        if rows:
            best = min(rows, key=lambda r: abs(math.log(
                float(r["size_mb"]) / size_mb)) if size_mb > 0 else 0.0)
            ratio = float(best["size_mb"]) / size_mb if size_mb > 0 else 0.0
            if 0.5 <= ratio <= 2.0:
                return float(best["latency_ms"]) * 1e3
        if not calibrated:
            return None
        from deepspeed_tpu.collectives import selector

        try:
            return selector.estimate_us(op, algorithm, codec, nbytes, world,
                                        itemsize=itemsize)
        except ValueError:
            return None

    def _check_drift(self, op, algorithm, codec, backend, nbytes, world,
                     itemsize, latency_ms, bucket) -> None:
        predicted = self._predicted_us(op, algorithm, codec, backend, nbytes,
                                       world, itemsize)
        if not predicted or predicted <= 0:
            return
        ratio = (latency_ms * 1e3) / predicted
        from deepspeed_tpu import telemetry

        tracer = telemetry.get_tracer()
        if tracer.enabled:
            tracer.registry.gauge(
                "coll/model_ratio", op=op, algorithm=algorithm, codec=codec,
                backend=backend, bucket=bucket, world=world).set(ratio)
        thresh = self.config.drift_ratio
        if thresh <= 0 or (1.0 / thresh) <= ratio <= thresh:
            return
        self.drift_events += 1
        direction = "slower" if ratio > 1 else "faster"
        msg = (
            f"COLLECTIVE DRIFT: {op} routed {algorithm}/{codec} "
            f"({backend}, {nbytes}B x{world}) measured {latency_ms:.3f} ms "
            f"vs predicted {predicted / 1e3:.3f} ms — {ratio:.1f}x "
            f"{direction} than the cost model (threshold {thresh}x). The "
            "selector may be mis-routing this mesh; re-sweep or let the "
            "observatory's refit converge. Arming profiler capture.")
        logger.warning(msg)
        from deepspeed_tpu.telemetry.events import emit_event

        emit_event("coll", "drift", msg, severity="warn",
                   labels={"op": op, "algorithm": algorithm, "codec": codec,
                           "backend": backend},
                   dedup_key=f"coll:drift:{op}/{algorithm}/{codec}/{backend}")
        if tracer.enabled:
            tracer.registry.counter("coll/drift_events").add(1.0)
            tracer.instant("coll:drift", cat="coll", op=op,
                           algorithm=algorithm, codec=codec, backend=backend,
                           bytes=int(nbytes), world=int(world),
                           observed_ms=round(latency_ms, 4),
                           predicted_ms=round(predicted / 1e3, 4),
                           ratio=round(ratio, 2))
        if self.profiler_arm is not None:
            try:
                self.profiler_arm(reason=f"coll_drift:{op}/{algorithm}")
            except Exception as e:  # noqa: BLE001
                logger.warning(f"collectives observatory: profiler arm "
                               f"failed: {e}")

    # --------------------------------------------------------------- refit
    def _note_fit_sample(self, op, algorithm, codec, backend, nbytes, world,
                         itemsize, latency_ms, block_size) -> None:
        try:
            hops, wire_mb = model_terms(op, algorithm, codec, nbytes, world,
                                        itemsize, block_size)
        except ValueError:
            return
        h, w, t = float(hops), float(wire_mb), latency_ms * 1e3
        with self._lock:
            s = self._fit_stats.setdefault(backend, [0.0] * 6)
            s[0] += h * h
            s[1] += h * w
            s[2] += w * w
            s[3] += h * t
            s[4] += w * t
            s[5] += 1.0

    def refit(self) -> Dict[str, Tuple[float, float]]:
        """Least-squares (alpha, beta) per backend over the accumulated
        samples: ``latency_us ~= hops * alpha + wire_mb * beta``; pushed
        into the selector (``selector.calibrate``) so model mode re-costs
        future decisions from what this mesh actually measured."""
        from deepspeed_tpu import telemetry
        from deepspeed_tpu.collectives import selector

        with self._lock:
            groups = {b: list(s) for b, s in self._fit_stats.items()}
        out: Dict[str, Tuple[float, float]] = {}
        for backend, stats in groups.items():
            fit = _fit_alpha_beta(stats)
            if fit is None:
                continue
            alpha, beta = fit
            out[backend] = (alpha, beta)
            selector.calibrate(backend, alpha_us=alpha, beta_us_per_mb=beta)
            tracer = telemetry.get_tracer()
            if tracer.enabled:
                tracer.registry.gauge("coll/alpha_us", backend=backend).set(alpha)
                # effective link bandwidth the beta term implies
                tracer.registry.gauge("coll/beta_gbps", backend=backend).set(
                    1e3 / beta if beta > 0 else 0.0)
        if out:
            with self._lock:
                self.calibration.update(out)
        d = self.config.fit_decay
        if 0.0 < d < 1.0:
            # exponential forgetting so calibration tracks regime changes:
            # history halves (at the default) every refit instead of
            # outweighing fresh samples forever
            with self._lock:
                for s in self._fit_stats.values():
                    for i in range(len(s)):
                        s[i] *= d
        return out

    # ------------------------------------------------------------- persist
    def table_rows(self) -> List[dict]:
        with self._lock:
            return list(self._table_rows)

    def persist(self, path: Optional[str] = None) -> Optional[str]:
        """Write the online table (versioned envelope) so the NEXT run's
        selector warm-starts measured mode from what this run observed."""
        from deepspeed_tpu.collectives import table as table_mod

        with self._lock:
            rows = list(self._table_rows)
            calib = {b: {"alpha_us": round(a, 4), "beta_us_per_mb": round(bt, 4)}
                     for b, (a, bt) in self.calibration.items()}
        if not rows:
            return None
        path = path or self.table_path()
        from deepspeed_tpu.telemetry.fleet import get_identity

        try:
            return table_mod.write_table(
                path, rows, source="online",
                extra={"calibration": calib,
                       "identity": get_identity().to_dict()})
        except OSError as e:
            self._warn_once("persist",
                            f"collectives observatory: cannot persist table "
                            f"to {path!r}: {e}")
            return None

    # -------------------------------------------------------------- report
    def summary(self) -> dict:
        with self._lock:
            return {
                "routes": len(self._routes),
                "table_rows": len(self._table_rows),
                "merged_samples": self._merged_samples,
                "drift_events": self.drift_events,
                "calibration": {b: list(v) for b, v in self.calibration.items()},
                "steps": self._steps,
            }

    def routes(self) -> List[RouteInfo]:
        with self._lock:
            return list(self._routes.values())

    def _warn_once(self, key, msg: str) -> None:
        self._warn_once_set(str(key), msg, log=logger)


def _fit_alpha_beta(stats: List[float]) -> Optional[Tuple[float, float]]:
    """Closed-form 2-parameter least squares of ``t = h*a + w*b`` from the
    running sufficient statistics ``[sum h², sum hw, sum w², sum ht,
    sum wt, n]``, with non-negativity clamps; ``None`` when the design is
    degenerate (fewer than 2 samples, or no spread in either regressor)."""
    shh, shw, sww, sht, swt, n = stats
    if n < 2:
        return None
    if shh == 0.0:
        # hop-free samples (the lax baseline): only beta is identifiable
        if sww == 0.0:
            return None
        return 0.0, max(swt / sww, 1e-9)
    det = shh * sww - shw * shw
    if abs(det) < 1e-12 * max(shh * sww, 1.0):
        # collinear design: fit alpha alone against the hop count
        return max(sht / shh, 1e-9), 0.0
    alpha = (sht * sww - swt * shw) / det
    beta = (swt * shh - sht * shw) / det
    if alpha < 0.0:
        # clamp and refit the other term unconstrained
        alpha = 0.0
        beta = max(swt / sww, 1e-9) if sww else 0.0
    elif beta < 0.0:
        beta = 0.0
        alpha = max(sht / shh, 1e-9)
    return float(alpha), float(beta)


def _bytes_bucket(nbytes: int) -> int:
    from deepspeed_tpu.collectives import selector

    return selector._bytes_bucket(nbytes)


def default_table_path() -> str:
    """Where the online table lives when no explicit path is configured —
    a function of the telemetry output dir only, never of the (process-
    global, possibly another engine's) observatory config. On a multi-
    process mesh each process gets its OWN file (``coll_table.p<N>.json``
    for process_index > 0): N observatory instances sharing one path would
    clobber each other's atomic writes; the fleet collector
    (``telemetry/collector.py``) is the one place per-process tables merge
    (``table.merge_rows``) into a mesh-wide view."""
    from deepspeed_tpu.telemetry import default_output_dir
    from deepspeed_tpu.telemetry.fleet import get_identity

    idx = get_identity().process_index
    name = "coll_table.json" if idx == 0 else f"coll_table.p{idx}.json"
    return os.path.join(default_output_dir(), name)


# ------------------------------------------------------------- module API

_observatory = CollectiveObservatory()


def get_observatory() -> CollectiveObservatory:
    return _observatory


def configure(config: Optional[ObservatoryConfig] = None,
              **kwargs) -> CollectiveObservatory:
    return _observatory.configure(config, **kwargs)


def enabled() -> bool:
    return _observatory.config.enabled


def note_route(op: str, algorithm: str, codec: str, nbytes: int,
               itemsize: int, world: int, axis, dtype: str,
               block_size: Optional[int] = None):
    return _observatory.note_route(op, algorithm, codec, nbytes, itemsize,
                                   world, axis, dtype, block_size)


def on_hop() -> None:
    _observatory.on_hop()


def on_wire(nbytes: int) -> None:
    _observatory.on_wire(nbytes)


def drain_program_wire() -> int:
    return _observatory.drain_program_wire()
