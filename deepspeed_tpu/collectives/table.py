"""Versioned decision-table schema shared by the sweep and the observatory.

ONE row format feeds the selector's measured mode, whether the rows came
from an offline ``comm/benchmark.py --sweep`` or from the online
observatory's sampled probes (``collectives/observatory.py``)::

    {"op": "all_reduce", "world": 8, "size_mb": 0.131,   # PER-DEVICE payload
     "algorithm": "ring", "codec": "int8", "backend": "ppermute",
     "latency_ms": 0.42, "busbw_gbps": 1.9, "itemsize": 2, "samples": 3}

``size_mb`` is the per-device payload (what the selector is queried with at
trace time), ``backend`` is the hop backend the row was measured with
(selector measured mode never applies a ppermute row to a pallas algorithm
or vice versa), ``itemsize`` is the payload element width the probe ran
with (the alpha/beta refit needs it to reconstruct wire bytes), ``samples``
counts how many observations were EMA-merged into the row.

On disk a table is a versioned envelope ``{"schema": 1, "source": ...,
"rows": [...]}``. Loading accepts the envelope (schema checked,
reject-with-warning on mismatch) AND the legacy bare-list format PR-3 sweep
files used — an old table keeps working, a FUTURE schema never silently
routes traffic. ``merge_rows`` is the one fold implementation behind all
three table producers: ``--merge`` (sweep into an existing table), the
observatory's online EMA, and the fleet collector's read-time federation
(``telemetry/collector.py`` folds each process's LATEST rows per read —
``source: "fleet"`` envelopes, served at ``GET /coll_table``); rows may
carry a ``proc`` identity stamp, which is provenance only — it is NOT part
of :func:`row_key`, so the same signature measured on two processes merges
into one row.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

SCHEMA_VERSION = 1

# the identity of a measurement: everything but the measured numbers
_KEY_FIELDS = ("op", "world", "algorithm", "codec")


def row_key(row: Dict) -> Tuple:
    """Merge identity of one row; ``size_mb`` participates rounded to the
    4 decimals every writer emits so float repr noise cannot split rows.
    ``itemsize`` participates too: a bf16 and an fp32 payload of the same
    per-device BYTES are different programs under a lossy codec (one wire
    byte per ELEMENT), so their measurements must not EMA into one row.
    Legacy rows default the missing fields — backend from the algorithm
    name, itemsize to the historical sweep default (bf16, 2) — so a fresh
    stamped sweep REPLACES an old row instead of duplicating it."""
    from deepspeed_tpu.collectives.pallas_backend import hop_backend

    backend = row.get("backend") or hop_backend(str(row.get("algorithm", "")))
    return tuple(row.get(f) for f in _KEY_FIELDS) + (
        backend, int(row.get("itemsize", 2)),
        round(float(row.get("size_mb", 0.0)), 4))


def load_table(path: str, strict: bool = False) -> List[Dict]:
    """Rows of a decision table file: versioned envelope or legacy bare
    list. A schema-version mismatch is rejected WITH a warning (an empty
    row list falls back to the alpha-beta model downstream) — mis-keyed
    rows from a future format must never route production collectives;
    ``strict=True`` raises on the mismatch instead (the ``--merge`` CLI
    must distinguish "no rows" from "rows I must not destroy").
    Raises ``OSError``/``ValueError`` like ``json.load`` for unreadable
    files (callers own that fallback)."""
    with open(path) as f:
        payload = json.load(f)
    if isinstance(payload, list):
        # legacy PR-3 sweep format (pre-versioning): accepted as-is
        return payload
    if not isinstance(payload, dict):
        raise ValueError(f"decision table {path!r} is neither a row list "
                         f"nor a schema envelope ({type(payload).__name__})")
    if "schema" not in payload:
        # a schema-LESS dict ({"rows": [...]}) is another legacy shape the
        # selector used to accept — only an explicit wrong version is a
        # future format worth rejecting
        rows = payload.get("rows", [])
        return rows if isinstance(rows, list) else []
    schema = payload.get("schema")
    if schema != SCHEMA_VERSION:
        if strict:
            raise ValueError(
                f"decision table {path!r} has schema {schema!r}, this build "
                f"speaks {SCHEMA_VERSION}")
        logger.warning(
            f"collectives: decision table {path!r} has schema {schema!r}, "
            f"this build speaks {SCHEMA_VERSION} — rejecting the table "
            "(selector falls back to the alpha-beta model; re-sweep or "
            "re-run the observatory to regenerate it)")
        return []
    rows = payload.get("rows", [])
    return rows if isinstance(rows, list) else []


def write_table(path: str, rows: List[Dict], source: str = "sweep",
                extra: Optional[Dict] = None) -> str:
    """Atomically write the versioned envelope (tmp + ``os.replace`` so a
    crash mid-write never leaves a half-table a warm-starting selector
    would choke on)."""
    payload = {"schema": SCHEMA_VERSION, "source": source,
               "rows": list(rows)}
    if extra:
        payload.update(extra)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + f".tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, path)
    return path


def merge_rows(base: List[Dict], new: List[Dict],
               ema: Optional[float] = None) -> List[Dict]:
    """Fold ``new`` measurements into ``base`` rows by :func:`row_key`.

    ``ema=None`` (the ``--merge`` CLI): a fresh measurement REPLACES the
    matching row's numbers (a full re-sweep is the better estimate), sample
    counts add. ``ema`` in (0, 1] (the online observatory): latency and
    bandwidth move by ``(1-ema)*old + ema*new`` so one noisy probe cannot
    flip a routing decision. Rows only in ``base`` are kept either way —
    folding a narrow sweep into a broad online table must not lose the
    signatures the sweep did not cover."""
    out: Dict[Tuple, Dict] = {row_key(r): dict(r) for r in base}
    for r in new:
        k = row_key(r)
        prev = out.get(k)
        if prev is None:
            merged = dict(r)
            merged.setdefault("samples", 1)
        else:
            merged = dict(prev)
            if ema is not None:
                a = float(ema)
                for f in ("latency_ms", "busbw_gbps"):
                    if f in r:
                        old = float(prev.get(f, r[f]))
                        merged[f] = round((1.0 - a) * old + a * float(r[f]), 4)
            else:
                merged.update(r)
            merged["samples"] = int(prev.get("samples", 1)) + int(r.get("samples", 1))
        out[k] = merged
    return list(out.values())
