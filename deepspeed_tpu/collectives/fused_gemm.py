"""Fused matmul⇄collective Pallas kernels — the T3 endgame.

Reference: T3 (arxiv 2401.16677) fuses a GEMM producer/consumer with the
collective that feeds or drains it so the interconnect time hides behind
the matmul's own compute; Google's GC3/async-collective work does the same
inside XLA. Here the fusion is explicit: each ring hop is ONE Pallas
kernel whose grid step ``j`` computes chunk ``j``'s partial matmul while
chunk ``j``'s wire DMA is in flight — the 2-slot VMEM wire staging, DMA
semaphore pairing, credit-based flow control, and entry barrier are the
PR-8 EQuARX fused-hop pattern (``pallas_backend._fused_hop``) with the
dequant-accumulate replaced by a ``dot_general``.

Two fused ops:

- :func:`all_gather_matmul` — ``y = x @ all_gather(w_shard, rows)``: the
  ZeRO-3 weight gather fused into its consumer GEMM. Hop ``k`` holds one
  originating rank's shard; while that shard's chunks stream to the next
  neighbor the kernel contracts them against the matching ``x`` columns
  (``out_block=True`` instead emits the independent output-column block
  ``x @ held.T`` — the backward ``dx`` form, no accumulation).
- :func:`matmul_reduce_scatter` — ``reduce_scatter(x @ w, rows)``: the
  gradient-shard GEMM fused into its producer ring. Hop ``k`` computes the
  outgoing row-block's partial product chunk-by-chunk, shipping chunk
  ``j`` while chunk ``j+1`` computes.

Both take an optional int8/fp8 wire codec (the shared ``ops.quant`` block
math): the shard/partial crosses the interconnect quantized and is
dequantized in the receiving kernel, ZeRO++-style. Exact wires stage the
raw fp32 chunks through the same slots, so the fused result is
bit-identical to the unfused composition on integer-valued payloads.

Execution modes mirror ``pallas_backend``: compiled Mosaic on TPU,
``interpret=True`` elsewhere (single-named-axis meshes only — the
interpreter cannot discharge remote DMA on multi-axis meshes, and these
helpers fall back to the unfused lax composition there). The module-level
``configure(enabled=...)`` knob (driven by
``CollectivesConfig.fused_gemm_collectives``) gates every caller: with it
off, :func:`sharded-matmul callers <deepspeed_tpu.parallel.tp>` emit the
plain lax composition — byte-identical programs to a build without this
module.
"""

from __future__ import annotations

import functools
import math
import threading
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.collectives import pallas_backend
from deepspeed_tpu.collectives.codecs import Codec, get_codec
from deepspeed_tpu.collectives.pallas_backend import (
    _block_math,
    _compiler_params,
    _entry_barrier,
    _interpret,
    _neighbor_logicals,
)
from deepspeed_tpu.utils.compat import axis_size

# --------------------------------------------------------------- config knob

_lock = threading.Lock()
_enabled = False


def configure(enabled: bool = False) -> None:
    """Process-global gate (set from ``CollectivesConfig.fused_gemm_collectives``
    by the engine, like ``selector.configure``)."""
    global _enabled
    with _lock:
        _enabled = bool(enabled)


def enabled() -> bool:
    with _lock:
        return _enabled


def supported(axis) -> bool:
    """Whether the fused kernels can express this trace context: a single
    named mesh axis, and a hop transport the backend can discharge
    (compiled Mosaic anywhere, the interpreter only on 1-axis meshes)."""
    return isinstance(axis, str) and pallas_backend.remote_dma_supported()


def _resolve_codec(codec, block_size: Optional[int]) -> Optional[Codec]:
    if codec is None or codec == "none":
        return None
    c = codec if isinstance(codec, Codec) else get_codec(codec, block_size or 64)
    if c.name not in ("int8", "fp8"):
        raise ValueError(f"no fused GEMM wire for codec {c.name!r}")
    return c


def _chunks_of(rows: int) -> int:
    """Grid chunks per hop: enough to overlap wire behind compute, exact
    divisors only (chunk rows must tile the shard)."""
    for d in (4, 3, 2):
        if rows % d == 0:
            return d
    return 1


def _wire_math(codec: Optional[Codec], B: int):
    """(encode, decode, wire_dtype, qb): the VMEM wire staging math. Exact
    wires pass raw fp32 through the same 2-slot buffers (qb spans the whole
    chunk; the scale buffers stay untouched)."""
    if codec is None:
        return None, None, jnp.float32, B
    encode, decode, wdtype = _block_math(codec)
    qb = math.gcd(B, max(int(codec.block_size), 1))
    return encode, decode, wdtype, qb


# ----------------------------------------------------------- fused hop kernels


def _wire_ops(send_w, send_s, recv_w, recv_s, sw_sem, ss_sem, rw_sem, rs_sem,
              dst):
    """Constructors for the two remote copies (values, scales) of one slot."""

    def w_copy(s):
        return pltpu.make_async_remote_copy(
            src_ref=send_w.at[s], dst_ref=recv_w.at[s],
            send_sem=sw_sem.at[s], recv_sem=rw_sem.at[s],
            device_id=dst, device_id_type=pltpu.DeviceIdType.LOGICAL)

    def s_copy(s):
        return pltpu.make_async_remote_copy(
            src_ref=send_s.at[s], dst_ref=recv_s.at[s],
            send_sem=ss_sem.at[s], recv_sem=rs_sem.at[s],
            device_id=dst, device_id_type=pltpu.DeviceIdType.LOGICAL)

    return w_copy, s_copy


def _slot_send(j, slot, payload, w_copy, s_copy, send_w, send_s, cap_sem, *,
               B, qb, encode, interpret):
    """Stage chunk ``j`` (fp32 ``(rows, cols)``) into wire slot ``slot`` and
    launch its remote DMA. Slot reuse waits our chunk ``j-2`` DMAs out and
    (compiled) one downstream consumption credit — the _fused_hop
    discipline verbatim."""

    @pl.when(j >= 2)
    def _():
        w_copy(slot).wait_send()
        if encode is not None:
            s_copy(slot).wait_send()
        if not interpret:
            pltpu.semaphore_wait(cap_sem, 1)

    if encode is not None:
        q, sc = encode(payload.reshape(B // qb, qb))
        send_w[slot] = q.reshape(B)
        send_s[slot] = sc.reshape(B // qb)
    else:
        send_w[slot] = payload.reshape(B)
    w_copy(slot).start()
    if encode is not None:
        s_copy(slot).start()


def _slot_recv(prev, out_ref, w_copy, s_copy, recv_w, recv_s, cap_sem, src, *,
               B, qb, decode, shape, interpret):
    """Wait chunk ``prev``'s arrival, dequantize (or pass through) into the
    blocked output, and grant the upstream sender one slot credit."""
    w_copy(prev).wait_recv()
    if decode is not None:
        s_copy(prev).wait_recv()
        deq = decode(recv_w[prev].reshape(B // qb, qb),
                     recv_s[prev].reshape(B // qb, 1))
        out_ref[...] = deq.reshape(shape).astype(jnp.float32)
    else:
        out_ref[...] = recv_w[prev].reshape(shape)
    if not interpret:
        pltpu.semaphore_signal(cap_sem, 1, device_id=src,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)


def _slot_drain(C, w_copy, s_copy, cap_sem, *, encode, interpret):
    """Semaphore balance at the final grid step (see _fused_hop_kernel):
    wait the last min(C, 2) outstanding sends and drain leftover credits."""
    for s in ([0] if C == 1 else [(C - 2) % 2, (C - 1) % 2]):
        w_copy(s).wait_send()
        if encode is not None:
            s_copy(s).wait_send()
    if not interpret:
        pltpu.semaphore_wait(cap_sem, min(C, 2))


def _ag_hop_kernel(idx_ref, x_blk, held_blk, yin_blk, y_blk, recv_blk,
                   send_w, send_s, recv_w, recv_s,
                   sw_sem, ss_sem, rw_sem, rs_sem, cap_sem,
                   *, C: int, B: int, qb: int, out_block: bool,
                   encode, decode, interpret: bool):
    """One all-gather+matmul ring hop: grid step ``j`` ships chunk ``j`` of
    the held weight shard to the next neighbor while contracting that SAME
    chunk against ``x`` — the chunk's interconnect time hides behind its
    own matmul. Step ``j`` also lands chunk ``j-1`` from the upstream
    neighbor into the receive buffer (next hop's held shard)."""
    j = pl.program_id(0)
    slot = lax.rem(j, 2)
    prev = lax.rem(j + 1, 2)
    dst, src = idx_ref[0], idx_ref[1]
    w_copy, s_copy = _wire_ops(send_w, send_s, recv_w, recv_s,
                               sw_sem, ss_sem, rw_sem, rs_sem, dst)

    @pl.when(j == 0)
    def _():
        _entry_barrier(dst, src, interpret)

    @pl.when(j < C)
    def _send_and_compute():
        h = held_blk[...].astype(jnp.float32)
        _slot_send(j, slot, h, w_copy, s_copy, send_w, send_s, cap_sem,
                   B=B, qb=qb, encode=encode, interpret=interpret)
        if out_block:
            # backward-dx form: this shard's chunk yields an independent
            # output-column block, x [M,N] @ held_chunk [Bk,N]^T
            y_blk[...] = lax.dot_general(
                x_blk[...].astype(jnp.float32), h,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            part = lax.dot_general(
                x_blk[...].astype(jnp.float32), h,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

            @pl.when(j == 0)
            def _():
                y_blk[...] = yin_blk[...] + part

            @pl.when(j > 0)
            def _():
                y_blk[...] = y_blk[...] + part

    @pl.when(j > 0)
    def _recv():
        _slot_recv(prev, recv_blk, w_copy, s_copy, recv_w, recv_s, cap_sem,
                   src, B=B, qb=qb, decode=decode, shape=recv_blk.shape,
                   interpret=interpret)

    @pl.when(j == C)
    def _drain():
        _slot_drain(C, w_copy, s_copy, cap_sem, encode=encode,
                    interpret=interpret)


def _rs_hop_kernel(idx_ref, x_blk, w_blk, rprev_blk, recv_blk,
                   send_w, send_s, recv_w, recv_s,
                   sw_sem, ss_sem, rw_sem, rs_sem, cap_sem,
                   *, C: int, B: int, qb: int, encode, decode,
                   interpret: bool):
    """One matmul+reduce-scatter ring hop: grid step ``j`` computes chunk
    ``j`` of the outgoing row-block's partial product (upstream partial +
    local ``x_blk @ w``) and launches its DMA — chunk ``j``'s wire flies
    while chunk ``j+1`` computes. The received chunks (the NEXT row-block's
    upstream partials) land as this hop's output."""
    j = pl.program_id(0)
    slot = lax.rem(j, 2)
    prev = lax.rem(j + 1, 2)
    dst, src = idx_ref[0], idx_ref[1]
    w_copy, s_copy = _wire_ops(send_w, send_s, recv_w, recv_s,
                               sw_sem, ss_sem, rw_sem, rs_sem, dst)

    @pl.when(j == 0)
    def _():
        _entry_barrier(dst, src, interpret)

    @pl.when(j < C)
    def _compute_and_send():
        part = rprev_blk[...] + lax.dot_general(
            x_blk[...].astype(jnp.float32), w_blk[...].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        _slot_send(j, slot, part, w_copy, s_copy, send_w, send_s, cap_sem,
                   B=B, qb=qb, encode=encode, interpret=interpret)

    @pl.when(j > 0)
    def _recv():
        _slot_recv(prev, recv_blk, w_copy, s_copy, recv_w, recv_s, cap_sem,
                   src, B=B, qb=qb, decode=decode, shape=recv_blk.shape,
                   interpret=interpret)

    @pl.when(j == C)
    def _drain():
        _slot_drain(C, w_copy, s_copy, cap_sem, encode=encode,
                    interpret=interpret)


# -------------------------------------------------------------- hop wrappers


def _hop_scratch(B: int, nb: int, wdtype):
    return [
        pltpu.VMEM((2, B), wdtype),               # send wire values
        pltpu.VMEM((2, max(nb, 1)), jnp.float32),  # send wire scales
        pltpu.VMEM((2, B), wdtype),               # recv wire values
        pltpu.VMEM((2, max(nb, 1)), jnp.float32),  # recv wire scales
        pltpu.SemaphoreType.DMA((2,)), pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA((2,)), pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.REGULAR,               # sender flow-control credits
    ]


def _ag_hop(x, held, y, s_held, dst, src, *, codec: Optional[Codec],
            out_block: bool) -> Tuple[jax.Array, jax.Array]:
    """One fused all-gather+matmul hop. ``held`` is shard ``s_held``'s
    ``[Ks, N]`` rows (fp32); returns ``(y_or_block, received_shard)``."""
    M = x.shape[0]
    Ks, N = held.shape
    C = _chunks_of(Ks)
    Bk = Ks // C
    B = Bk * N
    encode, decode, wdtype, qb = _wire_math(codec, B)
    interpret = _interpret()
    idx = jnp.stack([dst, src, s_held.astype(jnp.int32)])
    if out_block:
        in_specs = [
            pl.BlockSpec((M, N), lambda j, idx: (0, 0)),       # full x (= g)
            pl.BlockSpec((Bk, N), lambda j, idx: (jnp.minimum(j, C - 1), 0)),
            pl.BlockSpec((M, N), lambda j, idx: (0, 0)),       # unused y seed
        ]
        out_specs = [
            pl.BlockSpec((M, Bk), lambda j, idx: (0, jnp.minimum(j, C - 1))),
            pl.BlockSpec((Bk, N), lambda j, idx: (jnp.maximum(j - 1, 0), 0)),
        ]
        out_shape = [jax.ShapeDtypeStruct((M, Ks), jnp.float32),
                     jax.ShapeDtypeStruct((Ks, N), jnp.float32)]
    else:
        in_specs = [
            # x columns matching the held shard's K rows, chunk j
            pl.BlockSpec((M, Bk), lambda j, idx: (0, idx[2] * C + jnp.minimum(j, C - 1))),
            pl.BlockSpec((Bk, N), lambda j, idx: (jnp.minimum(j, C - 1), 0)),
            pl.BlockSpec((M, N), lambda j, idx: (0, 0)),       # running y in
        ]
        out_specs = [
            pl.BlockSpec((M, N), lambda j, idx: (0, 0)),       # running y out
            pl.BlockSpec((Bk, N), lambda j, idx: (jnp.maximum(j - 1, 0), 0)),
        ]
        out_shape = [jax.ShapeDtypeStruct((M, N), jnp.float32),
                     jax.ShapeDtypeStruct((Ks, N), jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(C + 1,),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=_hop_scratch(B, B // qb, wdtype),
    )
    out, recv = pl.pallas_call(
        functools.partial(_ag_hop_kernel, C=C, B=B, qb=qb,
                          out_block=out_block, encode=encode, decode=decode,
                          interpret=interpret),
        out_shape=out_shape,
        grid_spec=grid_spec,
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(idx, x, held, y)
    return out, recv


def _rs_hop(x, w, rprev, blk_idx, dst, src, *,
            codec: Optional[Codec]) -> jax.Array:
    """One fused matmul+reduce-scatter hop: send row-block ``blk_idx``'s
    accumulated partial (``rprev + x[block] @ w``), return the received
    row-block partials ``[Mb, N]``."""
    K = x.shape[1]
    N = w.shape[1]
    Mb = rprev.shape[0]
    C = _chunks_of(Mb)
    Bm = Mb // C
    B = Bm * N
    encode, decode, wdtype, qb = _wire_math(codec, B)
    interpret = _interpret()
    idx = jnp.stack([dst, src, blk_idx.astype(jnp.int32)])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(C + 1,),
        in_specs=[
            # rows of the outgoing block, chunk j
            pl.BlockSpec((Bm, K), lambda j, idx: (idx[2] * C + jnp.minimum(j, C - 1), 0)),
            pl.BlockSpec((K, N), lambda j, idx: (0, 0)),
            pl.BlockSpec((Bm, N), lambda j, idx: (jnp.minimum(j, C - 1), 0)),
        ],
        out_specs=pl.BlockSpec((Bm, N), lambda j, idx: (jnp.maximum(j - 1, 0), 0)),
        scratch_shapes=_hop_scratch(B, B // qb, wdtype),
    )
    return pl.pallas_call(
        functools.partial(_rs_hop_kernel, C=C, B=B, qb=qb,
                          encode=encode, decode=decode, interpret=interpret),
        out_shape=jax.ShapeDtypeStruct((Mb, N), jnp.float32),
        grid_spec=grid_spec,
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(idx, x, w, rprev)


# ------------------------------------------------------------ public fused ops


def _record_hop(axis, nbytes: int, codec: Optional[Codec]):
    from deepspeed_tpu.comm import comm as dist

    proxy = jax.ShapeDtypeStruct((max(int(nbytes), 1),), jnp.int8)
    return dist._record("remote_dma", axis, proxy, backend="pallas",
                        fused=f"gemm+{codec.name if codec else 'none'}")


def _wire_nbytes(rows: int, cols: int, codec: Optional[Codec]) -> int:
    if codec is None:
        return rows * cols * 4
    return rows * cols + 4 * max(rows * cols // max(int(codec.block_size), 1), 1)


def all_gather_matmul(x: jax.Array, w_shard: jax.Array, axis, *,
                      codec=None, block_size: Optional[int] = None,
                      out_block: bool = False,
                      fused: Optional[bool] = None) -> jax.Array:
    """``x [M, n*Ks] @ all_gather(w_shard [Ks, N], rows) -> [M, N]`` with the
    gather fused into the GEMM (``out_block=True``: ``x [M, N]`` against
    ``held.T`` per shard -> ``[M, n*Ks]``, the backward-``dx`` form).

    ``fused=None`` follows the module knob; ``False`` forces the unfused
    lax composition (all_gather then one dot — the config-off program);
    ``True`` forces the kernels (falling back only when the trace context
    cannot express remote DMA). Returns fp32 (callers cast at boundaries,
    like the collective algorithms). Must run inside full-manual shard_map.
    """
    c = _resolve_codec(codec, block_size)
    use = enabled() if fused is None else fused
    n = axis_size(axis)
    if n <= 1:
        w32 = w_shard.astype(jnp.float32)
        x32 = x.astype(jnp.float32)
        dims = (((1,), (1,)), ((), ())) if out_block else (((1,), (0,)), ((), ()))
        return lax.dot_general(x32, w32, dims,
                               preferred_element_type=jnp.float32)
    if not use or not supported(axis):
        return _unfused_all_gather_matmul(x, w_shard, axis, out_block=out_block)
    from deepspeed_tpu.collectives.algorithms import _ring_perm

    Ks, N = w_shard.shape
    M = x.shape[0]
    i = lax.axis_index(axis)
    dst, src = _neighbor_logicals(axis, _ring_perm(n, False))
    x32 = x.astype(jnp.float32)
    held = w_shard.astype(jnp.float32)
    nbytes = _wire_nbytes(Ks, N, c)
    if out_block:
        y = jnp.zeros((M, n * Ks), jnp.float32)
        for k in range(n - 1):
            s_k = (i - k) % n
            with _record_hop(axis, nbytes, c):
                # x32 doubles as the (unused) y-seed operand: out_block mode
                # writes whole blocks, there is no running accumulator
                blk, held = _ag_hop(x32, held, x32, s_k, dst, src,
                                    codec=c, out_block=True)
            y = lax.dynamic_update_slice(y, blk, (0, s_k * Ks))
        s_last = (i + 1) % n
        blk = lax.dot_general(x32, held, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
        return lax.dynamic_update_slice(y, blk, (0, s_last * Ks))
    y = jnp.zeros((M, N), jnp.float32)
    for k in range(n - 1):
        s_k = (i - k) % n
        with _record_hop(axis, nbytes, c):
            y, held = _ag_hop(x32, held, y, s_k, dst, src,
                              codec=c, out_block=False)
    # the final received shard never crosses another wire: one plain dot
    s_last = (i + 1) % n
    xs = lax.dynamic_slice(x32, (0, s_last * Ks), (M, Ks))
    return y + lax.dot_general(xs, held, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def matmul_reduce_scatter(x: jax.Array, w: jax.Array, axis, *,
                          codec=None, block_size: Optional[int] = None,
                          fused: Optional[bool] = None) -> jax.Array:
    """``reduce_scatter(x [M, K] @ w [K, N], rows) -> [M/n, N]`` (sum over
    the axis — rank ``i`` gets row block ``i``), with each ring hop's
    partial-product GEMM fused into its own wire. fp32 out; full-manual
    shard_map only. Falls back to the unfused lax composition when the
    module knob is off, ``M`` does not tile, or remote DMA cannot be
    expressed here."""
    c = _resolve_codec(codec, block_size)
    use = enabled() if fused is None else fused
    n = axis_size(axis)
    M = x.shape[0]
    if n <= 1:
        return lax.dot_general(x.astype(jnp.float32), w.astype(jnp.float32),
                               (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
    if not use or not supported(axis) or M % n != 0:
        return _unfused_matmul_reduce_scatter(x, w, axis)
    from deepspeed_tpu.collectives.algorithms import _ring_perm

    Mb = M // n
    N = w.shape[1]
    i = lax.axis_index(axis)
    dst, src = _neighbor_logicals(axis, _ring_perm(n, False))
    x32 = x.astype(jnp.float32)
    w32 = w.astype(jnp.float32)
    rprev = jnp.zeros((Mb, N), jnp.float32)
    nbytes = _wire_nbytes(Mb, N, c)
    for k in range(n - 1):
        b_k = (i - 1 - k) % n
        with _record_hop(axis, nbytes, c):
            rprev = _rs_hop(x32, w32, rprev, b_k, dst, src, codec=c)
    # own row block: upstream partials + the local product, no wire
    xs = lax.dynamic_slice(x32, (i * Mb, 0), (Mb, x.shape[1]))
    return rprev + lax.dot_general(xs, w32, (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)


# -------------------------------------------------------- unfused references


def _unfused_all_gather_matmul(x, w_shard, axis, *, out_block: bool = False):
    """The config-off program: one tiled all-gather then one dot."""
    wf = lax.all_gather(w_shard.astype(jnp.float32), axis, axis=0, tiled=True)
    dims = (((1,), (1,)), ((), ())) if out_block else (((1,), (0,)), ((), ()))
    return lax.dot_general(x.astype(jnp.float32), wf, dims,
                           preferred_element_type=jnp.float32)


def _unfused_matmul_reduce_scatter(x, w, axis):
    """The config-off program: one dot then one tiled psum_scatter."""
    p = lax.dot_general(x.astype(jnp.float32), w.astype(jnp.float32),
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
    return lax.psum_scatter(p, axis, scatter_dimension=0, tiled=True)
