"""Wire codecs: what a collective hop puts on the link.

Reference analog: the ZeRO++ CUDA quantizers (``csrc/quantization/
swizzled_quantize.cu``, ``quant_reduce.cu``) and the LoCo error-feedback
kernels (``pt_binding.cpp loco_*``) — there, quantization is fused into each
collective's staging buffers. Here a ``Codec`` is a pure encode/decode pair
over jax arrays that every algorithm in ``algorithms.py`` (and the
all_to_all-based helpers in ``parallel/quant_collectives.py`` /
``parallel/zeropp.py``) applies at the hop boundary, so one wire format
serves every algorithm and a Pallas backend can later fuse it per hop.

Shapes: codecs operate on **blocked rows** — a 2D ``[R, L]`` array where each
row is one wire unit (a ring chunk, a destination shard, a gather payload)
and blocks never straddle rows. ``encode_rows`` pads ``L`` up to a whole
number of blocks internally; ``decode_rows`` strips the padding. The wire is
a :class:`Wire` pytree so it can be ``tree_map``-ed through any collective.

Error feedback (LoCo, arxiv 2306.10209 §5): ``encode_rows_ef`` compensates
the input with a carried residual and returns the refreshed residual
(``v = x + err; wire = Q(v); new_err = v - deQ(Q(v))``). State threading is
the caller's job — see ``algorithms.ring_reduce_scatter(err=...)`` and the
zeropp LoCo custom-vjp.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

DEFAULT_BLOCK = 2048


class Wire(NamedTuple):
    """One hop's on-wire payload: quantized values + per-block scales.

    Passthrough codecs put the (possibly dtype-cast) payload in ``q`` and a
    zero-size placeholder in ``s`` so every codec shares one pytree shape.
    """

    q: jax.Array
    s: jax.Array


def _pad_rows(x: jax.Array, block: int) -> Tuple[jax.Array, int]:
    """Pad the row length up to a whole number of blocks."""
    R, L = x.shape
    Lp = -(-L // block) * block
    if Lp != L:
        x = jnp.pad(x, ((0, 0), (0, Lp - L)))
    return x, Lp


class Codec:
    """Interface: a named, stateless encode/decode pair.

    ``wire_bytes(L, itemsize)`` is the per-row on-wire byte count the
    selector's beta term uses; ``lossy`` gates the error-feedback path and
    the equivalence tolerance in tests.
    """

    name: str = "none"
    lossy: bool = False

    def __init__(self, block_size: int = DEFAULT_BLOCK):
        self.block_size = int(block_size)

    # -- wire size model (selector beta term) ------------------------------
    def wire_bytes(self, length: int, itemsize: int) -> int:
        return length * itemsize

    # -- encode/decode -----------------------------------------------------
    def encode_rows(self, x: jax.Array) -> Wire:
        """``[R, L] -> Wire``. Rows are independent wire units."""
        raise NotImplementedError

    def decode_rows(self, wire: Wire, length: int, dtype) -> jax.Array:
        """``Wire -> [R, length]`` in ``dtype`` (padding stripped)."""
        raise NotImplementedError

    # -- error feedback (lossy codecs only) --------------------------------
    def encode_rows_ef(self, x: jax.Array, err: jax.Array) -> Tuple[Wire, jax.Array]:
        """LoCo-style compensated encode: returns (wire, refreshed residual).

        ``err`` is in the same units/shape as ``x``; every codec — exact
        ones included — re-captures whatever its wire dropped (a bf16 "none"
        wire still rounds a compensated fp32 sum), so the residual invariant
        ``transmitted + new_err == x + err`` holds for all of them.
        """
        v = x.astype(jnp.float32) + err.astype(jnp.float32)
        wire = self.encode_rows(v if self.lossy else v.astype(x.dtype))
        new_err = v - self.decode_rows(wire, x.shape[1], jnp.float32)
        return wire, new_err.astype(err.dtype)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"{type(self).__name__}(name={self.name!r}, block={self.block_size})"


class PassthroughCodec(Codec):
    """Identity wire (optionally cast to a wire dtype: bf16 / fp32).

    ``bf16`` halves fp32 wire bytes at bf16 mantissa cost — exact when the
    payload already is bf16; ``none`` ships the payload dtype untouched.
    """

    def __init__(self, name: str = "none", wire_dtype=None, block_size: int = DEFAULT_BLOCK):
        super().__init__(block_size)
        self.name = name
        self.wire_dtype = wire_dtype
        # lossy iff the wire can downcast the payload (bf16 wire on fp32 data);
        # an fp32 wire only ever upcasts, which is exact
        self.lossy = wire_dtype is not None and jnp.dtype(wire_dtype).itemsize < 4

    def wire_bytes(self, length: int, itemsize: int) -> int:
        w = jnp.dtype(self.wire_dtype).itemsize if self.wire_dtype else itemsize
        return length * w

    def encode_rows(self, x: jax.Array) -> Wire:
        q = x.astype(self.wire_dtype) if self.wire_dtype else x
        return Wire(q=q, s=jnp.zeros((0,), jnp.float32))

    def decode_rows(self, wire: Wire, length: int, dtype) -> jax.Array:
        return wire.q[:, :length].astype(dtype)


class _BlockQuantCodec(Codec):
    """Shared shape for the 1-byte-per-element + fp32-scale-per-block wires
    (int8 and fp8 share it, so the selector's beta term ranks them from ONE
    formula)."""

    lossy = True

    def wire_bytes(self, length: int, itemsize: int) -> int:
        blocks = -(-length // self.block_size)
        return length + 4 * blocks


class Int8BlockCodec(_BlockQuantCodec):
    """Blockwise-symmetric int8: int8 values + one fp32 absmax scale per
    block (the qwZ/qgZ wire — ``csrc/quantization/swizzled_quantize.cu``).
    ~4x fp32 / ~2x bf16 wire reduction at ``block_size >> 4``.

    Quantization routes through the ``ops.quant`` registry (the ONE int8
    block format): the Pallas kernel wins dispatch on TPU, the jnp fallback
    elsewhere. Row padding here guarantees blocks never straddle rows, the
    invariant every collective relies on.
    """

    name = "int8"

    def encode_rows(self, x: jax.Array) -> Wire:
        from deepspeed_tpu.ops.quant import quantize_int8

        R, _ = x.shape
        block = min(self.block_size, x.shape[1])
        xp, Lp = _pad_rows(x.astype(jnp.float32), block)
        q, scale = quantize_int8(xp, block_size=block)  # row-aligned: Lp % block == 0
        return Wire(q=q.reshape(R, Lp), s=scale.reshape(R, Lp // block))

    def decode_rows(self, wire: Wire, length: int, dtype) -> jax.Array:
        from deepspeed_tpu.ops.quant import dequantize_int8

        R, Lp = wire.q.shape
        block = Lp // wire.s.shape[1]
        out = dequantize_int8(wire.q.reshape(-1), wire.s.reshape(-1), (R, Lp),
                              dtype=dtype, block_size=block)
        return out[:, :length]


class Fp8Codec(_BlockQuantCodec):
    """Emulated-fp8 E4M3 wire: ``float8_e4m3fn`` values + one fp32 absmax
    scale per block (reference ``csrc/fp_quantizer/fp_quantize.cu``; native
    MXU dtype on v5e+, ml_dtypes emulation on CPU). Same bytes as int8 but
    ~2 more effective mantissa bits near the block scale.
    """

    name = "fp8"

    def encode_rows(self, x: jax.Array) -> Wire:
        from deepspeed_tpu.ops.quant import fp8_block_math

        R, _ = x.shape
        block = min(self.block_size, x.shape[1])
        xp, Lp = _pad_rows(x.astype(jnp.float32), block)
        q, scale = fp8_block_math(xp.reshape(R * (Lp // block), block))
        return Wire(q=q.reshape(R, Lp), s=scale.reshape(R, Lp // block))

    def decode_rows(self, wire: Wire, length: int, dtype) -> jax.Array:
        from deepspeed_tpu.ops.quant import fp8_block_dequant

        R, Lp = wire.q.shape
        block = Lp // wire.s.shape[1]
        out = fp8_block_dequant(wire.q.reshape(-1, block),
                                wire.s.reshape(-1, 1))
        return out.reshape(R, Lp)[:, :length].astype(dtype)


CODECS: Dict[str, type] = {
    "none": lambda block_size=DEFAULT_BLOCK: PassthroughCodec("none", None, block_size),
    "fp32": lambda block_size=DEFAULT_BLOCK: PassthroughCodec("fp32", jnp.float32, block_size),
    "bf16": lambda block_size=DEFAULT_BLOCK: PassthroughCodec("bf16", jnp.bfloat16, block_size),
    "int8": Int8BlockCodec,
    "fp8": Fp8Codec,
}


def get_codec(codec, block_size: Optional[int] = None) -> Codec:
    """Resolve a codec name (or pass a ``Codec`` instance through)."""
    if isinstance(codec, Codec):
        return codec
    if codec is None:
        codec = "none"
    try:
        factory = CODECS[codec]
    except KeyError:
        raise ValueError(f"unknown codec {codec!r} (one of {sorted(CODECS)})") from None
    return factory(block_size=block_size) if block_size else factory()
