"""deepspeed_tpu.collectives: algorithmic collective library.

The layer between the ``deepspeed_tpu.comm`` facade and ``jax.lax``:
hop-composed collective algorithms (``algorithms.py``), wire codecs applied
per hop (``codecs.py``), an alpha-beta / measured cost model picking
algorithm+codec per (op, bytes, axis-size) (``selector.py``), and a chunked
double-buffered compute/comm overlap helper (``overlap.py``).

Reference analogs: ZeRO++'s quantized hierarchical collectives
(arxiv 2306.10209, ``deepspeed/runtime/comm/coalesced_collectives.py``) and
EQuARX-style in-XLA quantized all-reduce (arxiv 2506.17615). Everything here
is built from ``ppermute`` hops inside **full-manual** shard_map (via
``utils/compat.shard_map`` — partial-manual is broken upstream on jax 0.4.37)
so a later Pallas remote-DMA backend can replace the hop primitive without
touching the algorithm layer.
"""

from deepspeed_tpu.collectives.codecs import (
    CODECS,
    Codec,
    Wire,
    get_codec,
)
from deepspeed_tpu.collectives.algorithms import (
    ALGORITHMS,
    all_gather,
    all_reduce,
    all_to_all,
    reduce_scatter,
)
from deepspeed_tpu.collectives.pallas_backend import (
    PALLAS_ALGORITHMS,
)
from deepspeed_tpu.collectives.selector import (
    Decision,
    calibrate,
    configure,
    get_config,
    select,
)
from deepspeed_tpu.collectives.table import (
    SCHEMA_VERSION,
    load_table,
    merge_rows,
    write_table,
)
from deepspeed_tpu.collectives.observatory import (
    CollectiveObservatory,
    ObservatoryConfig,
    get_observatory,
)
from deepspeed_tpu.collectives.overlap import (
    double_buffered,
    double_buffered_scan,
)
from deepspeed_tpu.collectives.costmodel import (
    CostModel,
)
from deepspeed_tpu.collectives.schedule import (
    CompiledSchedule,
    Level,
    compile_schedule,
    parse_signature,
)
from deepspeed_tpu.collectives.fused_gemm import (
    all_gather_matmul,
    matmul_reduce_scatter,
)
