"""The ONE alpha-beta cost object shared by the selector, the schedule
compiler, and the observatory calibrator.

Before the schedule compiler existed, the alpha/beta constants lived as
plain fields on ``selector.SelectorConfig`` and the observatory's refit
wrote into its ``backend_ab`` dict. The compiler's search needs the same
constants as its objective — and it must FEEL a refit immediately, or the
measured-vs-predicted loop would tune a model the search no longer reads.
So the constants live here, in one mutable ``CostModel`` instance that

- ``selector.configure`` builds from the config block (sharing the
  ``backend_ab`` dict with the installed ``SelectorConfig``, so existing
  ``get_config().backend_ab`` consumers keep seeing calibrations),
- ``selector.estimate_us`` charges from,
- ``selector.calibrate`` (the observatory refit's landing point) writes
  into, bumping :attr:`version` so schedule-compile caches invalidate, and
- ``schedule.compile_schedule`` reads as its search objective via
  ``selector.cost_model()`` — the SAME object, by identity.

The per-hop charge is the classic point-to-point model::

    T(hop) = alpha_us + wire_mb * beta_us_per_mb

with per-backend (alpha, beta) overrides once the observatory has fit
observed hop timings, and an optional per-tier beta scaling for
hierarchical schedules (a GC3-style search only places codecs per phase
when the tiers cost differently — on a real pod the outer links are the
slow ones, which is exactly where an int8 wire pays).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple


class CostModel:
    """Mutable alpha-beta constants: static defaults + calibrated
    per-backend overrides. NOT thread-safe on its own — the selector's lock
    guards mutation (``calibrate``), and readers only do dict lookups."""

    def __init__(self, alpha_us: float = 1.0, beta_us_per_mb: float = 10.0,
                 pallas_alpha_scale: float = 0.5,
                 backend_ab: Optional[Dict[str, Tuple[float, float]]] = None):
        self.alpha_us = float(alpha_us)
        self.beta_us_per_mb = float(beta_us_per_mb)
        self.pallas_alpha_scale = float(pallas_alpha_scale)
        # shared BY REFERENCE with selector.SelectorConfig.backend_ab: a
        # refit through either handle is visible through both
        self.backend_ab: Dict[str, Tuple[float, float]] = (
            backend_ab if backend_ab is not None else {})
        # beta multiplier per schedule level (innermost tier first); levels
        # past the end reuse the last entry. Empty = every tier costs the
        # same link. The schedule compiler's codec-placement search only
        # has a gradient when this is non-flat (or a calibration is).
        self.tier_beta_scale: Tuple[float, ...] = ()
        # bumped on every mutation: schedule-compile caches key on it so a
        # refit re-runs the search instead of serving stale winners
        self.version = 0

    def calibrate(self, backend: str, alpha_us: float,
                  beta_us_per_mb: float) -> None:
        self.backend_ab[backend] = (float(alpha_us), float(beta_us_per_mb))
        self.version += 1

    def set_tier_beta_scale(self, scales: Tuple[float, ...]) -> None:
        self.tier_beta_scale = tuple(float(s) for s in scales)
        self.version += 1

    def constants(self, backend: str = "ppermute", *,
                  discount: bool = False) -> Tuple[float, float]:
        """(alpha_us, beta_us_per_mb) for one hop backend. ``discount``
        applies the pallas per-hop launch discount to the STATIC alpha
        (a calibration subsumes it, same as the selector always did)."""
        fitted = self.backend_ab.get(backend)
        if fitted is not None:
            return fitted
        alpha = self.alpha_us * (self.pallas_alpha_scale if discount else 1.0)
        return alpha, self.beta_us_per_mb

    def tier_beta(self, backend: str, depth: int, *,
                  discount: bool = False) -> float:
        """beta for a schedule level at ``depth`` (0 = innermost tier)."""
        _, beta = self.constants(backend, discount=discount)
        scales = self.tier_beta_scale
        if not scales:
            return beta
        return beta * scales[min(depth, len(scales) - 1)]

    def estimate_us(self, hops: float, wire_mb: float,
                    backend: str = "ppermute", *,
                    discount: bool = False) -> float:
        """The flat two-term charge — what ``selector.estimate_us`` applies
        to ``model_terms`` regressors."""
        alpha, beta = self.constants(backend, discount=discount)
        return hops * alpha + wire_mb * beta
