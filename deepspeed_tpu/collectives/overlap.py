"""Chunked compute/comm overlap (T3-style double buffering).

Reference analog: T3 (arxiv 2401.16677) / DeepSpeed's overlap_comm — split a
collective's payload into chunks and issue chunk k+1's communication while
chunk k's compute consumes the data that already arrived. In XLA the
"issuing" is purely structural: the chunked program presents the next
chunk's collective and the current chunk's compute as independent ops, so
the latency-hiding scheduler (and the GPU/TPU async collective runtime) can
run them concurrently — something a monolithic gather-then-compute program
forbids by construction.

Two shapes:

- :func:`double_buffered` — python-unrolled over a list of items (chunk
  count is static and small; each stage may be an arbitrary pytree).
- :func:`double_buffered_scan` — ``lax.scan`` over stacked chunks
  ``[C, ...]`` with the in-flight buffer carried, for chunk counts worth
  rolling (one compiled body instead of C copies).

Adopted by the zeropp qwZ gather path (``parallel/zeropp.py``): the int8
weight all-gather splits its wire into chunks so dequantize of chunk k
overlaps the gather of chunk k+1.

The Pallas collective backend moves this same pattern INSIDE a kernel:
``pallas_backend._fused_hop_kernel`` double-buffers wire chunks across its
grid so the remote DMA of chunk k+1 hides behind the dequant-accumulate of
chunk k — per hop, with no XLA scheduler in the loop. These helpers remain
the program-level shape for overlap XLA can schedule.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence

import jax
import jax.numpy as jnp


def double_buffered(items: Sequence[Any], comm_fn: Callable, compute_fn: Callable) -> List[Any]:
    """Software-pipelined ``[compute_fn(comm_fn(it)) for it in items]``.

    The communication for item k+1 is emitted *before* the compute on item
    k's result, so the two are schedulable concurrently. Unrolled: use for
    small static chunk counts (per-leaf param gathers, 2-8 chunks).
    """
    items = list(items)
    if not items:
        return []
    results = []
    inflight = comm_fn(items[0])
    for k in range(len(items)):
        upcoming = comm_fn(items[k + 1]) if k + 1 < len(items) else None
        results.append(compute_fn(inflight))
        inflight = upcoming
    return results


def double_buffered_scan(chunks: jax.Array, comm_fn: Callable, compute_fn: Callable) -> jax.Array:
    """Double-buffered ``lax.scan`` over stacked chunks ``[C, ...]``.

    Carry holds the in-flight communicated buffer; each iteration computes
    on it while starting the next chunk's communication — the two ops share
    an iteration and have no data dependence, so XLA may overlap them.
    Returns ``stack([compute_fn(comm_fn(c)) for c in chunks])``.
    """
    C = chunks.shape[0]
    if C == 1:
        return jax.tree_util.tree_map(lambda y: y[None], compute_fn(comm_fn(chunks[0])))
    first = comm_fn(chunks[0])

    def body(inflight, nxt):
        upcoming = comm_fn(nxt)  # independent of compute(inflight): overlappable
        y = compute_fn(inflight)
        return upcoming, y

    last, ys = jax.lax.scan(body, first, chunks[1:])
    y_last = compute_fn(last)
    return jax.tree_util.tree_map(
        lambda a, b: jnp.concatenate([a, b[None]], axis=0), ys, y_last)
