"""Hop-composed collective algorithms over ``ppermute``.

Each algorithm is built from neighbor-exchange hops (the SNIPPETS
right-permute pattern: ``perm = [(s, (s+1) % n) for s in range(n)]``) routed
through the ``comm`` facade's ``ppermute`` so (a) every hop lands in the
telemetry trace as a ``comm:ppermute`` span next to step time and (b) a later
Pallas remote-DMA backend (``make_async_remote_copy`` per hop) can replace
the primitive without touching this layer.

Algorithms (reference: NCCL/MPI collective-algorithm menus; ZeRO++ hpZ for
the hierarchical shape, arxiv 2306.10209):

- ``ring``    — classic n-1 hop ring. Bandwidth-optimal, latency O(n).
- ``bidir``   — bidirectional ring: payload halved onto two counter-rotating
  rings; same bus traffic, half the serial chain on full-duplex links.
- ``rhd``     — recursive halving/doubling: log2(n) hops, latency-optimal for
  small payloads; power-of-two axis sizes only (callers fall back to ring).
- ``ring2d``  — the axis factored into a near-square a x b grid (or a tuple
  of two mesh axes): intra-group reduce-scatter -> inter-group all-reduce ->
  intra-group all-gather — the ZeRO++ hierarchical all-reduce shape that
  keeps the quantized hops on the fast intra links.

Wire codecs (``codecs.py``) apply at hop granularity: all-gather-style
forwarding encodes once at the source and relays the wire; reduce paths
decode-accumulate-re-encode per hop (which is why LoCo error feedback exists
— pass ``err`` to ``reduce_scatter``).

Everything here must run inside **full-manual** shard_map (axis names bound;
partial-manual is broken on this jax 0.4.37 — see ``utils/compat.py``).
All functions accept arbitrary local shapes; reduce paths pad the flattened
payload up to ``n`` chunks internally and strip the padding on exit.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu import telemetry
from deepspeed_tpu.collectives.codecs import Codec, get_codec
from deepspeed_tpu.collectives import pallas_backend
from deepspeed_tpu.collectives.pallas_backend import PALLAS_ALGORITHMS
from deepspeed_tpu.utils.compat import axis_size

ALGORITHMS = ("ring", "bidir", "rhd", "ring2d")


def _permute_wire(wire, axis, perm):
    """Permute every leaf of a wire pytree one hop. On the default backend
    each leaf is a facade ``ppermute`` (a traced ``comm:ppermute`` span);
    inside a :func:`pallas_backend.hop_scope` the whole wire moves in ONE
    remote-DMA kernel (a ``comm:remote_dma`` span)."""
    if pallas_backend.hops_active() and pallas_backend.remote_dma_supported():
        return pallas_backend.permute_wire(wire, axis, perm)
    from deepspeed_tpu.comm import comm as dist

    return jax.tree_util.tree_map(
        lambda w: w if w.size == 0 else dist.ppermute(w, axis, perm), wire)


def _hop_span(name: str, axis, hop: int, codec: Codec, **tags):
    from deepspeed_tpu.collectives import observatory

    # trace-time hop census for the observatory (one count per hop, every
    # backend — ppermute, remote-DMA, and fused hops all come through here);
    # a no-op outside a routed-collective trace scope
    observatory.on_hop()
    tracer = telemetry.get_tracer()
    if not tracer.enabled:
        return telemetry.NOOP_SPAN
    axis_str = "+".join(axis) if isinstance(axis, (tuple, list)) else str(axis)
    if pallas_backend.hops_active():
        # honest transport label: interpret mode on a multi-axis mesh falls
        # back to ppermute hops (see pallas_backend.remote_dma_supported)
        tags.setdefault("backend", "pallas" if pallas_backend.remote_dma_supported()
                        else "ppermute_fallback")
    return tracer.span(f"coll:{name}", cat="coll", axis=axis_str, hop=hop,
                       codec=codec.name, **tags)


def _ring_perm(n: int, reverse: bool = False):
    if reverse:
        return [(s, (s - 1) % n) for s in range(n)]
    return [(s, (s + 1) % n) for s in range(n)]


# ---------------------------------------------------------------- all-gather


def _ring_all_gather_flat(x: jax.Array, axis, codec: Codec, *, reverse: bool = False,
                          sub: Optional[tuple] = None) -> jax.Array:
    """Ring all-gather of a flat local block: ``[L] -> [n, L]`` ordered by
    source rank. Encode once at the source, relay the wire n-1 hops, decode
    on each arrival (lossy codecs quantize exactly once).

    ``sub = (n, rank, perm, span_label)`` runs the SAME schedule on a
    sub-ring of the axis (ring2d's intra/inter groups): ``perm`` connects
    each group's members and ``rank`` is the position within the group."""
    if sub is not None:
        n, i, perm, label = sub
        step = 1
    else:
        n = axis_size(axis)
        i = jax.lax.axis_index(axis) if n > 1 else 0
        step = -1 if reverse else 1
        perm = _ring_perm(n, reverse)
        label = f"all_gather:ring{'-' if reverse else ''}"
    L = x.shape[0]
    if n == 1:
        return x[None]
    wire = codec.encode_rows(x[None])
    # the sender's own row comes from its own DECODED wire, not the raw
    # block: with a lossy codec every rank must see the same bytes for every
    # block or data-parallel replicas silently drift apart
    out = jnp.zeros((n, L), x.dtype).at[i].set(codec.decode_rows(wire, L, x.dtype)[0])
    for k in range(1, n):
        with _hop_span(label, axis, k, codec):
            wire = _permute_wire(wire, axis, perm)
        src = (i - step * k) % n
        out = out.at[src].set(codec.decode_rows(wire, L, x.dtype)[0])
    return out


def ring_all_gather(x: jax.Array, axis, codec: Codec, *, concat_axis: int = 0,
                    bidir: bool = False) -> jax.Array:
    """All-gather along ``concat_axis`` (tiled, matching
    ``lax.all_gather(..., tiled=True)`` semantics)."""
    n = axis_size(axis)
    moved = jnp.moveaxis(x, concat_axis, 0)
    lead, rest = moved.shape[0], moved.shape[1:]
    flat = moved.reshape(-1)
    if bidir and flat.shape[0] >= 2:
        h = flat.shape[0] // 2
        ga = _ring_all_gather_flat(flat[:h], axis, codec)
        gb = _ring_all_gather_flat(flat[h:], axis, codec, reverse=True)
        gathered = jnp.concatenate([ga, gb], axis=1)  # [n, L]
    else:
        gathered = _ring_all_gather_flat(flat, axis, codec)
    full = gathered.reshape((n * lead,) + rest)
    return jnp.moveaxis(full, 0, concat_axis)


def rhd_all_gather(x: jax.Array, axis, codec: Codec, *, concat_axis: int = 0) -> jax.Array:
    """Recursive-doubling all-gather: log2(n) hops, payload doubling each
    hop. Power-of-two axis sizes only.

    The working buffer stays in WIRE form the whole way (rows concatenate
    without decoding — every row was encoded independently), so lossy codecs
    quantize exactly once at the source, same as the ring relay."""
    n = axis_size(axis)
    if n & (n - 1):
        raise ValueError(f"rhd needs a power-of-two axis size, got {n}")
    moved = jnp.moveaxis(x, concat_axis, 0)
    lead, rest = moved.shape[0], moved.shape[1:]
    L = moved.size  # static row length of the single source row
    i = jax.lax.axis_index(axis)
    # [groups, ...] wire rows: groups of contiguous src ranks, one row each
    wire = codec.encode_rows(moved.reshape(1, -1))
    d = 1
    hop = 0
    while d < n:
        perm = [(s, s ^ d) for s in range(n)]
        with _hop_span("all_gather:rhd", axis, hop, codec):
            recv = _permute_wire(wire, axis, perm)
        # my block covers ranks [i & ~(d-1) ...]; the partner's covers the
        # sibling half — order rows by the side bit of this round
        upper = ((i & d) != 0)
        wire = jax.tree_util.tree_map(
            lambda own, rcv: jnp.concatenate(
                [jnp.where(upper, rcv, own), jnp.where(upper, own, rcv)], axis=0),
            wire, recv)
        d *= 2
        hop += 1
    full = codec.decode_rows(wire, L, x.dtype).reshape((n * lead,) + rest)
    return jnp.moveaxis(full, 0, concat_axis)


# ------------------------------------------------------------ reduce-scatter


def _pad_to_chunks(flat: jax.Array, n: int) -> Tuple[jax.Array, int, int]:
    N = flat.shape[0]
    chunk = -(-N // n)
    Np = chunk * n
    if Np != N:
        flat = jnp.pad(flat, (0, Np - N))
    return flat, N, chunk


def _ring_reduce_scatter_rows(rows: jax.Array, axis, codec: Codec, *,
                              err: Optional[jax.Array] = None,
                              reverse: bool = False,
                              sub: Optional[tuple] = None):
    """Ring reduce-scatter of ``[n, L]`` chunk rows: returns this rank's
    fully-reduced (summed) chunk ``[L]`` (+ refreshed EF residual rows).

    Hop schedule (right ring): at hop k rank i sends its accumulated chunk
    ``(i - 1 - k) % n`` and receives chunk ``(i - 2 - k) % n`` from the left,
    finishing after n-1 hops with chunk ``i`` reduced over all ranks.
    Lossy codecs re-encode partial sums each hop; ``err`` (shaped like
    ``rows``) turns on LoCo error feedback per sent chunk.

    ``sub = (n, rank, perm, span_label)`` runs the schedule on a sub-ring
    of the axis (see :func:`_ring_all_gather_flat`).
    """
    if (err is None and pallas_backend.hops_active()
            and pallas_backend.fusable(codec, rows.dtype)
            and pallas_backend.remote_dma_supported()):
        # EQuARX transport: the whole encode -> hop -> decode-accumulate
        # chain runs inside one Pallas kernel per hop (same schedule, fused
        # execution); exact wires and integer payloads fall through to the
        # generic loop below, whose hops remote-DMA the wire instead
        out = pallas_backend.fused_ring_reduce_scatter_rows(
            rows, axis, codec, reverse=reverse, sub=sub)
        return out, None
    if sub is not None:
        n, i, perm, label = sub
        step = 1
    else:
        n = axis_size(axis)
        i = jax.lax.axis_index(axis) if n > 1 else 0
        step = -1 if reverse else 1
        perm = _ring_perm(n, reverse)
        label = f"reduce_scatter:ring{'-' if reverse else ''}"
    L = rows.shape[1]
    if n == 1:
        out = rows[0]
        return (out, err) if err is not None else (out, None)
    # float payloads accumulate in fp32 — the WHOLE chain, not just each
    # add: a bf16 accumulator would round partial sums on every hop, drifting
    # from lax.psum as the world grows. Integer payloads accumulate in their
    # own dtype so exactness matches lax.psum (fp32 rounds above 2^24).
    acc_dtype = jnp.float32 if jnp.issubdtype(rows.dtype, jnp.floating) else rows.dtype
    acc = rows.astype(acc_dtype)
    new_err = err
    for k in range(n - 1):
        send_idx = (i - step * (1 + k)) % n
        v = jax.lax.dynamic_index_in_dim(acc, send_idx, axis=0)  # [1, L]
        if err is not None:
            e = jax.lax.dynamic_index_in_dim(new_err, send_idx, axis=0)
            wire, e2 = codec.encode_rows_ef(v, e)
            new_err = jax.lax.dynamic_update_index_in_dim(new_err, e2, send_idx, axis=0)
        else:
            wire = codec.encode_rows(v)
        with _hop_span(label, axis, k, codec):
            wire = _permute_wire(wire, axis, perm)
        recv = codec.decode_rows(wire, L, acc_dtype)
        recv_idx = (i - step * (2 + k)) % n
        mine = jax.lax.dynamic_index_in_dim(acc, recv_idx, axis=0)
        acc = jax.lax.dynamic_update_index_in_dim(acc, mine + recv, recv_idx, axis=0)
    out = jax.lax.dynamic_index_in_dim(acc, i, axis=0)[0]
    return out, new_err


def _rhd_reduce_scatter_rows(rows: jax.Array, axis, codec: Codec):
    """Recursive-halving reduce-scatter of ``[n, L]`` rows -> this rank's
    summed chunk ``[L]``; log2(n) hops, halving payload each hop."""
    n = axis_size(axis)
    if n & (n - 1):
        raise ValueError(f"rhd needs a power-of-two axis size, got {n}")
    if n == 1:
        return rows[0]
    i = jax.lax.axis_index(axis)
    L = rows.shape[1]
    # fp32 working set for floats; integer payloads keep their dtype (exact)
    acc_dtype = jnp.float32 if jnp.issubdtype(rows.dtype, jnp.floating) else rows.dtype
    buf = rows.astype(acc_dtype)  # [m, L] working set, m halves each round
    d = n >> 1
    hop = 0
    while d >= 1:
        m = buf.shape[0]
        upper = ((i & d) != 0)
        lo, hi = buf[: m // 2], buf[m // 2:]
        send = jnp.where(upper, lo, hi)  # the half the partner keeps
        keep = jnp.where(upper, hi, lo)
        wire = codec.encode_rows(send.reshape(1, -1))
        perm = [(s, s ^ d) for s in range(n)]
        with _hop_span("reduce_scatter:rhd", axis, hop, codec):
            wire = _permute_wire(wire, axis, perm)
        recv = codec.decode_rows(wire, send.size, acc_dtype).reshape(send.shape)
        buf = keep + recv
        d >>= 1
        hop += 1
    return buf[0]


def ring_reduce_scatter(x: jax.Array, axis, codec: Codec, *, scatter_axis: int = 0,
                        op: str = "sum", bidir: bool = False,
                        err: Optional[jax.Array] = None):
    """Reduce-scatter along ``scatter_axis`` (tiled ``lax.psum_scatter``
    semantics: rank i gets slice i of the reduction). ``err`` (same shape as
    the flattened chunk rows ``[n, L]``) enables LoCo error feedback and
    makes the return a ``(out, new_err)`` pair."""
    n = axis_size(axis)
    moved = jnp.moveaxis(x, scatter_axis, 0)
    lead, rest = moved.shape[0], moved.shape[1:]
    if lead % n:
        raise ValueError(f"reduce_scatter dim {lead} not divisible by axis size {n}")
    rows = moved.reshape(n, -1)
    if bidir and err is None and rows.shape[1] >= 2:
        h = rows.shape[1] // 2
        oa, _ = _ring_reduce_scatter_rows(rows[:, :h], axis, codec)
        ob, _ = _ring_reduce_scatter_rows(rows[:, h:], axis, codec, reverse=True)
        out = jnp.concatenate([oa, ob], axis=0)
        new_err = None
    else:
        out, new_err = _ring_reduce_scatter_rows(rows, axis, codec, err=err)
    out = out.reshape((lead // n,) + rest).astype(x.dtype)
    out = jnp.moveaxis(out, 0, scatter_axis)
    if op in ("mean", "avg"):
        out = out / n
    elif op != "sum":
        raise ValueError(f"reduce op {op!r} unsupported by algorithmic reduce_scatter")
    return (out, new_err) if err is not None else out


def rhd_reduce_scatter(x: jax.Array, axis, codec: Codec, *, scatter_axis: int = 0,
                       op: str = "sum") -> jax.Array:
    n = axis_size(axis)
    moved = jnp.moveaxis(x, scatter_axis, 0)
    lead, rest = moved.shape[0], moved.shape[1:]
    if lead % n:
        raise ValueError(f"reduce_scatter dim {lead} not divisible by axis size {n}")
    out = _rhd_reduce_scatter_rows(moved.reshape(n, -1), axis, codec)
    out = out.reshape((lead // n,) + rest).astype(x.dtype)
    out = jnp.moveaxis(out, 0, scatter_axis)
    return out / n if op in ("mean", "avg") else out


# ---------------------------------------------------------------- all-reduce


def _flat_all_reduce_ring(flat: jax.Array, axis, codec: Codec, *, bidir: bool = False,
                          n: Optional[int] = None) -> jax.Array:
    """Ring all-reduce of a flat payload (any length): pad to n chunks,
    ring RS then ring AG, strip padding."""
    n = axis_size(axis) if n is None else n
    if n == 1:
        return flat
    padded, N, chunk = _pad_to_chunks(flat, n)
    rows = padded.reshape(n, chunk)
    # the reduced shard returns fp32; gather it in the payload dtype so the
    # AG wire costs what the caller's dtype costs (one boundary rounding,
    # same as lax's psum_scatter + all_gather decomposition)
    if bidir and chunk >= 2:
        h = chunk // 2
        ra, _ = _ring_reduce_scatter_rows(rows[:, :h], axis, codec)
        rb, _ = _ring_reduce_scatter_rows(rows[:, h:], axis, codec, reverse=True)
        ga = _ring_all_gather_flat(ra.astype(flat.dtype), axis, codec)
        gb = _ring_all_gather_flat(rb.astype(flat.dtype), axis, codec, reverse=True)
        out = jnp.concatenate([ga, gb], axis=1).reshape(-1)[:N]
    else:
        red, _ = _ring_reduce_scatter_rows(rows, axis, codec)
        out = _ring_all_gather_flat(red.astype(flat.dtype), axis, codec).reshape(-1)[:N]
    return out.astype(flat.dtype)


def _flat_all_reduce_rhd(flat: jax.Array, axis, codec: Codec) -> jax.Array:
    n = axis_size(axis)
    if n == 1:
        return flat
    padded, N, chunk = _pad_to_chunks(flat, n)
    red = _rhd_reduce_scatter_rows(padded.reshape(n, chunk), axis, codec)
    return rhd_all_gather(red.astype(flat.dtype), axis, codec).reshape(-1)[:N]


def _factor_near_square(n: int) -> Tuple[int, int]:
    """n = a * b with a <= b and a as close to sqrt(n) as divides."""
    a = int(math.isqrt(n))
    while a > 1 and n % a:
        a -= 1
    return a, n // a


def _flat_all_reduce_ring2d(flat: jax.Array, axis, codec: Codec,
                            factors: Optional[Tuple[int, int]] = None) -> jax.Array:
    """Hierarchical 2D all-reduce on ONE mesh axis factored a x b
    (rank = u*b + v): intra-group (b, contiguous ranks — the fast/near links
    on a ring-ordered axis) reduce-scatter, inter-group (a, stride b) ring
    all-reduce of the shard, intra-group all-gather — the ZeRO++/hpZ shape.
    Every phase is ppermute hops with the codec applied, so the wire
    reduction lands on every link tier."""
    n = axis_size(axis)
    if n == 1:
        return flat
    a, b = factors if factors else _factor_near_square(n)
    if a * b != n:
        raise ValueError(f"ring2d factors {a}x{b} != axis size {n}")
    if a == 1 or b == 1:
        return _flat_all_reduce_ring(flat, axis, codec)

    i = jax.lax.axis_index(axis)
    u, v = i // b, i % b
    # sub-ring permutations: intra connects contiguous groups of b (the
    # near links on a ring-ordered axis); inter connects same-v ranks at
    # stride b across the a groups
    intra = [(s, (s // b) * b + ((s % b) + 1) % b) for s in range(n)]
    inter = [(s, ((s // b + 1) % a) * b + (s % b)) for s in range(n)]

    # phase 1: intra-group ring reduce-scatter over the v sub-axis
    padded, N, chunk = _pad_to_chunks(flat, b)
    shard, _ = _ring_reduce_scatter_rows(
        padded.reshape(b, chunk), axis, codec,
        sub=(b, v, intra, "all_reduce:ring2d/intra-rs"))  # [chunk]

    # phase 2: inter-group ring all-reduce of the shard over the u sub-axis
    sp, SN, schunk = _pad_to_chunks(shard, a)
    sred, _ = _ring_reduce_scatter_rows(
        sp.reshape(a, schunk), axis, codec,
        sub=(a, u, inter, "all_reduce:ring2d/inter-rs"))
    sout = _ring_all_gather_flat(
        sred.astype(flat.dtype), axis, codec,
        sub=(a, u, inter, "all_reduce:ring2d/inter-ag"))
    shard_full = sout.reshape(-1)[:SN]  # [chunk], reduced over ALL n ranks

    # phase 3: intra-group ring all-gather of the reduced shard
    out = _ring_all_gather_flat(
        shard_full.astype(flat.dtype), axis, codec,
        sub=(b, v, intra, "all_reduce:ring2d/intra-ag"))
    return out.reshape(-1)[:N].astype(flat.dtype)


def _hier_all_reduce_axes(x: jax.Array, axes: Sequence[str], codec: Codec) -> jax.Array:
    """Mesh-axis-factored hierarchical all-reduce over a tuple of named axes
    (intra ``axes[0]`` RS -> inter ``axes[1:]`` AR -> intra ``axes[0]`` AG)."""
    inner = axes[0]
    n = axis_size(inner)
    flat = x.reshape(-1)
    padded, N, chunk = _pad_to_chunks(flat, n)
    red, _ = _ring_reduce_scatter_rows(padded.reshape(n, chunk), axis=inner, codec=codec)
    rest = tuple(axes[1:])
    if len(rest) == 1:
        red = _flat_all_reduce_ring(red, rest[0], codec)
    elif rest:
        red = _hier_all_reduce_axes(red, rest, codec).reshape(-1)
    gathered = _ring_all_gather_flat(red.astype(flat.dtype), inner, codec)
    return gathered.reshape(-1)[:N].reshape(x.shape)


# ---------------------------------------------------------------- all-to-all
#
# Schedules (The Big Send-off, arxiv 2504.18658): the payload is [n]
# destination rows (row d = this rank's block for rank d, flattened).
#
# - ``ring``   — the shift schedule: phase k moves the row destined k ranks
#   ahead DIRECTLY via a distance-k permutation (n-1 serial phases, each a
#   single facade ppermute / remote-DMA kernel carrying one row's wire).
# - ``bidir``  — phases paired with their mirror distance: phase k also
#   moves the row destined k ranks BEHIND on the counter-rotating ring, so
#   full-duplex links finish in ceil((n-1)/2) serial phases.
# - ``ring2d`` — the Big-Send-off sub-ring factorization: the axis factored
#   a x b (rank = u*b + v), destination rows bundled by target column and
#   exchanged on the intra sub-ring (b-1 phases of a-row bundles), then
#   re-bundled by target row and exchanged on the inter sub-ring (a-1
#   phases of b-row bundles) — (a-1)+(b-1) hops instead of n-1, at
#   S*((b-1)/b + (a-1)/a) wire volume instead of S*(n-1)/n.
#
# Codec semantics: every destination row is encoded ONCE at the source and
# decoded once at its destination — relays (ring2d's middle hop) forward
# the WIRE, never re-quantizing. The own row never crosses a link and stays
# raw. There is no reduction, so no error feedback applies.


def _wire_take(wire: "Wire", idx) -> "Wire":
    """Rows ``idx`` of a blocked-rows wire (zero-size scale placeholders of
    passthrough codecs pass through untouched)."""
    take = lambda a: a if a.size == 0 else jnp.take(a, idx, axis=0)
    return type(wire)(*(take(leaf) for leaf in wire))


def _wire_update(wire: "Wire", rows: "Wire", idx) -> "Wire":
    """Write ``rows`` into ``wire`` at leading index ``idx`` (traced ok)."""
    upd = lambda a, r: a if a.size == 0 else jnp.asarray(a).at[idx].set(r)
    return type(wire)(*(upd(leaf, r) for leaf, r in zip(wire, rows)))


def _shift_perm(n: int, k: int):
    """Distance-k right-shift permutation of the whole axis."""
    return [(s, (s + k) % n) for s in range(n)]


def _ring_all_to_all_rows(rows: jax.Array, axis, codec: Codec, *,
                          bidir: bool = False) -> jax.Array:
    """All-to-all of ``[n, L]`` destination rows -> ``[n, L]`` rows ordered
    by source rank (shift schedule: phase k permutes the row destined k
    ranks ahead directly at distance k)."""
    n = axis_size(axis)
    i = jax.lax.axis_index(axis) if n > 1 else 0
    perm_k = lambda k: _shift_perm(n, k)
    label = f"all_to_all:{'bidir' if bidir else 'ring'}"
    L = rows.shape[1]
    if n == 1:
        return rows
    if (pallas_backend.hops_active() and not bidir
            and pallas_backend.fusable(codec, rows.dtype)
            and pallas_backend.remote_dma_supported()):
        # EQuARX transport minus the accumulate: each phase is ONE kernel
        # requantizing the outgoing row in VMEM, remote-DMAing the wire and
        # dequantizing at the receiver (bidir/exact wires take the generic
        # unfused loop below, whose hops remote-DMA the encoded wire)
        return pallas_backend.fused_ring_all_to_all_rows(
            rows, axis, codec, n=n, i=i, perm_k=perm_k, label=label)
    wire = codec.encode_rows(rows)  # encode once per destination row
    out = jnp.zeros((n, L), rows.dtype).at[i].set(
        jax.lax.dynamic_index_in_dim(rows, i, axis=0)[0])  # own row: raw
    phases = range(1, (n // 2) + 1) if bidir else range(1, n)
    hop = 0
    for k in phases:
        sends = [k] if (not bidir or (2 * k == n)) else [k, n - k]
        with _hop_span(label, axis, hop, codec):
            for d in sends:
                send = _wire_take(wire, (i + d) % n)
                recv = _permute_wire(send, axis, perm_k(d))
                dec = codec.decode_rows(
                    type(wire)(*(leaf if leaf.size == 0 else leaf[None]
                                 for leaf in recv)), L, rows.dtype)[0]
                out = jax.lax.dynamic_update_index_in_dim(
                    out, dec[None], (i - d) % n, axis=0)
        hop += 1
    return out


def _ring2d_all_to_all_rows(rows: jax.Array, axis, codec: Codec) -> jax.Array:
    """Sub-ring factored 2D all-to-all of ``[n, L]`` destination rows
    (rank = u*b + v). Phase 1 exchanges a-row bundles on the intra (v)
    sub-ring grouped by target column; phase 2 exchanges b-row bundles on
    the inter (u) sub-ring grouped by target row. The wire is encoded once
    at the source and relayed through the middle hop in WIRE form."""
    n = axis_size(axis)
    L = rows.shape[1]
    if n == 1:
        return rows
    a, b = _factor_near_square(n)
    if a == 1 or b == 1:
        return _ring_all_to_all_rows(rows, axis, codec)
    i = jax.lax.axis_index(axis)
    u, v = i // b, i % b
    intra_k = lambda k: [(s, (s // b) * b + ((s % b) + k) % b) for s in range(n)]
    inter_k = lambda k: [(s, (((s // b) + k) % a) * b + (s % b)) for s in range(n)]

    wire = codec.encode_rows(rows)  # [n, ...] encoded once per destination
    zero_like = lambda leaf, lead: (leaf if leaf.size == 0
                                    else jnp.zeros((lead,) + leaf.shape[1:], leaf.dtype))
    # buf1[w] = the a-row bundle from intra peer (u, w): rows destined to
    # the ranks of column v, ordered by target row u'
    buf1 = type(wire)(*(zero_like(leaf, b * a) for leaf in wire))
    own_idx = jnp.arange(a) * b + v
    buf1 = _wire_update(buf1, _wire_take(wire, own_idx), v * a + jnp.arange(a))
    for k in range(1, b):
        dest_col = (v + k) % b
        bundle = _wire_take(wire, jnp.arange(a) * b + dest_col)
        with _hop_span("all_to_all:ring2d/intra", axis, k - 1, codec):
            recv = _permute_wire(bundle, axis, intra_k(k))
        src_col = (v - k) % b
        buf1 = _wire_update(buf1, recv, src_col * a + jnp.arange(a))
    # buf1 leaves are [b*a, ...]: index w*a + u' = (source (u, w)) -> (u', v)

    # phase 2: bundle by target row u' (for each source column w) and
    # exchange on the inter sub-ring; out rows ordered by global source rank
    out_wire = type(wire)(*(zero_like(leaf, n) for leaf in wire))
    own_rows = _wire_take(buf1, jnp.arange(b) * a + u)
    out_wire = _wire_update(out_wire, own_rows, u * b + jnp.arange(b))
    for k in range(1, a):
        dest_row = (u + k) % a
        bundle = _wire_take(buf1, jnp.arange(b) * a + dest_row)  # [b, ...]
        with _hop_span("all_to_all:ring2d/inter", axis, k - 1, codec):
            recv = _permute_wire(bundle, axis, inter_k(k))
        src_row = (u - k) % a
        out_wire = _wire_update(out_wire, recv, src_row * b + jnp.arange(b))
    out = codec.decode_rows(out_wire, L, rows.dtype)
    # the own row never crossed a link: keep it raw (lossless)
    return jax.lax.dynamic_update_index_in_dim(
        out, jax.lax.dynamic_index_in_dim(rows, i, axis=0), i, axis=0)


def all_to_all(x: jax.Array, axis, *, split_axis: int, concat_axis: int,
               algorithm: str = "ring", codec="none",
               block_size: Optional[int] = None) -> jax.Array:
    """Algorithmic all-to-all with ``lax.all_to_all(tiled=True)`` semantics:
    the ``split_axis`` dim divides into n blocks (block d to rank d) and the
    received blocks concatenate along ``concat_axis`` ordered by source
    rank. Must run inside full-manual shard_map.
    """
    if isinstance(axis, (tuple, list)):
        if len(axis) != 1:
            raise ValueError(f"algorithmic all_to_all takes one axis, got {axis}")
        axis = axis[0]
    if algorithm == "rhd":
        raise ValueError(
            "all_to_all has no recursive-halving schedule (every block has "
            "exactly one destination); use ring / bidir / ring2d")
    known = tuple(a for a in ALGORITHMS if a != "rhd") + PALLAS_ALGORITHMS
    if algorithm not in known:
        raise ValueError(f"unknown algorithm {algorithm!r} (one of {known})")
    c = get_codec(codec, block_size)
    n = axis_size(axis)
    if x.shape[split_axis] % n:
        raise ValueError(
            f"all_to_all split dim {x.shape[split_axis]} not divisible by "
            f"axis size {n}")
    m = x.shape[split_axis] // n
    moved = jnp.moveaxis(x, split_axis, 0)  # [n*m, *rest]
    rest = moved.shape[1:]
    rows = moved.reshape(n, -1)  # [n, L]: row d = the block destined to rank d

    if algorithm in PALLAS_ALGORITHMS:
        with pallas_backend.hop_scope():
            if algorithm == "pallas_ring":
                out_rows = _ring_all_to_all_rows(rows, axis, c)
            else:  # pallas_ring2d: the same a x b factorization
                out_rows = _ring2d_all_to_all_rows(rows, axis, c)
    elif algorithm == "ring":
        out_rows = _ring_all_to_all_rows(rows, axis, c)
    elif algorithm == "bidir":
        out_rows = _ring_all_to_all_rows(rows, axis, c, bidir=True)
    else:  # ring2d (names validated above)
        out_rows = _ring2d_all_to_all_rows(rows, axis, c)

    # assemble with tiled semantics: out_rows[s] = block from source s
    blocks = out_rows.reshape((n, m) + rest)      # [n, m, *rest] (moved order)
    blocks = jnp.moveaxis(blocks, 1, split_axis + 1)  # m back to split slot
    blocks = jnp.moveaxis(blocks, 0, concat_axis)     # n in front of concat dim
    shape = list(x.shape)
    shape[split_axis] = m
    shape[concat_axis] = shape[concat_axis] * n if concat_axis != split_axis else n * m
    return blocks.reshape(shape)


# ------------------------------------------------------------------ dispatch


def all_reduce(x: jax.Array, axis, *, algorithm: str = "ring", codec="none",
               op: str = "sum", block_size: Optional[int] = None) -> jax.Array:
    """Algorithmic all-reduce (sum/mean) of an arbitrary-shaped local array.

    ``axis`` may be one mesh-axis name or a tuple of them; tuples route
    ``ring2d`` (and any multi-axis call) through the mesh-axis-factored
    hierarchical path. Must run inside full-manual shard_map.
    """
    c = get_codec(codec, block_size)
    if op not in ("sum", "mean", "avg"):
        raise ValueError(f"reduce op {op!r} unsupported by algorithmic all_reduce")
    axes = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)
    flat = x.reshape(-1)
    if algorithm == "compiled" or algorithm.startswith("compiled:"):
        # synthesized hierarchical schedule (collectives/schedule.py): the
        # level programs run the same sub-ring machinery as ring2d, so this
        # branch only resolves WHICH levels
        from deepspeed_tpu.collectives import schedule as _schedule

        levels = _schedule.resolve(
            algorithm, "all_reduce", axes, x.size * x.dtype.itemsize,
            codec, x.dtype.itemsize, block_size)
        out = (_schedule.compiled_all_reduce(x, levels, block_size).reshape(-1)
               if levels else flat)
    elif algorithm in PALLAS_ALGORITHMS:
        # same schedules, remote-DMA hops (fused quantized hops on the
        # reduce phases — see collectives/pallas_backend.py); axis tuples
        # run the mesh-axis-factored hierarchy like every other algorithm
        with pallas_backend.hop_scope():
            if len(axes) > 1:
                out = _hier_all_reduce_axes(x, axes, c).reshape(-1)
            elif algorithm == "pallas_ring":
                out = _flat_all_reduce_ring(flat, axes[0], c)
            else:  # pallas_ring2d: the SAME a x b factorization
                out = _flat_all_reduce_ring2d(flat, axes[0], c)
    elif len(axes) > 1:
        out = _hier_all_reduce_axes(x, axes, c).reshape(-1)
    elif algorithm == "ring":
        out = _flat_all_reduce_ring(flat, axes[0], c)
    elif algorithm == "bidir":
        out = _flat_all_reduce_ring(flat, axes[0], c, bidir=True)
    elif algorithm == "rhd":
        out = _flat_all_reduce_rhd(flat, axes[0], c)
    elif algorithm == "ring2d":
        out = _flat_all_reduce_ring2d(flat, axes[0], c)
    else:
        raise ValueError(
            f"unknown algorithm {algorithm!r} (one of {ALGORITHMS + PALLAS_ALGORITHMS})")
    out = out.reshape(x.shape)
    if op in ("mean", "avg"):
        total = 1
        for a in axes:
            total *= axis_size(a)
        out = (out.astype(jnp.float32) / total).astype(x.dtype)
    return out


def all_gather(x: jax.Array, axis, *, algorithm: str = "ring", codec="none",
               concat_axis: int = 0, block_size: Optional[int] = None) -> jax.Array:
    if algorithm == "compiled" or algorithm.startswith("compiled:"):
        # the schedule compiler is the ONE algorithmic gather that takes
        # mesh-axis tuples: levels are rank-ordered (minor axis digit
        # first), so the output matches lax.all_gather over the same tuple
        from deepspeed_tpu.collectives import schedule as _schedule

        axes = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)
        levels = _schedule.resolve(
            algorithm, "all_gather", axes, x.size * x.dtype.itemsize,
            codec, x.dtype.itemsize, block_size)
        n = 1
        for a in axes:
            n *= axis_size(a)
        moved = jnp.moveaxis(x, concat_axis, 0)
        lead, rest = moved.shape[0], moved.shape[1:]
        flat = moved.reshape(-1)
        gathered = (_schedule.compiled_all_gather_flat(flat, levels, block_size)
                    if levels else flat)
        full = gathered.reshape((n * lead,) + rest)
        return jnp.moveaxis(full, 0, concat_axis)
    if isinstance(axis, (tuple, list)):
        if len(axis) != 1:
            raise ValueError(f"algorithmic all_gather takes one axis, got {axis}")
        axis = axis[0]
    c = get_codec(codec, block_size)
    if algorithm in PALLAS_ALGORITHMS:
        # gathers have no reduction to fuse: encode-once relay over
        # remote-DMA hops (ring2d degrades to ring, same as below)
        with pallas_backend.hop_scope():
            return ring_all_gather(x, axis, c, concat_axis=concat_axis)
    if algorithm == "ring":
        return ring_all_gather(x, axis, c, concat_axis=concat_axis)
    if algorithm == "bidir":
        return ring_all_gather(x, axis, c, concat_axis=concat_axis, bidir=True)
    if algorithm == "rhd":
        return rhd_all_gather(x, axis, c, concat_axis=concat_axis)
    if algorithm == "ring2d":
        # the hierarchy only exists for reductions: a non-reducing ring2d is
        # a plain ring (exactly what the cost model charges it as)
        return ring_all_gather(x, axis, c, concat_axis=concat_axis)
    raise ValueError(
        f"unknown algorithm {algorithm!r} (one of {ALGORITHMS + PALLAS_ALGORITHMS})")


def reduce_scatter(x: jax.Array, axis, *, algorithm: str = "ring", codec="none",
                   scatter_axis: int = 0, op: str = "sum",
                   block_size: Optional[int] = None,
                   err: Optional[jax.Array] = None):
    if err is not None and algorithm != "ring":
        raise ValueError(
            f"error feedback is implemented for algorithm='ring' only, got {algorithm!r}")
    if algorithm == "compiled" or algorithm.startswith("compiled:"):
        # tuple-axis capable, rank-ordered levels (see all_gather above);
        # tiled psum_scatter semantics: rank i gets slice i of the sum
        from deepspeed_tpu.collectives import schedule as _schedule

        axes = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)
        levels = _schedule.resolve(
            algorithm, "reduce_scatter", axes, x.size * x.dtype.itemsize,
            codec, x.dtype.itemsize, block_size)
        n = 1
        for a in axes:
            n *= axis_size(a)
        moved = jnp.moveaxis(x, scatter_axis, 0)
        lead, rest = moved.shape[0], moved.shape[1:]
        if lead % n:
            raise ValueError(
                f"reduce_scatter dim {lead} not divisible by axis size {n}")
        rows = moved.reshape(n, -1)
        red = (_schedule.compiled_reduce_scatter_rows(rows, levels, block_size)
               if levels else rows.reshape(-1))
        out = red.reshape((lead // n,) + rest).astype(x.dtype)
        out = jnp.moveaxis(out, 0, scatter_axis)
        if op in ("mean", "avg"):
            out = out / n
        elif op != "sum":
            raise ValueError(
                f"reduce op {op!r} unsupported by algorithmic reduce_scatter")
        return out
    if isinstance(axis, (tuple, list)):
        if len(axis) != 1:
            raise ValueError(f"algorithmic reduce_scatter takes one axis, got {axis}")
        axis = axis[0]
    c = get_codec(codec, block_size)
    if algorithm in PALLAS_ALGORITHMS:
        # remote-DMA hops; a fusable codec runs the EQuARX fused hop kernel
        # (ring2d degrades to ring for a lone reduce-scatter, same as below)
        with pallas_backend.hop_scope():
            return ring_reduce_scatter(x, axis, c, scatter_axis=scatter_axis, op=op)
    if algorithm == "ring":
        return ring_reduce_scatter(x, axis, c, scatter_axis=scatter_axis, op=op, err=err)
    if algorithm == "bidir":
        return ring_reduce_scatter(x, axis, c, scatter_axis=scatter_axis, op=op, bidir=True)
    if algorithm == "rhd":
        # rhd_reduce_scatter itself raises on non-power-of-two axes — an
        # explicit request must not silently measure ring instead
        return rhd_reduce_scatter(x, axis, c, scatter_axis=scatter_axis, op=op)
    if algorithm == "ring2d":
        # the hierarchy only exists for reductions over BOTH tiers at once:
        # a lone reduce-scatter rides the plain ring (the model's costing)
        return ring_reduce_scatter(x, axis, c, scatter_axis=scatter_axis, op=op)
    raise ValueError(
        f"unknown algorithm {algorithm!r} (one of {ALGORITHMS + PALLAS_ALGORITHMS})")
