"""Algorithm + codec selection: alpha-beta cost model and measured mode.

Reference analog: NCCL's tuner (latency/bandwidth tables per algorithm and
protocol picking tree vs ring per message size) and DeepSpeed's autotuner.
Here the model is the classic alpha-beta point-to-point model::

    T(alg) = hops * alpha  +  wire_bytes_on_link * beta

with per-algorithm hop counts and busiest-link byte volumes (ring moves
2(n-1)/n * S for all-reduce in n-1+n-1 serial hops; recursive
halving/doubling moves the same bytes in 2*log2(n) hops; ring2d's a x b
factorization trades hop count for two link tiers). Codecs scale the beta
term by their wire ratio (int8 ~ S/4 + scales vs fp32).

``measured`` mode replaces the model with timings: ``comm/benchmark.py
--sweep`` emits a JSON decision table (rows of op/world/size/algorithm/codec/
latency) and the selector picks the nearest-size winner. Either way every
(op, bytes-bucket, axis-size) query is answered once and cached — the cache
IS the decision table the facade consults per traced collective, and each
fresh decision emits a ``telemetry`` instant event so choices land in the
same Perfetto trace as the step.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass, field, replace as dc_replace
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu import telemetry
from deepspeed_tpu.collectives.algorithms import ALGORITHMS, _factor_near_square
from deepspeed_tpu.collectives.codecs import get_codec
from deepspeed_tpu.collectives.costmodel import CostModel
from deepspeed_tpu.collectives import pallas_backend
from deepspeed_tpu.collectives.pallas_backend import PALLAS_ALGORITHMS
from deepspeed_tpu.utils.logging import logger

OPS = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all")

# AxesSig: ((axis_name, axis_size), ...) — the mesh-axis factorization a
# query runs over. Part of the decision-cache key (two meshes with equal
# world size but different axis splits must not share entries) and the
# schedule compiler's search domain.
AxesSig = Tuple[Tuple[str, int], ...]


def _is_compiled(algorithm: str) -> bool:
    return algorithm == "compiled" or algorithm.startswith("compiled:")


@dataclass(frozen=True)
class Decision:
    """One cached (op, bytes-bucket, world) routing decision."""

    op: str
    algorithm: str
    codec: str
    est_us: float
    source: str  # "model" | "measured" | "config"


@dataclass
class SelectorConfig:
    """Tunables for the cost model + measured table (see the ``collectives``
    config block in ``config/config.py``)."""

    # "auto": measured when a decision table is loaded, the alpha-beta model
    # otherwise; "model"/"measured" pin one source explicitly.
    mode: str = "auto"  # auto | model | measured
    alpha_us: float = 1.0  # per-hop latency
    beta_us_per_mb: float = 10.0  # inverse link bandwidth (~100 GB/s)
    codecs: Tuple[str, ...] = ("none",)  # candidate wire codecs
    block_size: int = 2048
    decision_table: Optional[str] = None  # JSON path from benchmark --sweep
    # payloads below this skip quantization entirely (scales overhead + host
    # side compute dominate); matches ZeRO++'s "quantize the big tensors"
    min_quant_bytes: int = 1 << 16
    # payloads below this stay on the native lax lowering in model mode: a
    # tiny psum as 2(n-1) serial ppermute hops loses to XLA's built-in
    # collective at any alpha; the "lax" verdict is the model's analog of
    # measured mode's don't-bother rows
    min_algorithmic_bytes: int = 1 << 12
    # Alpha discount for the pallas remote-DMA hop primitive: a fused hop is
    # one kernel where the ppermute path dispatches encode + permute +
    # decode programs, so its per-hop launch overhead is lower. Candidates
    # only enter the model when pallas_backend.available() (a real TPU).
    pallas_alpha_scale: float = 0.5
    # Facade defaults (the `collectives` config block's algorithm/codec):
    # applied by comm.all_reduce/all_gather/reduce_scatter when the call
    # passes no explicit algorithm/codec. None = plain jax.lax lowering.
    facade_algorithm: Optional[str] = None  # "auto" | concrete name | None
    facade_codec: Optional[str] = None
    # Per-backend (alpha_us, beta_us_per_mb) overrides fitted from OBSERVED
    # hop timings (collectives/observatory.py refit -> calibrate()); keys
    # "ppermute" / "pallas" / "xla". When present they replace the static
    # alpha/beta (and the pallas_alpha_scale discount) for that backend's
    # candidates, so model mode re-costs from what this mesh measured.
    backend_ab: Dict[str, Tuple[float, float]] = field(default_factory=dict)
    # Let model mode SYNTHESIZE hierarchical schedules (collectives/
    # schedule.py) as candidates next to the hand-written menu. Off by
    # default: under a flat alpha-beta model a multi-level schedule
    # strictly dominates ring on hops at equal wire, so enabling it shifts
    # routing everywhere — an explicit opt-in (config `compiled_search`).
    compiled_search: bool = False


_lock = threading.Lock()
_config = SelectorConfig()
# THE shared alpha-beta object: selector estimates, observatory refits
# (calibrate below) and the schedule compiler's search objective all read
# this one instance. backend_ab is the SAME dict as _config.backend_ab, so
# existing get_config().backend_ab consumers see calibrations unchanged.
_cost_model = CostModel(backend_ab=_config.backend_ab)
_cache: Dict[tuple, Decision] = {}
_measured: List[dict] = []
_stats = {"hits": 0, "misses": 0}


def configure(config: Optional[SelectorConfig] = None, **kwargs) -> SelectorConfig:
    """Install selector tunables (process-global, like the telemetry tracer);
    clears the decision cache. Accepts a ``SelectorConfig`` or field kwargs."""
    global _config, _cost_model
    with _lock:
        # copy, never mutate the caller's template instance
        cfg = dc_replace(config, **kwargs) if config is not None else SelectorConfig(**kwargs)
        cfg.backend_ab = dict(cfg.backend_ab)  # calibrate() mutates in place
        _config = cfg
        # rebuild the shared cost model around the NEW config's constants,
        # handing it the same backend_ab dict so calibrate() keeps writing
        # through both handles
        _cost_model = CostModel(
            alpha_us=cfg.alpha_us, beta_us_per_mb=cfg.beta_us_per_mb,
            pallas_alpha_scale=cfg.pallas_alpha_scale,
            backend_ab=cfg.backend_ab)
        _cache.clear()
        _measured.clear()
        _stats["hits"] = _stats["misses"] = 0
    from deepspeed_tpu.collectives import schedule as _schedule

    # a fresh model instance orphans every cached compile (the cache keys
    # on model identity + version) — drop them eagerly
    _schedule.invalidate_cache()
    with _lock:
        if cfg.decision_table and cfg.mode != "model":
            from deepspeed_tpu.collectives.table import load_table

            try:
                # versioned envelope or legacy bare list; a schema-version
                # mismatch is rejected (with its own warning) inside
                # load_table and leaves _measured empty -> model fallback
                _measured.extend(load_table(cfg.decision_table))
            except (OSError, ValueError) as e:
                logger.warning(
                    f"collectives: decision table {cfg.decision_table!r} unreadable "
                    f"({e}); falling back to the alpha-beta model")
    return _config


def calibrate(backend: str, alpha_us: float, beta_us_per_mb: float) -> None:
    """Install OBSERVED per-backend alpha/beta constants (the observatory's
    least-squares refit lands here); clears the decision cache so future
    picks re-cost under the calibrated model. Survives until the next
    :func:`configure` (a fresh engine re-installs its config — persistent
    calibration rides the observatory's on-disk table instead)."""
    with _lock:
        # writes through the SHARED dict (_config.backend_ab is
        # _cost_model.backend_ab) and bumps the model's version, so cached
        # schedule compiles re-search under the refit constants
        _cost_model.calibrate(backend, alpha_us, beta_us_per_mb)
        _cache.clear()


def get_config() -> SelectorConfig:
    return _config


def cost_model() -> CostModel:
    """THE alpha-beta object: what ``estimate_us`` charges, ``calibrate``
    refits, and the schedule compiler searches under — one instance, by
    identity (the measured-vs-predicted loop tunes the search objective)."""
    return _cost_model


def cache_info() -> Dict[str, int]:
    with _lock:
        return {"entries": len(_cache), **_stats}


# ----------------------------------------------------------------- the model


def _hops_and_volume(op: str, algorithm: str, nbytes: int, n: int) -> Tuple[int, float]:
    """(serial hop count, bytes crossing the busiest link) for one op.

    ``nbytes`` is what the facade queries with: the LOCAL payload. For
    all_reduce / reduce_scatter that is the full pre-reduction array (link
    volume ``2(n-1)/n * S`` / ``(n-1)/n * S``); for all_gather it is the
    SHARD, of which every link relays n-1 peers' worth: ``(n-1) * s``.
    """
    # pallas algorithms run the SAME schedules as their base (identical hop
    # counts and link volumes) — only the hop primitive and the per-hop
    # alpha differ (applied in estimate_us)
    algorithm = pallas_backend.base_algorithm(algorithm)
    ring_steps = n - 1
    log_steps = max(int(math.ceil(math.log2(n))), 1) if n > 1 else 0
    frac = (n - 1) / n if n > 1 else 0.0
    if op == "all_reduce":
        base = 2 * frac * nbytes
    elif op == "all_gather":
        base = ring_steps * nbytes
    else:  # reduce_scatter / all_to_all: each rank ships (n-1)/n of S
        base = frac * nbytes
    if op == "all_to_all":
        # shift schedule: n-1 direct distance-k permutes of one destination
        # row each; bidir pairs mirror distances on full-duplex links;
        # ring2d is the Big-Send-off a x b sub-ring factorization —
        # (a-1)+(b-1) hops at S*((b-1)/b + (a-1)/a) wire volume. rhd has no
        # all-to-all form (every block has exactly one destination).
        if algorithm == "lax":
            return 0, base / 2
        if algorithm == "ring":
            return ring_steps, base
        if algorithm == "bidir":
            return max(-(-ring_steps // 2), 0), base / 2
        if algorithm == "ring2d":
            a, b = _factor_near_square(n)
            hops = (a - 1) + (b - 1)
            vol = nbytes * ((b - 1) / b + (a - 1) / a)
            return hops, vol
        raise ValueError(f"no cost model for op={op!r} algorithm={algorithm!r}")
    if algorithm == "lax":
        # the native XLA lowering: assume the best exact schedule the
        # hardware offers (bidirectional, so half the per-link volume) with
        # no per-hop dispatch penalty — the conservative baseline every
        # algorithmic candidate must beat, so exact-wire rerouting never
        # wins and quantized routing must earn its keep
        return 0, base / 2
    if op == "all_reduce":
        vol = base
        if algorithm == "ring":
            return 2 * ring_steps, vol
        if algorithm == "bidir":
            # two counter-rotating rings each carry half the payload
            return 2 * ring_steps, vol / 2
        if algorithm == "rhd":
            return 2 * log_steps, vol
        if algorithm == "ring2d":
            # the SAME factorization the execution path uses
            a, b = _factor_near_square(n)
            hops = (b - 1) + 2 * (a - 1) + (b - 1)
            vol = nbytes * ((b - 1) / b + 2 * (a - 1) / (a * b) + (b - 1) / b)
            return hops, vol
    else:  # all_gather / reduce_scatter
        vol = base
        if algorithm in ("ring", "ring2d"):
            return ring_steps, vol
        if algorithm == "bidir":
            return ring_steps, vol / 2
        if algorithm == "rhd":
            return log_steps, vol
    raise ValueError(f"no cost model for op={op!r} algorithm={algorithm!r}")


def model_terms(op: str, algorithm: str, codec: str, nbytes: int, n: int,
                itemsize: int = 4, block_size: Optional[int] = None,
                cfg: Optional[SelectorConfig] = None) -> Tuple[int, float]:
    """(hops, wire_mb) — THE regressors of the alpha-beta model.
    ``estimate_us`` charges exactly ``hops*alpha + wire_mb*beta`` from
    these, and the observatory's refit fits observed latencies against the
    SAME terms — one formula, or fitted constants would be applied to
    different regressors than they were fit against."""
    cfg = cfg or _config
    if _is_compiled(algorithm):
        # synthesized schedules carry per-level codecs in the signature;
        # the codec argument is the row's stamped (lossiest) codec and the
        # terms come from the schedule IR under the shared cost model
        from deepspeed_tpu.collectives import schedule as _schedule

        sig = algorithm.split(":", 1)[1]
        if not sig:
            raise ValueError("model_terms needs a concrete compiled:<sig>")
        return _schedule.signature_terms(
            op, sig, nbytes, itemsize,
            block_size if block_size is not None else cfg.block_size,
            cm=_cost_model)
    hops, vol = _hops_and_volume(op, algorithm, nbytes, n)
    c = get_codec(codec, block_size if block_size is not None else cfg.block_size)
    wire = c.wire_bytes(max(int(vol // itemsize), 1), itemsize)
    return hops, wire / 1e6


def estimate_us(op: str, algorithm: str, codec: str, nbytes: int, n: int,
                cfg: Optional[SelectorConfig] = None, itemsize: int = 4) -> float:
    """Alpha-beta time estimate for one (algorithm, codec) pair.

    ``itemsize`` is the payload element width: the link volume converts to
    an element count before the codec's wire-byte model applies, so a bf16
    payload's int8 wire is costed at ~1/2, not the fp32 default's ~1/4."""
    cfg = cfg or _config
    hops, wire_mb = model_terms(op, algorithm, codec, nbytes, n, itemsize,
                                cfg=cfg)
    fitted = cfg.backend_ab.get(pallas_backend.hop_backend(algorithm))
    if fitted is not None:
        # observed constants (observatory refit) replace the static model —
        # including the pallas alpha discount, which the fit subsumes
        alpha, beta = fitted
    else:
        alpha = cfg.alpha_us * (cfg.pallas_alpha_scale
                                if pallas_backend.is_pallas(algorithm) else 1.0)
        beta = cfg.beta_us_per_mb
    return hops * alpha + wire_mb * beta


def _model_pick(op: str, nbytes: int, n: int, codec: Optional[str],
                cfg: SelectorConfig, itemsize: int = 4,
                axes_sig: Optional[AxesSig] = None) -> Decision:
    if nbytes < cfg.min_algorithmic_bytes and codec in (None, "none"):
        # the native lowering cannot apply a wire codec, so the lax floor
        # only covers queries that didn't force one
        return Decision(op, "lax", "none", 0.0, "model")
    codecs = (codec,) if codec else tuple(cfg.codecs) or ("none",)
    if codec is None and nbytes < cfg.min_quant_bytes:
        # small payloads never auto-quantize (scale overhead dominates); the
        # exact wire is always a legal candidate even when the configured
        # candidate list is all-lossy (e.g. codecs=["int8"])
        codecs = tuple(c for c in codecs if c == "none") or ("none",)
    pow2 = n > 0 and not (n & (n - 1))
    # the native lowering is a candidate whenever no lossy codec is forced:
    # an exact-wire algorithmic collective moves the same bytes as XLA's
    # fused native one plus hop latency, so it can only win by shrinking
    # the wire — but a FORCED lossy codec needs an algorithmic carrier
    best: Optional[Decision] = None
    if codec in (None, "none"):
        best = Decision(op, "lax", "none",
                        estimate_us(op, "lax", "none", nbytes, n, cfg, itemsize),
                        "model")
    candidates = ALGORITHMS + (PALLAS_ALGORITHMS if pallas_backend.available() else ())
    for alg in candidates:
        if alg == "rhd" and (not pow2 or op == "all_to_all"):
            continue
        for cd in codecs:
            est = estimate_us(op, alg, cd, nbytes, n, cfg, itemsize)
            if best is None or est < best.est_us:
                best = Decision(op, alg, cd, est, "model")
    if cfg.compiled_search and axes_sig:
        from deepspeed_tpu.collectives import schedule as _schedule

        if op in _schedule.SCHEDULED_OPS:
            for cd in codecs:
                sched = _schedule.compile_schedule(
                    op, axes_sig, nbytes, cd, itemsize=itemsize,
                    block_size=cfg.block_size, cm=_cost_model)
                if sched is None:
                    continue
                # the decision's codec is the schedule's LOSSIEST level
                # (what actually hits a wire), not the search input — a
                # mixed placement may keep cd off the inner rings entirely
                stamped = _schedule.signature_codec(sched.signature)
                if best is None or sched.est_us < best.est_us:
                    best = Decision(op, f"compiled:{sched.signature}",
                                    stamped, sched.est_us, "model")
    assert best is not None
    return best


def _row_mesh_ok(r: dict, op: str, axes_sig: Optional[AxesSig]) -> bool:
    """A ``compiled:<sig>`` row names mesh axes and their factor sizes: it
    may only route onto a query whose axis tuple the signature actually
    factors (and, for rank-ordered ops, in executable order). Hand-written
    algorithm rows are mesh-shape-agnostic — the world-size match the
    caller already did is all they claim."""
    alg = str(r.get("algorithm", ""))
    if not _is_compiled(alg):
        return True
    if ":" not in alg or axes_sig is None:
        return False
    from deepspeed_tpu.collectives import schedule as _schedule

    try:
        levels = _schedule.parse_signature(alg.split(":", 1)[1])
        _schedule._validate_levels(levels, axes_sig, op)
    except ValueError:
        return False
    return True


def _measured_pick(op: str, nbytes: int, n: int, codec: Optional[str],
                   cfg: SelectorConfig, itemsize: int = 4,
                   axes_sig: Optional[AxesSig] = None) -> Optional[Decision]:
    if codec is not None:
        allowed = {codec}
    else:
        # same guardrails as the model path: only configured codec
        # candidates, and never a lossy wire under min_quant_bytes —
        # measured rows for a bigger bucket must not smuggle one in
        allowed = set(cfg.codecs) | {"none"}
        if nbytes < cfg.min_quant_bytes:
            allowed = {"none"}
    rows = [r for r in _measured
            if r.get("op") == op and int(r.get("world", 0)) == n
            and r.get("codec", "none") in allowed and _row_backend_ok(r)
            and _row_mesh_ok(r, op, axes_sig)]
    # a mixed-itemsize table (online rows + sweeps at different dtypes)
    # keeps separate rows per element width because a lossy wire costs per
    # ELEMENT: answer from rows measured at the querying payload's width
    # when any exist; tables without itemsize coverage keep the legacy
    # any-row behavior rather than starving measured mode
    # legacy rows default to the historical sweep width (bf16, 2) — the
    # same default table.row_key uses, so they stay visible to bf16 queries
    same_width = [r for r in rows if int(r.get("itemsize", 2)) == int(itemsize)]
    rows = same_width or rows
    if not rows:
        return None
    size_mb = nbytes / 1e6

    def closeness(r):
        return abs(math.log((float(r["size_mb"]) + 1e-9) / (size_mb + 1e-9)))

    nearest = min(closeness(r) for r in rows)
    bucket = [r for r in rows if closeness(r) <= nearest + 1e-12]
    win = min(bucket, key=lambda r: float(r["latency_ms"]))
    return Decision(op, win["algorithm"], win.get("codec", "none"),
                    float(win["latency_ms"]) * 1e3, "measured")


def _row_backend_ok(r: dict) -> bool:
    """A decision-table row may only route algorithms of the hop backend it
    was MEASURED with (``--sweep`` stamps ``backend``): ppermute timings say
    nothing about remote-DMA hop counts and vice versa. Un-stamped legacy
    rows are ppermute-era sweeps; a pallas algorithm in one is a schema
    mismatch and never routes. ``lax`` rows (stamped ``xla``) are
    backend-neutral don't-bother verdicts. Pallas rows additionally need
    the backend to be usable in THIS process."""
    alg = str(r.get("algorithm", ""))
    stamp = r.get("backend", "ppermute")
    if alg == "lax":
        return True
    implied = "pallas" if pallas_backend.is_pallas(alg) else "ppermute"
    if stamp != implied:
        return False
    return implied != "pallas" or pallas_backend.available()


def pick_codec(op: str, nbytes: int, axis_size: int, algorithm: str,
               itemsize: int = 4) -> str:
    """Best wire codec from the configured candidates for a FORCED
    algorithm (the config block's concrete ``algorithm`` + ``codec: auto``
    combination) — same guardrails as the joint model pick."""
    cfg = _config
    if nbytes < cfg.min_quant_bytes:
        return "none"
    if algorithm not in ALGORITHMS + PALLAS_ALGORITHMS:
        algorithm = "ring"
    alg = algorithm
    candidates = tuple(cfg.codecs) or ("none",)
    return min(candidates,
               key=lambda cd: estimate_us(op, alg, cd, nbytes, axis_size, cfg, itemsize))


def _bytes_bucket(nbytes: int) -> int:
    """Power-of-two size bucket so near-identical payloads share a cache
    entry (and one telemetry decision event)."""
    return max(int(nbytes), 1).bit_length()


def select(op: str, nbytes: int, axis_size: int, codec: Optional[str] = None,
           itemsize: int = 4, axes_sig: Optional[AxesSig] = None) -> Decision:
    """Pick (algorithm, codec) for one collective; cached per
    (op, bytes-bucket, axis-size, mesh factorization, payload itemsize
    [, forced codec])."""
    if op not in OPS:
        raise ValueError(f"unknown op {op!r} (one of {OPS})")
    # the hop backend is part of the decision's identity: a cache warmed
    # while pallas hops were unavailable must not answer for a process (or
    # restored table) where they are, and vice versa. So is the mesh-axis
    # FACTORIZATION (axes_sig): two meshes with equal world size but
    # different axis splits — (("dp", 8),) vs (("dp", 4), ("ep", 2)) — take
    # different schedules, so they must not share a cache entry (and a
    # legacy axes_sig-less query must not answer a factorized one).
    key = (op, _bytes_bucket(nbytes), int(axis_size), axes_sig, codec,
           int(itemsize), pallas_backend.backend_token())
    with _lock:
        hit = _cache.get(key)
        if hit is not None:
            _stats["hits"] += 1
            return hit
        _stats["misses"] += 1
        cfg = _config
    decision = None
    if nbytes < cfg.min_algorithmic_bytes and codec in (None, "none"):
        # the lax floor applies in EVERY mode: a measured table's smallest
        # swept size must not extrapolate onto tiny step-critical psums.
        # A FORCED lossy codec needs an algorithmic path, so it bypasses it.
        decision = Decision(op, "lax", "none", 0.0, "model")
    elif cfg.mode == "measured" or (cfg.mode == "auto" and _measured):
        decision = _measured_pick(op, nbytes, axis_size, codec, cfg, itemsize,
                                  axes_sig)
    if decision is None:
        decision = _model_pick(op, nbytes, axis_size, codec, cfg, itemsize,
                               axes_sig)
    with _lock:
        decision = _cache.setdefault(key, decision)
    tracer = telemetry.get_tracer()
    if tracer.enabled:
        tracer.instant("coll:select", cat="coll", op=op, bytes=int(nbytes),
                       world=int(axis_size), algorithm=decision.algorithm,
                       codec=decision.codec, est_us=round(decision.est_us, 3),
                       source=decision.source)
    return decision
