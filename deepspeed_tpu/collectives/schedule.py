"""Collective schedule compiler: synthesized hop programs (the GC3 shape).

``algorithms.py`` picks among four HAND-WRITTEN schedules; ``ring2d``
hard-codes one two-level factorization of one axis. This module replaces the
menu with a search: given (op, mesh-axis tuple, payload bytes, codec) it
enumerates **hierarchical schedules** — ordered sub-ring factorizations of
every axis, mixed intra/inter orderings, per-level codec placement (exact
inner rings, lossy outer rings: the ZeRO++ shape) — costs each candidate
with the selector's :class:`~deepspeed_tpu.collectives.costmodel.CostModel`
(the SAME object the observatory refit calibrates, so a live refit re-aims
the search), and emits the winner as the hop-scope sub-ring programs
``algorithms.py`` already runs (``_ring_reduce_scatter_rows(sub=...)`` /
``_ring_all_gather_flat(sub=...)`` — ppermute hops, or Pallas remote-DMA /
fused hops inside a hop scope).

Schedule IR
-----------
A schedule is a tuple of :class:`Level`, in PROCESSING order (level 0 runs
first = the innermost ring). Each level is one ring pass over a sub-ring of
one mesh axis: ``size`` members at ``stride`` within the axis (member digit
``(axis_index // stride) % size``). Strides follow the signature convention:
a level's stride is the product of the sizes of PRIOR levels on the same
axis, so the string form needs no explicit strides::

    dp*2.none/dp*4.int8      # dp=8: exact stride-1 ring of 2, int8 stride-2
                             # ring of 4 (ZeRO++: exact intra, lossy inter)
    ep*2.none/dp*4.none      # mesh tuple ("dp","ep"): inner ep, outer dp

Semantics per op (all telescoping to the flat ring's wire volume, with
``sum(m_j - 1)`` hops instead of ``n - 1``):

- ``all_reduce``      — recursive RS(level j) ... AR(rest) ... AG(level j);
  any level order is valid (the sum commutes), so orderings are SEARCHED.
- ``all_gather`` / ``reduce_scatter`` — level order is FIXED by output rank
  order (minor rank digit first: last mesh axis, stride-1 first); only the
  per-axis factorizations and codec placement are searched.

Determinism: the search is a pure function of its arguments and the cost
model's constants; ties break by (fewer lossy levels, signature string), so
equal-cost candidates resolve identically everywhere — and on a free inner
tier (``tier_beta_scale``) the tie-break IS what surfaces the ZeRO++
exact-intra/lossy-inter placement over lossy-everywhere.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from deepspeed_tpu import telemetry
from deepspeed_tpu.collectives.algorithms import (
    _pad_to_chunks,
    _ring_all_gather_flat,
    _ring_reduce_scatter_rows,
)
from deepspeed_tpu.collectives.codecs import get_codec
from deepspeed_tpu.collectives.costmodel import CostModel
from deepspeed_tpu.utils.compat import axis_size

# AxesSig: the mesh-axis tuple a collective runs over, with sizes —
# (("dp", 8),) or (("dp", 4), ("ep", 2)). THE factorization identity the
# selector's decision cache must key on (two meshes with equal world size
# but different axis splits get different schedules).
AxesSig = Tuple[Tuple[str, int], ...]

SCHEDULED_OPS = ("all_reduce", "all_gather", "reduce_scatter")

# search bounds: factor chains per axis and total level count are small on
# purpose — every level is a serial ring pass, and past ~4 levels the alpha
# term eats any wire win at realistic world sizes
_MAX_LEVELS = 4
_MAX_ORDERS = 24  # AR level-order permutations evaluated (4! covers depth 4)

# lossiness rank for signature_codec (wire compression order): the stamped
# codec of a mixed schedule is its LOSSIEST level, so the selector's
# min_quant_bytes / allowed-codec guardrails see the worst wire it applies
_LOSSY_RANK = {"none": 0, "fp32": 1, "bf16": 2, "fp8": 3, "int8": 4}


@dataclass(frozen=True)
class Level:
    """One ring pass: a ``size``-member sub-ring at ``stride`` within
    ``axis``, with its own wire ``codec``."""

    axis: str
    size: int
    stride: int
    codec: str


@dataclass(frozen=True)
class CompiledSchedule:
    """A search winner: the executable levels plus the model's verdict."""

    op: str
    signature: str
    levels: Tuple[Level, ...]
    est_us: float
    hops: int
    wire_mb: float
    candidates: int  # search-space size actually costed


# ----------------------------------------------------------------- signatures


def format_signature(levels: Sequence[Level]) -> str:
    return "/".join(f"{lv.axis}*{lv.size}.{lv.codec}" for lv in levels)


def parse_signature(sig: str) -> Tuple[Level, ...]:
    """``axis*size.codec`` terms, "/"-joined, processing order; strides are
    derived (cumulative product of prior same-axis sizes)."""
    levels: List[Level] = []
    strides: Dict[str, int] = {}
    for term in sig.split("/"):
        try:
            ax, rest = term.split("*", 1)
            size_s, codec = rest.split(".", 1)
            size = int(size_s)
        except ValueError:
            raise ValueError(
                f"bad schedule signature term {term!r} in {sig!r} "
                "(want axis*size.codec, e.g. dp*4.int8)") from None
        if size < 1 or not ax:
            raise ValueError(f"bad schedule signature term {term!r} in {sig!r}")
        if size > 1:  # size-1 levels are no-ops; never emitted, always legal
            levels.append(Level(ax, size, strides.get(ax, 1), codec))
        strides[ax] = strides.get(ax, 1) * size
    if not levels:
        raise ValueError(f"schedule signature {sig!r} has no non-trivial level")
    return tuple(levels)


def signature_codec(sig: str) -> str:
    """The lossiest per-level codec — what a decision-table row for
    ``compiled:<sig>`` is stamped with (selector codec guardrails)."""
    worst = "none"
    for lv in parse_signature(sig):
        if _LOSSY_RANK.get(lv.codec, 99) > _LOSSY_RANK.get(worst, 99):
            worst = lv.codec
    return worst


def _validate_levels(levels: Sequence[Level], axes_sig: AxesSig, op: str) -> None:
    sizes: Dict[str, int] = {}
    for lv in levels:
        sizes[lv.axis] = sizes.get(lv.axis, 1) * lv.size
    want = {name: n for name, n in axes_sig}
    if sizes != {k: v for k, v in want.items() if v > 1}:
        raise ValueError(
            f"schedule {format_signature(levels)!r} does not factor the mesh "
            f"axes {axes_sig} (covers {sizes})")
    if op in ("all_gather", "reduce_scatter"):
        canon = _canonical_axis_order(axes_sig)
        got = [lv.axis for lv in levels]
        # rank order fixes BOTH the axis grouping (contiguous, minor axis
        # first) and the within-axis stride order (stride-increasing falls
        # out of the signature convention once each axis is contiguous)
        if got != sorted(got, key=canon.index):
            raise ValueError(
                f"{op} schedule {format_signature(levels)!r} is not in rank "
                f"order (minor digit first: {'/'.join(canon)}); only "
                "all_reduce may reorder levels")


def _canonical_axis_order(axes_sig: AxesSig) -> List[str]:
    """Axes minor-digit-first: lax's tuple collectives order output by the
    FIRST listed axis major, so the innermost ring lives on the LAST axis."""
    return [name for name, _n in reversed(axes_sig)]


# ---------------------------------------------------------------- the search


def _factor_chains(n: int, max_factors: int) -> List[Tuple[int, ...]]:
    """All ordered chains of factors >= 2 with product n (incl. ``(n,)``)."""
    if n == 1:
        return [()]
    out: List[Tuple[int, ...]] = []

    def rec(rem: int, acc: Tuple[int, ...]):
        if rem == 1:
            out.append(acc)
            return
        if len(acc) == max_factors - 1:
            out.append(acc + (rem,))
            return
        f = 2
        while f <= rem:
            if rem % f == 0:
                rec(rem // f, acc + (f,))
            f += 1

    rec(n, ())
    # deterministic enumeration order (rec already is, but make it explicit)
    return sorted(set(out))


def _level_plans(op: str, axes_sig: AxesSig) -> List[Tuple[Level, ...]]:
    """Codec-free level tuples to cost: per-axis factor chains in canonical
    order, plus (all_reduce only) bounded level-order permutations. Strides
    are re-derived per plan from the signature convention, so a permuted
    plan is itself a valid factorization."""
    live = [(name, n) for name, n in axes_sig if n > 1]
    if not live:
        return []
    budget = max(_MAX_LEVELS - (len(live) - 1), 1)
    per_axis = [
        [chain for chain in _factor_chains(n, budget)] for _name, n in live]
    plans: List[Tuple[Level, ...]] = []
    order = _canonical_axis_order(tuple(live))
    for combo in itertools.product(*per_axis):
        if sum(len(c) for c in combo) > _MAX_LEVELS:
            continue
        chain_of = dict(zip([name for name, _ in live], combo))
        base = [(ax, m) for ax in order for m in chain_of[ax]]
        seqs = [base]
        if op == "all_reduce" and len(base) > 1:
            seqs = list(itertools.islice(
                itertools.permutations(base), _MAX_ORDERS))
        for seq in seqs:
            strides: Dict[str, int] = {}
            levels = []
            for ax, m in seq:
                levels.append(Level(ax, m, strides.get(ax, 1), "none"))
                strides[ax] = strides.get(ax, 1) * m
            plans.append(tuple(levels))
    # dedupe permutations that collide (repeated equal factors)
    return list(dict.fromkeys(plans))


def _codec_placements(levels: Tuple[Level, ...], codec: Optional[str]
                      ) -> List[Tuple[Level, ...]]:
    """Per-level codec assignments: exact everywhere, or ``codec`` on the
    OUTER levels from some boundary out (the ZeRO++ placement family —
    inner rings are the fast links where an exact wire stays cheap). A
    forced lossy codec always lands on at least the outermost level."""
    if codec in (None, "none"):
        return [levels]
    out = []
    for k in range(len(levels)):  # k = first lossy level index
        out.append(tuple(
            Level(lv.axis, lv.size, lv.stride, codec if i >= k else "none")
            for i, lv in enumerate(levels)))
    return out


def level_terms(op: str, levels: Sequence[Level], nbytes: int,
                itemsize: int = 4, block_size: Optional[int] = None,
                cm: Optional[CostModel] = None
                ) -> Tuple[int, float]:
    """(hops, effective wire_mb) for a schedule — the SAME two regressors
    ``selector.model_terms`` returns for hand-written algorithms, so
    ``estimate_us``/the observatory refit treat compiled rows identically.
    ``nbytes`` follows the selector's convention: the LOCAL payload (full
    pre-reduction array for all_reduce/reduce_scatter, the shard for
    all_gather). Tier beta scaling folds into wire_mb (level 0 = tier 0)."""
    if op not in SCHEDULED_OPS:
        raise ValueError(f"no schedule form for op {op!r} (one of {SCHEDULED_OPS})")
    cm = cm if cm is not None else CostModel()
    scales = cm.tier_beta_scale
    hops = 0
    wire_mb = 0.0
    payload = float(nbytes)  # shrinks (AR/RS) or grows (AG) through levels
    for depth, lv in enumerate(levels):
        m = lv.size
        if op == "all_reduce":
            level_hops, vol = 2 * (m - 1), 2.0 * (m - 1) / m * payload
            payload /= m
        elif op == "reduce_scatter":
            level_hops, vol = m - 1, (m - 1) / m * payload
            payload /= m
        else:  # all_gather: each link relays m-1 blocks of the current size
            level_hops, vol = m - 1, (m - 1) * payload
            payload *= m
        c = get_codec(lv.codec, block_size)
        wire = c.wire_bytes(max(int(vol // itemsize), 1), itemsize)
        scale = scales[min(depth, len(scales) - 1)] if scales else 1.0
        hops += level_hops
        wire_mb += scale * wire / 1e6
    return hops, wire_mb


def signature_terms(op: str, sig: str, nbytes: int, itemsize: int = 4,
                    block_size: Optional[int] = None,
                    cm: Optional[CostModel] = None) -> Tuple[int, float]:
    """``level_terms`` from a ``compiled:<sig>`` string (the selector's
    ``model_terms`` delegates here for compiled algorithms)."""
    return level_terms(op, parse_signature(sig), nbytes, itemsize,
                       block_size, cm)


# compile cache: (search inputs, cost-model identity+version) -> winner.
# cm.version bumps on every calibrate()/tier change, so a live observatory
# refit invalidates exactly the schedules whose objective moved.
_cache_lock = threading.Lock()
_compile_cache: Dict[tuple, CompiledSchedule] = {}


def invalidate_cache() -> None:
    with _cache_lock:
        _compile_cache.clear()


def _bytes_bucket(nbytes: int) -> int:
    return max(int(nbytes), 1).bit_length()


def compile_schedule(op: str, axes_sig: AxesSig, nbytes: int,
                     codec: Optional[str] = None, *, itemsize: int = 4,
                     block_size: Optional[int] = None,
                     cm: Optional[CostModel] = None,
                     backend: str = "ppermute") -> Optional[CompiledSchedule]:
    """Search factorizations x orderings x codec placements; return the
    cheapest schedule under ``cm`` (None when the mesh tuple is trivial —
    world size 1 has nothing to schedule). Deterministic: equal-cost
    candidates resolve by (fewer lossy levels, signature string)."""
    if op not in SCHEDULED_OPS:
        return None
    axes_sig = tuple((str(a), int(n)) for a, n in axes_sig)
    if not axes_sig or all(n <= 1 for _a, n in axes_sig):
        return None
    cm = cm if cm is not None else _selector_cost_model()
    key = (op, axes_sig, _bytes_bucket(nbytes), codec, int(itemsize),
           block_size, backend, id(cm), cm.version)
    with _cache_lock:
        hit = _compile_cache.get(key)
    if hit is not None:
        return hit
    best = None
    best_key = None
    n_cand = 0
    for plan in _level_plans(op, axes_sig):
        for levels in _codec_placements(plan, codec):
            hops, wire_mb = level_terms(op, levels, nbytes, itemsize,
                                        block_size, cm)
            est = cm.estimate_us(hops, wire_mb, backend)
            n_cand += 1
            sig = format_signature(levels)
            lossy = sum(1 for lv in levels if lv.codec != "none")
            k = (est, lossy, sig)
            if best_key is None or k < best_key:
                best_key = k
                best = CompiledSchedule(op, sig, tuple(levels), est, hops,
                                        wire_mb, 0)
    if best is None:
        return None
    best = CompiledSchedule(best.op, best.signature, best.levels, best.est_us,
                            best.hops, best.wire_mb, n_cand)
    with _cache_lock:
        best = _compile_cache.setdefault(key, best)
    tracer = telemetry.get_tracer()
    if tracer.enabled:
        reg = tracer.registry
        reg.counter("coll/schedule_compiles").add(1)
        reg.gauge("coll/schedule_candidates", op=op).set(float(n_cand))
        reg.gauge("coll/schedule_pred_us", op=op).set(float(best.est_us))
        reg.gauge("coll/schedule_levels", op=op).set(float(len(best.levels)))
    return best


def _selector_cost_model() -> CostModel:
    from deepspeed_tpu.collectives import selector

    return selector.cost_model()


def candidate_signatures(op: str, axis: str, world: int,
                         codecs: Sequence[str] = ("none",),
                         nbytes: int = 1 << 20,
                         itemsize: int = 2) -> List[str]:
    """A bounded set of schedules worth MEASURING for one (op, axis, world):
    the search winner per codec class at a representative payload. Feeds
    ``benchmark.candidate_pairs`` so sweeps/probes stamp
    ``algorithm="compiled:<sig>"`` rows and measured mode can prefer or
    demote a synthesized schedule per bytes-bucket like any hand-written
    one. Flat exact single-level winners are skipped (they time identically
    to ``ring``, which is already swept)."""
    if op not in SCHEDULED_OPS or world <= 1:
        return []
    out: List[str] = []
    for cd in dict.fromkeys(tuple(codecs) + ("none",)):
        sched = compile_schedule(op, ((axis, world),), nbytes, cd,
                                 itemsize=itemsize)
        if sched is None:
            continue
        trivial = len(sched.levels) == 1 and all(
            lv.codec == "none" for lv in sched.levels)
        if not trivial and sched.signature not in out:
            out.append(sched.signature)
    return out[:3]


# ---------------------------------------------------------------- execution
#
# Every level runs through the existing sub-ring machinery
# (algorithms._ring_reduce_scatter_rows / _ring_all_gather_flat with
# ``sub=``), so compiled schedules inherit the whole transport stack:
# facade-ppermute hops by default, remote-DMA / fused Pallas hops inside a
# pallas_backend.hop_scope, codecs and their telemetry spans per hop.


def _sub(level: Level, label: str):
    """The ``sub=(n, rank, perm, label)`` handle for one level's sub-ring."""
    total = axis_size(level.axis)
    m, st = level.size, level.stride
    perm = []
    for s in range(total):
        d = (s // st) % m
        perm.append((s, s - st * d + st * ((d + 1) % m)))
    import jax

    idx = jax.lax.axis_index(level.axis) if total > 1 else 0
    rank = (idx // st) % m
    return (m, rank, perm,
            f"{label}:compiled/{level.axis}*{level.size}s{level.stride}")


def _ar_levels(flat, levels: Sequence[Level], block_size: Optional[int],
               out_dtype):
    """Recursive hierarchical all-reduce of a flat payload: RS over level 0,
    all-reduce the shard over the remaining levels, AG back over level 0 —
    the ``_flat_all_reduce_ring2d`` recursion generalized to any depth,
    per-level codecs included."""
    lv = levels[0]
    codec = get_codec(lv.codec, block_size)
    sub = _sub(lv, "all_reduce")
    padded, N, _chunk = _pad_to_chunks(flat, lv.size)
    shard, _ = _ring_reduce_scatter_rows(
        padded.reshape(lv.size, -1), lv.axis, codec, sub=sub)
    if len(levels) > 1:
        shard = _ar_levels(shard, levels[1:], block_size, out_dtype).reshape(-1)
    else:
        shard = shard.astype(out_dtype)
    gathered = _ring_all_gather_flat(shard, lv.axis, codec, sub=sub)
    return gathered.reshape(-1)[:N].astype(out_dtype)


def compiled_all_reduce(x, levels: Sequence[Level],
                        block_size: Optional[int] = None):
    flat = x.reshape(-1)
    return _ar_levels(flat, list(levels), block_size, x.dtype).reshape(x.shape)


def compiled_all_gather_flat(block, levels: Sequence[Level],
                             block_size: Optional[int] = None):
    """``[L] -> [n*L]`` in global rank order: gather the minor rank digit
    first, each gathered block becoming the next level's payload (levels
    must be rank-ordered — validated at resolve time)."""
    for lv in levels:
        codec = get_codec(lv.codec, block_size)
        block = _ring_all_gather_flat(
            block, lv.axis, codec, sub=_sub(lv, "all_gather")).reshape(-1)
    return block


def compiled_reduce_scatter_rows(rows, levels: Sequence[Level],
                                 block_size: Optional[int] = None):
    """``[n, L]`` destination rows -> this rank's summed row ``[L]``: each
    level bundles rows by the level's rank digit (minor first) and
    reduce-scatters the bundles on its sub-ring, shrinking the working set
    by 1/size per level — the transpose-regroup recursion."""
    for lv in levels:
        m = lv.size
        rest, L = rows.shape[0] // m, rows.shape[1]
        bundles = rows.reshape(rest, m, L).transpose(1, 0, 2).reshape(m, rest * L)
        codec = get_codec(lv.codec, block_size)
        shard, _ = _ring_reduce_scatter_rows(
            bundles, lv.axis, codec, sub=_sub(lv, "reduce_scatter"))
        rows = shard.reshape(rest, L)
    return rows.reshape(-1)


def resolve(algorithm: str, op: str, axes: Sequence[str], nbytes: int,
            codec, itemsize: int, block_size: Optional[int]
            ) -> Tuple[Level, ...]:
    """Turn ``"compiled"`` (search here, at trace time — deterministic and
    cached) or ``"compiled:<sig>"`` (parse + validate) into executable
    levels for the bound mesh axes."""
    axes_sig = tuple((str(a), int(axis_size(a))) for a in axes)
    if all(n <= 1 for _a, n in axes_sig):
        return ()
    if algorithm == "compiled":
        cd = codec if isinstance(codec, str) else getattr(codec, "name", None)
        sched = compile_schedule(op, axes_sig, nbytes, cd, itemsize=itemsize,
                                 block_size=block_size)
        assert sched is not None  # non-trivial axes_sig checked above
        return sched.levels
    sig = algorithm.split(":", 1)[1]
    if not sig:
        raise ValueError(f"empty compiled schedule signature in {algorithm!r}")
    levels = parse_signature(sig)
    _validate_levels(levels, axes_sig, op)
    return levels
