"""Capture a jax.profiler trace of the engine train step; parse trace.json.gz
for the device-op breakdown."""

from __future__ import annotations

import collections
import glob
import gzip
import json
import shutil

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, causal_lm_spec


def main():
    cfg = TransformerConfig(
        vocab_size=50304, hidden_size=768, intermediate_size=3072,
        num_layers=12, num_heads=12, max_seq_len=1024,
        norm="layernorm", activation="gelu", position="learned",
        tie_embeddings=True, dtype=jnp.bfloat16,
    )
    micro, seq = 8, 1024
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": micro,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 1},
            "bf16": {"enabled": True},
            "steps_per_print": 10_000,
        },
    )
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}
    placed = engine._shard_global_batch(batch)
    state = engine.state
    step_fn = engine._train_step
    for _ in range(3):
        state, m = step_fn(state, placed)
    _ = np.asarray(m["loss"])

    shutil.rmtree("/tmp/steptrace", ignore_errors=True)
    with jax.profiler.trace("/tmp/steptrace"):
        for _ in range(3):
            state, m = step_fn(state, placed)
        _ = np.asarray(m["loss"])

    tj = sorted(glob.glob("/tmp/steptrace/**/*.trace.json.gz", recursive=True))[-1]
    with gzip.open(tj, "rt") as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    # find device-side complete events (ph == 'X'); aggregate by name
    pid_names = {e["pid"]: e["args"].get("name", "") for e in events
                 if e.get("ph") == "M" and e.get("name") == "process_name" and "args" in e}
    agg = collections.defaultdict(float)
    cnt = collections.Counter()
    total_by_pid = collections.defaultdict(float)
    for e in events:
        if e.get("ph") != "X":
            continue
        pid = e.get("pid")
        dur = e.get("dur", 0) / 1e6  # us -> s
        total_by_pid[pid] += dur
        nm = e.get("name", "?")
        agg[(pid, nm)] += dur
        cnt[(pid, nm)] += 1
    print("pids:", {p: pid_names.get(p, "?") for p in total_by_pid})
    for pid in total_by_pid:
        label = pid_names.get(pid, "?")
        if "TPU" in label or "tpu" in label or total_by_pid[pid] > 0.01:
            print(f"\n== pid {pid} ({label}) total {total_by_pid[pid]*1e3:.1f} ms ==")
            rows = sorted(((v, k) for k, v in agg.items() if k[0] == pid), reverse=True)[:25]
            for v, (p, nm) in rows:
                print(f"  {v*1e3:8.2f} ms  x{cnt[(p, nm)]:4d}  {nm[:110]}")


if __name__ == "__main__":
    main()
