#!/usr/bin/env python
"""Post-mortem generator: one markdown timeline per incident.

Joins the incident plane's artifacts around a correlated incident id:

  - **events** from one or more ``event_log.jsonl`` exports
    (``telemetry/events.py``) and/or a live ``FleetCollector`` URL
    (``GET /incidents`` — the collector's correlation is authoritative
    when a URL is given; local JSONL files are correlated here with the
    same ``correlate_events`` join);
  - **flight-recorder dumps** (``flight_record*.jsonl``) whose header
    timestamp falls inside the incident window (+/- margin), with the
    step records nearest the incident inlined;
  - **profiler-capture trace dirs** (``profiling/capture.py`` writes
    ``step{N}`` dirs) whose mtime falls inside the window;
  - **perf-ledger rows** (``telemetry/perfledger.py``) stamped inside
    the window.

Usage:
  python tools/incident_report.py --events telemetry_out/event_log.jsonl \
      --flight-records 'telemetry_out/flight_record*.jsonl' \
      --captures telemetry_out --out incident_report.md
  python tools/incident_report.py --url http://127.0.0.1:9400 \
      --incident inc-ab12cd34ef
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
import urllib.request
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_local_events(paths: List[str]) -> List[Dict[str, Any]]:
    """Event wire dicts from ``export_jsonl`` files, annotated with the
    ``proc`` key the collector would have stamped."""
    out: List[Dict[str, Any]] = []
    for pattern in paths:
        for path in sorted(glob.glob(pattern)) or [pattern]:
            if not os.path.exists(path):
                continue
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    d = json.loads(line)
                    if d.get("kind") == "process_meta" or "severity" not in d:
                        continue
                    ident = d.get("identity") or {}
                    d.setdefault("proc", f"{ident.get('run_id', '?')}"
                                         f"/p{ident.get('process_index', 0)}")
                    out.append(d)
    return out


def _fetch(url: str, path: str) -> Dict[str, Any]:
    with urllib.request.urlopen(url.rstrip("/") + path, timeout=10) as resp:
        return json.loads(resp.read().decode())


def _flight_dumps(patterns: List[str]) -> List[Dict[str, Any]]:
    """Parsed flight records: header + step records per dump file."""
    dumps = []
    for pattern in patterns:
        for path in sorted(glob.glob(pattern)) or [pattern]:
            if not os.path.exists(path):
                continue
            header, steps = None, []
            try:
                with open(path, encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        d = json.loads(line)
                        if d.get("kind") == "header":
                            header = d
                        elif d.get("kind") == "step_record":
                            steps.append(d)
            except (OSError, ValueError):
                continue
            if header is not None:
                dumps.append({"path": path, "header": header, "steps": steps})
    return dumps


def _capture_dirs(roots: List[str]) -> List[Dict[str, Any]]:
    """Profiler-capture trace dirs (``**/step*/``) with their mtimes."""
    out = []
    for root in roots:
        if not os.path.isdir(root):
            continue
        for dirpath, dirnames, _ in os.walk(root):
            for d in dirnames:
                if d.startswith("step") and d[4:].isdigit():
                    full = os.path.join(dirpath, d)
                    try:
                        out.append({"path": full,
                                    "mtime": os.path.getmtime(full)})
                    except OSError:
                        pass
    return out


def _ledger_rows(root: Optional[str]) -> List[Dict[str, Any]]:
    try:
        from deepspeed_tpu.telemetry.perfledger import PerfLedger

        return PerfLedger(root).rows()
    except Exception:  # noqa: BLE001 - ledger is optional evidence
        return []


def _ts(t: float) -> str:
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(t)) + f".{int((t % 1) * 1000):03d}"


def render_incident(inc: Dict[str, Any], dumps: List[Dict[str, Any]],
                    captures: List[Dict[str, Any]],
                    ledger: List[Dict[str, Any]],
                    margin_s: float = 60.0) -> str:
    """One incident -> one markdown section: the event timeline plus every
    artifact whose timestamp lands inside the widened window."""
    lo = float(inc["start_ts"]) - margin_s
    hi = float(inc["end_ts"]) + margin_s
    lines = [
        f"## Incident `{inc['id']}`",
        "",
        f"- **run**: `{inc['run_id']}`  |  **severity**: {inc['severity']}"
        f"  |  **events**: {inc['event_count']}"
        f"  |  **duration**: {inc['duration_s']:.1f}s",
        f"- **window**: {_ts(inc['start_ts'])} — {_ts(inc['end_ts'])}",
        f"- **processes**: {', '.join('`%s`' % p for p in inc['procs'])}",
        f"- **kinds**: {', '.join('`%s`' % k for k in inc['kinds'])}",
        "",
        "### Timeline",
        "",
        "| time | proc | sev | subsystem/kind | message |",
        "|---|---|---|---|---|",
    ]
    for ev in inc["events"]:
        msg = str(ev.get("message", "")).replace("|", "\\|").replace("\n", " ")
        if len(msg) > 160:
            msg = msg[:157] + "..."
        count = int(ev.get("count", 1))
        if count > 1:
            msg += f" (x{count})"
        step = ev.get("step")
        lines.append(
            f"| {_ts(float(ev['ts']))}"
            + (f" (step {step})" if step is not None else "")
            + f" | `{ev.get('proc', '?')}` | {ev.get('severity')} "
            f"| `{ev.get('subsystem')}/{ev.get('kind')}` | {msg} |")

    near_dumps = [d for d in dumps
                  if lo <= float(d["header"].get("time_unix", 0.0)) <= hi]
    if near_dumps:
        lines += ["", "### Flight records", ""]
        for d in near_dumps:
            hdr = d["header"]
            lines.append(
                f"- `{d['path']}` — reason `{hdr.get('reason')}`, "
                f"{hdr.get('n_records', 0)} step records, dumped "
                f"{_ts(float(hdr.get('time_unix', 0.0)))}")
            tail = d["steps"][-3:]
            for s in tail:
                mets = {k: v for k, v in (s.get("metrics") or {}).items()
                        if isinstance(v, (int, float))}
                brief = ", ".join(f"{k}={v:.4g}" for k, v in
                                  sorted(mets.items())[:6])
                lines.append(f"    - step {s.get('step')}: {brief}")

    near_caps = [c for c in captures if lo <= c["mtime"] <= hi]
    if near_caps:
        lines += ["", "### Profiler captures", ""]
        for c in sorted(near_caps, key=lambda c: c["mtime"]):
            lines.append(f"- `{c['path']}` ({_ts(c['mtime'])})")

    near_rows = [r for r in ledger
                 if lo <= float(r.get("time_unix") or 0.0) <= hi]
    if near_rows:
        lines += ["", "### Perf-ledger rows in window", ""]
        for r in near_rows[:20]:
            lines.append(
                f"- [{r.get('backend')}] {r.get('suite')}/{r.get('metric')}"
                f" = {r.get('value')} {r.get('unit', '')}"
                f" (r{r.get('round')})")
    lines.append("")
    return "\n".join(lines)


def build_report(incidents: List[Dict[str, Any]],
                 dumps: List[Dict[str, Any]],
                 captures: List[Dict[str, Any]],
                 ledger: List[Dict[str, Any]],
                 margin_s: float = 60.0) -> str:
    head = [
        "# Incident report",
        "",
        f"Generated {_ts(time.time())} — {len(incidents)} incident(s).",
        "",
    ]
    if not incidents:
        head.append("No incidents correlated from the provided events.")
        head.append("")
    body = [render_incident(inc, dumps, captures, ledger, margin_s)
            for inc in incidents]
    return "\n".join(head + body)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--events", nargs="*", default=[],
                    help="event_log.jsonl export path(s)/glob(s)")
    ap.add_argument("--url", default=None,
                    help="FleetCollector URL (uses its GET /incidents)")
    ap.add_argument("--flight-records", nargs="*", default=[],
                    help="flight_record*.jsonl path(s)/glob(s)")
    ap.add_argument("--captures", nargs="*", default=[],
                    help="dir(s) scanned for profiler capture stepN dirs")
    ap.add_argument("--ledger-root", default=None,
                    help="perf ledger root (default <repo>/perf/ledger; "
                         "'' skips the ledger join)")
    ap.add_argument("--incident", default=None,
                    help="report only this incident id")
    ap.add_argument("--window", type=float, default=30.0,
                    help="correlation window seconds (local events)")
    ap.add_argument("--severity", default="warn",
                    help="min severity folded into incidents")
    ap.add_argument("--margin", type=float, default=60.0,
                    help="artifact-join margin seconds around the window")
    ap.add_argument("--out", default=None, help="markdown path (default stdout)")
    args = ap.parse_args(argv)

    from deepspeed_tpu.telemetry.collector import correlate_events

    incidents: List[Dict[str, Any]] = []
    if args.url:
        doc = _fetch(args.url, f"/incidents?window_s={args.window}"
                               f"&severity={args.severity}")
        incidents.extend(doc.get("incidents", []))
    local = _load_local_events(args.events)
    if local:
        have = {i["id"] for i in incidents}
        for inc in correlate_events(local, window_s=args.window,
                                    min_severity=args.severity):
            if inc["id"] not in have:
                incidents.append(inc)
    if args.incident:
        incidents = [i for i in incidents if i["id"] == args.incident]
        if not incidents:
            print(f"incident_report: no incident {args.incident!r} found",
                  file=sys.stderr)
            return 2
    incidents.sort(key=lambda i: i["start_ts"])

    dumps = _flight_dumps(args.flight_records)
    captures = _capture_dirs(args.captures)
    ledger = [] if args.ledger_root == "" else _ledger_rows(args.ledger_root)
    report = build_report(incidents, dumps, captures, ledger, args.margin)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(report)
        print(f"incident_report: wrote {args.out} "
              f"({len(incidents)} incident(s))")
    else:
        print(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
