"""Fourth stage: per-shape matmul efficiency, flash vs xla attention, one
block, and a jax.profiler trace attempt."""

from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp
import numpy as np


def fetch_time(fn, out_leaf=lambda r: r, n=10, warmup=3):
    for _ in range(warmup):
        r = fn()
    _ = np.asarray(out_leaf(r))
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    _ = np.asarray(out_leaf(r))
    return (time.perf_counter() - t0) / n


def mm_rate(M, K, N, dtype=jnp.bfloat16, n=10):
    a = jnp.zeros((M, K), dtype)
    b = jnp.zeros((K, N), dtype)
    f = jax.jit(lambda a, b: (a @ b).sum())
    t = fetch_time(lambda: f(a, b), n=n)
    return t, 2 * M * K * N / t / 1e12


def main():
    print("matmul shape sweep (bf16):")
    for (M, K, N) in [(8192, 768, 768), (8192, 768, 3072), (8192, 3072, 768),
                      (8192, 768, 50304), (32768, 768, 3072), (8192, 8192, 8192)]:
        t, r = mm_rate(M, K, N)
        print(f"  [{M},{K}]x[{K},{N}]: {t*1e3:.2f} ms {r:.1f} TF/s")

    # attention: flash vs xla, fwd only
    from deepspeed_tpu.ops.registry import dispatch
    B, S, H, D = 8, 1024, 12, 64
    q = jnp.zeros((B, S, H, D), jnp.bfloat16)
    k = jnp.zeros((B, S, H, D), jnp.bfloat16)
    v = jnp.zeros((B, S, H, D), jnp.bfloat16)
    att_fl = 4 * B * H * S * S * D
    for impl in ("pallas", "xla"):
        try:
            fn = jax.jit(lambda q, k, v, f=dispatch("causal_attention", impl): f(q, k, v, mask=None).sum())
            t = fetch_time(lambda: fn(q, k, v))
            print(f"attention {impl}: {t*1e3:.2f} ms ({att_fl/t/1e12:.1f} TF/s)")
        except Exception as e:
            print(f"attention {impl}: FAILED {type(e).__name__} {e}")

    # attention bwd: flash vs xla
    for impl in ("pallas", "xla"):
        try:
            f = dispatch("causal_attention", impl)
            fn = jax.jit(lambda q, k, v: jax.grad(lambda qq: f(qq, k, v, mask=None).astype(jnp.float32).sum())(q).sum())
            t = fetch_time(lambda: fn(q, k, v))
            print(f"attention-bwd {impl}: {t*1e3:.2f} ms")
        except Exception as e:
            print(f"attention-bwd {impl}: FAILED {type(e).__name__} {e}")

    # profiler trace attempt
    try:
        a = jnp.zeros((4096, 4096), jnp.bfloat16)
        f = jax.jit(lambda a: a @ a)
        with jax.profiler.trace("/tmp/jaxtrace"):
            r = f(a)
            np.asarray(r[0, 0])
        import glob
        files = glob.glob("/tmp/jaxtrace/**/*", recursive=True)
        print(f"profiler trace files: {len(files)}")
        for p in files[:8]:
            print("  ", p, os.path.getsize(p) if os.path.isfile(p) else "dir")
    except Exception as e:
        print(f"profiler trace FAILED: {type(e).__name__} {e}")


if __name__ == "__main__":
    main()
