#!/usr/bin/env python
"""MoE-at-scale nightly smoke (ISSUE 15).

Exit-gated evidence, one JSON line (committed as MOE_rNN.log by
``tools/run_nightly.sh``; ``--output`` also writes the machine-readable
MOE_rNN.json artifact):

  1. **ep x tp interpret smoke** — a dp2 x ep2 x tp2 CPU-mesh MoE engine
     (the composition the engine used to refuse) trains finite steps
     through the collective token dispatch, and a replay of its trained
     params through the plain GLOBAL math matches the mesh loss (the
     mis-routing gate).
  2. **quantized dispatch wire** — the same mesh with
     ``moe_wire_codec='int8'`` stays within a pinned bound of the exact
     wire.
  3. **expert-parallel v2 decode parity** — an ``ep_size=2`` v2 inference
     engine decodes greedy TOKEN-IDENTICAL to the ep=1 engine on the same
     bf16 checkpoint, with the collective dispatch actually traced.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _train_gates() -> dict:
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec
    from deepspeed_tpu.topology import mesh as mesh_mod

    base = dict(
        vocab_size=256, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, max_seq_len=32, num_experts=4, moe_top_k=2,
        moe_capacity_factor=2.0)

    def build(**overrides):
        cfg = TransformerConfig(**{**base, **overrides})
        eng, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(cfg), config={
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 0,
                                      "param_persistence_threshold": 1},
                "mesh": {"dp": 2, "ep": 2, "tp": 2},
                "steps_per_print": 1000,
            }, seed=21)
        return eng

    def tokens(seed):
        rng = np.random.default_rng(seed)
        return {"input_ids": rng.integers(0, 256, size=(4, 16), dtype=np.int32)}

    eng = build()
    losses = [float(eng.train_batch(tokens(90 + i))["loss"]) for i in range(6)]
    # mis-routing gate: replay the engine's own params through plain global
    # math; the collective dispatch must reproduce it (the GSPMD constraint
    # path deviates ~0.5% here — the silent corruption the old refusal
    # guarded against)
    host = jax.device_get(eng.state.params)
    rng = jax.random.PRNGKey(7)
    mesh_mod.set_mesh(eng.mesh)
    mesh_loss = float(jax.jit(eng.model.loss_fn)(host, tokens(99), rng)[0])
    mesh_mod._ACTIVE_MESH = None
    global_loss = float(jax.jit(eng.model.loss_fn)(host, tokens(99), rng)[0])
    parity_rel = abs(mesh_loss - global_loss) / max(abs(global_loss), 1e-9)

    q = build(moe_dispatch_algorithm="ring", moe_wire_codec="int8")
    q_losses = [float(q.train_batch(tokens(90 + i))["loss"]) for i in range(6)]
    wire_rel = max(abs(a - b) / max(abs(a), 1e-9)
                   for a, b in zip(losses, q_losses))
    return {
        "ep_tp_losses": [round(v, 4) for v in losses],
        "ep_tp_finite": bool(np.isfinite(losses).all()),
        "ep_tp_learns": losses[-1] < losses[0],
        "global_math_rel_err": parity_rel,
        "global_math_ok": parity_rel < 1e-5,
        "int8_wire_rel_err": wire_rel,
        "int8_wire_ok": bool(np.isfinite(q_losses).all()) and wire_rel < 0.05,
    }


def _decode_gates() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu.parallel.moe as pmoe
    from deepspeed_tpu.inference import InferenceEngineV2
    from deepspeed_tpu.models.transformer import CausalLM, TransformerConfig

    cfg = TransformerConfig(
        vocab_size=97, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=128, num_experts=4,
        moe_top_k=2)
    module = CausalLM(cfg)
    rng = jax.random.PRNGKey(0)
    params = module.init({"params": rng, "dropout": rng},
                         {"input_ids": jnp.zeros((1, 8), jnp.int32)},
                         train=False)["params"]
    prng = np.random.RandomState(7)
    prompts = [prng.randint(0, cfg.vocab_size, (n,)) for n in (6, 9, 4)]
    base = {"dtype": "bf16", "kv_block_size": 4, "num_kv_blocks": 64}
    ref = InferenceEngineV2(cfg, params, dict(base)).generate(
        prompts, max_new_tokens=8)
    calls = []
    orig = pmoe.collective_moe_apply
    try:
        pmoe.collective_moe_apply = lambda *a, **k: (calls.append(1),
                                                     orig(*a, **k))[1]
        ep_eng = InferenceEngineV2(cfg, params, dict(base, ep_size=2))
        outs = ep_eng.generate(prompts, max_new_tokens=8)
    finally:
        pmoe.collective_moe_apply = orig
    identical = all((np.asarray(a) == np.asarray(b)).all()
                    for a, b in zip(outs, ref))
    sharded = "ep" in str(
        ep_eng.params["layers"]["moe"]["experts"]["w_up"].sharding.spec)
    return {
        "v2_ep_collective_traced": bool(calls),
        "v2_ep_weights_sharded": sharded,
        "v2_ep_decode_token_identical": bool(identical),
    }


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--output", default=None,
                    help="also write the gates as a JSON artifact")
    args = ap.parse_args(argv)

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    from deepspeed_tpu.utils.cpu_backend import force_cpu_backend

    force_cpu_backend()

    gates = {**_train_gates(), **_decode_gates()}
    ok = all(gates[k] for k in (
        "ep_tp_finite", "ep_tp_learns", "global_math_ok", "int8_wire_ok",
        "v2_ep_collective_traced", "v2_ep_weights_sharded",
        "v2_ep_decode_token_identical"))
    doc = {"moe_smoke": gates, "ok": ok}
    print(json.dumps(doc), flush=True)
    if args.output:
        with open(args.output, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
