#!/usr/bin/env python
"""Nightly fault-injection smoke: prove the resilience stack end to end.

One run on the CPU bench model (the tiny causal LM ``bench.py`` falls back
to) with BOTH headline faults injected (``diagnostics/faultinject.py``):

  - **NaN at step K** — params poisoned on device (a causal LM batch is
    integer-only, so the injection point is the model, not the data); the
    in-step health probe fires ``nonfinite`` under the ``abort`` policy and
    ``elasticity.run_resilient`` must rewind to the last-good snapshot and
    complete to the target step anyway.
  - **writer killed mid-save** — the async snapshot writer dies between two
    shard writes; the ``latest`` pointer must keep naming the previous
    durable snapshot (crash-mid-save atomicity) and training must keep going
    forward (a save failure never rewinds healthy state).

Prints one JSON line and exits 0 iff every claim held — wired into
``tools/run_nightly.sh`` so the committed nightly log carries the proof
(ISSUE 6; see docs/elastic.md).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NAN_STEP = 5
TARGET_STEPS = 8
SNAPSHOT_EVERY = 2


def main() -> int:
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.checkpoint import snapshot as snap
    from deepspeed_tpu.diagnostics import FaultInjector
    from deepspeed_tpu.elasticity import run_resilient
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    tmp = tempfile.mkdtemp(prefix="dstpu_fault_smoke_")
    cfg = TransformerConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_layers=2, num_heads=4, max_seq_len=256,
    )
    seq = 128
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 1},
            "steps_per_print": 10_000,
            "diagnostics": {
                "enabled": True,
                "health": {"nonfinite_policy": "abort"},
                "flight_recorder": {"dump_dir": f"{tmp}/fr",
                                    "install_signal_handlers": False,
                                    "dump_on_exception": False},
            },
            # blocking=True surfaces the injected writer crash deterministically
            # at its own boundary (as a logged save failure, never a rewind)
            "snapshot": {"enabled": True, "dir": tmp,
                         "every_n_steps": SNAPSHOT_EVERY, "blocking": True},
            "recovery": {"backoff_base_s": 0.0},
        })

    def batch_fn(step: int):
        r = np.random.default_rng(1000 + step)
        return {"input_ids": r.integers(0, cfg.vocab_size,
                                        (engine.train_batch_size, seq),
                                        dtype=np.int32)}

    fi = FaultInjector()
    # step-0 anchor BEFORE arming the writer kill: the injected crash must
    # hit a cadenced mid-run save, not the supervisor's anchor snapshot
    engine.snapshot_manager.snapshot(blocking=True)
    fi.kill_writer(engine.snapshot_manager, after_shards=1, times=1)
    rewound_to = []
    report = run_resilient(
        engine,
        fi.nan_params_fn(engine, batch_fn, at_steps=[NAN_STEP]),
        num_steps=TARGET_STEPS,
        on_rewind=lambda entry: rewound_to.append(entry["step"]),
    )

    latest = snap.latest_tag(tmp)
    checks = {
        "completed_to_target": report.steps_completed == TARGET_STEPS
                               and engine.global_steps == TARGET_STEPS,
        "nan_fired_at_k": fi.nan_steps_fired == [NAN_STEP],
        "rewound_once_below_k": report.rewinds == 1
                                and rewound_to and rewound_to[0] < NAN_STEP,
        "writer_kill_fired": fi.writer_kills_fired == 1,
        "save_failure_no_rewind": report.save_failures >= 1,
        "latest_still_loads": False,
        "flight_record_dumped": bool(report.flight_record),
    }
    try:
        atoms, _manifest = snap.load_latest_atoms(tmp, fallback=False)
        checks["latest_still_loads"] = latest is not None and bool(atoms)
    except snap.SnapshotError:
        pass

    ok = all(checks.values())
    print(json.dumps({
        "fault_smoke": "nan_inject+kill_mid_save",
        "ok": ok,
        "target_steps": TARGET_STEPS,
        "nan_step": NAN_STEP,
        "checks": checks,
        "rewind_log": report.rewind_log,
        "save_failures": report.save_failures,
        "latest": latest,
        "injections": fi.summary(),
    }), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
