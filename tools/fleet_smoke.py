#!/usr/bin/env python
"""Fleet telemetry smoke: collector + N worker PROCESSES, exit-gated.

The multi-process proof of ISSUE 13's federation semantics, run by
``tools/run_nightly.sh`` (committing ``FLEET_rNN.log``) and by the tier-1
integration test (``tests/unit/test_fleet.py``). Three processes on CPU:

  parent   role=router: starts an in-process :class:`FleetCollector`,
           mints one ``fleet.TraceContext`` per synthetic request, emits
           each request's admission span + flow START on its own tracer,
           then spawns the workers with the contexts on their argv.
  workers  role=replica, process_index 1..N (separate ``python``
           processes): observe deterministic counters/histograms, wrap a
           fake dispatch of every received context in
           ``fleet.dispatch_span`` (the ``serve:dispatch`` span + in-span
           flow STEP), push their registry dump + heartbeat to the
           collector over HTTP, and export their tracer stream as JSONL.

Exit gates (any failure => exit 1):
  1. federated counters BIT-EXACTLY equal the sum of the per-process
     dumps the collector holds (counters sum, histogram counts add);
  2. ``tools/trace_merge.py`` joins the parent + worker JSONL streams into
     ONE trace in which at least one flow id links events from >= 2
     distinct pids, and every worker contributed a ``serve:dispatch`` span;
  3. every worker registered (ledger rows with heartbeats + clock offsets);
  4. a federated observatory table round-trips: rows pushed by the workers
     merge at the collector and a fresh selector consumes them in
     measured mode.

Prints one JSON line of evidence (the committed-log artifact).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# deterministic per-worker workload: counters/histogram samples a verifier
# can predict, chosen so float sums are exact (integers)
REQUESTS_PER_WORKER = 5
TOKENS_PER_WORKER = 40.0
HIST_SAMPLES = [1.5, 3.0, 12.0, 55.0, 130.0]


def _coll_row(world: int, latency_ms: float, proc: str) -> dict:
    """A plausible observatory row (same schema the online table emits)."""
    return {"op": "all_reduce", "world": world, "size_mb": 0.125,
            "algorithm": "ring", "codec": "none", "backend": "ppermute",
            "latency_ms": latency_ms, "busbw_gbps": 1.0, "itemsize": 4,
            "samples": 1, "proc": proc}


def worker_main(args) -> int:
    """One replica process: metrics + dispatch spans + push + JSONL."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.telemetry import fleet
    from deepspeed_tpu.telemetry.collector import FleetClient

    idx = int(args.index)
    ident = fleet.configure_identity(run_id=args.run_id, process_index=idx,
                                     role="replica")
    tr = telemetry.get_tracer()
    tr.configure(enabled=True)
    reg = tr.registry
    for _ in range(REQUESTS_PER_WORKER):
        reg.counter("serving/requests").add(1.0)
    reg.counter("serving/tokens", replica=idx).add(TOKENS_PER_WORKER)
    for v in HIST_SAMPLES:
        reg.histogram("serving/ttft_ms").observe(v)
    fleet.note_step(idx * 100 + 7)
    for wire in json.loads(args.contexts):
        ctx = fleet.TraceContext.from_wire(wire)
        with fleet.dispatch_span(ctx, replica=idx):
            time.sleep(0.002)
    client = FleetClient(args.collector, identity=ident, registry=reg,
                         observatory=None)
    ack = client.register()
    if not (ack and ack.get("ok")):
        print(json.dumps({"ok": False, "error": "register failed"}))
        return 1
    # per-process observatory rows ride the same push (table federation);
    # distinct latencies per worker so the collector's EMA fold is visible
    client.push(include_table=False,
                coll_rows=[_coll_row(8, 2.0 + idx, ident.key())])
    out = os.path.join(args.out, f"events.p{idx}.jsonl")
    telemetry.export_jsonl(out, tracer=tr)
    print(json.dumps({"ok": True, "index": idx, "events": out}))
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--requests", type=int, default=2)
    ap.add_argument("--out", default=None)
    ap.add_argument("--timeout", type=float, default=240.0)
    # worker mode (internal): spawned with the shared run id + contexts
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--index", type=int, default=1)
    ap.add_argument("--run-id", dest="run_id", default=None)
    ap.add_argument("--collector", default=None)
    ap.add_argument("--contexts", default="[]")
    args = ap.parse_args()
    if args.worker:
        return worker_main(args)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import tempfile

    from deepspeed_tpu import telemetry
    from deepspeed_tpu.telemetry import fleet
    from deepspeed_tpu.telemetry.collector import FleetCollector

    out_dir = args.out or tempfile.mkdtemp(prefix="fleet_smoke_")
    os.makedirs(out_dir, exist_ok=True)
    run_id = f"fleet-smoke-{os.getpid():x}"
    fleet.configure_identity(run_id=run_id, process_index=0, role="router")
    tr = telemetry.get_tracer()
    tr.configure(enabled=True)
    collector = FleetCollector(stale_after_s=60.0).start()

    # router side: one trace context per request, admission span + flow
    # START on the request's track — the arrow the workers' dispatch steps
    # must bind to in the merged trace
    contexts = [fleet.TraceContext.mint(i, run_id=run_id)
                for i in range(args.requests)]
    for ctx in contexts:
        with tr.span("admit", cat="router", request_id=ctx.request_id):
            tr.flow(ctx.flow_name, ctx.flow_id, "start")
    wire = json.dumps([c.to_wire() for c in contexts])

    procs = []
    for i in range(1, args.workers + 1):
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             "--index", str(i), "--run-id", run_id,
             "--collector", collector.url, "--contexts", wire,
             "--out", out_dir],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO))
    worker_fail = []
    for p in procs:
        try:
            stdout, stderr = p.communicate(timeout=args.timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            worker_fail.append("timeout")
            continue
        if p.returncode != 0:
            worker_fail.append(stderr.decode()[-400:])

    gates = {}
    # gate 1: federated counters == bit-exact sum of the stored dumps
    expected: dict = {}
    for d in collector.dumps().values():
        for k, v in d["counters"].items():
            expected[k] = expected.get(k, 0.0) + float(v)
    fed = collector.federated_registry().counters()
    gates["counters_bit_exact"] = (
        bool(expected)
        and all(fed.get(k) == v for k, v in expected.items()))
    gates["federated_requests"] = fed.get("serving/requests")
    gates["expected_requests"] = float(args.workers * REQUESTS_PER_WORKER)

    # gate 2: merged trace with cross-process flow links + worker dispatches
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import trace_merge

    parent_stream = os.path.join(out_dir, "events.p0.jsonl")
    telemetry.export_jsonl(parent_stream, tracer=tr)
    streams = [parent_stream] + [
        os.path.join(out_dir, f"events.p{i}.jsonl")
        for i in range(1, args.workers + 1)]
    streams = [s for s in streams if os.path.exists(s)]
    merged = trace_merge.merge_streams(streams)
    merged_path = os.path.join(out_dir, "merged_trace.json")
    with open(merged_path, "w") as f:
        json.dump(merged, f)
    links = {f: p for f, p in trace_merge.linked_flow_pids(merged).items()
             if len(p) > 1}
    dispatch_pids = sorted({ev["pid"] for ev in merged["traceEvents"]
                            if ev.get("name") == "serve:dispatch"})
    gates["cross_process_flow_links"] = len(links)
    gates["dispatch_pids"] = dispatch_pids
    gates["trace_linked"] = bool(links) and len(dispatch_pids) >= args.workers

    # gate 3: ledger saw every worker (heartbeat + clock offset)
    ledger = collector.ledger()
    replica_rows = [r for r in ledger["processes"]
                    if r["identity"]["role"] == "replica"]
    gates["ledger_replicas"] = len(replica_rows)
    gates["ledger_ok"] = (
        len(replica_rows) == args.workers
        and all(r["heartbeat"] is not None and r["clock_offset_s"] is not None
                and not r["stale"] for r in replica_rows))

    # gate 4: federated observatory table -> fresh selector measured mode
    rows = collector.table_rows()
    table_ok = False
    if rows:
        from deepspeed_tpu.collectives import selector
        from deepspeed_tpu.collectives import table as table_mod

        tpath = os.path.join(out_dir, "fleet_coll_table.json")
        table_mod.write_table(tpath, rows, source="fleet")
        # a FRESH selector (new-process analog) warm-starts measured mode
        # from the FEDERATED table — the round-trip the ISSUE gates on
        selector.configure(decision_table=tpath, mode="measured",
                           min_algorithmic_bytes=0)
        pick = selector.select("all_reduce", int(0.125 * 1e6), 8, itemsize=4)
        table_ok = (pick.source == "measured" and pick.algorithm == "ring")
        selector.configure()  # restore process-global defaults
    gates["coll_table_rows"] = len(rows)
    gates["coll_table_round_trip"] = bool(table_ok)

    collector.stop()
    ok = (not worker_fail and gates["counters_bit_exact"]
          and gates["trace_linked"] and gates["ledger_ok"]
          and gates["coll_table_round_trip"])
    print(json.dumps({"ok": ok, "workers": args.workers,
                      "worker_failures": worker_fail, **gates,
                      "merged_trace": merged_path, "out_dir": out_dir}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
