#!/usr/bin/env python
"""Incident-plane smoke: alerts + incidents, exit-gated BOTH ways.

The nightly's proof that ISSUE 20's incident plane actually fires and
actually stays quiet (``tools/run_nightly.sh`` commits ``ALERTS_rNN.log``):

  1. **Clean run MUST be quiet** — a 20-step train run with the numerics
     sentinel sampling every step, the default alert rule pack evaluating,
     and events federating to a live :class:`FleetCollector` produces ZERO
     warn+ events, ZERO firing alerts, and ZERO incidents. An alert plane
     that cries wolf gets ignored; a noisy round fails the stage.
  2. **Injected faults MUST correlate into ONE incident** — the classic
     double fault: ``flip_param_bit`` (one mantissa bit on one dp
     replica's param copy -> the numerics divergence sentinel) plus a
     SIGKILLed serving-fabric replica daemon (heartbeat death on the
     ``RemoteReplica`` -> ``fabric/replica_unreachable``). Both typed
     events ship to the collector and MUST correlate into exactly one
     incident naming both kinds, visible at ``GET /incidents``; the
     matching alerts (``numerics_divergence``, ``replica_unreachable``)
     MUST reach the firing state; and ``tools/incident_report.py`` run
     against the collector MUST emit a timeline naming both events.

Prints one JSON line of evidence (the committed-log artifact).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

CLEAN_STEPS = 20


def _model_spec():
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from unit.simple_model import simple_model_spec

    return simple_model_spec()


def _batch(eng, seed):
    from unit.simple_model import random_batch

    return random_batch(eng.train_batch_size, seed=seed)


def _engine():
    import deepspeed_tpu

    eng, *_ = deepspeed_tpu.initialize(
        model=_model_spec(),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "steps_per_print": 10_000,
            "numerics": {
                "enabled": True,
                "sample_every": 4,
                "sentinel_sample_every": 1,
                "divergence_policy": "log",
            },
        },
    )
    return eng


def _spawn_daemon(run_id: str, timeout: float = 120.0):
    """One replica daemon subprocess sharing the smoke's run id; returns
    (Popen, url) once it prints its port line."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "deepspeed_tpu.fabric.replica_daemon",
         "--port", "0", "--index", "1", "--run-id", run_id],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO, text=True)
    t0 = time.monotonic()
    # scan past any log lines for the one JSON port announcement
    while time.monotonic() - t0 < timeout:
        line = proc.stdout.readline()
        if not line:
            break
        s = line.strip()
        if s.startswith("{") and '"port"' in s:
            return proc, f"http://127.0.0.1:{json.loads(s)['port']}"
    proc.kill()
    raise RuntimeError("replica daemon failed to announce a port")


def run_smoke() -> dict:
    from deepspeed_tpu.diagnostics.faultinject import FaultInjector
    from deepspeed_tpu.telemetry import alerts as alerts_mod
    from deepspeed_tpu.telemetry import events as events_mod
    from deepspeed_tpu.telemetry import fleet, get_tracer
    from deepspeed_tpu.telemetry import numerics
    from deepspeed_tpu.telemetry.collector import FleetClient, FleetCollector

    evidence: dict = {"clean": {}, "incident": {}}
    gates: dict = {}
    tmp = tempfile.mkdtemp(prefix="dstpu_alerts_smoke_")

    run_id = f"alerts-smoke-{os.getpid():x}"
    ident = fleet.configure_identity(run_id=run_id, process_index=0,
                                     role="train")
    tr = get_tracer()
    tr.configure(enabled=True)
    stream = events_mod.configure_events(capacity=4096)
    stream.clear()
    notif_path = os.path.join(tmp, "alert_notifications.jsonl")
    alert_eng = alerts_mod.configure_alerts(jsonl_path=notif_path)

    collector = FleetCollector(stale_after_s=120.0,
                               incident_window_s=30.0).start()
    client = FleetClient(collector.url, identity=ident, registry=tr.registry)
    ack = client.register()
    if not (ack and ack.get("ok")):
        raise RuntimeError("collector registration failed")

    # ---- gate 1: clean 20-step run is ALL quiet ------------------------
    eng = _engine()
    for s in range(CLEAN_STEPS):
        eng.train_batch(batch=_batch(eng, seed=s))
    alert_eng.evaluate()
    client.push()
    warn_events = stream.events(min_severity="warn")
    clean_incidents = collector.incidents()
    evidence["clean"] = {
        "steps": CLEAN_STEPS,
        "warn_events": [f"{e.subsystem}/{e.kind}" for e in warn_events],
        "firing_alerts": [f["rule"] for f in alert_eng.firing()],
        "incidents": len(clean_incidents),
        "events_total": stream.total_emitted,
    }
    gates["clean_quiet"] = (not warn_events and not alert_eng.firing()
                            and not clean_incidents)

    # ---- gate 2: double fault -> ONE correlated incident ---------------
    from deepspeed_tpu.fabric.remote import (
        RemoteReplica,
        RemoteReplicaDownError,
    )

    daemon, url = _spawn_daemon(run_id)
    replica = RemoteReplica(url, heartbeat_interval_s=0.05,
                            heartbeat_miss_limit=3)
    evidence["incident"]["daemon_url"] = url
    evidence["incident"]["daemon_alive_rpc"] = bool(
        replica.heartbeat_now())

    fi = FaultInjector()
    flipped = fi.flip_param_bit(eng)
    obs = numerics.get_observatory()
    before = obs.divergence_events_seen
    detect_steps = -1
    for extra in range(1, 5):
        eng.train_batch(batch=_batch(eng, seed=100 + extra))
        if obs.divergence_events_seen > before:
            detect_steps = extra
            break
    gates["divergence_detected"] = detect_steps > 0
    evidence["incident"]["flipped_leaf"] = flipped
    evidence["incident"]["divergence_detect_steps"] = detect_steps

    fi.kill_replica_daemon(daemon)
    deadline = time.monotonic() + 10.0
    while replica.alive and time.monotonic() < deadline:
        time.sleep(0.05)
    gates["replica_unreachable_detected"] = not replica.alive
    # a dispatch into the dead daemon: the per-endpoint failure path
    try:
        replica.query(1)
    except (RemoteReplicaDownError, ValueError):
        pass
    replica.close()

    alert_eng.evaluate()
    firing = {f["rule"] for f in alert_eng.firing()}
    evidence["incident"]["firing_alerts"] = sorted(firing)
    gates["alerts_fired"] = {"numerics_divergence",
                             "replica_unreachable"} <= firing

    client.push()
    incidents = collector.incidents()
    evidence["incident"]["incidents"] = [
        {"id": i["id"], "kinds": i["kinds"], "severity": i["severity"],
         "event_count": i["event_count"]} for i in incidents]
    want = {"numerics/divergence", "fabric/replica_unreachable"}
    gates["one_incident_names_both"] = (
        len(incidents) == 1 and want <= set(incidents[0]["kinds"]))

    # ---- gate 3: incident_report joins the artifacts -------------------
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import incident_report

    report_path = os.path.join(tmp, "incident_report.md")
    rc = incident_report.main(["--url", collector.url, "--ledger-root", "",
                               "--out", report_path])
    with open(report_path, encoding="utf-8") as f:
        report = f.read()
    gates["report_names_both"] = (
        rc == 0 and bool(incidents)
        and incidents[0]["id"] in report
        and "numerics/divergence" in report
        and "fabric/replica_unreachable" in report)
    evidence["incident"]["report"] = report_path
    evidence["incident"]["alert_notifications"] = (
        os.path.getsize(notif_path) > 0 if os.path.exists(notif_path)
        else False)

    collector.stop()
    return {"gates": gates, "evidence": evidence, "out_dir": tmp}


def main() -> int:
    result = run_smoke()
    ok = all(bool(v) for v in result["gates"].values())
    print(json.dumps({"alerts_smoke": "clean_quiet+double_fault_incident",
                      "ok": ok, **result}), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
