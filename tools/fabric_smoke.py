#!/usr/bin/env python
"""Serving-fabric smoke: REAL replica-daemon processes, exit-gated.

The multi-process proof of ISSUE 18's cross-process serving fabric, run by
``tools/run_nightly.sh`` (committing ``FABRIC_rNN.log``) and — in its
``--smoke`` subset — by the tier-1 integration test
(``tests/unit/test_fabric.py``). The parent drives an UNCHANGED
:class:`ServingRouter` whose roster is :class:`RemoteReplica` proxies over
``fabric/replica_daemon.py`` processes; every daemon builds the same
deterministic tiny model (flax init from PRNGKey(0) is bit-identical across
processes), so token comparisons against a local reference engine are exact.

``--smoke`` legs (tier-1):
  1. disagg serve, bf16 AND int8 KV: admit → prefill on one process →
     wire-migrate across the process boundary → decode on another; greedy
     outputs token-identical to a single LOCAL reference engine;
  2. migration fidelity: export on daemon A → import on daemon B → the
     per-block blake2b digests (``/block_hashes``) are identical, byte for
     byte, after the KV crossed the wire;
  3. drain/handoff: ``request_drain`` mid-burst quiesces one daemon; its
     admitted requests hand off to the peer through the ordinary migration
     tickets and EVERY request completes (zero drops);
  4. merged trace: daemon ``/dump_trace`` streams + the parent's join via
     ``tools/trace_merge.py`` — at least one request flow links >= 2 pids
     and ``serve:dispatch`` spans appear from >= 2 pids.

Full (nightly) adds:
  5. SIGKILL mid-burst (``faultinject.kill_replica_daemon``): the router
     detects the death (heartbeat / dispatch failure), re-admits the dead
     replica's admitted requests on the survivor, and completes ALL of them;
  6. elastic training: a trainer child self-preempts (SIGTERM) at a step
     boundary, exits ``EXIT_PREEMPTED`` with a durable snapshot; the
     relaunched process auto-restores and the finished trajectory is
     BIT-IDENTICAL to an uninterrupted run; a second relaunch under a
     CHANGED mesh shape restores and completes (fp32 reduction order
     differs across dp widths, so that leg gates on restore+completion).

Prints one JSON line of evidence (the committed-log artifact); exit 0/1.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PROMPT_SEED = 7
N_PROMPTS = 4
MAX_NEW = 16


# ---------------------------------------------------------------- daemons
class Daemon:
    """A spawned replica-daemon process + its announced URL."""

    def __init__(self, proc: subprocess.Popen, port: int, index: int):
        self.proc = proc
        self.port = port
        self.index = index
        self.url = f"http://127.0.0.1:{port}"


def spawn_daemon(index: int, run_id: str, engine_config: dict, out_dir: str,
                 boot_timeout_s: float = 240.0) -> Daemon:
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [sys.executable, "-m", "deepspeed_tpu.fabric.replica_daemon",
         "--index", str(index), "--run-id", run_id,
         "--engine-config", json.dumps(engine_config), "--out", out_dir],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=env, cwd=REPO)
    # the daemon prints {"port": N, "pid": ...} once the engine is built;
    # scan past the repo's stdout log lines for it, and bound the wait via
    # an event so a wedged boot fails loudly. The reader thread then keeps
    # DRAINING stdout for the daemon's lifetime — a full 64K pipe would
    # block the daemon on its next log write
    box: dict = {}
    booted = threading.Event()

    def read():
        for line in proc.stdout:
            s = line.strip()
            if not booted.is_set() and s.startswith("{") and '"port"' in s:
                box["line"] = s
                booted.set()
        booted.set()  # EOF: boot failed if the line never appeared

    threading.Thread(target=read, daemon=True).start()
    booted.wait(boot_timeout_s)
    line = box.get("line", "")
    if not line:
        proc.kill()
        raise RuntimeError(f"daemon {index} did not announce a port "
                           f"within {boot_timeout_s:.0f}s")
    return Daemon(proc, int(json.loads(line)["port"]), index)


def shutdown_daemon(d: Daemon, timeout: float = 30.0) -> None:
    try:
        from deepspeed_tpu.fabric.remote import _post

        _post(d.url, "/shutdown", {}, timeout=5.0)
    except Exception:
        pass
    try:
        d.proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        d.proc.kill()


def _prompts(vocab: int = 512, n: int = N_PROMPTS):
    import numpy as np

    rng = np.random.default_rng(PROMPT_SEED)
    return [rng.integers(1, vocab, size=int(ln)).astype(np.int32)
            for ln in rng.integers(6, 24, size=n)]


def _engine_cfg(kv_cache_dtype=None, role="mixed"):
    cfg = {"dtype": "bf16", "kv_block_size": 16, "num_kv_blocks": 96,
           "max_seqs": 4, "role": role}
    if kv_cache_dtype:
        cfg["kv_cache_dtype"] = kv_cache_dtype
    return cfg


# ------------------------------------------------------------ serving legs
def leg_disagg_tokens(run_id: str, out_dir: str, kv_cache_dtype=None) -> dict:
    """Prefill on one PROCESS, decode on another; tokens must equal a local
    single-engine reference exactly (greedy is placement-independent)."""
    import numpy as np

    from deepspeed_tpu.fabric.remote import RemoteReplica
    from deepspeed_tpu.fabric.replica_daemon import _build_model
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.inference.router import ServingRouter

    tag = kv_cache_dtype or "bf16"
    da = spawn_daemon(1, run_id, _engine_cfg(kv_cache_dtype, "prefill"), out_dir)
    db = spawn_daemon(2, run_id, _engine_cfg(kv_cache_dtype, "decode"), out_dir)
    remotes = []
    try:
        remotes = [RemoteReplica(da.url), RemoteReplica(db.url)]
        router = ServingRouter(remotes, roles=["prefill", "decode"])
        prompts = _prompts()
        outs = router.serve(prompts, max_new_tokens=MAX_NEW)

        mc, params = _build_model()
        ref = InferenceEngineV2(mc, params, _engine_cfg(kv_cache_dtype))
        ref_outs = ref.generate(prompts, max_new_tokens=MAX_NEW)
        identical = (all(o is not None for o in outs)
                     and all(np.array_equal(a, b)
                             for a, b in zip(outs, ref_outs)))
        return {f"tokens_identical_{tag}": bool(identical),
                f"migrations_{tag}": int(router.migrations),
                f"ok_{tag}": bool(identical and router.migrations >= 1)}
    finally:
        for r in remotes:
            r.close()
        shutdown_daemon(da)
        shutdown_daemon(db)


def leg_migration_digests(run_id: str, out_dir: str) -> dict:
    """Export a live request from daemon A, import on daemon B: the pool
    bytes crossed the wire verbatim iff every per-block blake2b digest
    matches."""
    import jax

    from deepspeed_tpu.fabric.remote import RemoteReplica

    da = spawn_daemon(3, run_id, _engine_cfg(), out_dir)
    db = spawn_daemon(4, run_id, _engine_cfg(), out_dir)
    ra = rb = None
    try:
        ra = RemoteReplica(da.url, start_heartbeat=False)
        rb = RemoteReplica(db.url, start_heartbeat=False)
        prompt = _prompts(n=1)[0]
        suffix = ra.try_admit(11, prompt, [], [])
        rng = jax.random.PRNGKey(0)
        toks, rng = ra._put_sample([11], [suffix.tolist()], rng,
                                   (("do_sample", False),))
        ra.decode_chain([11], [int(toks[0])], [8], 4, rng)
        h_src = ra.block_hashes(11)
        export = ra.export_request(11)
        assert rb.import_request(12, export)
        h_dst = rb.block_hashes(12)
        ra.flush(11)
        rb.flush(12)
        return {"digest_blocks": len(h_src),
                "digests_identical": bool(h_src and h_src == h_dst)}
    finally:
        for r in (ra, rb):
            if r is not None:
                r.close()
        shutdown_daemon(da)
        shutdown_daemon(db)


def leg_drain(run_id: str, out_dir: str) -> dict:
    """Drain one daemon mid-burst: admitted requests hand off to the peer
    and every output completes."""
    from deepspeed_tpu.fabric.remote import RemoteReplica
    from deepspeed_tpu.inference.router import ServingRouter

    da = spawn_daemon(5, run_id, _engine_cfg(), out_dir)
    db = spawn_daemon(6, run_id, _engine_cfg(), out_dir)
    remotes = []
    try:
        remotes = [RemoteReplica(da.url), RemoteReplica(db.url)]
        router = ServingRouter(remotes)
        prompts = _prompts()
        box: dict = {}

        def run():
            box["outs"] = router.serve(prompts, max_new_tokens=32)

        t = threading.Thread(target=run)
        t.start()
        # drain replica 0 while its first admissions are still decoding
        # (the first chain compile alone outlasts this poll)
        deadline = time.time() + 120.0
        while time.time() < deadline and t.is_alive():
            if router.replicas[0].active:
                break
            time.sleep(0.02)
        drained = False
        if t.is_alive():
            router.request_drain(0)
            drained = True
        t.join(600.0)
        outs = box.get("outs", [])
        complete = len(outs) == len(prompts) and all(
            o is not None for o in outs)
        return {"drain_requested": drained,
                "drain_complete": bool(complete),
                "drain_handoffs": int(router.migrations),
                "drain_ok": bool(complete and drained
                                 and router.drains >= 1)}
    finally:
        for r in remotes:
            r.close()
        shutdown_daemon(da)
        shutdown_daemon(db)


def leg_merged_trace(run_id: str, out_dir: str) -> dict:
    """One roster serve, then join the parent + daemon trace streams: the
    request flows must link >= 2 pids through ``serve:dispatch``."""
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.fabric.remote import RemoteReplica
    from deepspeed_tpu.inference.router import ServingRouter

    sys.path.insert(0, os.path.join(REPO, "tools"))
    import trace_merge

    da = spawn_daemon(7, run_id, _engine_cfg(), out_dir)
    db = spawn_daemon(8, run_id, _engine_cfg(), out_dir)
    remotes = []
    try:
        remotes = [RemoteReplica(da.url), RemoteReplica(db.url)]
        router = ServingRouter(remotes)
        outs = router.serve(_prompts(), max_new_tokens=8)
        streams = [os.path.join(out_dir, "events.p0.jsonl")]
        telemetry.export_jsonl(streams[0])
        for r, idx in ((remotes[0], 7), (remotes[1], 8)):
            p = os.path.join(out_dir, f"events.p{idx}.jsonl")
            r.dump_trace(p)
            streams.append(p)
        merged = trace_merge.merge_streams(
            [s for s in streams if os.path.exists(s)])
        merged_path = os.path.join(out_dir, "merged_trace.json")
        with open(merged_path, "w") as f:
            json.dump(merged, f)
        links = {f: p for f, p in trace_merge.linked_flow_pids(merged).items()
                 if len(p) > 1}
        dispatch_pids = sorted({ev["pid"] for ev in merged["traceEvents"]
                                if ev.get("name") == "serve:dispatch"})
        return {"trace_flow_links": len(links),
                "trace_dispatch_pids": len(dispatch_pids),
                "trace_ok": bool(links) and len(dispatch_pids) >= 2
                and all(o is not None for o in outs),
                "merged_trace": merged_path}
    finally:
        for r in remotes:
            r.close()
        shutdown_daemon(da)
        shutdown_daemon(db)


def leg_sigkill(run_id: str, out_dir: str) -> dict:
    """SIGKILL a daemon mid-burst: admitted-but-unfinished requests must
    complete on the survivor (the fabric's never-drop contract)."""
    from deepspeed_tpu.diagnostics import FaultInjector
    from deepspeed_tpu.fabric.remote import RemoteReplica
    from deepspeed_tpu.inference.router import ServingRouter

    da = spawn_daemon(9, run_id, _engine_cfg(), out_dir)
    db = spawn_daemon(10, run_id, _engine_cfg(), out_dir)
    remotes = []
    try:
        remotes = [RemoteReplica(da.url), RemoteReplica(db.url)]
        router = ServingRouter(remotes)
        prompts = _prompts(n=6)
        box: dict = {}

        def run():
            box["outs"] = router.serve(prompts, max_new_tokens=32)

        t = threading.Thread(target=run)
        t.start()
        deadline = time.time() + 120.0
        while time.time() < deadline and t.is_alive():
            if router.replicas[1].active:
                break
            time.sleep(0.02)
        killed = False
        if t.is_alive():
            FaultInjector().kill_replica_daemon(db.proc)
            killed = True
        t.join(600.0)
        outs = box.get("outs", [])
        complete = len(outs) == len(prompts) and all(
            o is not None for o in outs)
        return {"sigkill_fired": killed,
                "sigkill_complete": bool(complete),
                "sigkill_dead_replicas": int(router.dead_replicas),
                "sigkill_ok": bool(complete and killed
                                   and router.dead_replicas >= 1)}
    finally:
        for r in remotes:
            r.close()
        shutdown_daemon(da)
        shutdown_daemon(db)


# ------------------------------------------------------------- elastic leg
def trainer_main(args) -> int:
    """Trainer child: N resilient steps; optionally self-preempt (SIGTERM to
    OWN pid from the step-``preempt_at`` batch_fn — the guard honors it at
    the next step boundary with a blocking snapshot + exit 143)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import signal

    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.elasticity import run_resilient
    from tests.unit.simple_model import random_batch, simple_model_spec

    # mesh shape = however many virtual devices the parent forced via
    # XLA_FLAGS (--dp in the parent): dp defaults to the full device count,
    # so the changed-mesh relaunch is a genuinely different mesh shape
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 1000,
        "snapshot": {"enabled": True, "dir": args.snapshot_dir,
                     "every_n_steps": 2, "fsync": False, "blocking": True},
    }
    engine, *_ = deepspeed_tpu.initialize(
        model=simple_model_spec(), config=cfg, seed=3)

    preempt_at = int(args.preempt_at)

    def batch_fn(step):
        if preempt_at >= 0 and step == preempt_at:
            os.kill(os.getpid(), signal.SIGTERM)
        return random_batch(engine.train_batch_size, seed=step)

    report = run_resilient(engine, batch_fn, num_steps=int(args.steps),
                           preemptible=True)
    import hashlib

    import jax

    digest = hashlib.sha256()
    host = jax.device_get(engine.state.params)
    leaves, _ = jax.tree_util.tree_flatten_with_path(host)
    for path, leaf in leaves:
        digest.update(str(path).encode())
        digest.update(np.ascontiguousarray(
            np.asarray(leaf, dtype=np.float32)).tobytes())
    print(json.dumps({"ok": True, "steps": int(engine.global_steps),
                      "rewinds": report.rewinds,
                      "params_digest": digest.hexdigest()}), flush=True)
    return 0


def _run_trainer(snapshot_dir: str, steps: int, dp: int, preempt_at: int,
                 timeout: float = 600.0):
    import re

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    # the child's mesh width IS its virtual device count: strip any
    # inherited forcing (the test harness pins 8) and pin the leg's own
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={dp}").strip()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--trainer",
         "--snapshot-dir", snapshot_dir, "--steps", str(steps),
         "--dp", str(dp), "--preempt-at", str(preempt_at)],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=timeout)
    doc = None
    for line in reversed(proc.stdout.strip().splitlines() or [""]):
        try:
            doc = json.loads(line)
            break
        except ValueError:
            continue
    return proc.returncode, doc


def leg_elastic(out_dir: str) -> dict:
    from deepspeed_tpu.elasticity.resilience import EXIT_PREEMPTED

    res: dict = {}
    # preempt at step 3 of 8, same-mesh relaunch: trajectory bit-identical
    snap_a = os.path.join(out_dir, "snap_resume")
    rc1, _ = _run_trainer(snap_a, steps=8, dp=2, preempt_at=3)
    res["preempt_exit_code"] = rc1
    rc2, resumed = _run_trainer(snap_a, steps=8, dp=2, preempt_at=-1)
    snap_ref = os.path.join(out_dir, "snap_ref")
    rc3, ref = _run_trainer(snap_ref, steps=8, dp=2, preempt_at=-1)
    res["resumed_steps"] = (resumed or {}).get("steps")
    res["elastic_bit_identical"] = bool(
        rc1 == EXIT_PREEMPTED and rc2 == 0 and rc3 == 0
        and resumed and ref and resumed["steps"] == 8
        and resumed["params_digest"] == ref["params_digest"])
    # changed mesh shape on restart: restore + completion (fp32 reduction
    # order differs across dp widths, so no bit-identity gate here)
    snap_b = os.path.join(out_dir, "snap_remesh")
    rc4, _ = _run_trainer(snap_b, steps=8, dp=2, preempt_at=3)
    rc5, remesh = _run_trainer(snap_b, steps=8, dp=4, preempt_at=-1)
    res["elastic_remesh_ok"] = bool(
        rc4 == EXIT_PREEMPTED and rc5 == 0
        and remesh and remesh["steps"] == 8)
    res["elastic_ok"] = bool(res["elastic_bit_identical"]
                             and res["elastic_remesh_ok"])
    return res


# ------------------------------------------------------------------- main
def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 subset: serving legs only, no kill/elastic")
    ap.add_argument("--out", default=None)
    # trainer mode (internal): the elastic leg's child process
    ap.add_argument("--trainer", action="store_true")
    ap.add_argument("--snapshot-dir", default=None)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--preempt-at", dest="preempt_at", type=int, default=-1)
    args = ap.parse_args()
    if args.trainer:
        return trainer_main(args)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import tempfile

    from deepspeed_tpu import telemetry
    from deepspeed_tpu.telemetry import fleet

    out_dir = args.out or tempfile.mkdtemp(prefix="fabric_smoke_")
    os.makedirs(out_dir, exist_ok=True)
    # children share one persistent XLA compile cache (env-inherited):
    # daemons 2..N and every trainer relaunch reuse daemon 1's compiles
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(out_dir, "jax_cache"))
    run_id = f"fabric-smoke-{os.getpid():x}"
    fleet.configure_identity(run_id=run_id, process_index=0, role="router")
    telemetry.get_tracer().configure(enabled=True)

    gates: dict = {}
    failures = []
    legs = [
        ("disagg_bf16", lambda: leg_disagg_tokens(run_id, out_dir)),
        ("disagg_int8", lambda: leg_disagg_tokens(run_id, out_dir,
                                                  kv_cache_dtype="int8")),
        ("digests", lambda: leg_migration_digests(run_id, out_dir)),
        ("drain", lambda: leg_drain(run_id, out_dir)),
        ("trace", lambda: leg_merged_trace(run_id, out_dir)),
    ]
    if not args.smoke:
        legs.append(("sigkill", lambda: leg_sigkill(run_id, out_dir)))
        legs.append(("elastic", lambda: leg_elastic(out_dir)))
    for name, fn in legs:
        try:
            gates.update(fn())
        except Exception as e:  # noqa: BLE001 - a leg crash IS the finding
            failures.append(f"{name}: {type(e).__name__}: {e}")

    ok_keys = [k for k in gates
               if k.startswith("ok_") or k.endswith("_ok")
               or k in ("digests_identical",)]
    ok = not failures and bool(ok_keys) and all(gates[k] for k in ok_keys)
    print(json.dumps({"ok": ok, "mode": "smoke" if args.smoke else "full",
                      "leg_failures": failures, **gates,
                      "out_dir": out_dir}), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
