#!/usr/bin/env bash
# Nightly deep-tier run with a COMMITTED, hash-stamped artifact
# (VERDICT r5 weak item 8: the nightly tier was builder's-word-only).
#
# Usage: tools/run_nightly.sh [rNN]
# Writes NIGHTLY_rNN.log at the repo root: tree identity (HEAD sha + sha256
# of the uncommitted diff), per-test pass/fail lines, and pytest's census
# summary. Commit the log with the round notes so any auditor can match it
# to the exact tree it ran on.
set -uo pipefail
cd "$(dirname "$0")/.."

ROUND="${1:-r$(date -u +%y%m%d)}"
OUT="NIGHTLY_${ROUND}.log"

HEAD_SHA=$(git rev-parse HEAD 2>/dev/null || echo unknown)
DIFF_SHA=$(git diff HEAD 2>/dev/null | sha256sum | cut -d' ' -f1)

{
  echo "# nightly tier — $(date -u +%Y-%m-%dT%H:%M:%SZ)"
  echo "# HEAD: ${HEAD_SHA}"
  echo "# uncommitted-diff sha256: ${DIFF_SHA}"
  echo "# cmd: pytest tests/ -q -m nightly"
} > "${OUT}"

JAX_PLATFORMS=cpu python -m pytest tests/ -q -m nightly \
  -p no:cacheprovider -p no:xdist -p no:randomly \
  --continue-on-collection-errors -rA --tb=line 2>&1 | tee -a "${OUT}"
rc=${PIPESTATUS[0]}

# Fault-injection smoke (ISSUE 6): NaN at step K + writer killed mid-save on
# the CPU bench model must complete to the target step via auto-rewind, with
# 'latest' still loadable. One JSON line of evidence into the committed log.
{
  echo "# fault-injection smoke: tools/fault_smoke.py"
} >> "${OUT}"
JAX_PLATFORMS=cpu python tools/fault_smoke.py 2>/dev/null | tee -a "${OUT}"
smoke_rc=${PIPESTATUS[0]}
[ "${smoke_rc}" -ne 0 ] && rc=1

# Pallas-collectives interpret smoke (ISSUE 8): the remote-DMA hop kernels
# and the fused quantized all-reduce must keep their interpret-mode
# equivalence vs the ppermute algorithms — the census line lands in the
# committed log so a kernel regression is auditable per round.
{
  echo "# pallas-collectives interpret smoke: pytest tests/unit/comm/test_collectives.py -k pallas"
} >> "${OUT}"
# prefixed so the smoke's own pytest summary can never win the footer's
# nightly-tier census grep (^[0-9]+ (passed|failed))
JAX_PLATFORMS=cpu python -m pytest tests/unit/comm/test_collectives.py -q \
  -k "pallas" -p no:cacheprovider -p no:xdist -p no:randomly \
  --tb=line 2>&1 | tail -5 | sed 's/^/pallas-smoke: /' | tee -a "${OUT}"
pallas_rc=${PIPESTATUS[0]}
[ "${pallas_rc}" -ne 0 ] && rc=1

# Quantized-serving smoke (ISSUE 10): int8 KV chain decode on the CPU bench
# model must stay token-identical to the fp pool, the fused Pallas loads
# must match the XLA fallback under interpret, and the decode program census
# must show no full-precision pool materialization. Census line lands in the
# committed log so a quantization regression is auditable per round.
{
  echo "# quantized-serving smoke: pytest tests/unit/inference/test_quantized_serving.py"
} >> "${OUT}"
# prefixed for the same reason as the pallas smoke: the footer census grep
# must only match the nightly tier's own summary
JAX_PLATFORMS=cpu python -m pytest tests/unit/inference/test_quantized_serving.py -q \
  -p no:cacheprovider -p no:xdist -p no:randomly \
  --tb=line 2>&1 | tail -5 | sed 's/^/quant-serving-smoke: /' | tee -a "${OUT}"
quant_rc=${PIPESTATUS[0]}
[ "${quant_rc}" -ne 0 ] && rc=1

# Serving-router smoke (ISSUE 12): 2 CPU replicas under a shared-prefix
# burst through the real router — exit-gates on prefix_hit_rate > 0 (the
# content-hash cache actually served blocks) and ZERO dropped-but-admitted
# requests (shedding happens strictly before admission; an admitted request
# always finishes). The JSON line lands in the committed log.
{
  echo "# serving-router smoke: python tools/bench_serving.py --router-smoke"
} >> "${OUT}"
JAX_PLATFORMS=cpu python tools/bench_serving.py --router-smoke 2>/dev/null \
  | sed 's/^/router-smoke: /' | tee -a "${OUT}"
router_rc=${PIPESTATUS[0]}
[ "${router_rc}" -ne 0 ] && rc=1

# Disaggregated-serving smoke (ISSUE 14): a 2-pool CPU run (1 prefill + 1
# decode replica) exit-gated on zero dropped-but-admitted requests, >= 1
# successful KV-block migration, and migrated output token-identical to a
# never-migrated run — on a bf16 AND an int8 pool (quantized bytes move
# verbatim). Committed as its own artifact so the migration data plane is
# auditable per round.
DISAGG_OUT="DISAGG_${ROUND}.log"
{
  echo "# disaggregated-serving smoke — $(date -u +%Y-%m-%dT%H:%M:%SZ)"
  echo "# HEAD: ${HEAD_SHA}"
  echo "# uncommitted-diff sha256: ${DIFF_SHA}"
  echo "# cmd: python tools/bench_serving.py --disagg-smoke"
} > "${DISAGG_OUT}"
JAX_PLATFORMS=cpu python tools/bench_serving.py --disagg-smoke 2>/dev/null \
  | tee -a "${DISAGG_OUT}"
disagg_rc=${PIPESTATUS[0]}
[ "${disagg_rc}" -ne 0 ] && rc=1
echo "# disagg smoke: ${DISAGG_OUT} (exit ${disagg_rc})" >> "${OUT}"

# MoE-at-scale smoke (ISSUE 15): dp2 x ep2 x tp2 collective-dispatch
# training must match a global-math replay of its own params (the
# mis-routing gate), the int8 dispatch wire must stay within its pinned
# bound, and an ep-sharded v2 engine must decode token-identical to ep=1
# through the collective dispatch. Committed as its own artifact (log +
# JSON) so the ep x tp composition is auditable per round.
MOE_OUT="MOE_${ROUND}.log"
{
  echo "# moe-at-scale smoke — $(date -u +%Y-%m-%dT%H:%M:%SZ)"
  echo "# HEAD: ${HEAD_SHA}"
  echo "# uncommitted-diff sha256: ${DIFF_SHA}"
  echo "# cmd: python tools/moe_smoke.py --output MOE_${ROUND}.json"
} > "${MOE_OUT}"
JAX_PLATFORMS=cpu python tools/moe_smoke.py --output "MOE_${ROUND}.json" \
  2>/dev/null | tee -a "${MOE_OUT}"
moe_rc=${PIPESTATUS[0]}
[ "${moe_rc}" -ne 0 ] && rc=1
echo "# moe smoke: ${MOE_OUT} (exit ${moe_rc})" >> "${OUT}"

# Compiled-program inventory (ISSUE 7): the registry must capture a real
# train-step and v2 decode-chain program with nonzero flops/peak-HBM and a
# computed hbm/estimate_ratio. Committed alongside this log as its own
# artifact so the device-side inventory is auditable per round.
PROG_OUT="PROGRAMS_${ROUND}.log"
{
  echo "# program inventory — $(date -u +%Y-%m-%dT%H:%M:%SZ)"
  echo "# HEAD: ${HEAD_SHA}"
  echo "# uncommitted-diff sha256: ${DIFF_SHA}"
  echo "# cmd: python tools/program_report.py"
} > "${PROG_OUT}"
JAX_PLATFORMS=cpu python tools/program_report.py 2>/dev/null | tee -a "${PROG_OUT}"
prog_rc=${PIPESTATUS[0]}
[ "${prog_rc}" -ne 0 ] && rc=1
echo "# program inventory: ${PROG_OUT} (exit ${prog_rc})" >> "${OUT}"

# Collective observatory report (ISSUE 11): routed hop-scope probes on the
# 8-CPU mesh must persist a consumable decision table, the alpha/beta refit
# must land in the selector, and the drift alarm must fire on an injected
# slow sample without poisoning the table. Committed as its own artifact so
# the selector's feedback loop is auditable per round.
COLL_OUT="COLL_${ROUND}.log"
{
  echo "# collective observatory — $(date -u +%Y-%m-%dT%H:%M:%SZ)"
  echo "# HEAD: ${HEAD_SHA}"
  echo "# uncommitted-diff sha256: ${DIFF_SHA}"
  echo "# cmd: python tools/coll_report.py"
} > "${COLL_OUT}"
JAX_PLATFORMS=cpu python tools/coll_report.py \
  --table telemetry_out/coll_table.json 2>/dev/null | tee -a "${COLL_OUT}"
coll_rc=${PIPESTATUS[0]}
[ "${coll_rc}" -ne 0 ] && rc=1
echo "# collective observatory: ${COLL_OUT} (exit ${coll_rc})" >> "${OUT}"

# Fleet telemetry smoke (ISSUE 13): an in-process collector + 2 real CPU
# worker processes — exit-gates on the federated counters BIT-EXACTLY
# equaling the per-process sums, on a merged Perfetto trace containing
# flow-linked spans from both worker processes (router admission arrow ->
# remote serve:dispatch slice), on every worker landing in the health
# ledger with a clock offset, and on the federated observatory table
# round-tripping into a fresh selector's measured mode. Committed as its
# own artifact so the fleet plane is auditable per round.
FLEET_OUT="FLEET_${ROUND}.log"
{
  echo "# fleet telemetry smoke — $(date -u +%Y-%m-%dT%H:%M:%SZ)"
  echo "# HEAD: ${HEAD_SHA}"
  echo "# uncommitted-diff sha256: ${DIFF_SHA}"
  echo "# cmd: python tools/fleet_smoke.py"
} > "${FLEET_OUT}"
JAX_PLATFORMS=cpu python tools/fleet_smoke.py 2>/dev/null | tee -a "${FLEET_OUT}"
fleet_rc=${PIPESTATUS[0]}
[ "${fleet_rc}" -ne 0 ] && rc=1
echo "# fleet smoke: ${FLEET_OUT} (exit ${fleet_rc})" >> "${OUT}"

# Numerics observatory smoke (ISSUE 17), exit-gated BOTH ways: a clean
# 20-step run must raise ZERO divergence/drift events AND an injected
# single-replica bit flip (faultinject.flip_param_bit) must latch a
# divergence event within one sampled step; wire probes must cover every
# lossy codec inside its pinned bound; the abort policy must raise. The
# accuracy trajectories (wire_rel_err/<codec>, divergence_detect_steps)
# land in the unified perf ledger, suite "numerics", so the perf-gate
# stage above MAD-gates them next round exactly like latency.
NUMERICS_OUT="NUMERICS_${ROUND}.log"
{
  echo "# numerics observatory smoke — $(date -u +%Y-%m-%dT%H:%M:%SZ)"
  echo "# HEAD: ${HEAD_SHA}"
  echo "# uncommitted-diff sha256: ${DIFF_SHA}"
  echo "# cmd: python tools/numerics_smoke.py --ledger"
} > "${NUMERICS_OUT}"
JAX_PLATFORMS=cpu python tools/numerics_smoke.py --ledger 2>/dev/null \
  | tee -a "${NUMERICS_OUT}"
numerics_rc=${PIPESTATUS[0]}
[ "${numerics_rc}" -ne 0 ] && rc=1
echo "# numerics smoke: ${NUMERICS_OUT} (exit ${numerics_rc})" >> "${OUT}"

# Incident-plane smoke (ISSUE 20), exit-gated BOTH ways: a clean 20-step
# run with the default alert rule pack and a live collector must stay ALL
# quiet (zero warn+ events, zero firing alerts, zero incidents), and the
# injected double fault (flip_param_bit + SIGKILLed replica daemon) must
# correlate into exactly ONE incident naming both typed events at
# GET /incidents, fire the matching alerts, and render through
# tools/incident_report.py.
ALERTS_OUT="ALERTS_${ROUND}.log"
{
  echo "# incident-plane smoke — $(date -u +%Y-%m-%dT%H:%M:%SZ)"
  echo "# HEAD: ${HEAD_SHA}"
  echo "# uncommitted-diff sha256: ${DIFF_SHA}"
  echo "# cmd: python tools/alerts_smoke.py"
} > "${ALERTS_OUT}"
JAX_PLATFORMS=cpu python tools/alerts_smoke.py 2>/dev/null \
  | tee -a "${ALERTS_OUT}"
alerts_rc=${PIPESTATUS[0]}
[ "${alerts_rc}" -ne 0 ] && rc=1
echo "# alerts smoke: ${ALERTS_OUT} (exit ${alerts_rc})" >> "${OUT}"

# Collective schedule compiler + fused GEMM smoke (ISSUE 19), exit-gated:
# synthesized hop programs must execute bit-identically to jax.lax on the
# CPU mesh (1D ring AND a (4,2) sub-ring factorization), the compiled
# schedule must be >= parity with the best hand-written pick under the
# selector's own calibrated cost model (and a beta-dominant refit must
# flip the SAME query back to a hand pick — the model is live, not a
# frozen copy), and the fused ZeRO-3 sharded_matmul trajectory must track
# the unfused composition over a multi-step SGD loop. Headline ratios
# (compiled_vs_hand/pred_ratio, fused_gemm/step_time_ratio) land in the
# unified perf ledger, suite "schedule", for next round's MAD gate.
SCHED_OUT="SCHED_${ROUND}.log"
{
  echo "# schedule compiler smoke — $(date -u +%Y-%m-%dT%H:%M:%SZ)"
  echo "# HEAD: ${HEAD_SHA}"
  echo "# uncommitted-diff sha256: ${DIFF_SHA}"
  echo "# cmd: python tools/schedule_smoke.py --ledger"
} > "${SCHED_OUT}"
JAX_PLATFORMS=cpu python tools/schedule_smoke.py --ledger 2>/dev/null \
  | tee -a "${SCHED_OUT}"
sched_rc=${PIPESTATUS[0]}
[ "${sched_rc}" -ne 0 ] && rc=1
echo "# schedule smoke: ${SCHED_OUT} (exit ${sched_rc})" >> "${OUT}"

# Cross-process serving fabric smoke (ISSUE 18): real replica-daemon
# processes behind the unchanged router. Exit-gates: remote greedy decode
# token-identical to a local engine on bf16 AND int8 KV, cross-process
# migration preserves every per-block blake2b digest, drain completes
# without drops, merged trace links >= 2 pids through serve:dispatch,
# a SIGKILLed daemon mid-burst loses ZERO admitted requests, and a
# SIGTERMed trainer (exit 143) restarts bit-identically — including onto
# a different mesh shape (dp=2 -> dp=4). Committed as its own artifact so
# the fabric's liveness/identity story is auditable per round.
FABRIC_OUT="FABRIC_${ROUND}.log"
{
  echo "# serving fabric smoke — $(date -u +%Y-%m-%dT%H:%M:%SZ)"
  echo "# HEAD: ${HEAD_SHA}"
  echo "# uncommitted-diff sha256: ${DIFF_SHA}"
  echo "# cmd: python tools/fabric_smoke.py --out telemetry_out/fabric"
} > "${FABRIC_OUT}"
JAX_PLATFORMS=cpu python tools/fabric_smoke.py \
  --out telemetry_out/fabric 2>/dev/null | tee -a "${FABRIC_OUT}"
fabric_rc=${PIPESTATUS[0]}
[ "${fabric_rc}" -ne 0 ] && rc=1
echo "# fabric smoke: ${FABRIC_OUT} (exit ${fabric_rc})" >> "${OUT}"

# Fabric wire-cost bench: remote dispatch RTT / wire KV migration / drain
# handoff as perf-ledger suite "fabric" rows (gated by the perf stage once
# history reaches quorum).
JAX_PLATFORMS=cpu python tools/bench_serving.py --remote 2>/dev/null \
  | tail -20 | sed 's/^/bench-remote: /' | tee -a "${FABRIC_OUT}"
[ "${PIPESTATUS[0]}" -ne 0 ] && { fabric_rc=1; rc=1; }

# Perf-gate stage (ISSUE 16): (a) migrate-check — the committed ledger must
# still cover every legacy *_rNN.json artifact; (b) the noise-aware gate
# must PASS at HEAD against the committed history; (c) the same gate must
# FAIL on a synthetic 30% regression (inverted exit check — a sentinel that
# can't fire is worse than none); (d) the step-time attribution smoke must
# decompose a real CPU bench step into buckets that sum to the wall.
# Committed as its own artifact so the regression observatory is auditable
# per round.
PERFGATE_OUT="PERFGATE_${ROUND}.log"
{
  echo "# perf gate — $(date -u +%Y-%m-%dT%H:%M:%SZ)"
  echo "# HEAD: ${HEAD_SHA}"
  echo "# uncommitted-diff sha256: ${DIFF_SHA}"
  echo "# cmd: perf_ledger.py migrate --check && perf_gate.py && ! perf_gate.py --inject-pct 30 && perf_report.py --smoke"
} > "${PERFGATE_OUT}"
perfgate_rc=0
JAX_PLATFORMS=cpu python tools/perf_ledger.py migrate --check 2>/dev/null \
  | tee -a "${PERFGATE_OUT}"
[ "${PIPESTATUS[0]}" -ne 0 ] && perfgate_rc=1
JAX_PLATFORMS=cpu python tools/perf_gate.py 2>/dev/null \
  | tee -a "${PERFGATE_OUT}"
[ "${PIPESTATUS[0]}" -ne 0 ] && perfgate_rc=1
# the sentinel demonstration: this run MUST exit nonzero
JAX_PLATFORMS=cpu python tools/perf_gate.py --inject-pct 30 --json 2>/dev/null \
  | sed 's/^/inject-30pct: /' | tee -a "${PERFGATE_OUT}"
if [ "${PIPESTATUS[0]}" -eq 0 ]; then
  echo "inject-30pct: FAIL — gate did not fire on a 30% synthetic regression" \
    | tee -a "${PERFGATE_OUT}"
  perfgate_rc=1
else
  echo "inject-30pct: OK — gate fired (nonzero exit) as required" \
    | tee -a "${PERFGATE_OUT}"
fi
JAX_PLATFORMS=cpu python tools/perf_report.py --smoke 2>/dev/null \
  | tail -3 | sed 's/^/attribution: /' | tee -a "${PERFGATE_OUT}"
[ "${PIPESTATUS[0]}" -ne 0 ] && perfgate_rc=1
echo "# perf gate exit: ${perfgate_rc}" >> "${PERFGATE_OUT}"
[ "${perfgate_rc}" -ne 0 ] && rc=1
echo "# perf gate: ${PERFGATE_OUT} (exit ${perfgate_rc})" >> "${OUT}"

{
  echo "# exit code: ${rc} (fault smoke: ${smoke_rc}, pallas smoke: ${pallas_rc}, quant-serving smoke: ${quant_rc}, router smoke: ${router_rc}, disagg smoke: ${disagg_rc}, moe smoke: ${moe_rc}, program report: ${prog_rc}, coll report: ${coll_rc}, fleet smoke: ${fleet_rc}, numerics smoke: ${numerics_rc}, alerts smoke: ${alerts_rc}, fabric smoke: ${fabric_rc}, perf gate: ${perfgate_rc})"
  echo "# census: $(grep -aE '^[0-9]+ (passed|failed)' "${OUT}" | tail -1)"
} >> "${OUT}"
echo "wrote ${OUT} ${PROG_OUT} ${COLL_OUT} ${FLEET_OUT} ${DISAGG_OUT} ${MOE_OUT} ${NUMERICS_OUT} ${ALERTS_OUT} ${FABRIC_OUT} ${PERFGATE_OUT}"
exit "${rc}"
