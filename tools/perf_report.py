#!/usr/bin/env python
"""Perf report renderer: ledger -> round tables + trajectory curves.

  (default)            print the markdown report for the committed ledger
  --update-perf-md F   regenerate the marker-delimited generated section
                       inside PERF.md (everything between the BEGIN/END
                       markers is owned by this tool; the hand-written
                       narrative above them is not touched)
  --smoke              step-time attribution smoke on the CPU bench config:
                       builds the tiny bench engine, measures one steady
                       step, decomposes it via profiling/attribution.py and
                       exit-gates on the four buckets summing exactly to
                       the measured wall (the decomposition's contract) —
                       the nightly's attribution stage

The report body is selective on purpose: the table shows the gate's own
rows (headline metrics + overhead bounds per round); sparklines show every
headline key with >=2 rounds of history. The full 460+-row ledger stays
queryable via ``tools/perf_ledger.py show``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BEGIN_MARK = "<!-- BEGIN GENERATED: perf_report (tools/perf_report.py) -->"
END_MARK = "<!-- END GENERATED: perf_report -->"

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: List[float]) -> str:
    lo, hi = min(values), max(values)
    if hi <= lo:
        return _SPARK[0] * len(values)
    return "".join(_SPARK[int((v - lo) / (hi - lo) * (len(_SPARK) - 1))]
                   for v in values)


def _table_rows(ledger) -> List[Tuple]:
    from deepspeed_tpu.telemetry.perfgate import (
        GateConfig, is_headline, is_overhead_metric,
    )

    cfg = GateConfig()
    out = []
    for r in ledger.rows():
        if is_headline(r, cfg) or is_overhead_metric(r["metric"]):
            out.append((r["suite"], r["metric"], int(r["round"]),
                        r["backend"], float(r["value"]), r["unit"],
                        r["method"]))
    return sorted(out)


def render_report(ledger) -> str:
    from deepspeed_tpu.telemetry.perfledger import row_key

    lines = ["## Perf ledger round table", "",
             f"{len(ledger.rows())} rows in `perf/ledger/` "
             f"({', '.join(ledger.suites())}). Gate-relevant rows "
             "(headline metrics and overhead bounds):", "",
             "| suite | metric | round | backend | value | unit | method |",
             "|---|---|---|---|---|---|---|"]
    for suite, metric, rnd, backend, value, unit, method in _table_rows(ledger):
        lines.append(f"| {suite} | `{metric}` | r{rnd:02d} | {backend} "
                     f"| {value:g} | {unit} | {method} |")

    # trajectories: headline keys with history
    by_key: Dict[Tuple[str, str, str], List[Tuple[int, float]]] = {}
    for suite, metric, rnd, backend, value, _unit, _m in _table_rows(ledger):
        by_key.setdefault((backend, suite, metric), []).append((rnd, value))
    lines += ["", "### Trajectories", ""]
    curves = 0
    for (backend, suite, metric), pts in sorted(by_key.items()):
        pts = sorted(pts)
        if len(pts) < 2:
            continue
        vals = [v for _, v in pts]
        rounds = [r for r, _ in pts]
        lines.append(f"- `{suite}/{metric}` [{backend}] "
                     f"r{rounds[0]:02d}→r{rounds[-1]:02d}: "
                     f"{sparkline(vals)}  ({vals[0]:g} → {vals[-1]:g})")
        curves += 1
    if not curves:
        lines.append("- (no key has multi-round history yet)")
    return "\n".join(lines) + "\n"


def update_perf_md(path: str, body: str) -> bool:
    with open(path, encoding="utf-8") as f:
        text = f.read()
    block = f"{BEGIN_MARK}\n\n{body}\n{END_MARK}"
    if BEGIN_MARK in text and END_MARK in text:
        head, rest = text.split(BEGIN_MARK, 1)
        _, tail = rest.split(END_MARK, 1)
        new = head + block + tail
    else:
        new = text.rstrip() + "\n\n" + block + "\n"
    if new == text:
        return False
    with open(path, "w", encoding="utf-8") as f:
        f.write(new)
    return True


def attribution_smoke() -> int:
    """Exit-gated attribution on the CPU bench config: buckets must sum
    exactly to the measured wall, the compute bucket must be nonzero (the
    program registry captured real flops), and the verdict must name a
    bucket."""
    import time

    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec
    from deepspeed_tpu.profiling.attribution import attribute_program

    telemetry.configure(enabled=True)
    cfg = TransformerConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_layers=2, num_heads=4, max_seq_len=256,
    )
    seq, micro = 256, 4
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": micro,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 1},
            "bf16": {"enabled": True},
            "steps_per_print": 10_000,
            "telemetry": {"enabled": True},
        })
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}
    for _ in range(3):  # warm the compile cache off the clock
        m = engine.train_batch(batch)
    np.asarray(m["loss"])
    t0 = time.perf_counter()
    m = engine.train_batch(batch)
    np.asarray(m["loss"])
    wall_s = time.perf_counter() - t0

    attr = attribute_program("train_step", wall_s)
    print(attr.render())
    print(json.dumps(attr.as_dict(), sort_keys=True))

    bucket_sum = sum(attr.buckets().values())
    ok = (abs(bucket_sum - attr.wall_ms) < 1e-6 * max(attr.wall_ms, 1.0)
          and attr.compute_ms > 0.0
          and all(v >= 0.0 for v in attr.buckets().values())
          and attr.bound in ("compute", "memory", "comm", "host", "stall"))
    print(f"attribution-smoke: {'OK' if ok else 'FAIL'} "
          f"(buckets_sum={bucket_sum:.4f}ms wall={attr.wall_ms:.4f}ms "
          f"bound={attr.bound})")
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ledger", default=None,
                    help="ledger dir (default: <repo>/perf/ledger)")
    ap.add_argument("--update-perf-md", default=None, metavar="PERF_MD",
                    help="rewrite the generated block inside this file")
    ap.add_argument("--smoke", action="store_true",
                    help="run the exit-gated attribution smoke instead")
    args = ap.parse_args(argv)

    if args.smoke:
        return attribution_smoke()

    from deepspeed_tpu.telemetry.perfledger import PerfLedger

    ledger = PerfLedger(args.ledger)
    body = render_report(ledger)
    if args.update_perf_md:
        changed = update_perf_md(args.update_perf_md, body)
        print(f"perf_report: {args.update_perf_md} "
              f"{'updated' if changed else 'unchanged'}")
        return 0
    print(body, end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
