#!/usr/bin/env python
"""Noise-aware perf regression gate (telemetry/perfgate.py).

Modes:

  (default)        self-check: gate the latest round of every
                   (backend, suite, metric) key in the committed ledger
                   against its own strictly-older history — the nightly's
                   HEAD-must-pass stage
  --fresh FILE     gate a fresh run's rows (JSON list or JSONL of schema-v1
                   rows) against the full ledger history
  --inject-pct P   degrade the self-check's fresh rows by P% in each row's
                   bad direction before gating — proves the sentinel FIRES
                   (the nightly runs this with an inverted exit check)

Exit 0 iff no regression. A regression also increments the
``perf/regression_events`` counter, publishes ``perf/trajectory`` gauges,
and arms every live profiler capture (``--no-arm`` to skip), so a nightly
regression leaves a profiler trace.

Gate policy (see perfgate.py): ``*overhead_pct`` rows gate on the repo's
absolute <2% bound; per-suite headline metrics gate on median+MAD (quorum
>=3) with a 30% relative fallback below quorum; everything else is
trajectory-only. Backends never mix.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_rows(path: str):
    with open(path, encoding="utf-8") as f:
        text = f.read().strip()
    if text.startswith("["):
        return json.loads(text)
    return [json.loads(line) for line in text.splitlines() if line.strip()]


def main(argv=None) -> int:
    from deepspeed_tpu.telemetry import perfgate
    from deepspeed_tpu.telemetry.perfledger import PerfLedger, row_key

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ledger", default=None,
                    help="ledger dir (default: <repo>/perf/ledger)")
    ap.add_argument("--fresh", default=None,
                    help="JSON/JSONL file of fresh schema-v1 rows to gate")
    ap.add_argument("--inject-pct", type=float, default=None,
                    help="synthetically degrade fresh rows by this %% "
                         "(sentinel demonstration; expected to FAIL)")
    ap.add_argument("--policy", choices=["headline", "all"], default="headline")
    ap.add_argument("--mads", type=float, default=6.0)
    ap.add_argument("--quorum", type=int, default=3)
    ap.add_argument("--rel-bound", type=float, default=0.30)
    ap.add_argument("--overhead-bound", type=float, default=2.0)
    ap.add_argument("--no-arm", action="store_true",
                    help="do not arm profiler captures on regression")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON summary line")
    args = ap.parse_args(argv)

    cfg = perfgate.GateConfig(
        mads=args.mads, quorum=args.quorum, rel_bound=args.rel_bound,
        overhead_bound_pct=args.overhead_bound, policy=args.policy)
    ledger = PerfLedger(args.ledger)

    if args.fresh:
        rows = _load_rows(args.fresh)
        if args.inject_pct:
            rows = perfgate.inject_regression(rows, args.inject_pct)
        report = perfgate.gate_fresh(rows, ledger, cfg)
    elif args.inject_pct:
        # self-check's fresh rows, degraded, re-gated as a next round
        by_key = {}
        for r in ledger.rows():
            by_key.setdefault(row_key(r), []).append(r)
        fresh = []
        for rows_ in by_key.values():
            latest = max(int(r["round"]) for r in rows_)
            fresh += [dict(r, round=latest + 1) for r in rows_
                      if int(r["round"]) == latest]
        report = perfgate.gate_fresh(
            perfgate.inject_regression(fresh, args.inject_pct), ledger, cfg)
    else:
        report = perfgate.self_check(ledger, cfg)

    pub = perfgate.publish(report, arm=not args.no_arm)
    if args.json:
        print(json.dumps({
            "rows": len(report.verdicts),
            "gated": sum(1 for v in report.verdicts if v.mode != "info"),
            "regressions": pub["regressions"],
            "captures_armed": pub["captures_armed"],
            "ok": report.ok,
        }, sort_keys=True))
    else:
        print(report.summary())
        if report.regressions:
            print(f"perf_gate: armed {pub['captures_armed']} profiler "
                  f"capture(s)")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
