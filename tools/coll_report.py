#!/usr/bin/env python
"""Collective observatory report (collectives/observatory.py).

Two uses:

  - **library**: ``render_report()`` formats whatever the process-global
    observatory + selector hold — the measured-vs-model latency curves per
    hop backend, the calibrated alpha/beta constants, drift counters, and a
    staleness check on the persisted decision table.
  - **CLI / nightly stage**: run standalone it forces an 8-device CPU mesh,
    routes the four algorithmic collectives (all_to_all included) through the comm facade,
    drains the observatory's probe queue (real timed hop-scope dispatches),
    refits alpha/beta, injects one deliberately slow sample to prove the
    drift alarm arms, and persists the online table — proving on every
    nightly that the selector's feedback loop closes end to end
    (``tools/run_nightly.sh`` commits the output as COLL_rNN.log).

Exit 0 iff probes ran for every op, the table holds at least two algorithm
families per op, the refit produced finite constants the selector consumes,
the injected slow sample fired the drift alarm (without poisoning the
table), and the persisted table round-trips through the versioned loader.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def render_report(max_table_age_hours: Optional[float] = None,
                  table: Optional[str] = None) -> str:
    """Text report of the process-global observatory + selector state;
    ``table`` overrides which file the staleness section inspects (the CLI
    passes ``--table`` so the printed verdict and the exit gate agree)."""
    from deepspeed_tpu.collectives import observatory, selector

    obs = observatory.get_observatory()
    cfg = selector.get_config()
    rows = obs.table_rows()
    lines = ["# collective observatory report", ""]

    s = obs.summary()
    lines.append(f"routes={s['routes']} probes_merged={s['merged_samples']} "
                 f"table_rows={s['table_rows']} drift_events={s['drift_events']}")
    lines.append("")

    lines.append("## calibrated cost model (alpha us/hop, beta us/MB)")
    if not s["calibration"]:
        lines.append("  (no refit ran)")
    for backend, (a, b) in sorted(s["calibration"].items()):
        bw = 1e3 / b if b > 0 else float("inf")
        lines.append(f"  {backend:<10} alpha={a:10.3f}  beta={b:10.3f}"
                     f"  (~{bw:.2f} GB/s effective)")
    lines.append("")

    lines.append("## measured vs model, per backend")
    hdr = (f"  {'op':<15} {'alg':<12} {'codec':<6} {'backend':<9} "
           f"{'world':>5} {'size_mb':>8} {'meas_ms':>9} {'model_ms':>9} {'ratio':>7}")
    lines.append(hdr)
    for r in sorted(rows, key=lambda r: (r.get("backend", ""), r["op"],
                                         float(r["size_mb"]), r["algorithm"])):
        nbytes = int(float(r["size_mb"]) * 1e6)
        try:
            model_ms = selector.estimate_us(
                r["op"], r["algorithm"], r.get("codec", "none"), nbytes,
                int(r["world"]), cfg, int(r.get("itemsize", 4))) / 1e3
        except ValueError:
            model_ms = float("nan")
        meas = float(r["latency_ms"])
        ratio = meas / model_ms if model_ms > 0 else float("nan")
        lines.append(f"  {r['op']:<15} {r['algorithm']:<12} "
                     f"{r.get('codec', 'none'):<6} {r.get('backend', '?'):<9} "
                     f"{int(r['world']):>5} {float(r['size_mb']):>8.4f} "
                     f"{meas:>9.4f} {model_ms:>9.4f} {ratio:>7.2f}")
    lines.append("")

    path = table or obs.table_path()
    if os.path.exists(path):
        age_h = (time.time() - os.path.getmtime(path)) / 3600.0
        stale = (max_table_age_hours is not None
                 and age_h > max_table_age_hours)
        lines.append(f"## table: {path} age={age_h:.2f}h"
                     + (f"  ** STALE (> {max_table_age_hours}h): re-sweep or "
                        "re-run with the observatory enabled **" if stale
                        else ""))
    else:
        lines.append(f"## table: {path} (not persisted yet)")
    return "\n".join(lines)


def table_age_hours(path: str) -> Optional[float]:
    if not os.path.exists(path):
        return None
    return (time.time() - os.path.getmtime(path)) / 3600.0


def _drive_probes(table_path: str, rounds: int) -> dict:
    """Route the four algorithmic ops on an 8-device CPU mesh, drain the
    observatory probe queue, refit, and fire the injected-drift check."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import deepspeed_tpu.comm as dist
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.collectives import observatory, selector, table
    from deepspeed_tpu.utils.compat import shard_map

    telemetry.configure(enabled=True)
    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
    obs = observatory.configure(
        enabled=True, sample_every=1, persist=True, table_path=table_path,
        refit_every=4, drift_ratio=3.0)
    obs.install(mesh=mesh)

    def route(fn, out_specs):
        f = jax.jit(shard_map(fn, mesh=mesh, in_specs=P("dp"),
                              out_specs=out_specs, check_vma=False))
        # flat payload, local length divisible by the world (reduce_scatter)
        f(jnp.ones((8 * 4096,), jnp.float32)).block_until_ready()

    route(lambda v: dist.all_reduce(v, "dp", algorithm="ring", codec="int8",
                                    block_size=64), P("dp"))
    route(lambda v: dist.all_gather(v, "dp", algorithm="ring", codec="none"),
          P("dp"))
    route(lambda v: dist.reduce_scatter(v, "dp", algorithm="ring",
                                        codec="none"), P("dp"))
    # all_to_all (ISSUE 15): the MoE dispatch wire enters the same feedback
    # loop — quantized ring route + a second family via the probe queue
    route(lambda v: dist.all_to_all(v, "dp", split_axis=0, concat_axis=0,
                                    algorithm="ring", codec="int8",
                                    block_size=64), P("dp"))

    step = 0
    for _ in range(rounds):
        # sample_now drains the PENDING queue (bounded); a subsequent
        # on_step refills it for the next round (the queue re-arms itself so
        # steady state keeps re-measuring — an unbounded `while ran` here
        # would spin forever)
        obs.sample_now()
        step += 1
        obs.on_step(step)
    obs.refit()

    # injected slow sample: 100x a routed row's measured latency must trip
    # the drift alarm — WITHOUT merging into the table (merge=False)
    drift_before = obs.drift_events
    rows = obs.table_rows()
    routed = next((r for r in rows if r["algorithm"] == "ring"
                   and r["op"] == "all_reduce"), None)
    pre_latency = float(routed["latency_ms"]) if routed else None
    if routed is not None:
        obs.record_sample(
            op=routed["op"], algorithm=routed["algorithm"],
            codec=routed["codec"], backend=routed["backend"],
            world=routed["world"], size_mb=float(routed["size_mb"]),
            latency_ms=float(routed["latency_ms"]) * 100.0,
            itemsize=int(routed.get("itemsize", 4)),
            check_drift=True, merge=False)
    drift_fired = obs.drift_events > drift_before

    persisted = obs.persist()
    loaded = table.load_table(persisted) if persisted else []
    post = next((r for r in loaded
                 if table.row_key(r) == table.row_key(routed)), None
                ) if routed else None
    drift_clean = (post is not None and pre_latency is not None
                   and float(post["latency_ms"]) == pre_latency)
    per_op_algs = {}
    for r in obs.table_rows():
        per_op_algs.setdefault(r["op"], set()).add(r["algorithm"])
    calib = dict(obs.calibration)
    return {
        "probes_per_op": {op: len(a) for op, a in per_op_algs.items()},
        "ops_probed": sorted(per_op_algs),
        "multi_algorithm_coverage": all(len(a) >= 2 for a in per_op_algs.values()),
        "refit_finite": bool(calib) and all(
            all(abs(v) < float("inf") for v in ab) for ab in calib.values()),
        "selector_calibrated": bool(selector.get_config().backend_ab),
        "drift_fired": drift_fired,
        # the injected (merge=False) slow sample must NOT have moved the
        # persisted routed row — the alarm path never poisons the table
        "drift_kept_out_of_table": drift_clean,
        "table_roundtrip_rows": len(loaded),
    }


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--table", default=os.path.join(
        "telemetry_out", "coll_table.json"))
    ap.add_argument("--rounds", type=int, default=1,
                    help="full probe-queue drains to run")
    ap.add_argument("--max-table-age-hours", type=float, default=None,
                    help="flag (and gate on) a persisted table older than this")
    ap.add_argument("--no-probe", action="store_true",
                    help="report only what the process already observed")
    args = ap.parse_args(argv)

    if not args.no_probe:
        # 8 virtual CPU devices BEFORE jax initializes (the probe mesh)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        from deepspeed_tpu.utils.cpu_backend import force_cpu_backend

        force_cpu_backend()

    gates = {}
    if not args.no_probe:
        gates = _drive_probes(args.table, args.rounds)

    print(render_report(args.max_table_age_hours, table=args.table), flush=True)

    if args.no_probe:
        age = table_age_hours(args.table)
        stale = (args.max_table_age_hours is not None and age is not None
                 and age > args.max_table_age_hours)
        return 1 if stale else 0

    ok = {
        "ops_probed": set(gates.get("ops_probed", ())) == {
            "all_reduce", "all_gather", "reduce_scatter", "all_to_all"},
        "multi_algorithm_coverage": gates.get("multi_algorithm_coverage", False),
        "refit_finite": gates.get("refit_finite", False),
        "selector_calibrated": gates.get("selector_calibrated", False),
        "drift_fired": gates.get("drift_fired", False),
        "drift_kept_out_of_table": gates.get("drift_kept_out_of_table", False),
        "table_roundtrip": gates.get("table_roundtrip_rows", 0) > 0,
    }
    print(json.dumps({"coll_report": {**gates, **{f"ok_{k}": v for k, v in ok.items()}},
                      "ok": all(ok.values())}), flush=True)
    return 0 if all(ok.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
