#!/usr/bin/env python
"""Compiled-program inventory report (telemetry/programs.py).

Two uses:

  - **library**: ``render_report()`` formats whatever the process-global
    ``ProgramRegistry`` has captured — call it at the end of any run with
    telemetry enabled to see every program XLA built, its cost/memory
    analysis, collective content, and the HBM estimate-vs-actual ratio.
  - **CLI / nightly stage**: run standalone it builds the tiny CPU bench
    engines (one training engine, one v2 serving engine), drives a few
    steps through each, and dumps the inventory — proving on every nightly
    that the capture path records real train-step and decode-chain programs
    with nonzero flops/peak-HBM and a computed calibration ratio
    (``tools/run_nightly.sh`` commits the output as PROGRAMS_rNN.log).

Exit 0 iff the inventory holds a captured training step AND a v2 serving
program, each with nonzero flops and peak HBM, and an ``hbm/estimate_ratio``
was computed for both scopes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _fmt_bytes(b: float) -> str:
    if b >= (1 << 30):
        return f"{b / (1 << 30):.2f}G"
    if b >= (1 << 20):
        return f"{b / (1 << 20):.2f}M"
    if b >= (1 << 10):
        return f"{b / (1 << 10):.1f}K"
    return f"{int(b)}"


def _fmt_flops(f: float) -> str:
    if f >= 1e12:
        return f"{f / 1e12:.2f}T"
    if f >= 1e9:
        return f"{f / 1e9:.2f}G"
    if f >= 1e6:
        return f"{f / 1e6:.2f}M"
    return f"{f:.3g}"


def render_report(registry=None) -> str:
    """Text inventory of every captured compile, in capture order."""
    if registry is None:
        from deepspeed_tpu.telemetry.programs import get_program_registry

        registry = get_program_registry()
    records = registry.records()
    header = (f"{'#':>3} {'program':<28} {'hlo':<12} {'instr':>6} "
              f"{'compile':>9} {'flops':>8} {'bytes':>8} {'peak_hbm':>9} "
              f"{'alias':>8} {'coll':>4} {'coll_B':>8} {'est_ratio':>9}")
    lines = ["compiled-program inventory "
             f"({len(records)} capture(s), {len(registry.labels())} program(s), "
             f"{registry.capture_failures} capture failure(s))",
             header, "-" * len(header)]
    for r in records:
        wall = (f"{r.compile_wall_s * 1e3:8.1f}ms"
                if r.compile_wall_s is not None else "        -")
        ratio = (f"{r.hbm_estimate_ratio:9.2f}"
                 if r.hbm_estimate_ratio is not None else "        -")
        lines.append(
            f"{r.index:>3} {r.label:<28} {r.fingerprint:<12} "
            f"{r.instruction_count:>6} {wall} {_fmt_flops(r.flops):>8} "
            f"{_fmt_bytes(r.bytes_accessed):>8} {_fmt_bytes(r.peak_hbm_bytes):>9} "
            f"{_fmt_bytes(r.alias_bytes):>8} {len(r.collectives):>4} "
            f"{_fmt_bytes(r.collective_bytes):>8} {ratio}")
        for c in r.collectives:
            lines.append(f"      - {c['kind']:<20} {_fmt_bytes(c['bytes']):>8} "
                         f"{c['replica_groups']}")
    for scope in ("train", "serving"):
        est = registry.hbm_estimate(scope)
        if est:
            lines.append(f"hbm estimate [{scope}]: {_fmt_bytes(est)} "
                         "(utils/hbm.py pre-flight; ratio = XLA peak / estimate)")
    return "\n".join(lines)


def _drive_probe_engines(steps: int, decode_tokens: int) -> None:
    """Build the tiny CPU bench engines and step them so the registry holds
    a real train-step and a real v2 decode-chain program."""
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec
    from deepspeed_tpu.models.transformer import CausalLM
    import jax
    import jax.numpy as jnp

    cfg = TransformerConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_layers=2, num_heads=4, max_seq_len=256,
    )
    seq = 64
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "steps_per_print": 10_000,
            "telemetry": {"enabled": True},
        })
    r = np.random.default_rng(0)
    for step in range(steps):
        engine.train_batch({"input_ids": r.integers(
            0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)})

    module = CausalLM(cfg)
    params = module.init(
        {"params": jax.random.PRNGKey(0)},
        {"input_ids": jnp.zeros((1, 8), jnp.int32)})["params"]
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2

    v2 = InferenceEngineV2(cfg, params, config={
        "num_kv_blocks": 128, "kv_block_size": 16, "max_seqs": 4,
        "decode_chain": 4, "hbm_check": "warn"})
    prompts = [np.arange(6, dtype=np.int32), np.arange(9, dtype=np.int32)]
    v2.generate(prompts, max_new_tokens=decode_tokens)


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="also write the full inventory as JSON")
    ap.add_argument("--no-probe", action="store_true",
                    help="report only what the process already captured "
                         "(library mode; skips building the probe engines)")
    ap.add_argument("--steps", type=int, default=2,
                    help="training steps to drive through the probe engine")
    ap.add_argument("--decode-tokens", type=int, default=8,
                    help="tokens to decode through the probe v2 engine")
    args = ap.parse_args(argv)

    from deepspeed_tpu.telemetry import configure as telemetry_configure
    from deepspeed_tpu.telemetry.programs import get_program_registry

    registry = get_program_registry()
    if not args.no_probe:
        telemetry_configure(enabled=True)
        _drive_probe_engines(args.steps, args.decode_tokens)

    print(render_report(registry), flush=True)
    if args.json:
        with open(args.json, "w") as f:
            json.dump({
                "records": [r.as_dict() for r in registry.records()],
                "hbm_estimates": {s: registry.hbm_estimate(s)
                                  for s in ("train", "serving")},
                "capture_failures": registry.capture_failures,
            }, f, indent=1)
        print(f"wrote {args.json}", flush=True)

    if args.no_probe:
        return 0
    # nightly gate: real programs, real costs, calibrated against the guard
    train = [r for r in registry.records() if r.label == "train_step"]
    serving = [r for r in registry.records() if r.label.startswith("v2:")]
    ok = {
        "train_step_captured": bool(train),
        "train_step_costs": any(r.flops > 0 and r.peak_hbm_bytes > 0 for r in train),
        "train_ratio": any(r.hbm_estimate_ratio is not None for r in train),
        "v2_captured": bool(serving),
        "v2_decode_chain": any(r.label.startswith("v2:decode_chain")
                               for r in serving),
        "v2_costs": any(r.flops > 0 and r.peak_hbm_bytes > 0 for r in serving),
        "v2_ratio": any(r.hbm_estimate_ratio is not None for r in serving),
    }
    print(json.dumps({"program_report": ok, "ok": all(ok.values())}), flush=True)
    return 0 if all(ok.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
