#!/usr/bin/env python
"""Unified perf ledger CLI (telemetry/perfledger.py + perfmigrate.py).

  migrate          append every legacy ``*_rNN.json`` family into
                   ``perf/ledger/*.jsonl`` (idempotent — re-running appends
                   nothing; originals stay in place as the evidence)
  migrate --check  verify the committed ledger still contains every row a
                   fresh migration would produce (subset check: rows
                   appended live since migration are fine) — nonzero on
                   drift; the nightly's migrate-check stage
  list             per-suite row/key census of the ledger
  show             print rows (optionally one --suite / --metric) as JSONL

The ledger root defaults to ``<repo>/perf/ledger`` (override with
``--ledger`` or ``$DSTPU_PERF_LEDGER_DIR``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    from deepspeed_tpu.telemetry import perfmigrate
    from deepspeed_tpu.telemetry.perfledger import PerfLedger, row_key

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("cmd", choices=["migrate", "list", "show"])
    ap.add_argument("--ledger", default=None,
                    help="ledger dir (default: <repo>/perf/ledger)")
    ap.add_argument("--repo", default=REPO,
                    help="root holding the legacy *_rNN.json artifacts")
    ap.add_argument("--check", action="store_true",
                    help="migrate: verify-only, nonzero if the ledger is "
                         "missing any freshly-migratable row")
    ap.add_argument("--suite", default=None)
    ap.add_argument("--metric", default=None)
    args = ap.parse_args(argv)

    ledger = PerfLedger(args.ledger)

    if args.cmd == "migrate":
        if args.check:
            missing = perfmigrate.check(args.repo, ledger)
            if missing:
                print(f"perf_ledger check: FAIL — {len(missing)} legacy "
                      f"row(s) missing from {ledger.root}")
                for r in missing[:10]:
                    print(f"  missing: [{r['backend']}] {r['suite']}/"
                          f"{r['metric']} r{r['round']} = {r['value']}")
                return 1
            print(f"perf_ledger check: OK — ledger at {ledger.root} covers "
                  f"all legacy artifacts")
            return 0
        stats = perfmigrate.migrate(args.repo, ledger)
        print(f"perf_ledger migrate: {stats['found']} legacy rows found, "
              f"{stats['appended']} appended -> {ledger.root}")
        return 0

    if args.cmd == "list":
        rows = ledger.rows()
        by_suite = {}
        for r in rows:
            s = by_suite.setdefault(r["suite"], {"rows": 0, "keys": set(),
                                                 "rounds": set()})
            s["rows"] += 1
            s["keys"].add(row_key(r))
            s["rounds"].add(int(r["round"]))
        print(f"# ledger {ledger.root}: {len(rows)} rows, "
              f"{len(by_suite)} suites")
        for suite in sorted(by_suite):
            s = by_suite[suite]
            rounds = sorted(s["rounds"])
            print(f"  {suite:<10} rows={s['rows']:<5} keys={len(s['keys']):<4}"
                  f" rounds={rounds[0]}..{rounds[-1]}")
        return 0

    # show
    for r in ledger.rows(args.suite):
        if args.metric and r["metric"] != args.metric:
            continue
        print(json.dumps(r, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
