"""Profile the bench train step on the real chip: where does the time go?

Breakdown measured:
  1. pure jitted step latency (device program, steady-state, async dispatch)
  2. engine.train_batch latency (adds batch placement + metrics sync)
  3. XLA cost analysis flops of the compiled step vs model flops estimate
  4. dispatch-only latency (tiny no-op jit) to bound per-call RPC overhead
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, causal_lm_spec


def timeit(fn, n=10, warmup=3, block=lambda r: jax.block_until_ready(r)):
    for _ in range(warmup):
        r = fn()
    block(r)
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    block(r)
    return (time.perf_counter() - t0) / n


def main():
    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    print(f"backend={backend}")

    if on_tpu:
        cfg = TransformerConfig(
            vocab_size=50304, hidden_size=768, intermediate_size=3072,
            num_layers=12, num_heads=12, max_seq_len=1024,
            norm="layernorm", activation="gelu", position="learned",
            tie_embeddings=True, dtype=jnp.bfloat16,
        )
        micro, seq = 8, 1024
        peak_flops = 197e12
    else:
        cfg = TransformerConfig(vocab_size=512, hidden_size=128, intermediate_size=256,
                                num_layers=2, num_heads=4, max_seq_len=256)
        micro, seq = 2, 128
        peak_flops = 1e12

    config = {
        "train_micro_batch_size_per_gpu": micro,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.1}},
        "zero_optimization": {"stage": 1},
        "bf16": {"enabled": True},
        "gradient_clipping": 1.0,
        "steps_per_print": 10_000,
    }
    engine, *_ = deepspeed_tpu.initialize(model=causal_lm_spec(cfg, example_seq_len=seq), config=config)

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}

    # 4. dispatch floor: trivial jit call round-trip
    f_nop = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8,), jnp.float32)
    t_nop_async = timeit(lambda: f_nop(x), n=50, warmup=5, block=lambda r: None)
    t_nop_sync = timeit(lambda: jax.block_until_ready(f_nop(x)), n=50, warmup=5)
    print(f"dispatch nop: async={t_nop_async*1e3:.2f} ms, sync-roundtrip={t_nop_sync*1e3:.2f} ms")

    # 1. pure jitted step
    placed = engine._shard_global_batch(batch)
    state = engine.state
    step_fn = engine._train_step

    def pure():
        nonlocal state
        state, m = step_fn(state, placed)
        return m["loss"]

    t_pure = timeit(pure, n=10, warmup=3)
    print(f"pure jitted step: {t_pure*1e3:.1f} ms")
    engine.state = state

    # 1b. pure step without re-placing batch, async chain of 10 then block
    def chain10():
        nonlocal state
        for _ in range(10):
            state, m = step_fn(state, placed)
        return m["loss"]
    t_chain = timeit(chain10, n=3, warmup=1) / 10
    engine.state = state
    print(f"chained x10 step (amortized dispatch): {t_chain*1e3:.1f} ms")

    # 2. engine.train_batch (includes _shard_global_batch + metrics np.asarray sync)
    t_engine = timeit(lambda: engine.train_batch(batch)["loss"], n=10, warmup=3,
                      block=lambda r: None)
    print(f"engine.train_batch: {t_engine*1e3:.1f} ms")

    # batch placement cost alone
    t_place = timeit(lambda: engine._shard_global_batch(batch), n=10, warmup=3,
                     block=lambda r: jax.block_until_ready(r))
    print(f"batch placement: {t_place*1e3:.1f} ms")

    # 3. cost analysis
    lowered = step_fn.lower(engine.state, placed)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    xla_flops = ca.get("flops", float("nan"))
    tokens = engine.train_batch_size * seq
    model_flops = cfg.flops_per_token(seq) * tokens
    print(f"xla flops/step: {xla_flops:.3e}; model flops/step (6ND-style): {model_flops:.3e}")

    best = min(t_pure, t_chain)
    mfu_pure = model_flops / best / peak_flops
    mfu_engine = model_flops / t_engine / peak_flops
    print(json.dumps({
        "t_pure_ms": t_pure * 1e3, "t_chain_ms": t_chain * 1e3,
        "t_engine_ms": t_engine * 1e3, "t_place_ms": t_place * 1e3,
        "nop_async_ms": t_nop_async * 1e3, "nop_sync_ms": t_nop_sync * 1e3,
        "mfu_pure": mfu_pure, "mfu_engine": mfu_engine,
        "xla_flops": xla_flops, "model_flops": model_flops,
    }))


if __name__ == "__main__":
    main()
