#!/usr/bin/env python
"""Profile the bench train step on the real chip — all four stages in one
parameterized tool (formerly profile_bench.py + profile_bench{2,3,4}.py).

  --stage 1   step/engine/dispatch breakdown + XLA cost analysis: pure jitted
              step latency, chained x10 amortized dispatch, engine.train_batch,
              batch placement, no-op dispatch floor, flops + MFU
  --stage 2   block_until_ready honesty + true device times: chained
              dispatch/block/fetch split, fwd-only, fwd+bwd, 8k matmul rate
  --stage 3   step decomposition: in-program matmul rate (50x fori_loop),
              fwd and fwd+bwd at micro 8/32, optimizer-only update, lm-head
              matmul
  --stage 4   per-shape matmul sweep, flash-vs-xla attention fwd/bwd, and a
              jax.profiler trace attempt
  --stage attn
              tuned flash kernel vs xla reference: fwd + bwd latency and
              max-abs-diff parity check (formerly profile_attn.py)
  --stage attn-sweep
              flash kernel block-size sweep chained inside ONE jitted program
              via lax.scan so dispatch amortizes away; --grad times fwd+bwd
              (formerly profile_attn_sweep.py)
  --stage all run every stage in order

Usage: python tools/profile_bench.py [--stage 1|2|3|4|attn|attn-sweep|all]
                                     [--batch B] [--seq S] [--grad]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


# --------------------------------------------------------------- shared bits
def timeit(fn, n=10, warmup=3, block=lambda r: jax.block_until_ready(r)):
    for _ in range(warmup):
        r = fn()
    block(r)
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    block(r)
    return (time.perf_counter() - t0) / n


def fetch_time(fn, out_leaf=lambda r: r, n=5, warmup=2):
    """Time dispatch->device->host-fetch of one output leaf (the honest
    per-call latency on an async-dispatch runtime)."""
    for _ in range(warmup):
        r = fn()
    _ = np.asarray(out_leaf(r))
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    _ = np.asarray(out_leaf(r))
    return (time.perf_counter() - t0) / n


def _gpt2_cfg():
    from deepspeed_tpu.models import TransformerConfig

    return TransformerConfig(
        vocab_size=50304, hidden_size=768, intermediate_size=3072,
        num_layers=12, num_heads=12, max_seq_len=1024,
        norm="layernorm", activation="gelu", position="learned",
        tie_embeddings=True, dtype=jnp.bfloat16,
    )


def _engine(cfg, micro, seq, stage3=False):
    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm_spec

    config = {
        "train_micro_batch_size_per_gpu": micro,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}
                      if stage3 else {"lr": 1e-4, "weight_decay": 0.1}},
        "zero_optimization": {"stage": 1},
        "bf16": {"enabled": True},
        "steps_per_print": 10_000,
    }
    if not stage3:
        config["gradient_clipping"] = 1.0
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq), config=config)
    return engine


# ------------------------------------------------------------------ stage 1
def stage1():
    """Where does the time go: step vs engine vs dispatch floor + MFU."""
    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    print(f"backend={backend}")

    if on_tpu:
        cfg = _gpt2_cfg()
        micro, seq = 8, 1024
        peak_flops = 197e12
    else:
        from deepspeed_tpu.models import TransformerConfig

        cfg = TransformerConfig(vocab_size=512, hidden_size=128, intermediate_size=256,
                                num_layers=2, num_heads=4, max_seq_len=256)
        micro, seq = 2, 128
        peak_flops = 1e12

    engine = _engine(cfg, micro, seq)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}

    # dispatch floor: trivial jit call round-trip
    f_nop = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8,), jnp.float32)
    t_nop_async = timeit(lambda: f_nop(x), n=50, warmup=5, block=lambda r: None)
    t_nop_sync = timeit(lambda: jax.block_until_ready(f_nop(x)), n=50, warmup=5)
    print(f"dispatch nop: async={t_nop_async*1e3:.2f} ms, sync-roundtrip={t_nop_sync*1e3:.2f} ms")

    # pure jitted step
    placed = engine._shard_global_batch(batch)
    state = engine.state
    step_fn = engine._train_step

    def pure():
        nonlocal state
        state, m = step_fn(state, placed)
        return m["loss"]

    t_pure = timeit(pure, n=10, warmup=3)
    print(f"pure jitted step: {t_pure*1e3:.1f} ms")
    engine.state = state

    # pure step, async chain of 10 then block (amortized dispatch)
    def chain10():
        nonlocal state
        for _ in range(10):
            state, m = step_fn(state, placed)
        return m["loss"]

    t_chain = timeit(chain10, n=3, warmup=1) / 10
    engine.state = state
    print(f"chained x10 step (amortized dispatch): {t_chain*1e3:.1f} ms")

    # engine.train_batch (adds _shard_global_batch + metrics np.asarray sync)
    t_engine = timeit(lambda: engine.train_batch(batch)["loss"], n=10, warmup=3,
                      block=lambda r: None)
    print(f"engine.train_batch: {t_engine*1e3:.1f} ms")

    t_place = timeit(lambda: engine._shard_global_batch(batch), n=10, warmup=3,
                     block=lambda r: jax.block_until_ready(r))
    print(f"batch placement: {t_place*1e3:.1f} ms")

    # XLA cost analysis vs model flops
    lowered = step_fn.lower(engine.state, placed)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    xla_flops = ca.get("flops", float("nan"))
    tokens = engine.train_batch_size * seq
    model_flops = cfg.flops_per_token(seq) * tokens
    print(f"xla flops/step: {xla_flops:.3e}; model flops/step (6ND-style): {model_flops:.3e}")

    best = min(t_pure, t_chain)
    print(json.dumps({
        "t_pure_ms": t_pure * 1e3, "t_chain_ms": t_chain * 1e3,
        "t_engine_ms": t_engine * 1e3, "t_place_ms": t_place * 1e3,
        "nop_async_ms": t_nop_async * 1e3, "nop_sync_ms": t_nop_sync * 1e3,
        "mfu_pure": model_flops / best / peak_flops,
        "mfu_engine": model_flops / t_engine / peak_flops,
        "xla_flops": xla_flops, "model_flops": model_flops,
    }))


# ------------------------------------------------------------------ stage 2
def stage2():
    """Is block_until_ready honest, and what is the true device time?"""
    from deepspeed_tpu.models import CausalLM
    from deepspeed_tpu.topology.mesh import set_mesh

    cfg = _gpt2_cfg()
    micro, seq = 8, 1024
    engine = _engine(cfg, micro, seq)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}
    placed = engine._shard_global_batch(batch)
    state = engine.state
    step_fn = engine._train_step

    for _ in range(2):
        state, m = step_fn(state, placed)
    _ = np.asarray(m["loss"])

    # A: chain 5 steps; dispatch vs block vs fetch
    t0 = time.perf_counter()
    for _ in range(5):
        state, m = step_fn(state, placed)
    t_dispatch = time.perf_counter() - t0
    jax.block_until_ready(m["loss"])
    t_block = time.perf_counter() - t0
    _ = np.asarray(m["loss"])
    t_fetch = time.perf_counter() - t0
    print(f"5 steps: dispatch={t_dispatch*1e3:.1f}ms block={t_block*1e3:.1f}ms fetch={t_fetch*1e3:.1f}ms")
    print(f"=> true per-step: {t_fetch*1e3/5:.1f} ms")

    # B: forward-only loss
    module = CausalLM(cfg)
    set_mesh(engine.mesh)
    params16 = jax.jit(lambda p: jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        p))(state.params)
    micro_b = {"input_ids": jnp.asarray(batch["input_ids"])}

    @jax.jit
    def fwd(p, b):
        loss, _ = module.apply({"params": p}, b, train=False)
        return loss

    t_fwd = fetch_time(lambda: fwd(params16, micro_b))
    print(f"fwd-only: {t_fwd*1e3:.1f} ms")

    # C: fwd+bwd grads only (no optimizer)
    @jax.jit
    def fwdbwd(p, b):
        def loss_fn(pp):
            loss, _ = module.apply({"params": pp}, b, train=False)
            return loss
        return jax.value_and_grad(loss_fn)(p)[0]

    t_fb = fetch_time(lambda: fwdbwd(params16, micro_b))
    print(f"fwd+bwd: {t_fb*1e3:.1f} ms")

    # D: big matmul sanity
    a = jnp.zeros((8192, 8192), jnp.bfloat16)
    b = jnp.zeros((8192, 8192), jnp.bfloat16)
    mm = jax.jit(lambda a, b: a @ b)
    t_mm = fetch_time(lambda: mm(a, b), lambda r: r[0, 0], n=10)
    fl = 2 * 8192**3
    print(f"8k matmul: {t_mm*1e3:.2f} ms => {fl/t_mm/1e12:.1f} TFLOP/s")


# ------------------------------------------------------------------ stage 3
def stage3():
    """Decompose the step: honest fwd+bwd, optimizer-only, in-program rate."""
    from deepspeed_tpu.models import CausalLM
    from deepspeed_tpu.topology.mesh import set_mesh

    cfg = _gpt2_cfg()
    seq = 1024
    module = CausalLM(cfg)
    engine = _engine(cfg, 8, seq, stage3=True)
    set_mesh(engine.mesh)
    state = engine.state
    params16 = jax.jit(lambda p: jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        p))(state.params)
    rng = np.random.default_rng(0)

    # true device matmul rate: 50 matmuls inside one program
    a = jnp.zeros((8192, 8192), jnp.bfloat16)

    @jax.jit
    def mm50(a):
        def body(i, acc):
            return acc + a @ a * (1.0 / (i + 1))
        return jax.lax.fori_loop(0, 50, body, jnp.zeros_like(a))[0, 0]

    t = fetch_time(lambda: mm50(a), n=2, warmup=1)
    print(f"50x 8k matmul in-program: {t*1e3:.1f} ms => {50*2*8192**3/t/1e12:.1f} TFLOP/s")

    for micro in (8, 32):
        b = {"input_ids": jnp.asarray(rng.integers(0, cfg.vocab_size, (micro, seq), dtype=np.int32))}

        @jax.jit
        def fwd(p, b):
            loss, _ = module.apply({"params": p}, b, train=False)
            return loss

        @jax.jit
        def fwdbwd(p, b):
            def loss_fn(pp):
                loss, _ = module.apply({"params": pp}, b, train=False)
                return loss
            return jax.value_and_grad(loss_fn)(p)

        t_f = fetch_time(lambda: fwd(params16, b))
        t_fb = fetch_time(
            lambda: fwdbwd(params16, b),
            lambda r: r[1]["lm_head"]["embedding"] if "lm_head" in r[1]
            else jax.tree_util.tree_leaves(r[1])[0])
        fwd_fl = 2 * 124e6 * micro * seq  # 2*N*T matmul flops approx (fwd)
        print(f"micro={micro}: fwd={t_f*1e3:.1f}ms ({fwd_fl/t_f/1e12:.1f} TF/s) "
              f"fwd+bwd={t_fb*1e3:.1f}ms ({3*fwd_fl/t_fb/1e12:.1f} TF/s)")

    # optimizer-only update (adamw on fp32 master)
    tx = engine.tx
    grads = jax.tree_util.tree_map(lambda x: jnp.ones(x.shape, jnp.float32), state.params)

    @jax.jit
    def opt_only(params, opt_state, grads):
        import optax

        updates, new_opt = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt

    t_o = fetch_time(lambda: opt_only(state.params, state.opt_state, grads),
                     lambda r: jax.tree_util.tree_leaves(r[0])[0])
    print(f"optimizer-only: {t_o*1e3:.1f} ms")

    # lm-head matmul microbench (vocab is the big matmul)
    emb = jnp.zeros((50304, 768), jnp.bfloat16)
    h = jnp.zeros((8 * 1024, 768), jnp.bfloat16)
    head = jax.jit(lambda h, emb: (h @ emb.T)[0, 0])
    t_h = fetch_time(lambda: head(h, emb))
    print(f"lm head matmul (8k x 768 x 50k): {t_h*1e3:.2f} ms => {2*8192*768*50304/t_h/1e12:.1f} TF/s")


# ------------------------------------------------------------------ stage 4
def stage4():
    """Per-shape matmul sweep, flash-vs-xla attention, profiler trace."""
    def mm_rate(M, K, N, dtype=jnp.bfloat16, n=10):
        a = jnp.zeros((M, K), dtype)
        b = jnp.zeros((K, N), dtype)
        f = jax.jit(lambda a, b: (a @ b).sum())
        t = fetch_time(lambda: f(a, b), n=n, warmup=3)
        return t, 2 * M * K * N / t / 1e12

    print("matmul shape sweep (bf16):")
    for (M, K, N) in [(8192, 768, 768), (8192, 768, 3072), (8192, 3072, 768),
                      (8192, 768, 50304), (32768, 768, 3072), (8192, 8192, 8192)]:
        t, r = mm_rate(M, K, N)
        print(f"  [{M},{K}]x[{K},{N}]: {t*1e3:.2f} ms {r:.1f} TF/s")

    # attention: flash vs xla, fwd + bwd
    from deepspeed_tpu.ops.registry import dispatch
    B, S, H, D = 8, 1024, 12, 64
    q = jnp.zeros((B, S, H, D), jnp.bfloat16)
    k = jnp.zeros((B, S, H, D), jnp.bfloat16)
    v = jnp.zeros((B, S, H, D), jnp.bfloat16)
    att_fl = 4 * B * H * S * S * D
    for impl in ("pallas", "xla"):
        try:
            fn = jax.jit(lambda q, k, v, f=dispatch("causal_attention", impl): f(q, k, v, mask=None).sum())
            t = fetch_time(lambda: fn(q, k, v), n=10, warmup=3)
            print(f"attention {impl}: {t*1e3:.2f} ms ({att_fl/t/1e12:.1f} TF/s)")
        except Exception as e:
            print(f"attention {impl}: FAILED {type(e).__name__} {e}")
    for impl in ("pallas", "xla"):
        try:
            f = dispatch("causal_attention", impl)
            fn = jax.jit(lambda q, k, v: jax.grad(
                lambda qq: f(qq, k, v, mask=None).astype(jnp.float32).sum())(q).sum())
            t = fetch_time(lambda: fn(q, k, v), n=10, warmup=3)
            print(f"attention-bwd {impl}: {t*1e3:.2f} ms")
        except Exception as e:
            print(f"attention-bwd {impl}: FAILED {type(e).__name__} {e}")

    # profiler trace attempt
    try:
        a = jnp.zeros((4096, 4096), jnp.bfloat16)
        f = jax.jit(lambda a: a @ a)
        with jax.profiler.trace("/tmp/jaxtrace"):
            r = f(a)
            np.asarray(r[0, 0])
        import glob
        files = glob.glob("/tmp/jaxtrace/**/*", recursive=True)
        print(f"profiler trace files: {len(files)}")
        for p in files[:8]:
            print("  ", p, os.path.getsize(p) if os.path.isfile(p) else "dir")
    except Exception as e:
        print(f"profiler trace FAILED: {type(e).__name__} {e}")


# --------------------------------------------------------------- stage attn
def stage_attn(batch=None, seq=None, grad=False):
    """Tuned flash kernel vs the xla reference: fwd/bwd latency + parity."""
    del grad  # attn always times both fwd and bwd
    from deepspeed_tpu.ops.registry import dispatch

    B, S, H, D = batch or 8, seq or 1024, 12, 64
    q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, S, H, D), jnp.bfloat16)
    att_fl = 4 * B * H * S * S * D  # fwd flops (causal halves useful work)

    outs = {}
    for impl in ("pallas", "xla"):
        f = dispatch("causal_attention", impl)
        fn = jax.jit(lambda q, k, v, f=f: f(q, k, v))
        r = fn(q, k, v)
        outs[impl] = np.asarray(r, np.float32)
        t = fetch_time(lambda: fn(q, k, v), lambda r: r[0, 0, 0, 0], n=10, warmup=3)
        print(f"fwd {impl}: {t*1e3:.2f} ms ({att_fl/t/1e12:.1f} TF/s)")

    err = np.abs(outs["pallas"] - outs["xla"]).max()
    print(f"fwd max abs diff pallas vs xla: {err:.4f}")

    grads = {}
    for impl in ("pallas", "xla"):
        f = dispatch("causal_attention", impl)

        @jax.jit
        def gfn(q, k, v, f=f):
            def loss(q, k, v):
                return f(q, k, v).astype(jnp.float32).sum()
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        r = gfn(q, k, v)
        t = fetch_time(lambda: gfn(q, k, v), lambda r: r[0][0, 0, 0, 0], n=10, warmup=3)
        print(f"bwd {impl}: {t*1e3:.2f} ms")
        grads[impl] = [np.asarray(x, np.float32) for x in r]
    for nm, a, b in zip("qkv", grads["pallas"], grads["xla"]):
        print(f"d{nm} max abs diff: {np.abs(a-b).max():.4f} (scale {np.abs(b).max():.2f})")


# --------------------------------------------------------- stage attn-sweep
def stage_attn_sweep(batch=None, seq=None, grad=False):
    """Sweep flash-attention block sizes inside ONE jitted program.

    A lax.scan chains the kernel invocations with a data dependency (the
    output feeds the next query), so per-program relay dispatch (~6 ms)
    amortizes away and the measured time is the kernel itself.
    """
    from deepspeed_tpu.ops.pallas.flash_attention import flash_causal_attention

    def bench(fn, *args, iters=20):
        # grad mode differentiates w.r.t. ALL of q/k/v and feeds every
        # gradient back into the carry — otherwise the dkv kernel is dead
        # code under jit and the sweep never times it.
        inner = jax.grad(lambda q, k, v: (fn(q, k, v).astype(jnp.float32) ** 2).sum(),
                         argnums=(0, 1, 2))

        @jax.jit
        def chained(q, k, v):
            def body(carry, _):
                q, k, v = carry
                decay = jnp.asarray(0.999, q.dtype)
                eps = jnp.asarray(1e-3, q.dtype)
                if grad:
                    dq, dk, dv = inner(q, k, v)
                    new = (q * decay + dq.astype(q.dtype) * eps,
                           k * decay + dk.astype(k.dtype) * eps,
                           v * decay + dv.astype(v.dtype) * eps)
                else:
                    new = (fn(q, k, v) * eps + q * decay, k, v)
                return new, ()

            (q, k, v), _ = jax.lax.scan(body, (q, k, v), None, length=iters)
            return q

        r = chained(*args)
        _ = np.asarray(r[0, 0, 0, 0])  # warm compile + sync
        t0 = time.perf_counter()
        r = chained(*args)
        _ = np.asarray(r[0, 0, 0, 0])
        return (time.perf_counter() - t0) / iters

    B, S, H, D = batch or 4, seq or 1024, 12, 64
    q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, S, H, D), jnp.bfloat16)
    fl = 4 * B * H * S * S * D  # dense fwd flops; causal useful ~ (1+nblk)/(2 nblk)
    if grad:
        # fwd (2 matmuls) + dq kernel (3: s, dp, ds@k) + dkv kernel (4: s, dv,
        # dp, dk) = 18 B·H·S²·D dense matmul flops per step
        fl = fl * 18 // 4

    # k_splits > 1 = sub-chunked online softmax (next QK^T hoisted over the
    # previous chunk's VPU passes) — the round-5 attack on the per-cell
    # softmax serialization named in PERF.md.
    for bq, bk, ks in ((256, 256, 1), (256, 512, 1), (512, 256, 1),
                       (512, 512, 1), (512, 512, 2), (512, 1024, 1),
                       (512, 1024, 2), (512, 1024, 4),
                       (1024, 512, 1), (1024, 512, 2),
                       (1024, 1024, 1), (1024, 1024, 2), (1024, 1024, 4),
                       (1024, 2048, 4), (2048, 2048, 4)):
        if bq > S or bk > S:
            continue
        fn = lambda q, k, v: flash_causal_attention(q, k, v, block_q=bq,
                                                    block_k=bk, k_splits=ks)
        try:
            t = bench(fn, q, k, v)
        except Exception as e:  # noqa: BLE001 - sweep keeps going past bad configs
            print(f"bq={bq} bk={bk} ks={ks}: FAIL {type(e).__name__}")
            continue
        print(f"bq={bq:5d} bk={bk:5d} ks={ks}: {t*1e3:7.3f} ms  "
              f"dense-rate {fl/t/1e12:6.1f} TF/s")


STAGES = {"1": stage1, "2": stage2, "3": stage3, "4": stage4}
ATTN_STAGES = {"attn": stage_attn, "attn-sweep": stage_attn_sweep}


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--stage", choices=[*STAGES, *ATTN_STAGES, "all"], default="1")
    ap.add_argument("--batch", type=int, default=None,
                    help="batch dim for the attn stages (attn: 8, attn-sweep: 4)")
    ap.add_argument("--seq", type=int, default=None,
                    help="seq dim for the attn stages (default 1024)")
    ap.add_argument("--grad", action="store_true",
                    help="attn-sweep: time fwd+bwd instead of fwd-only")
    args = ap.parse_args()
    for name in STAGES if args.stage == "all" else [args.stage]:
        if args.stage == "all":
            print(f"\n===== stage {name} =====")
        if name in ATTN_STAGES:
            ATTN_STAGES[name](batch=args.batch, seq=args.seq, grad=args.grad)
        else:
            STAGES[name]()


if __name__ == "__main__":
    main()
