"""Sweep flash-attention kernel block sizes inside ONE jitted program.

A lax.scan chains ITER kernel invocations with a data dependency (the output
feeds the next query), so per-program relay dispatch (~6 ms) amortizes away
and the measured time is the kernel itself.
"""
from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.pallas.flash_attention import flash_causal_attention


def bench(fn, *args, iters=20, grad=False):
    # grad mode differentiates w.r.t. ALL of q/k/v and feeds every gradient
    # back into the carry — otherwise the dkv kernel is dead code under jit
    # and the sweep never times it.
    inner = jax.grad(lambda q, k, v: (fn(q, k, v).astype(jnp.float32) ** 2).sum(),
                     argnums=(0, 1, 2))

    @jax.jit
    def chained(q, k, v):
        def body(carry, _):
            q, k, v = carry
            decay = jnp.asarray(0.999, q.dtype)
            eps = jnp.asarray(1e-3, q.dtype)
            if grad:
                dq, dk, dv = inner(q, k, v)
                new = (q * decay + dq.astype(q.dtype) * eps,
                       k * decay + dk.astype(k.dtype) * eps,
                       v * decay + dv.astype(v.dtype) * eps)
            else:
                new = (fn(q, k, v) * eps + q * decay, k, v)
            return new, ()

        (q, k, v), _ = jax.lax.scan(body, (q, k, v), None, length=iters)
        return q

    r = chained(*args)
    _ = np.asarray(r[0, 0, 0, 0])  # warm compile + sync
    t0 = time.perf_counter()
    r = chained(*args)
    _ = np.asarray(r[0, 0, 0, 0])
    return (time.perf_counter() - t0) / iters


def main():
    B, S, H, D = 4, 1024, 12, 64
    grad = "--grad" in sys.argv
    argv = [a for a in sys.argv if a != "--grad"]
    if len(argv) > 2:
        B, S = int(argv[1]), int(argv[2])
    elif len(argv) > 1:
        B = int(argv[1])
    q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, S, H, D), jnp.bfloat16)
    fl = 4 * B * H * S * S * D  # dense fwd flops; causal useful ~ (1+nblk)/(2 nblk)
    if grad:
        # fwd (2 matmuls) + dq kernel (3: s, dp, ds@k) + dkv kernel (4: s, dv,
        # dp, dk) = 18 B·H·S²·D dense matmul flops per step
        fl = fl * 18 // 4

    # k_splits > 1 = sub-chunked online softmax (next QK^T hoisted over the
    # previous chunk's VPU passes) — the round-5 attack on the per-cell
    # softmax serialization named in PERF.md.
    for bq, bk, ks in ((256, 256, 1), (256, 512, 1), (512, 256, 1),
                       (512, 512, 1), (512, 512, 2), (512, 1024, 1),
                       (512, 1024, 2), (512, 1024, 4),
                       (1024, 512, 1), (1024, 512, 2),
                       (1024, 1024, 1), (1024, 1024, 2), (1024, 1024, 4),
                       (1024, 2048, 4), (2048, 2048, 4)):
        if bq > S or bk > S:
            continue
        fn = lambda q, k, v: flash_causal_attention(q, k, v, block_q=bq,
                                                    block_k=bk, k_splits=ks)
        try:
            t = bench(fn, q, k, v, grad=grad)
        except Exception as e:  # noqa: BLE001 - sweep keeps going past bad configs
            print(f"bq={bq} bk={bk} ks={ks}: FAIL {type(e).__name__}")
            continue
        print(f"bq={bq:5d} bk={bk:5d} ks={ks}: {t*1e3:7.3f} ms  "
              f"dense-rate {fl/t/1e12:6.1f} TF/s")


if __name__ == "__main__":
    main()
