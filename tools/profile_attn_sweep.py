"""Sweep flash-attention kernel block sizes inside ONE jitted program.

A lax.scan chains ITER kernel invocations with a data dependency (the output
feeds the next query), so per-program relay dispatch (~6 ms) amortizes away
and the measured time is the kernel itself.
"""
from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.pallas.flash_attention import flash_causal_attention


def bench(fn, *args, iters=20):
    @jax.jit
    def chained(q, k, v):
        def body(q, _):
            o = fn(q, k, v)
            return (o * jnp.asarray(1e-3, o.dtype) + q * jnp.asarray(0.999, q.dtype)), ()

        out, _ = jax.lax.scan(body, q, None, length=iters)
        return out

    r = chained(*args)
    _ = np.asarray(r[0, 0, 0, 0])  # warm compile + sync
    t0 = time.perf_counter()
    r = chained(*args)
    _ = np.asarray(r[0, 0, 0, 0])
    return (time.perf_counter() - t0) / iters


def main():
    B, S, H, D = 4, 1024, 12, 64
    if len(sys.argv) > 2:
        B, S = int(sys.argv[1]), int(sys.argv[2])
    elif len(sys.argv) > 1:
        B = int(sys.argv[1])
    q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, S, H, D), jnp.bfloat16)
    fl = 4 * B * H * S * S * D  # dense fwd flops; causal useful ~ (1+nblk)/(2 nblk)

    for bq, bk in ((256, 256), (256, 512), (512, 256), (512, 512), (512, 1024),
                   (1024, 512), (1024, 1024)):
        if bq > S or bk > S:
            continue
        fn = lambda q, k, v: flash_causal_attention(q, k, v, block_q=bq, block_k=bk)
        try:
            t = bench(fn, q, k, v)
        except Exception as e:  # noqa: BLE001 - sweep keeps going past bad configs
            print(f"bq={bq} bk={bk}: FAIL {type(e).__name__}")
            continue
        print(f"bq={bq:5d} bk={bk:5d}: {t*1e3:7.3f} ms  dense-rate {fl/t/1e12:6.1f} TF/s")


if __name__ == "__main__":
    main()
