#!/usr/bin/env python
"""Collective schedule compiler + fused GEMM smoke, exit-gated (ISSUE 19).

The nightly's proof that the GC3/T3 stack holds its two contracts
(``tools/run_nightly.sh`` commits ``SCHED_rNN.log``):

  1. **Compiled programs MUST execute bit-identically** — the synthesized
     hop programs (``algorithm="compiled[:sig]"``) round-trip through the
     facade onto the CPU mesh and match ``jax.lax`` exactly on exact
     wires, on a 1D world-8 ring AND a (4,2) two-axis mesh (the sub-ring
     factorization path).
  2. **Compiled MUST be >= parity with the best hand-written pick under
     the calibrated model** — at the representative query (int8 1 MB
     all_reduce, world 30) both sides are costed by THE selector's own
     refit-calibrated :class:`CostModel`; ``pred_ratio`` > 1 means the
     search started losing to its own baseline. Under the alpha-dominant
     refit the compiled [2,3,5] program must strictly WIN (14 hops vs
     ring2d's 18 / bidir's 58) and the selector must route to it.
  3. **A refit MUST be able to flip the pick** — recalibrating the SAME
     model to beta-dominant constants flips the SAME query to ``bidir``
     (half per-link wire beats single-direction sub-rings). The cost
     model the compiler consumed is observably the live calibrated
     object, not a frozen copy.
  4. **Fused ZeRO-3 trajectory MUST track unfused** — a multi-step SGD
     loop through ``zeropp.sharded_matmul`` (fused all-gather+matmul
     forward, fused matmul+reduce-scatter backward, batch-sharded x)
     must keep its loss trajectory within tolerance of the config-off
     lax composition over every step.

Headline trajectories land in the perf ledger (``--ledger``), suite
``schedule``: ``compiled_vs_hand/pred_ratio`` and
``fused_gemm/step_time_ratio`` (both direction=lower, gated by the PR-16
median+MAD machinery via ``perfgate.HEADLINE_PATTERNS``), plus the
trajectory-only ``fused_gemm/traj_rel_err``.

Prints one JSON line of evidence (the committed-log artifact).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

TRAJ_STEPS = 10
TRAJ_RTOL = 1e-4


def _gate_compiled_bit_identity(evidence: dict, gates: dict) -> None:
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from deepspeed_tpu.collectives import algorithms
    from deepspeed_tpu.utils.compat import shard_map

    devs = np.array(jax.devices()[:8])
    rng = np.random.default_rng(0)
    checks: dict = {}

    # 1D world-8 ring: searched program + a forced deep factorization,
    # including a non-divisible payload (L=333 exercises the pad path).
    mesh1 = Mesh(devs, ("dp",))

    def run1(f, x, outs):
        return jax.jit(shard_map(f, mesh=mesh1, in_specs=P("dp"),
                                 out_specs=outs, check_vma=False))(x)

    for L in (1000, 333):
        x = jnp.asarray(rng.integers(-8, 8, size=(8 * L,)).astype(np.float32))
        for alg in ("compiled", "compiled:dp*2.none/dp*2.none/dp*2.none"):
            got = run1(lambda v, a=alg: algorithms.all_reduce(
                v, "dp", algorithm=a), x, P("dp"))
            want = run1(lambda v: jax.lax.psum(v, "dp"), x, P("dp"))
            checks[f"ar_1d_L{L}_{alg}"] = bool(
                (np.asarray(got) == np.asarray(want)).all())

    # (4,2) two-axis mesh: the sub-ring factorization path (tuple axes).
    mesh2 = Mesh(devs.reshape(4, 2), ("a", "b"))

    def run2(f, x, outs):
        return jax.jit(shard_map(f, mesh=mesh2, in_specs=P(("a", "b")),
                                 out_specs=outs, check_vma=False))(x)

    x = jnp.asarray(rng.integers(-8, 8, size=(8 * 96,)).astype(np.float32))
    got = run2(lambda v: algorithms.all_reduce(
        v, ("a", "b"), algorithm="compiled"), x, P(("a", "b")))
    want = run2(lambda v: jax.lax.psum(v, ("a", "b")), x, P(("a", "b")))
    checks["ar_2d_compiled"] = bool(
        (np.asarray(got) == np.asarray(want)).all())

    got = run2(lambda v: algorithms.all_gather(
        v, ("a", "b"), algorithm="compiled:b*2.none/a*4.none"), x, P())
    want = run2(lambda v: jax.lax.all_gather(
        v, ("a", "b"), tiled=True), x, P())
    checks["ag_2d_compiled"] = bool(
        (np.asarray(got) == np.asarray(want)).all())

    got = run2(lambda v: algorithms.reduce_scatter(
        v, ("a", "b"), algorithm="compiled:b*2.none/a*4.none"),
        x, P(("a", "b")))
    want = run2(lambda v: jax.lax.psum_scatter(
        v, ("a", "b"), tiled=True), x, P(("a", "b")))
    checks["rs_2d_compiled"] = bool(
        (np.asarray(got) == np.asarray(want)).all())

    evidence["bit_identity"] = checks
    gates["compiled_bit_identical_vs_lax"] = all(checks.values())


def _gate_parity_and_refit(evidence: dict, gates: dict) -> None:
    from deepspeed_tpu.collectives import schedule, selector
    from deepspeed_tpu.collectives.algorithms import ALGORITHMS

    op, nbytes, codec, world = "all_reduce", 1 << 20, "int8", 30
    axes_sig = (("dp", world),)
    try:
        selector.configure(compiled_search=True, codecs=(codec,))

        # alpha-dominant refit: hop count decides; compiled [2,3,5]
        # (14 hops) must beat every hand algorithm at world 30.
        selector.calibrate("ppermute", 10.0, 0.1)
        cm = selector.cost_model()
        hand = min(
            selector.estimate_us(op, alg, codec, nbytes, world)
            for alg in ALGORITHMS
            if not (alg == "rhd" and (world & (world - 1))))
        sched = schedule.compile_schedule(op, axes_sig, nbytes, codec, cm=cm)
        pred_ratio = sched.est_us / hand if hand > 0 else 1.0
        pick = selector.select(op, nbytes, world, codec=codec,
                               axes_sig=axes_sig)
        evidence["parity"] = {
            "world": world, "codec": codec, "nbytes": nbytes,
            "compiled_signature": sched.signature,
            "compiled_pred_us": round(sched.est_us, 4),
            "hand_pred_us": round(hand, 4),
            "pred_ratio": round(pred_ratio, 6),
            "selector_pick": pick.algorithm,
        }
        gates["compiled_parity_with_hand"] = pred_ratio <= 1.0 + 1e-9
        gates["selector_routes_to_compiled"] = (
            pick.algorithm.startswith("compiled:"))

        # beta-dominant refit of the SAME model object flips the SAME
        # query to the hand-written bidir pick.
        selector.calibrate("ppermute", 0.01, 100.0)
        flipped = selector.select(op, nbytes, world, codec=codec,
                                  axes_sig=axes_sig)
        evidence["refit"] = {"flipped_pick": flipped.algorithm,
                             "same_model": cm is selector.cost_model()}
        gates["refit_flips_pick"] = (flipped.algorithm == "bidir"
                                     and cm is selector.cost_model())
    finally:
        # configure() rebuilds the model around default constants — the
        # refits above don't leak into the fused-trajectory gate
        selector.configure()


def _gate_fused_trajectory(evidence: dict, gates: dict) -> None:
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from deepspeed_tpu.collectives import fused_gemm
    from deepspeed_tpu.parallel import zeropp
    from deepspeed_tpu.utils.compat import shard_map

    n = 4
    mesh = Mesh(np.array(jax.devices()[:n]), ("fsdp",))
    Mb, Ks, N = 8, 8, 16
    K = n * Ks
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(n * Mb, K)).astype(np.float32))
    w0 = jnp.asarray(rng.normal(size=(K, N)).astype(np.float32) * 0.1)
    t = jnp.asarray(rng.normal(size=(n * Mb, N)).astype(np.float32))
    lr = 1e-3

    def sgd_step(xv, wv, tv):
        # ZeRO-3 shape: batch-sharded x, parameter shard wv; the fused
        # forward gathers w on the fly, the fused backward reduce-scatters
        # dw so each rank updates only its own shard.
        def loss(a, b):
            y = zeropp.sharded_matmul(a, b, "fsdp", False, 64)
            return jnp.sum((y - tv) * (y - tv))

        lval, dw = jax.value_and_grad(loss, argnums=1)(xv, wv)
        return wv - lr * dw, jnp.reshape(lval, (1,))

    def trajectory(fused):
        fused_gemm.configure(enabled=fused)
        f = jax.jit(shard_map(
            sgd_step, mesh=mesh,
            in_specs=(P("fsdp"), P("fsdp"), P("fsdp")),
            out_specs=(P("fsdp"), P("fsdp")), check_vma=False))
        w, losses = w0, []
        np.asarray(f(x, w, t)[0])  # compile off the clock
        t0 = time.perf_counter()
        for _ in range(TRAJ_STEPS):
            w, lv = f(x, w, t)
            losses.append(float(np.asarray(lv).sum()))
        wall = time.perf_counter() - t0
        return np.asarray(losses), np.asarray(w), wall

    try:
        l_unfused, w_unfused, t_unfused = trajectory(False)
        l_fused, w_fused, t_fused = trajectory(True)
    finally:
        fused_gemm.configure(enabled=False)

    rel = np.abs(l_fused - l_unfused) / (np.abs(l_unfused) + 1e-12)
    w_rel = float(np.abs(w_fused - w_unfused).max()
                  / (np.abs(w_unfused).max() + 1e-12))
    step_ratio = t_fused / t_unfused if t_unfused > 0 else 1.0
    evidence["fused_traj"] = {
        "steps": TRAJ_STEPS, "world": n, "rtol": TRAJ_RTOL,
        "loss_first": round(float(l_unfused[0]), 6),
        "loss_last_unfused": round(float(l_unfused[-1]), 6),
        "loss_last_fused": round(float(l_fused[-1]), 6),
        "max_loss_rel_err": float(rel.max()),
        "final_w_rel_err": w_rel,
        "loss_decreased": bool(l_unfused[-1] < l_unfused[0]),
        "step_time_ratio": round(step_ratio, 4),
    }
    gates["fused_traj_within_tolerance"] = bool(
        rel.max() < TRAJ_RTOL and w_rel < TRAJ_RTOL
        and l_unfused[-1] < l_unfused[0])


def run_smoke() -> dict:
    evidence: dict = {}
    gates: dict = {}
    _gate_compiled_bit_identity(evidence, gates)
    _gate_parity_and_refit(evidence, gates)
    _gate_fused_trajectory(evidence, gates)
    evidence["gates"] = gates
    evidence["pass"] = all(gates.values())
    return evidence


def emit_ledger(evidence: dict) -> int:
    """Append the headline trajectories to the unified perf ledger (suite
    ``schedule``). Best-effort like the other smokes: the verdict never
    depends on the ledger dir being writable."""
    try:
        from deepspeed_tpu.telemetry.fleet import get_identity
        from deepspeed_tpu.telemetry.perfledger import (
            PerfLedger, default_backend, default_round, make_row,
            resolve_git_sha,
        )

        common = dict(backend=default_backend(), round=default_round(),
                      run_id=get_identity().run_id,
                      git_sha=resolve_git_sha(), time_unix=time.time())
        rows = [
            make_row("schedule", "compiled_vs_hand/pred_ratio",
                     float(evidence["parity"]["pred_ratio"]), "ratio",
                     direction="lower", method="probe", samples=1, **common),
            make_row("schedule", "fused_gemm/step_time_ratio",
                     float(evidence["fused_traj"]["step_time_ratio"]),
                     "ratio", direction="lower", method="probe",
                     samples=TRAJ_STEPS, **common),
            make_row("schedule", "fused_gemm/traj_rel_err",
                     float(evidence["fused_traj"]["max_loss_rel_err"]),
                     "rel", direction="lower", method="probe",
                     samples=TRAJ_STEPS, **common),
        ]
        return PerfLedger().append(rows)
    except Exception as e:  # noqa: BLE001 — evidence plane, not the gate
        print(f"[schedule_smoke] perf-ledger append skipped: {e}",
              file=sys.stderr)
        return 0


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ledger", action="store_true",
                    help="append headline rows to the unified perf ledger")
    args = ap.parse_args()
    evidence = run_smoke()
    if args.ledger:
        evidence["ledger_rows"] = emit_ledger(evidence)
    print(json.dumps(evidence, sort_keys=True))
    sys.exit(0 if evidence["pass"] else 1)


if __name__ == "__main__":
    main()
