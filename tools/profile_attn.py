"""Measure flash attention kernel after tuning; compare to xla impl."""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.registry import dispatch


def fetch_time(fn, out_leaf=lambda r: r, n=10, warmup=3):
    for _ in range(warmup):
        r = fn()
    _ = np.asarray(out_leaf(r))
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    _ = np.asarray(out_leaf(r))
    return (time.perf_counter() - t0) / n


def main():
    B, S, H, D = 8, 1024, 12, 64
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, S, H, D), jnp.bfloat16)
    att_fl = 4 * B * H * S * S * D  # fwd flops (causal halves useful work)

    outs = {}
    for impl in ("pallas", "xla"):
        f = dispatch("causal_attention", impl)
        fn = jax.jit(lambda q, k, v, f=f: f(q, k, v))
        r = fn(q, k, v)
        outs[impl] = np.asarray(r, np.float32)
        t = fetch_time(lambda: fn(q, k, v)[0, 0, 0, 0])
        print(f"fwd {impl}: {t*1e3:.2f} ms ({att_fl/t/1e12:.1f} TF/s)")

    err = np.abs(outs["pallas"] - outs["xla"]).max()
    print(f"fwd max abs diff pallas vs xla: {err:.4f}")

    for impl in ("pallas", "xla"):
        f = dispatch("causal_attention", impl)

        @jax.jit
        def gfn(q, k, v, f=f):
            def loss(q, k, v):
                return f(q, k, v).astype(jnp.float32).sum()
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        r = gfn(q, k, v)
        t = fetch_time(lambda: gfn(q, k, v)[0][0, 0, 0, 0])
        print(f"bwd {impl}: {t*1e3:.2f} ms")
        if impl == "pallas":
            gp = [np.asarray(x, np.float32) for x in r]
        else:
            gx = [np.asarray(x, np.float32) for x in r]
    for nm, a, b in zip("qkv", gp, gx):
        print(f"d{nm} max abs diff: {np.abs(a-b).max():.4f} (scale {np.abs(b).max():.2f})")


if __name__ == "__main__":
    main()
