#!/usr/bin/env python
"""Merge per-process telemetry JSONL streams into ONE Perfetto trace.

Each process of a fleet exports its own ``events.jsonl``
(``telemetry.export_jsonl``): a ``process_meta`` line (identity + the
wall-clock ``origin_unix`` its event ``ts`` values are relative to),
``track_name`` lines, then raw tracer events. This tool joins K such
streams on a shared timeline:

  - every stream gets a DISTINCT, stable Chrome pid (the identity's
    process_index — not the OS pid, which collides across hosts), with a
    ``process_name`` metadata row naming run_id/role/host;
  - timestamps align via each stream's ``origin_unix`` anchor:
    ``merged_ts = (origin_unix + ts) - min(origin_unix)``. Optionally
    ``--ledger fleet.json`` (the collector's ``GET /fleet`` document)
    applies the clock-offset handshake each process performed at collector
    registration — for fleets whose hosts' wall clocks disagree;
  - flow events pass through untouched: both sides of a cross-process
    dispatch derived the SAME flow id from the trace context
    (``fleet.TraceContext``), so the router process's admission arrow
    lands in the replica process's ``serve:dispatch`` slice once the
    streams share a timeline.

Usage:
  python tools/trace_merge.py -o merged_trace.json p0/events.jsonl p1/events.jsonl
  python tools/trace_merge.py -o merged.json --ledger fleet.json telemetry_out/*/events.jsonl

Open the output at https://ui.perfetto.dev.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional


def read_stream(path: str) -> Dict[str, Any]:
    """One JSONL stream -> {"meta", "tracks": {tid: name}, "events": [...]}.
    Streams from pre-fleet exports (no meta line) still merge: identity
    defaults empty and the origin anchor falls back to 0 (events keep
    their relative timeline)."""
    meta: Dict[str, Any] = {}
    tracks: Dict[int, str] = {}
    events: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            kind = rec.get("kind")
            if kind == "process_meta":
                meta = rec
            elif kind == "track_name":
                tracks[int(rec["tid"])] = rec.get("track", "")
            elif kind in ("span", "instant", "flow", "counter"):
                events.append(rec)
    return {"path": path, "meta": meta, "tracks": tracks, "events": events}


def _ledger_offsets(ledger_path: Optional[str]) -> Dict[str, float]:
    """proc key -> clock_offset_s from a collector ``GET /fleet`` doc."""
    if not ledger_path:
        return {}
    with open(ledger_path) as f:
        doc = json.load(f)
    out = {}
    for row in doc.get("processes", []):
        if row.get("clock_offset_s") is not None:
            out[row["proc"]] = float(row["clock_offset_s"])
    return out


def merge_streams(paths: List[str], ledger: Optional[str] = None
                  ) -> Dict[str, Any]:
    """K per-process JSONL streams -> one Chrome trace-event JSON object."""
    streams = [read_stream(p) for p in paths]
    offsets = _ledger_offsets(ledger)

    def proc_key(s) -> str:
        ident = s["meta"].get("identity") or {}
        return f"{ident.get('run_id', '?')}/p{ident.get('process_index', 0)}"

    def origin(s) -> float:
        o = float(s["meta"].get("origin_unix", 0.0))
        # the handshake offset maps the sender's clock onto the collector's:
        # adding it places every stream on the COLLECTOR's wall clock
        return o + offsets.get(proc_key(s), 0.0)

    base = min((origin(s) for s in streams), default=0.0)
    out: List[Dict[str, Any]] = []
    used_pids: Dict[int, int] = {}
    for i, s in enumerate(streams):
        ident = s["meta"].get("identity") or {}
        pid = int(ident.get("process_index", i))
        if pid in used_pids:  # two streams claiming one index still separate
            pid = max(used_pids) + 1
        used_pids[pid] = 1
        shift_us = (origin(s) - base) * 1e6
        label = (f"p{ident.get('process_index', i)} "
                 f"{ident.get('role', '?')}@{ident.get('host', '?')} "
                 f"run={ident.get('run_id', '?')}")
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": label}})
        out.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                    "args": {"sort_index": pid}})
        for tid, tname in sorted(s["tracks"].items()):
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tid, "args": {"name": tname}})
        for ev in s["events"]:
            ts_us = ev["ts"] * 1e6 + shift_us
            kind = ev["kind"]
            if kind == "span":
                rec: Dict[str, Any] = {
                    "name": ev["name"], "cat": ev.get("cat", "span"),
                    "ph": "X", "ts": ts_us, "dur": ev["dur"] * 1e6,
                    "pid": pid, "tid": ev["tid"]}
                if "args" in ev:
                    rec["args"] = ev["args"]
            elif kind == "instant":
                rec = {"name": ev["name"], "cat": ev.get("cat", "event"),
                       "ph": "i", "s": "t", "ts": ts_us, "pid": pid,
                       "tid": ev["tid"]}
                if "args" in ev:
                    rec["args"] = ev["args"]
            elif kind == "flow":
                rec = {"name": ev["name"], "cat": ev.get("cat", "flow"),
                       "ph": ev["ph"], "id": ev["id"], "ts": ts_us,
                       "pid": pid, "tid": ev["tid"]}
                if ev["ph"] == "f":
                    rec["bp"] = "e"
            else:  # counter
                rec = {"name": ev["name"], "ph": "C", "ts": ts_us,
                       "pid": pid, "args": {"value": ev["value"]}}
            out.append(rec)
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged_from": [s["path"] for s in streams],
            "processes": [
                {**(s["meta"].get("identity") or {}),
                 "origin_unix": s["meta"].get("origin_unix")}
                for s in streams],
        },
    }


def linked_flow_pids(trace: Dict[str, Any]) -> Dict[int, List[int]]:
    """flow id -> sorted pids that emitted BINDABLE events for it — keyed
    the way Chrome actually binds arrows, on (cat, name, id), so two
    processes that share an id but disagree on the name (no arrow drawn)
    do NOT count as linked. The smoke's exit-gate asks whether any flow
    links spans from >= 2 processes."""
    by_key: Dict[tuple, set] = {}
    for ev in trace["traceEvents"]:
        if ev.get("ph") in ("s", "t", "f") and "id" in ev:
            key = (ev.get("cat", "flow"), ev.get("name", ""), ev["id"])
            by_key.setdefault(key, set()).add(ev["pid"])
    # per flow id, report the pid set of its most-connected bindable key —
    # events under a DIFFERENT name never merge, exactly like the viewer
    out: Dict[int, List[int]] = {}
    for (_cat, _name, fid), pids in by_key.items():
        if fid not in out or len(pids) > len(out[fid]):
            out[fid] = sorted(pids)
    return out


def migration_links(trace: Dict[str, Any]) -> Dict[int, List[int]]:
    """flow id -> sorted pids, for flows that STEP inside a
    ``serve:migrate`` slice — the disaggregated-serving hand-off arrow
    (ISSUE 14): a request's admission flow starts on the prefill replica's
    stream and steps inside the decode replica's ``serve:migrate`` import
    slice, so the merged trace draws the prefill->decode migration arrow.
    The disagg smoke gates on at least one such link."""
    # one pass each over slices and flow events (a nightly merge can carry
    # thousands of migrations — no per-step rescans of the whole stream)
    slices: Dict[Any, List[Any]] = {}
    for e in trace["traceEvents"]:
        if e.get("ph") == "X" and e.get("name") == "serve:migrate":
            slices.setdefault((e["pid"], e.get("tid")), []).append(
                (e["ts"], e["ts"] + e.get("dur", 0.0)))
    flow_pids: Dict[int, set] = {}  # fid -> pids of EVERY bindable event of
    migrated: set = set()           # the flow — the arrow spans src and dst
    for ev in trace["traceEvents"]:
        if ev.get("ph") not in ("s", "t", "f") or "id" not in ev:
            continue
        fid = ev["id"]
        flow_pids.setdefault(fid, set()).add(ev["pid"])
        if ev["ph"] == "t":
            spans = slices.get((ev["pid"], ev.get("tid")), ())
            if any(a <= ev["ts"] <= b for a, b in spans):
                migrated.add(fid)
    return {f: sorted(flow_pids[f]) for f in migrated}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("inputs", nargs="+", help="per-process events.jsonl files")
    ap.add_argument("-o", "--output", default="merged_trace.json")
    ap.add_argument("--ledger", default=None,
                    help="collector GET /fleet JSON (clock-offset handshake)")
    args = ap.parse_args(argv)
    trace = merge_streams(args.inputs, ledger=args.ledger)
    with open(args.output, "w") as f:
        json.dump(trace, f)
    links = {f: p for f, p in linked_flow_pids(trace).items() if len(p) > 1}
    n_mig = len(migration_links(trace))
    n_ev = len(trace["traceEvents"])
    print(f"wrote {args.output}: {n_ev} events from {len(args.inputs)} "
          f"stream(s); {len(links)} cross-process flow link(s); "
          f"{n_mig} migration flow link(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
