"""Second-stage profiling: is block_until_ready broken on axon, and what is
the true device-time of the step vs its parts?"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, causal_lm_spec, CausalLM
from deepspeed_tpu.topology.mesh import set_mesh


def main():
    cfg = TransformerConfig(
        vocab_size=50304, hidden_size=768, intermediate_size=3072,
        num_layers=12, num_heads=12, max_seq_len=1024,
        norm="layernorm", activation="gelu", position="learned",
        tie_embeddings=True, dtype=jnp.bfloat16,
    )
    micro, seq = 8, 1024
    config = {
        "train_micro_batch_size_per_gpu": micro,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.1}},
        "zero_optimization": {"stage": 1},
        "bf16": {"enabled": True},
        "gradient_clipping": 1.0,
        "steps_per_print": 10_000,
    }
    engine, *_ = deepspeed_tpu.initialize(model=causal_lm_spec(cfg, example_seq_len=seq), config=config)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}
    placed = engine._shard_global_batch(batch)
    state = engine.state
    step_fn = engine._train_step

    # warmup/compile
    for _ in range(2):
        state, m = step_fn(state, placed)
    _ = np.asarray(m["loss"])

    # A: is block_until_ready honest? chain 5 steps, block, then fetch.
    t0 = time.perf_counter()
    for _ in range(5):
        state, m = step_fn(state, placed)
    t_dispatch = time.perf_counter() - t0
    jax.block_until_ready(m["loss"])
    t_block = time.perf_counter() - t0
    _ = np.asarray(m["loss"])
    t_fetch = time.perf_counter() - t0
    print(f"5 steps: dispatch={t_dispatch*1e3:.1f}ms block={t_block*1e3:.1f}ms fetch={t_fetch*1e3:.1f}ms")
    print(f"=> true per-step: {(t_fetch)*1e3/5:.1f} ms")

    # B: forward-only loss
    module = CausalLM(cfg)
    set_mesh(engine.mesh)
    params16 = jax.jit(lambda p: jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16) if jnp.issubdtype(x.dtype, jnp.floating) else x, p))(state.params)
    micro_b = {"input_ids": jnp.asarray(batch["input_ids"])}

    @jax.jit
    def fwd(p, b):
        loss, _ = module.apply({"params": p}, b, train=False)
        return loss

    _ = np.asarray(fwd(params16, micro_b))
    t0 = time.perf_counter()
    for _ in range(5):
        l = fwd(params16, micro_b)
    _ = np.asarray(l)
    t_fwd = (time.perf_counter() - t0) / 5
    print(f"fwd-only: {t_fwd*1e3:.1f} ms")

    # C: fwd+bwd grads only (no optimizer)
    @jax.jit
    def fwdbwd(p, b):
        def loss_fn(pp):
            loss, _ = module.apply({"params": pp}, b, train=False)
            return loss
        return jax.value_and_grad(loss_fn)(p)[0]

    _ = np.asarray(fwdbwd(params16, micro_b))
    t0 = time.perf_counter()
    for _ in range(5):
        l = fwdbwd(params16, micro_b)
    _ = np.asarray(l)
    t_fb = (time.perf_counter() - t0) / 5
    print(f"fwd+bwd: {t_fb*1e3:.1f} ms")

    # D: big matmul sanity — what matmul TFLOPs does this chip actually hit?
    a = jnp.zeros((8192, 8192), jnp.bfloat16)
    b = jnp.zeros((8192, 8192), jnp.bfloat16)

    @jax.jit
    def mm(a, b):
        return a @ b

    _ = np.asarray(mm(a, b)[0, 0])
    t0 = time.perf_counter()
    n = 10
    for _ in range(n):
        c = mm(a, b)
    _ = np.asarray(c[0, 0])
    t_mm = (time.perf_counter() - t0) / n
    fl = 2 * 8192**3
    print(f"8k matmul: {t_mm*1e3:.2f} ms => {fl/t_mm/1e12:.1f} TFLOP/s")


if __name__ == "__main__":
    main()
