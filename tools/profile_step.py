"""Chained engine-step timing (the one true number) + trace op breakdown."""
from __future__ import annotations

import collections
import glob
import gzip
import json
import shutil
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, causal_lm_spec


def main():
    positional = [a for a in sys.argv[1:] if not a.startswith("-")]
    micro = int(positional[0]) if positional else 8
    gas = int(positional[1]) if len(positional) > 1 else 1
    trace = "--trace" in sys.argv
    cfg = TransformerConfig(
        vocab_size=50304, hidden_size=768, intermediate_size=3072,
        num_layers=12, num_heads=12, max_seq_len=1024,
        norm="layernorm", activation="gelu", position="learned",
        tie_embeddings=True, dtype=jnp.bfloat16,
        scan_layers="--unroll" not in sys.argv,
        fused_ce="--nofuse" not in sys.argv,
        attn_impl="xla" if "--xlaattn" in sys.argv else "auto",
    )
    seq = 1024
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": micro,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 1},
            "bf16": {"enabled": True},
            "steps_per_print": 10_000,
        },
    )
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}
    placed = engine._shard_global_batch(batch)
    state = engine.state
    step_fn = engine._train_step
    for _ in range(3):
        state, m = step_fn(state, placed)
    _ = np.asarray(m["loss"])

    n = 10
    t0 = time.perf_counter()
    for _ in range(n):
        state, m = step_fn(state, placed)
    _ = np.asarray(m["loss"])
    dt = (time.perf_counter() - t0) / n
    tokens = engine.train_batch_size * seq
    mfu = cfg.flops_per_token(seq) * tokens / dt / 197e12
    print(f"micro={micro}: step={dt*1e3:.1f} ms tok/s={tokens/dt:,.0f} mfu={mfu*100:.1f}%")

    if trace:
        shutil.rmtree("/tmp/steptrace", ignore_errors=True)
        with jax.profiler.trace("/tmp/steptrace"):
            for _ in range(3):
                state, m = step_fn(state, placed)
            _ = np.asarray(m["loss"])
        tj = sorted(glob.glob("/tmp/steptrace/**/*.trace.json.gz", recursive=True))[-1]
        with gzip.open(tj, "rt") as f:
            tr = json.load(f)
        agg = collections.defaultdict(float)
        cnt = collections.Counter()
        pid_names = {e["pid"]: e["args"].get("name", "") for e in tr["traceEvents"]
                     if e.get("ph") == "M" and e.get("name") == "process_name" and "args" in e}
        dev = [p for p, nm in pid_names.items() if "TPU" in nm]
        for e in tr["traceEvents"]:
            if e.get("ph") == "X" and e.get("pid") in dev:
                agg[e.get("name", "?")] += e.get("dur", 0) / 1e3
                cnt[e.get("name", "?")] += 1
        for nm, v in sorted(agg.items(), key=lambda kv: -kv[1])[:20]:
            print(f"  {v/3:8.2f} ms/step x{cnt[nm]//3:4d}  {nm[:100]}")


if __name__ == "__main__":
    main()
