#!/usr/bin/env python
"""Serving-overhead microbenchmark (CPU-runnable, wedge-proof).

Measures the HOST side of the v2 serving loop — the part PERF.md's platform
facts make load-bearing (~6-7 ms fixed relay overhead per dispatched program,
so decode throughput is dispatch-bound, not kernel-bound):

  1. allocator ops/s           — BlockedAllocator (numpy free-stack) vs the
                                 legacy list/set implementation (in-file)
  2. assembly µs/seq           — staged vectorized build_ragged_batch vs the
                                 legacy per-row-loop/fresh-array build
  3. serving loop (tiny model) — decode_chain=1 (per-token dispatch) vs
                                 decode_chain=K: host µs per decoded token
                                 (assemble + dispatch-call time off the
                                 tracer spans), programs dispatched and host
                                 syncs per token, tokens scheduled/s

No TPU required and nothing is materialized beyond a toy model — safe to run
inside any relay window or on a laptop. Results feed PERF.md's "serving
overhead" section.

Usage: python tools/bench_serving.py [--rows 8] [--tokens 64] [--chain 8]
                                     [--output serving.json]
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Dict, List, Sequence

import numpy as np


# --------------------------------------------------------------------------
# Legacy (pre-fast-path) implementations, kept here so before/after can be
# re-measured from one file forever. Semantics match the old inference/ragged
# code: Python-list free list, per-row loops, fresh arrays every step.
# --------------------------------------------------------------------------
class _LegacyAllocator:
    def __init__(self, num_blocks: int):
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._free_set = set(self._free)
        self.num_blocks = num_blocks

    @property
    def free_blocks(self):
        return len(self._free)

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError("oom")
        out = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(out)
        return out

    def free(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            if b < 0 or b >= self.num_blocks or b in self._free_set:
                raise ValueError("bad free")
            self._free.append(b)
            self._free_set.add(b)


class _LegacySeq:
    def __init__(self, uid):
        self.uid = uid
        self.seen_tokens = 0
        self.blocks: List[int] = []  # python list, as before the fast path

    def blocks_needed(self, new_tokens, block_size):
        total = self.seen_tokens + new_tokens
        return max(0, -(-total // block_size) - len(self.blocks))


class _LegacyManager:
    """Pre-fast-path StateManager: list-based descriptors + legacy allocator."""

    def __init__(self, num_blocks, block_size):
        self.allocator = _LegacyAllocator(num_blocks)
        self.block_size = block_size
        self._seqs = {}

    def extend(self, uid, new_tokens):
        seq = self._seqs.setdefault(uid, _LegacySeq(uid))
        need = seq.blocks_needed(new_tokens, self.block_size)
        if need:
            seq.blocks.extend(self.allocator.allocate(need))
        return seq


def _legacy_build(manager, uids, token_lists, max_pages, row_bucket=8, chunk_bucket=8):
    """The old build_ragged_batch: fresh arrays + per-row python fills."""
    n = len(uids)
    chunk = max(max(len(t) for t in token_lists), 1)
    chunk = ((chunk + chunk_bucket - 1) // chunk_bucket) * chunk_bucket
    rows = ((n + row_bucket - 1) // row_bucket) * row_bucket
    tokens = np.zeros((rows, chunk), np.int32)
    positions = np.zeros((rows, chunk), np.int32)
    new_lens = np.zeros((rows,), np.int32)
    block_tables = np.zeros((rows, max_pages), np.int32)
    seen = np.zeros((rows,), np.int32)
    for i, (uid, toks) in enumerate(zip(uids, token_lists)):
        toks = np.asarray(toks, np.int32)
        seq = manager.extend(uid, len(toks))
        tokens[i, : len(toks)] = toks
        positions[i, : len(toks)] = seq.seen_tokens + np.arange(len(toks))
        new_lens[i] = len(toks)
        block_tables[i, : len(seq.blocks)] = seq.blocks
        seen[i] = seq.seen_tokens
    return tokens, positions, new_lens, block_tables, seen


# --------------------------------------------------------------------------
def bench_allocator(num_blocks=8192, rounds=2000) -> Dict:
    """Alloc/free churn at the serving hot path's granularity.

    The vectorized assembly batches the whole step into ONE allocator call
    (rows × blocks-per-row), and flush frees a whole block table at once —
    so the batched shape (32 blocks/call) is what serving actually does;
    the 4-block shape shows the small-call floor. Reported as blocks/s."""
    from deepspeed_tpu.inference.ragged import BlockedAllocator

    def run(alloc_cls, per_call):
        a = alloc_cls(num_blocks)
        live = []
        t0 = time.perf_counter()
        blocks = 0
        for r in range(rounds):
            live.append(a.allocate(per_call))
            blocks += per_call
            if len(live) >= (num_blocks // per_call) // 2:
                for blk in live:
                    a.free(blk)
                    blocks += per_call
                live = []
        for blk in live:
            a.free(blk)
            blocks += per_call
        return blocks / (time.perf_counter() - t0)

    out = {}
    for label, per_call in (("batched32", 32), ("small4", 4)):
        new = run(BlockedAllocator, per_call)
        old = run(_LegacyAllocator, per_call)
        out[label] = {"new_blocks_per_sec": round(new),
                      "legacy_blocks_per_sec": round(old),
                      "speedup": round(new / old, 2)}
    return out


def bench_assembly(row_counts=(8, 32), steps=2000, prompt_len=64) -> Dict:
    """Decode-shaped assembly (1 token/row): µs per sequence-row, staged
    vectorized build vs the full legacy stack (list descriptors + legacy
    allocator + per-row loop + fresh arrays)."""
    from deepspeed_tpu.inference.ragged import BatchStaging, StateManager, build_ragged_batch

    out = {}
    for rows in row_counts:
        uids = list(range(rows))
        toks = [np.asarray([7], np.int32)] * rows

        m = StateManager(num_blocks=8192, block_size=16, max_seqs=256,
                         max_blocks_per_seq=64)
        for u in uids:
            m.extend(u, prompt_len)
            m.get(u).seen_tokens = prompt_len
        st = BatchStaging(max_pages=64)
        build_ragged_batch(m, uids, toks, 64, row_bucket=rows, staging=st)
        t0 = time.perf_counter()
        for _ in range(steps):
            build_ragged_batch(m, uids, toks, 64, row_bucket=rows, staging=st)
        staged_us = (time.perf_counter() - t0) / (steps * rows) * 1e6

        lm = _LegacyManager(8192, 16)
        for u in uids:
            lm.extend(u, prompt_len)
            lm._seqs[u].seen_tokens = prompt_len
        t0 = time.perf_counter()
        for _ in range(steps):
            _legacy_build(lm, uids, toks, 64, row_bucket=rows)
        legacy_us = (time.perf_counter() - t0) / (steps * rows) * 1e6
        out[f"rows{rows}"] = {
            "staged_us_per_seq": round(staged_us, 2),
            "legacy_us_per_seq": round(legacy_us, 2),
            "speedup": round(legacy_us / staged_us, 2)}
    return out


def _tiny_model():
    import jax

    from deepspeed_tpu.models import CausalLM, TransformerConfig

    cfg = TransformerConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=256)
    module = CausalLM(cfg)
    params = module.init({"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(0)},
                         {"input_ids": np.zeros((1, 8), np.int32)}, train=False)["params"]
    return cfg, params


def bench_host_path(rows=8, n_new=64, chain=8, prompt_len=32) -> Dict:
    """Pure host serving overhead: the device programs are replaced by
    shape-correct host stubs, so the measured time is EXACTLY the work the
    host does per decoded token — assembly, scheduling, bookkeeping,
    dispatch-call plumbing, fetch. On a real accelerator this is the part
    that serializes with the device when every token round-trips, and the
    part the K-chain divides by K (the device side is one program either
    way; its relay cost is the ~6-7 ms/dispatch platform fact)."""
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2

    class NullDeviceEngine(InferenceEngineV2):
        def _sample_step_fn(self, n_rows, chunk, sample_kw):
            def step(params, pool, tokens, positions, new_lens, block_tables, rng):
                return np.ones((tokens.shape[0],), np.int32), rng, pool

            return step

        def _chain_fn(self, n_rows, k, eos_id, sample_kw):
            def chain_fn(params, pool, tokens, start_pos, block_tables,
                         active, budgets, rng):
                act = np.asarray(active)
                emitted = np.where(act, np.asarray(budgets), 0).astype(np.int32)
                out = np.where(np.arange(k)[None, :] < emitted[:, None],
                               1, -1).astype(np.int32)
                return out, emitted, act & False, rng, pool

            return chain_fn

    cfg, params = _tiny_model()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (prompt_len,)) for _ in range(rows)]

    def run(k):
        eng = NullDeviceEngine(cfg, params, {
            "dtype": "fp32", "kv_block_size": 16, "num_kv_blocks": 2048,
            "max_seqs": rows, "decode_chain": k, "hbm_check": "off"})
        eng.generate(prompts, max_new_tokens=4)  # warm staging buckets
        for u in list(eng.state._seqs):
            eng.flush(u)
        d0, s0 = eng.dispatch_count, eng.host_sync_count
        t0 = time.perf_counter()
        eng.generate(prompts, max_new_tokens=n_new)
        wall = time.perf_counter() - t0
        decoded = max(eng.tokens_decoded, 1)
        return {
            "decode_chain": k,
            "host_us_per_decode_token": round(wall * 1e6 / decoded, 2),
            "tokens_scheduled_per_sec": round((decoded + rows) / wall),
            "programs_per_decode_token": round(
                (eng.dispatch_count - d0 - 1) / decoded, 4),
            "host_syncs_per_decode_token": round(
                (eng.host_sync_count - s0 - 1) / decoded, 4),
        }

    before = run(1)
    after = run(chain)
    return {
        "rows": rows, "new_tokens": n_new,
        "per_token_loop": before, "chained": after,
        "host_us_speedup": round(
            before["host_us_per_decode_token"]
            / max(after["host_us_per_decode_token"], 1e-9), 2),
    }


def bench_end_to_end(rows=8, n_new=64, chain=8, prompt_len=32) -> Dict:
    """Tiny-model generate wall clock, decode_chain=1 vs =chain (CPU: device
    compute shares the host, so this understates the accelerator-side win —
    the host-path benchmark above is the isolation)."""
    from deepspeed_tpu.inference import InferenceEngineV2

    cfg, params = _tiny_model()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (prompt_len,)) for _ in range(rows)]

    def run(k):
        eng = InferenceEngineV2(cfg, params, {
            "dtype": "fp32", "kv_block_size": 16, "num_kv_blocks": 512,
            "max_seqs": rows, "decode_chain": k, "hbm_check": "off"})
        eng.generate(prompts, max_new_tokens=4)  # compiles prefill + k-chain
        for u in list(eng.state._seqs):
            eng.flush(u)
        t0 = time.perf_counter()
        outs = eng.generate(prompts, max_new_tokens=n_new)
        wall = time.perf_counter() - t0
        total = sum(len(o) for o in outs)
        return {"decode_chain": k,
                "tokens_per_sec": round(total / wall, 1),
                "wall_s": round(wall, 3)}

    return {"rows": rows, "new_tokens": n_new,
            "per_token_loop": run(1), "chained": run(chain)}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=8)
    ap.add_argument("--tokens", type=int, default=64)
    ap.add_argument("--chain", type=int, default=8)
    ap.add_argument("--output", type=str, default=None)
    args = ap.parse_args()

    out = {
        "allocator": bench_allocator(),
        "assembly": bench_assembly(row_counts=(args.rows, 4 * args.rows)),
        "host_path": bench_host_path(rows=args.rows, n_new=args.tokens,
                                     chain=args.chain),
        "end_to_end": bench_end_to_end(rows=args.rows, n_new=args.tokens,
                                       chain=args.chain),
    }
    text = json.dumps(out, indent=2)
    print(text)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text + "\n")


if __name__ == "__main__":
    main()
